"""Async load tester: throughput + latency percentiles + bandit feedback.

Parity (C24): reference util/loadtester/scripts/predict_rest_locust.py — a
locust swarm that fetches an OAuth token (:107-121), sends random ndarray
predictions (:123-139), and closes the bandit loop with reward feedback
whose probability depends on the taken route (:83-103 — route-dependent
reward probabilities are how an A/B or epsilon-greedy router is exercised
under load). This asyncio implementation replaces the locust dependency and
reports p50/90/95/99 like the reference's Grafana dashboard percentiles.

Multi-process mode (reference parity: the locust harness runs master/slave
across pods — util/loadtester/scripts/predict_rest_locust.py:17-30 reads
master host/port from the environment): ``--workers N`` re-execs this module
N times, splits the users across the worker processes, and merges exact
latency distributions (each worker dumps raw float32 latencies to a temp
.npy the parent reads back). One asyncio process tops out as a generator
well below a multi-core server's ceiling; N workers prove whether a
measured ceiling is the server's or the client's.

CLI:
    python -m seldon_core_tpu.tools.loadtest http://HOST:PORT \
        [--users 10] [--duration 10] [--features 4] [--batch 1] \
        [--workers 1] [--oauth-key K --oauth-secret S] \
        [--feedback-route-rewards 0.4,0.9] [--json]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import random
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field

from seldon_core_tpu.utils.env import LOADTEST_OAUTH_KEY, LOADTEST_OAUTH_SECRET


@dataclass
class LoadStats:
    latencies_s: list[float] = field(default_factory=list)
    errors: int = 0
    feedback_sent: int = 0
    started: float = 0.0
    finished: float = 0.0
    workers: int = 1
    # completion timestamps (same clock as started), parallel to
    # latencies_s: lets the rate count only requests that finished inside
    # the intended window. Closed-loop users drain their LAST in-flight
    # request after the deadline; a single multi-second stall (network
    # hiccup, device preemption) would otherwise stretch the measured wall
    # and poison the throughput 10-100x while every percentile stays sane.
    completions_s: list[float] = field(default_factory=list)
    deadline: float = 0.0  # perf_counter timestamp of intended window end
    # multiprocess mode: per-worker request counts, in worker order — lets
    # callers verify every worker's dump actually contributed to the merge
    worker_requests: list[int] = field(default_factory=list)
    # multiprocess mode: sum of the workers' windowed rates (each worker
    # computes its own window; the merged latency list spans all of them)
    rps_override: float | None = None
    # multiprocess mode: summed drain_requests across workers — the tail
    # signal must survive the merge (a huge p99 with no drain count would
    # be indistinguishable from slow steady-state latency)
    drain_override: int = 0

    def percentile(self, q: float) -> float:
        if not self.latencies_s:
            return 0.0
        xs = sorted(self.latencies_s)
        idx = min(len(xs) - 1, int(q / 100.0 * len(xs)))
        return xs[idx]

    def summary(self) -> dict:
        n = len(self.latencies_s)
        wall = max(self.finished - self.started, 1e-9)
        drain = 0
        if self.rps_override is not None:
            rps = self.rps_override
            drain = self.drain_override
        elif self.deadline and self.completions_s:
            in_window = sum(1 for t in self.completions_s if t <= self.deadline)
            drain = n - in_window
            window = max(self.deadline - self.started, 1e-9)
            rps = in_window / window
        else:
            rps = n / wall
        out = {
            "requests": n,
            "errors": self.errors,
            "feedback_sent": self.feedback_sent,
            "duration_s": round(wall, 3),
            "requests_per_sec": round(rps, 2),
            "p50_ms": round(self.percentile(50) * 1e3, 2),
            "p90_ms": round(self.percentile(90) * 1e3, 2),
            "p95_ms": round(self.percentile(95) * 1e3, 2),
            "p99_ms": round(self.percentile(99) * 1e3, 2),
            "workers": self.workers,
        }
        if drain:
            # requests that completed after the window (their latencies ARE
            # in the percentiles; they just don't inflate the denominator)
            out["drain_requests"] = drain
        return out


async def _fetch_token(session, base: str, key: str, secret: str) -> str:
    async with session.post(
        f"{base}/oauth/token",
        data={"grant_type": "client_credentials", "client_id": key, "client_secret": secret},
    ) as resp:
        body = await resp.json()
        return body["access_token"]


def _make_payload(rng: random.Random, batch: int, shape) -> dict:
    """Random ndarray payload: ``shape`` is an int (flat feature count, the
    locust-script shape) or a tuple (e.g. (224, 224, 3) images)."""

    def _fill(dims):
        if not dims:
            return rng.random()
        return [_fill(dims[1:]) for _ in range(dims[0])]

    dims = (batch, shape) if isinstance(shape, int) else (batch, *tuple(shape))
    return {"data": {"ndarray": _fill(dims)}}


class _RawHttpConn:
    """Minimal persistent HTTP/1.1 client over asyncio streams.

    The load generator shares one core with the server under test on this
    harness; aiohttp's client stack costs ~150 us/request of that core —
    measurement harness, not stack-under-test. Pre-built request bytes +
    readline header parse is ~5x cheaper, so the numbers reflect the
    SERVER. Supports exactly what the bench needs: POST, keep-alive,
    Content-Length bodies (aiohttp server never chunks Response(body=...)),
    reconnect on server close."""

    def __init__(self, host: str, port: int, use_tls: bool = False):
        self.host, self.port = host, port
        self.use_tls = use_tls
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None

    async def _connect(self) -> None:
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port, ssl=True if self.use_tls else None
        )

    def build_request(
        self, path: str, body: bytes, content_type: str, extra_headers: dict
    ) -> bytes:
        lines = [
            f"POST {path} HTTP/1.1",
            f"Host: {self.host}:{self.port}",
            f"Content-Type: {content_type}",
            f"Content-Length: {len(body)}",
            "Connection: keep-alive",
        ]
        lines.extend(f"{k}: {v}" for k, v in extra_headers.items())
        return ("\r\n".join(lines) + "\r\n\r\n").encode() + body

    async def request_raw(self, req: bytes) -> tuple[int, dict, bytes]:
        """Send pre-built request bytes; returns (status, headers, body).
        Retries ONCE on a dead keep-alive connection."""
        for attempt in (0, 1):
            if self._writer is None:
                await self._connect()
            try:
                self._writer.write(req)
                await self._writer.drain()
                status_line = await self._reader.readline()
                if not status_line:
                    raise ConnectionResetError("server closed keep-alive")
                status = int(status_line.split(b" ", 2)[1])
                headers: dict = {}
                while True:
                    line = await self._reader.readline()
                    if line in (b"\r\n", b"\n", b""):
                        break
                    k, _, v = line.decode("latin-1").partition(":")
                    headers[k.strip().lower()] = v.strip()
                clen = int(headers.get("content-length", "0"))
                body = await self._reader.readexactly(clen) if clen else b""
                if headers.get("connection", "").lower() == "close":
                    await self.close()
                return status, headers, body
            except (ConnectionError, asyncio.IncompleteReadError, OSError):
                await self.close()
                if attempt:
                    raise
        raise ConnectionError("unreachable")

    async def post(
        self, path: str, body: bytes, content_type: str, extra_headers: dict
    ) -> tuple[int, dict, bytes]:
        return await self.request_raw(
            self.build_request(path, body, content_type, extra_headers)
        )

    async def close(self) -> None:
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:  # noqa: BLE001 - already-dead socket
                pass
        self._reader = self._writer = None


def _split_base(base: str) -> tuple[str, int, bool]:
    from urllib.parse import urlparse

    u = urlparse(base)
    tls = u.scheme == "https"
    return u.hostname or "127.0.0.1", u.port or (443 if tls else 80), tls


async def _user(
    base: str,
    stats: LoadStats,
    stop_at: float,
    *,
    features,
    batch: int,
    headers: dict,
    route_rewards: list[float],
    rng: random.Random,
    wait_range: tuple[float, float] | None,
    static_payload: bool = False,
    payload_format: str = "json",
    payload_fn=None,
) -> None:
    # static_payload: generate + encode ONCE per user and re-post the same
    # bytes — large-tensor benches (images) must not measure the CLIENT's
    # random-number and json.dumps cost
    npy = payload_format == "npy"

    def encode() -> bytes:
        if npy:
            # binary tensor wire path: uint8 npy (images' natural wire dtype,
            # ~8x smaller than JSON text; the server casts to model dtype)
            import numpy as np

            from seldon_core_tpu.core.codec_npy import npy_from_array

            shape = (
                (batch, *tuple(features))
                if not isinstance(features, int)
                else (batch, features)
            )
            nprng = np.random.default_rng(rng.randrange(2**31))
            return npy_from_array(nprng.integers(0, 256, shape, dtype=np.uint8))
        if payload_fn is not None:
            # caller-shaped request bodies (e.g. the soak's shared-system-
            # prompt generative mix); varies per request, so incompatible
            # with the static_payload fast path
            return json.dumps(payload_fn(rng)).encode()
        return json.dumps(_make_payload(rng, batch, features)).encode()

    ctype = "application/x-npy" if npy else "application/json"
    host, port, tls = _split_base(base)
    conn = _RawHttpConn(host, port, use_tls=tls)
    pre_built: bytes | None = (
        conn.build_request("/api/v0.1/predictions", encode(), ctype, headers)
        if static_payload and payload_fn is None
        else None
    )
    parse_body = bool(route_rewards)
    try:
        while time.perf_counter() < stop_at:
            req = (
                pre_built
                if pre_built is not None
                else conn.build_request("/api/v0.1/predictions", encode(), ctype, headers)
            )
            t0 = time.perf_counter()
            try:
                status, resp_headers, raw = await conn.request_raw(req)
                ok = status == 200
                if npy:
                    meta = json.loads(resp_headers.get("seldon-meta", "{}"))
                    body = {"meta": meta} if ok else {}
                elif parse_body and ok:
                    # the bandit loop needs meta.routing from the body
                    body = json.loads(raw)
                else:
                    # latency/throughput mode: body already drained; skip
                    # the JSON parse — the CLIENT's decode cost must not
                    # count against the serving stack under test
                    body = {}
            except Exception:  # noqa: BLE001
                ok = False
                body = {}
            done_at = time.perf_counter()
            dt = done_at - t0
            if ok:
                stats.latencies_s.append(dt)
                stats.completions_s.append(done_at)
            else:
                stats.errors += 1

            # bandit loop: reward probability depends on the route taken
            # (reference predict_rest_locust.py:83-103)
            routing = (body.get("meta") or {}).get("routing") or {}
            if ok and route_rewards and routing:
                branch = next(iter(routing.values()))
                p = route_rewards[branch % len(route_rewards)]
                reward = 1.0 if rng.random() < p else 0.0
                fb = json.dumps(
                    {"response": {"meta": body.get("meta", {})}, "reward": reward}
                ).encode()
                try:
                    st, _, _ = await conn.post(
                        "/api/v0.1/feedback", fb, "application/json", headers
                    )
                    if st == 200:
                        stats.feedback_sent += 1
                except Exception:  # noqa: BLE001
                    pass
            if wait_range:
                await asyncio.sleep(rng.uniform(*wait_range))
    finally:
        await conn.close()


async def run_load(
    base: str,
    *,
    users: int = 10,
    duration_s: float = 10.0,
    features=4,
    batch: int = 1,
    oauth_key: str = "",
    oauth_secret: str = "",
    route_rewards: list[float] | None = None,
    locust_pacing: bool = False,
    seed: int = 0,
    static_payload: bool = False,
    payload_format: str = "json",
    payload_fn=None,
) -> LoadStats:
    stats = LoadStats()
    # reference locust pacing: min_wait 900 / max_wait 1100 ms (~1 req/s/user);
    # default here is closed-loop max throughput
    wait_range = (0.9, 1.1) if locust_pacing else None
    headers = {}
    if oauth_key:
        # one-time token fetch: aiohttp is fine off the measured loop
        import aiohttp

        async with aiohttp.ClientSession() as session:
            token = await _fetch_token(session, base, oauth_key, oauth_secret)
        headers["Authorization"] = f"Bearer {token}"
    stats.started = time.perf_counter()
    stop_at = stats.started + duration_s
    stats.deadline = stop_at
    await asyncio.gather(
        *(
            _user(
                base,
                stats,
                stop_at,
                features=features,
                batch=batch,
                headers=headers,
                route_rewards=route_rewards or [],
                rng=random.Random(seed + i),
                wait_range=wait_range,
                static_payload=static_payload,
                payload_format=payload_format,
                payload_fn=payload_fn,
            )
            for i in range(users)
        )
    )
    stats.finished = time.perf_counter()
    return stats


def run_load_multiprocess(
    base: str,
    *,
    workers: int,
    users: int = 10,
    duration_s: float = 10.0,
    features=4,
    batch: int = 1,
    oauth_key: str = "",
    oauth_secret: str = "",
    route_rewards: list[float] | None = None,
    locust_pacing: bool = False,
    seed: int = 0,
    static_payload: bool = False,
    payload_format: str = "json",
    timeout_s: float | None = None,
) -> LoadStats:
    """Fan the load across ``workers`` OS processes and merge exact stats.

    Each worker is a fresh `python -m seldon_core_tpu.tools.loadtest` with a
    slice of the users; it prints its summary JSON on stdout and dumps raw
    per-request latencies (float32 seconds) to a parent-owned .npy file, so
    merged percentiles are computed over the union, not approximated.
    """
    import numpy as np

    if workers < 2:
        raise ValueError("run_load_multiprocess needs workers >= 2")
    if users < workers:
        workers = max(1, users)
    per = users // workers
    extras = users % workers

    # workers must import this package regardless of the caller's cwd;
    # PREPEND the repo root — wiping PYTHONPATH would drop sitecustomize
    # entries the interpreter environment depends on
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")

    with tempfile.TemporaryDirectory(prefix="loadtest_") as tmp:
        procs: list[tuple[subprocess.Popen, str]] = []
        for w in range(workers):
            w_users = per + (1 if w < extras else 0)
            dump = os.path.join(tmp, f"lat_{w}.npy")
            cmd = [
                sys.executable, "-m", "seldon_core_tpu.tools.loadtest", base,
                "--users", str(w_users),
                "--duration", str(duration_s),
                "--batch", str(batch),
                "--seed", str(seed + w * 100003),
                "--payload", payload_format,
                "--latency-dump", dump,
                "--json",
            ]
            if isinstance(features, int):
                cmd += ["--features", str(features)]
            else:
                cmd += ["--shape", ",".join(str(d) for d in features)]
            if oauth_key:
                cmd += ["--oauth-key", oauth_key, "--oauth-secret", oauth_secret]
            if route_rewards:
                cmd += [
                    "--feedback-route-rewards",
                    ",".join(str(r) for r in route_rewards),
                ]
            if locust_pacing:
                cmd += ["--locust-pacing"]
            if static_payload:
                cmd += ["--static-payload"]
            procs.append(
                (
                    subprocess.Popen(
                        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env
                    ),
                    dump,
                )
            )

        merged = LoadStats(workers=workers)
        walls: list[float] = []
        rps_sum = 0.0
        deadline = duration_s + (timeout_s if timeout_s is not None else 120.0)
        try:
            for proc, dump in procs:
                try:
                    out, err = proc.communicate(timeout=deadline)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    out, err = proc.communicate()
                if proc.returncode != 0:
                    raise RuntimeError(
                        f"loadtest worker failed rc={proc.returncode}: "
                        f"{err.decode()[-500:]}"
                    )
                summary = json.loads(out.decode().strip().splitlines()[-1])
                merged.errors += summary["errors"]
                merged.feedback_sent += summary["feedback_sent"]
                walls.append(summary["duration_s"])
                rps_sum += summary["requests_per_sec"]
                merged.drain_override += summary.get("drain_requests", 0)
                n_before = len(merged.latencies_s)
                if os.path.exists(dump):
                    merged.latencies_s.extend(np.load(dump).tolist())
                merged.worker_requests.append(len(merged.latencies_s) - n_before)
            # each worker reports a windowed rate over its own timing; the
            # aggregate is their sum (workers run concurrently)
            merged.rps_override = round(rps_sum, 2)
        finally:
            # one failed worker must not leave the rest hammering the target
            # (and unreaped) for the remaining duration
            for proc, _ in procs:
                if proc.poll() is None:
                    proc.kill()
                    proc.wait()
        # workers run concurrently: aggregate throughput is the union of
        # requests over the LONGEST worker wall (start skew between worker
        # process launches is excluded by each worker timing itself)
        merged.started = 0.0
        merged.finished = max(walls) if walls else 0.0
        return merged


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("base", help="http://HOST:PORT")
    p.add_argument("--users", type=int, default=10)
    p.add_argument("--duration", type=float, default=10.0)
    p.add_argument("--features", type=int, default=4)
    p.add_argument(
        "--shape",
        default="",
        help="comma tensor shape per item (e.g. 224,224,3); overrides --features",
    )
    p.add_argument("--batch", type=int, default=1)
    p.add_argument(
        "--workers",
        type=int,
        default=1,
        help="fan load across N OS processes (locust master/slave equivalent)",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--static-payload",
        action="store_true",
        help="encode the payload once per user and re-post the same bytes",
    )
    p.add_argument(
        "--latency-dump",
        default="",
        help="write raw per-request latencies (float32 s) to this .npy path",
    )
    # env fallbacks let a k8s Job inject credentials from a Secret instead
    # of exposing them in the pod spec's command args
    p.add_argument("--oauth-key", default=os.environ.get(LOADTEST_OAUTH_KEY, ""))
    p.add_argument(
        "--oauth-secret", default=os.environ.get(LOADTEST_OAUTH_SECRET, "")
    )
    p.add_argument(
        "--feedback-route-rewards",
        default="",
        help="comma list of per-route reward probabilities, e.g. 0.4,0.9",
    )
    p.add_argument("--locust-pacing", action="store_true", help="~1 req/s/user")
    p.add_argument(
        "--payload",
        choices=("json", "npy"),
        default="json",
        dest="payload_format",
        help="wire format: json ndarray envelope or raw npy (binary fast path)",
    )
    p.add_argument("--json", action="store_true", dest="as_json")
    args = p.parse_args()
    rewards = (
        [float(x) for x in args.feedback_route_rewards.split(",")]
        if args.feedback_route_rewards
        else None
    )
    features = (
        tuple(int(d) for d in args.shape.split(",")) if args.shape else args.features
    )
    common = dict(
        users=args.users,
        duration_s=args.duration,
        features=features,
        batch=args.batch,
        oauth_key=args.oauth_key,
        oauth_secret=args.oauth_secret,
        route_rewards=rewards,
        locust_pacing=args.locust_pacing,
        seed=args.seed,
        static_payload=args.static_payload,
        payload_format=args.payload_format,
    )
    if args.workers > 1:
        stats = run_load_multiprocess(
            args.base.rstrip("/"), workers=args.workers, **common
        )
    else:
        stats = asyncio.run(run_load(args.base.rstrip("/"), **common))
    if args.latency_dump:
        import numpy as np

        np.save(args.latency_dump, np.asarray(stats.latencies_s, dtype=np.float32))
    out = stats.summary()
    print(json.dumps(out) if args.as_json else out)


if __name__ == "__main__":
    main()
