"""Model packaging CLI: wrap a user model directory into a deployable bundle.

Parity (C22): reference wrappers/python/wrap_model.py — copies the model dir
and renders Dockerfile/build_image.sh/push_image.sh templates so the model
becomes a runnable microservice image. Here the bundle targets the TPU
serving runtime instead of the Py2 Flask wrapper:

    <out>/
      Dockerfile          serve the class via seldon_core_tpu microservice
      build_image.sh      docker build tag $repo/$name:$version
      push_image.sh       docker push
      deployment.json     ready-to-apply SeldonDeployment CR for the model

CLI (argument order mirrors wrap_model.py):
    python -m seldon_core_tpu.tools.wrap MODEL_DIR MODEL_NAME VERSION REPO \
        [--grpc] [--persistence] [--base-image IMAGE] [--force]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import stat

DOCKERFILE_TMPL = """FROM {base_image}
COPY . /microservice
WORKDIR /microservice
RUN test -f requirements.txt && pip install -r requirements.txt || true
EXPOSE 5000
ENV PREDICTIVE_UNIT_SERVICE_PORT 5000
CMD ["python", "-m", "seldon_core_tpu.serving.microservice", "{name}", "{api}", "--service-type", "{service_type}", "--model-dir", "/microservice"{persistence_arg}]
"""

BUILD_SH_TMPL = """#!/bin/sh
set -e
docker build --force-rm=true -t {repo}/{name}:{version} .
"""

PUSH_SH_TMPL = """#!/bin/sh
set -e
docker push {repo}/{name}:{version}
"""


def deployment_cr(name: str, image: str, service_type: str = "MODEL") -> dict:
    """A minimal SeldonDeployment CR for the wrapped image (the reference
    docs show the same hand-written JSON, e.g. sklearn_iris_deployment.json)."""
    return {
        "apiVersion": "machinelearning.seldon.io/v1alpha1",
        "kind": "SeldonDeployment",
        "metadata": {"name": name},
        "spec": {
            "name": f"{name}-deployment",
            "oauth_key": "oauth-key",
            "oauth_secret": "oauth-secret",
            "predictors": [
                {
                    "name": f"{name}-predictor",
                    "replicas": 1,
                    "componentSpec": {
                        "containers": [{"name": name, "image": image}]
                    },
                    "graph": {
                        "name": name,
                        "type": service_type,
                        "endpoint": {"type": "REST"},
                        "children": [],
                    },
                }
            ],
        },
    }


def wrap_model(
    model_dir: str,
    name: str,
    version: str,
    repo: str,
    *,
    out_dir: str | None = None,
    api: str = "REST",
    service_type: str = "MODEL",
    base_image: str = "python:3.12-slim",
    persistence: bool = False,
    force: bool = False,
) -> str:
    """Build the bundle directory; returns its path."""
    out = out_dir or os.path.join(model_dir, "build")
    if os.path.exists(out):
        if not force:
            raise FileExistsError(f"{out} exists; use --force to overwrite")
        shutil.rmtree(out)
    shutil.copytree(model_dir, out, ignore=shutil.ignore_patterns("build"))

    image = f"{repo}/{name}:{version}"
    files = {
        "Dockerfile": DOCKERFILE_TMPL.format(
            base_image=base_image,
            name=name,
            api=api,
            service_type=service_type,
            persistence_arg=', "--persistence"' if persistence else "",
        ),
        "build_image.sh": BUILD_SH_TMPL.format(repo=repo, name=name, version=version),
        "push_image.sh": PUSH_SH_TMPL.format(repo=repo, name=name, version=version),
        "deployment.json": json.dumps(
            deployment_cr(name, image, service_type), indent=2
        ),
    }
    for fname, content in files.items():
        path = os.path.join(out, fname)
        with open(path, "w") as f:
            f.write(content)
        if fname.endswith(".sh"):
            os.chmod(path, os.stat(path).st_mode | stat.S_IEXEC)
    return out


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("model_dir")
    p.add_argument("name")
    p.add_argument("version")
    p.add_argument("repo")
    p.add_argument("--out-dir", default=None)
    p.add_argument("--grpc", action="store_true")
    p.add_argument("--service-type", default="MODEL")
    p.add_argument("--base-image", default="python:3.12-slim")
    p.add_argument("--persistence", action="store_true")
    p.add_argument("-f", "--force", action="store_true")
    args = p.parse_args()
    out = wrap_model(
        args.model_dir,
        args.name,
        args.version,
        args.repo,
        out_dir=args.out_dir,
        api="GRPC" if args.grpc else "REST",
        service_type=args.service_type,
        base_image=args.base_image,
        persistence=args.persistence,
        force=args.force,
    )
    print(out)


if __name__ == "__main__":
    main()
