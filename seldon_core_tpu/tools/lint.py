"""Invariant linter CLI (seldon_core_tpu/analysis).

    python -m seldon_core_tpu.tools.lint [paths...]
        [--rules trace-safety,CP001,...] [--json]
        [--baseline FILE | --no-baseline] [--write-baseline FILE]
        [--list-rules]

Exit codes: 0 = clean (no non-baselined findings), 1 = findings,
2 = usage / IO error. Default path is the ``seldon_core_tpu`` package;
the default baseline is ``lint-baseline.json`` next to pyproject.toml
(the repo root), when present.

Pure stdlib — safe for CI preflight and the tier-1 guard test (no JAX
import, runs in well under a second on this tree).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from seldon_core_tpu.analysis import (
    Baseline,
    lint_paths,
    rule_catalogue,
)

BASELINE_NAME = "lint-baseline.json"


def repo_root_for(path: str) -> str:
    """Nearest ancestor holding pyproject.toml (else the path itself) —
    finding paths are reported relative to it, which is what keeps the
    checked-in baseline stable regardless of the invoking cwd."""
    d = os.path.abspath(path if os.path.isdir(path) else os.path.dirname(path))
    while True:
        if os.path.exists(os.path.join(d, "pyproject.toml")):
            return d
        nd = os.path.dirname(d)
        if nd == d:
            return os.path.abspath(path if os.path.isdir(path) else os.path.dirname(path))
        d = nd


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m seldon_core_tpu.tools.lint",
        description="AST invariant linter: trace-safety, commit-point, "
        "registry-drift, phase-registry, ladder-coverage (docs/linting.md)",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        help="files/dirs to lint (default: the seldon_core_tpu package)",
    )
    ap.add_argument(
        "--rules",
        default="",
        help="comma-separated pass names or rule ids (e.g. "
        "'trace-safety,RD001'); default: all",
    )
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    ap.add_argument(
        "--baseline",
        default=None,
        help=f"baseline file of accepted findings (default: {BASELINE_NAME} "
        "at the repo root, when present)",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore any baseline (report every finding)",
    )
    ap.add_argument(
        "--write-baseline",
        default=None,
        metavar="FILE",
        help="write current findings as the new baseline and exit 0",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule catalogue"
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for pass_name, rules in rule_catalogue().items():
            print(pass_name)
            for rid, desc in rules.items():
                print(f"  {rid}  {desc}")
        return 0

    paths = args.paths
    if not paths:
        pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = [pkg]
    for p in paths:
        if not os.path.exists(p):
            print(f"lint: no such path: {p}", file=sys.stderr)
            return 2
    root = repo_root_for(paths[0])

    rules = [r for r in args.rules.split(",") if r.strip()] or None
    try:
        findings = lint_paths(paths, root=root, rules=rules)
    except ValueError as e:
        print(f"lint: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        Baseline.from_findings(findings).dump(args.write_baseline)
        print(
            f"lint: wrote {len(findings)} finding(s) to {args.write_baseline}"
        )
        return 0

    baseline = Baseline()
    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline:
        candidate = os.path.join(root, BASELINE_NAME)
        if os.path.exists(candidate):
            baseline_path = candidate
    if baseline_path is not None and not args.no_baseline:
        try:
            baseline = Baseline.load(baseline_path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"lint: cannot load baseline {baseline_path}: {e}", file=sys.stderr)
            return 2

    new, baselined, stale = baseline.split(findings)

    if args.json:
        print(
            json.dumps(
                {
                    "version": 1,
                    "findings": [f.to_dict() for f in new],
                    "baselined": [f.to_dict() for f in baselined],
                    "stale_baseline_entries": stale,
                    "counts": {
                        "new": len(new),
                        "baselined": len(baselined),
                        "stale_baseline_entries": len(stale),
                    },
                },
                indent=2,
            )
        )
        return 1 if new else 0

    for f in new:
        print(f.render())
    if baselined:
        print(f"lint: {len(baselined)} baselined finding(s) suppressed")
    for e in stale:
        print(
            "lint: stale baseline entry (matched nothing): "
            f"{e['rule']} {e['path']} {e['symbol']}",
            file=sys.stderr,
        )
    if new:
        print(f"lint: {len(new)} finding(s)", file=sys.stderr)
        return 1
    print("lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
