"""The measured serving stack, built ONE way.

bench.py's legs, the soak harness, and any future measurement tool must
all boot the exact stack the product boots (warmed PredictorServer behind
the OAuth gateway + in-process backend, serving GC policy applied) — a
second hand-rolled copy is how a tool silently stops measuring what the
platform runs. This is that single definition.
"""

from __future__ import annotations


def build_gateway_stack(
    predictor,
    *,
    deployment_name: str = "bench",
    oauth_key: str = "bench-key",
    oauth_secret: str = "bench-secret",
):
    """Returns (server, gw, oauth, token): warmed PredictorServer wired
    behind the OAuth gateway with the serving GC policy applied, exactly
    as PredictorServer.start / platform.serve do at boot."""
    from seldon_core_tpu.gateway.app import Gateway, InProcessBackend
    from seldon_core_tpu.gateway.oauth import OAuthProvider
    from seldon_core_tpu.gateway.store import DeploymentStore
    from seldon_core_tpu.graph.spec import DeploymentSpec
    from seldon_core_tpu.serving.gc_policy import apply_serving_gc_policy
    from seldon_core_tpu.serving.server import PredictorServer

    server = PredictorServer(predictor, deployment_name=deployment_name)
    server.warmup()
    apply_serving_gc_policy()
    oauth = OAuthProvider()
    store = DeploymentStore(oauth=oauth)
    backend = InProcessBackend()
    gw = Gateway(store=store, oauth=oauth, backend=backend)
    store.deployment_added(
        DeploymentSpec(
            name=deployment_name,
            oauth_key=oauth_key,
            oauth_secret=oauth_secret,
            predictors=[predictor],
        )
    )
    backend.register(deployment_name, server.service)
    token = oauth.issue_token(oauth_key, oauth_secret)["access_token"]
    return server, gw, oauth, token
