"""Release automation (C29; reference release.py + Jenkinsfile).

The reference's release.py rewrites the version in every pom/chart; the
Jenkinsfile builds and publishes the service images. Here one command does
the equivalent for the single-image platform:

    python -m seldon_core_tpu.tools.release 0.2.0            # set version
    python -m seldon_core_tpu.tools.release 0.2.0 --tag      # + git commit + tag v0.2.0
    python -m seldon_core_tpu.tools.release 0.2.0 --build    # + docker build
    python -m seldon_core_tpu.tools.release 0.2.0 --push --registry ghcr.io/me

Files rewritten (the version's single sources of truth):
- seldon_core_tpu/version.py        __version__
- pyproject.toml                    [project] version
- deploy/values.yaml                platform.image tag

CI integration: .github/workflows/release.yaml runs the --build/--push half
on every v* tag push.
"""

from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys

from seldon_core_tpu.utils.env import SELDON_TPU_REGISTRY

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))
IMAGE_BASENAME = "seldon-core-tpu/platform"


def _rewrite(path: str, pattern: str, replacement: str) -> bool:
    full = os.path.join(REPO_ROOT, path)
    with open(full) as f:
        src = f.read()
    out, n = re.subn(pattern, replacement, src, count=1)
    if n:
        with open(full, "w") as f:
            f.write(out)
    return bool(n)


def set_version(version: str) -> list[str]:
    """Rewrite the version everywhere it lives; returns the changed files."""
    changed = []
    if _rewrite(
        "seldon_core_tpu/version.py",
        r'__version__ = "[^"]+"',
        f'__version__ = "{version}"',
    ):
        changed.append("seldon_core_tpu/version.py")
    if _rewrite(
        "pyproject.toml", r'(?m)^version = "[^"]+"', f'version = "{version}"'
    ):
        changed.append("pyproject.toml")
    if _rewrite(
        "deploy/values.yaml",
        rf"(image: {re.escape(IMAGE_BASENAME)}):\S+",
        rf"\1:{version}",
    ):
        changed.append("deploy/values.yaml")
    return changed


def run(cmd: list[str]) -> None:
    print("+ " + " ".join(cmd), flush=True)
    subprocess.run(cmd, check=True, cwd=REPO_ROOT)


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("version", help="semver to release, e.g. 0.2.0")
    p.add_argument("--tag", action="store_true", help="git commit + tag v<version>")
    p.add_argument("--build", action="store_true", help="docker build the platform image")
    p.add_argument("--push", action="store_true", help="docker push (implies --build)")
    p.add_argument(
        "--registry",
        default=os.environ.get(SELDON_TPU_REGISTRY, ""),
        help="registry prefix for --push, e.g. ghcr.io/org (env SELDON_TPU_REGISTRY)",
    )
    args = p.parse_args()
    if not re.fullmatch(r"\d+\.\d+\.\d+([-.+][\w.]+)?", args.version):
        sys.exit(f"not a version: {args.version}")

    changed = set_version(args.version)
    print(f"version {args.version} -> {', '.join(changed) or 'nothing changed'}")

    if args.tag:
        if changed:
            run(["git", "add", *changed])
            run(["git", "commit", "-m", f"Release {args.version}"])
        else:
            print("version already current; tagging HEAD")
        run(["git", "tag", f"v{args.version}"])

    if args.build or args.push:
        image = f"{IMAGE_BASENAME}:{args.version}"
        if args.registry:
            image = f"{args.registry.rstrip('/')}/{image}"
        run(["docker", "build", "-t", image, "."])
        run(["docker", "tag", image, image.rsplit(":", 1)[0] + ":latest"])
        if args.push:
            run(["docker", "push", image])
            run(["docker", "push", image.rsplit(":", 1)[0] + ":latest"])


if __name__ == "__main__":
    main()
