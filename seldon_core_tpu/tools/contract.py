"""Contract tester: random-input conformance testing for a served model.

Parity (C23): reference wrappers/tester.py — reads a ``contract.json`` data
contract (features with name/dtype/ftype/range/values/repeat/shape), builds
random batches matching the declared schema (generate_batch:30), and fires
REST or gRPC predictions at a running endpoint (run:116-152), printing each
request/response. Same contract schema, including the "inf" range sentinel.

CLI:
    python -m seldon_core_tpu.tools.contract contract.json HOST PORT \
        [--endpoint predict|send-feedback] [--batch-size N] [-n ROUNDS] \
        [--grpc] [--prnt] [--oauth-key K --oauth-secret S]
"""

from __future__ import annotations

import argparse
import json
import urllib.request
from typing import Any

import numpy as np


def _bound(v: Any, default: float) -> float:
    if v in ("inf", "-inf", None):
        return default
    return float(v)


def generate_column(feature: dict, batch_size: int, rng: np.random.Generator):
    """One contract feature -> ndarray column(s) (tester.py generate_batch)."""
    repeat = int(feature.get("repeat", 1))
    ftype = feature.get("ftype", "continuous")
    dtype = feature.get("dtype", "FLOAT")
    shape = feature.get("shape")
    if shape:  # image-style features declare a full shape (deep_mnist)
        n = int(np.prod([int(s) for s in shape]))
        repeat = n
    if ftype == "categorical":
        values = feature.get("values", [0, 1])
        idx = rng.integers(0, len(values), size=(batch_size, repeat))
        col = np.asarray(values, dtype=object)[idx]
        try:
            col = col.astype(np.float64)
        except (ValueError, TypeError):
            pass  # string categories stay strings (ndarray payload)
        return col
    lo = _bound(feature.get("range", ["inf", "inf"])[0], -1.0)
    hi = _bound(feature.get("range", ["inf", "inf"])[1], 1.0)
    col = rng.uniform(lo, hi, size=(batch_size, repeat))
    if dtype == "INT":
        col = np.round(col).astype(np.int64)
    return col


def generate_batch(contract: dict, batch_size: int, rng: np.random.Generator):
    """Returns (names, batch array/list-of-rows)."""
    names: list[str] = []
    cols = []
    for feature in contract["features"]:
        col = generate_column(feature, batch_size, rng)
        repeat = col.shape[1]
        base = feature["name"]
        names.extend([base] if repeat == 1 else [f"{base}_{i}" for i in range(repeat)])
        cols.append(col)
    if any(c.dtype == object for c in cols):
        # mixed string/numeric rows: coerce numpy scalars to JSON-safe
        # Python types (np.float64 is not json-serializable)
        def py(v):
            return v.item() if isinstance(v, np.generic) else v

        rows = [
            [py(c[i, j]) for c in cols for j in range(c.shape[1])]
            for i in range(batch_size)
        ]
        return names, rows
    return names, np.concatenate(cols, axis=1)


def rest_request(host: str, port: int, payload: dict, endpoint: str, token: str | None):
    path = "predictions" if endpoint == "predict" else "feedback"
    headers = {"Content-Type": "application/json"}
    if token:
        headers["Authorization"] = f"Bearer {token}"
    req = urllib.request.Request(
        f"http://{host}:{port}/api/v0.1/{path}",
        json.dumps(payload).encode(),
        headers,
    )
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read())


def grpc_request(host: str, port: int, payload: dict, token: str | None):
    import grpc

    from seldon_core_tpu.core.codec_json import message_from_dict
    from seldon_core_tpu.core.codec_proto import message_to_proto
    from seldon_core_tpu.proto.services import ServiceStub

    msg = message_from_dict(payload)
    channel = grpc.insecure_channel(f"{host}:{port}")
    stub = ServiceStub(channel, "Seldon")
    metadata = (("oauth_token", token),) if token else ()
    reply = stub.Predict(message_to_proto(msg), metadata=metadata)
    from google.protobuf import json_format

    return json.loads(json_format.MessageToJson(reply))


def fetch_token(host: str, port: int, key: str, secret: str) -> str:
    body = f"grant_type=client_credentials&client_id={key}&client_secret={secret}"
    req = urllib.request.Request(
        f"http://{host}:{port}/oauth/token",
        body.encode(),
        {"Content-Type": "application/x-www-form-urlencoded"},
    )
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read())["access_token"]


def run(
    contract: dict,
    host: str,
    port: int,
    *,
    rounds: int = 1,
    batch_size: int = 1,
    endpoint: str = "predict",
    use_grpc: bool = False,
    oauth_key: str = "",
    oauth_secret: str = "",
    oauth_port: int | None = None,
    seed: int | None = None,
    prnt: bool = False,
) -> list[dict]:
    rng = np.random.default_rng(seed)
    token = (
        fetch_token(host, oauth_port or port, oauth_key, oauth_secret)
        if oauth_key
        else None
    )
    responses = []
    for _ in range(rounds):
        names, batch = generate_batch(contract, batch_size, rng)
        data = batch.tolist() if isinstance(batch, np.ndarray) else batch
        payload = {"data": {"names": names, "ndarray": data}}
        if endpoint == "send-feedback":
            payload = {
                "request": payload,
                "response": {},
                "reward": float(rng.random()),
            }
        if prnt:
            print("SENDING:", json.dumps(payload)[:400])
        out = (
            grpc_request(host, port, payload, token)
            if use_grpc and endpoint == "predict"
            else rest_request(host, port, payload, endpoint, token)
        )
        if prnt:
            print("RECEIVED:", json.dumps(out)[:400])
        responses.append(out)
    return responses


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("contract")
    p.add_argument("host")
    p.add_argument("port", type=int)
    p.add_argument("--endpoint", default="predict", choices=["predict", "send-feedback"])
    p.add_argument("-b", "--batch-size", type=int, default=1)
    p.add_argument("-n", "--n-requests", type=int, default=1)
    p.add_argument("--grpc", action="store_true")
    p.add_argument("--prnt", action="store_true", help="print requests/responses")
    p.add_argument("--oauth-key", default="")
    p.add_argument("--oauth-secret", default="")
    p.add_argument("--oauth-port", type=int, default=None)
    args = p.parse_args()
    with open(args.contract) as f:
        contract = json.load(f)
    run(
        contract,
        args.host,
        args.port,
        rounds=args.n_requests,
        batch_size=args.batch_size,
        endpoint=args.endpoint,
        use_grpc=args.grpc,
        oauth_key=args.oauth_key,
        oauth_secret=args.oauth_secret,
        oauth_port=args.oauth_port,
        prnt=args.prnt,
    )


if __name__ == "__main__":
    main()
