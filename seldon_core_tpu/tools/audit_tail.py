"""Audit-stream consumer: read the request/response log back out.

Parity (C17/C28 closing corner): the reference ships a Kafka consumer that
reads the per-client prediction topics and prints the pairs
(kafka/tests/src/read_predictions.py — the smoke test that the audit
pipeline actually records traffic). Same tool here for both sink forms:

    python -m seldon_core_tpu.tools.audit_tail file:///var/log/seldon-audit \
        [--client CLIENT] [--follow] [--json]
    python -m seldon_core_tpu.tools.audit_tail kafka://broker:9092 --client c1

Each record is {ts, request, response} with SeldonMessage JSON bodies —
the same shape the JSONL and Kafka sinks write (gateway/audit.py).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from typing import Iterator


def _iter_jsonl(
    directory: str, client: str | None, follow: bool
) -> Iterator[dict]:
    """Yield records from the per-client JSONL files; --follow tails."""
    positions: dict[str, int] = {}
    while True:
        pattern = os.path.join(directory, f"{client}.jsonl" if client else "*.jsonl")
        for path in sorted(glob.glob(pattern)):
            try:
                if os.path.getsize(path) < positions.get(path, 0):
                    # file truncated/rotated under us: restart from the top
                    # instead of seeking past EOF forever
                    positions[path] = 0
                with open(path) as f:
                    f.seek(positions.get(path, 0))
                    # readline (not iteration): f.tell() is illegal inside a
                    # text-file line iterator, and the offset is how --follow
                    # resumes without re-reading
                    while True:
                        line = f.readline()
                        if not line or not line.endswith("\n"):
                            break  # EOF or partial write; re-read next pass
                        positions[path] = f.tell()
                        try:
                            record = json.loads(line)
                        except json.JSONDecodeError:
                            continue  # torn line: skip, keep the stream alive
                        record["client"] = os.path.splitext(os.path.basename(path))[0]
                        yield record
            except OSError:
                continue
        if not follow:
            return
        time.sleep(0.5)


def _iter_kafka(bootstrap: str, client: str, follow: bool) -> Iterator[dict]:
    from kafka import KafkaConsumer  # gated: not in the base image

    consumer = KafkaConsumer(
        client,
        bootstrap_servers=bootstrap,
        auto_offset_reset="earliest",
        consumer_timeout_ms=(1 << 31) if follow else 5000,
        value_deserializer=lambda b: json.loads(b.decode()),
    )
    for msg in consumer:
        record = dict(msg.value)
        record["client"] = client
        yield record


def iter_records(url: str, client: str | None, follow: bool) -> Iterator[dict]:
    if url.startswith("file://"):
        return _iter_jsonl(url[len("file://") :], client, follow)
    if url.startswith("kafka://"):
        if not client:
            raise SystemExit("kafka:// needs --client (topic == client id)")
        return _iter_kafka(url[len("kafka://") :], client, follow)
    raise SystemExit(f"unsupported audit url: {url} (file:// or kafka://)")


def _summarize(record: dict) -> str:
    req = record.get("request") or {}
    resp = record.get("response") or {}
    meta = resp.get("meta") or {}
    shape = ""
    data = req.get("data") or {}
    if "ndarray" in data:
        arr = data["ndarray"]
        rows = len(arr) if isinstance(arr, list) else "?"
        shape = f" rows={rows}"
    routing = meta.get("routing") or {}
    return (
        f"{time.strftime('%H:%M:%S', time.localtime(record.get('ts', 0)))} "
        f"client={record.get('client')} puid={meta.get('puid', '')}{shape}"
        + (f" routing={routing}" if routing else "")
    )


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("url", help="file:///audit/dir or kafka://host:port")
    p.add_argument("--client", default=None, help="client id (kafka topic)")
    p.add_argument("--follow", action="store_true", help="tail new records")
    p.add_argument("--json", action="store_true", dest="as_json")
    args = p.parse_args()
    try:
        for record in iter_records(args.url, args.client, args.follow):
            print(json.dumps(record) if args.as_json else _summarize(record))
            sys.stdout.flush()
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
