"""Soak harness: serve under sustained load and report stability.

The reference validated long-running behavior with locust soaks against a
cluster (SURVEY C24); this is the single-process twin with the two signals
that actually catch serving regressions early:

- **RSS slope** (MB/min, least-squares over per-second samples): a
  positive slope under steady load is a leak — e.g. an unbounded cache, a
  GC-frozen object churn, or a native buffer that never returns.
- **event-loop lag** (p99 of per-second max samples): scheduling stalls
  from GC, host-side compute, or ingress pathology, the same signal the
  `seldon_tpu_event_loop_lag_ms` gauge exports in production.

Runs the REAL stack: OAuth gateway -> fast ingress -> micro-batcher ->
model, driven by the raw-conn load generator. One JSON line on stdout.

    python -m seldon_core_tpu.tools.soak --duration 60 --users 16
"""

from __future__ import annotations

import argparse
import asyncio
import json
import resource
import socket
import sys
import time


def _rss_mb() -> float:
    """CURRENT resident set (VmRSS), not the getrusage high-water mark —
    a leak running below a prior RSS peak would be invisible to
    ru_maxrss (it only ratchets), which is exactly the case a soak
    exists to catch. Falls back to the high-water mark off-Linux."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


async def soak(
    duration_s: float = 60.0,
    users: int = 16,
    model: str = "iris_mlp",
    features: int = 4,
    batch: int = 4,
    fault_spec=None,
    trace_summary: int = 0,
    spec_k: int = 0,
    spec_tree: str = "",
    prefix_share: float = 0.0,
    paged: bool = False,
    tp: int = 0,
    replicas: int = 0,
    profile_out: str = "",
    kill_replica: str = "",
    drain_replica: str = "",
    kv_overflow: bool = False,
) -> dict:
    from seldon_core_tpu.graph.defaulting import default_deployment
    from seldon_core_tpu.graph.spec import SeldonDeployment
    from seldon_core_tpu.graph.validation import validate_deployment
    from seldon_core_tpu.serving.fast_http import gateway_routes, start_fast_server
    from seldon_core_tpu.tools.loadtest import run_load
    from seldon_core_tpu.tools.stack import build_gateway_stack

    graph: dict = {
        "name": "m",
        "type": "MODEL",
        "implementation": "JAX_MODEL",
        "parameters": [{"name": "model", "value": model, "type": "STRING"}],
    }
    predictor_extra: dict = {}
    if paged and prefix_share <= 0:
        # the paged soak's point is CoW + reclaim under a SHARED/divergent
        # traffic mix — default the mix on when the caller didn't shape it
        prefix_share = 0.6
    if tp > 1 and not paged:
        # the tp soak's point is the sharded program set under sustained
        # load INCLUDING the paged copy/CoW ladder — default the pool on
        paged = True
        if prefix_share <= 0:
            prefix_share = 0.6
    if replicas > 1:
        # the replica soak's point is prefix-AFFINITY routing across the
        # fleet: it needs the paged pool's small page size (the affinity
        # key is one page of tokens — the default 16-token block exceeds
        # the soak's short prompts) and a shared-prefix traffic mix
        if tp > 1:
            raise RuntimeError("soak --replicas does not compose with --tp")
        paged = True
        if prefix_share <= 0:
            prefix_share = 0.6
    if kv_overflow:
        # the kv-overflow soak's point is the demote/promote churn of the
        # host tier under sustained load: a paged pool, a DELIBERATELY
        # tiny device prefix index, and a multi-group shared-prefix mix
        # wide enough to overflow it — every capture evicts (demotes) and
        # revisited groups promote back, all while the allocator audit
        # and zero-recompile gates run as usual
        paged = True
        if prefix_share <= 0:
            prefix_share = 0.6
    generative = (
        spec_k > 0 or bool(spec_tree) or prefix_share > 0 or paged or tp > 1
        or replicas > 1 or kv_overflow
    )
    if generative:
        if model != "iris_mlp":
            import sys as _sys

            print(
                f"soak: --spec-k/--prefix-share override --model (generative "
                f"soaks run tiny_gpt, ignoring {model!r})",
                file=_sys.stderr,
            )
        # generative soak: a deployment (prompt bucket = --features) served
        # by the decode scheduler, so sustained load drives the decode-loop
        # programs instead of the iris classifier. --spec-k adds a
        # seed-shared 1-layer draft (draft + widened verify programs);
        # --prefix-share shapes the prompt mix so that fraction of requests
        # share a system prefix, driving the prefix pool's match/gather/
        # capture/evict cycle under load. The soak's signals are RSS slope
        # / loop lag / error budget, not model quality.
        graph["parameters"] = [
            {"name": "model", "value": "tiny_gpt", "type": "STRING"},
            {"name": "seq", "value": str(features), "type": "INT"},
            {"name": "max_new_tokens", "value": "16", "type": "INT"},
            {"name": "resid_scale", "value": "0.1", "type": "FLOAT"},
        ]
        predictor_extra["tpu"] = {"decode_slots": 4}
        if tp > 1:
            # tensor-parallel mesh: hidden 256 -> 4 heads / ffn 1024, both
            # divisible by every width the 8-device host mesh can carry
            graph["parameters"] += [
                {"name": "hidden", "value": "256", "type": "INT"},
                {"name": "ffn", "value": "1024", "type": "INT"},
            ]
            predictor_extra["tpu"]["decode_mesh_axes"] = {"tp": tp}
        if spec_k > 0 or spec_tree:
            draft_uri = "zoo://draft?layers=1&resid_scale=0.1"
            if tp > 1:
                # the draft shards on the same mesh — pin its geometry to
                # the target's (only vocab/max_len are auto-injected)
                draft_uri += "&hidden=256&ffn=1024"
            predictor_extra["tpu"]["decode_draft_model"] = draft_uri
            if spec_tree:
                # tree speculation: the same draft proposes per-depth
                # top-b candidate branches, one widened verify scores the
                # flattened tree — sustained load drives the tree round
                # pair (and, with --paged/--tp, the same allocator and
                # per-shard audits the chain soaks run)
                predictor_extra["tpu"]["decode_spec_tree"] = spec_tree
            else:
                predictor_extra["tpu"]["decode_spec_k"] = spec_k
        if prefix_share > 0:
            predictor_extra["tpu"].update(
                decode_prefix_slots=8,
                decode_prefill_chunk=max(1, features // 4),
            )
        if paged:
            # a DELIBERATELY tight page budget: ~half the flat-equivalent
            # capacity, so shared-prefix admissions share pages copy-free,
            # divergent tails copy-on-write, and sustained load drives pin
            # reclaim — the allocator surface the soak exists to stress.
            # Chunk rounds page-aligned per the validation contract.
            ps = max(2, features // 4)
            pages_per_slot = -(-(features + 16) // ps)
            n_slots = predictor_extra["tpu"]["decode_slots"]
            budget = max(
                pages_per_slot + 2, n_slots + 1, 1 + 2 * pages_per_slot + 2
            )
            predictor_extra["tpu"].update(
                decode_prefix_slots=8,
                decode_kv_page_size=ps,
                decode_kv_pages=budget,
                decode_prefill_chunk=ps,
            )
        if kv_overflow:
            # squeeze the device prefix index down to TWO entries and hang
            # a host tier below it: with ~8 distinct shared-prefix groups
            # in the mix, every capture evicts an older group (demotion)
            # and every revisit of an evicted group promotes it back —
            # sustained demote/promote churn over the full soak duration
            predictor_extra["tpu"].update(
                decode_prefix_slots=2,
                decode_kv_host_bytes=32 << 20,
            )
        if replicas > 1:
            predictor_extra["tpu"].update(
                decode_replicas=replicas,
                decode_router_policy="affinity",
                # fleet health polling on: the poller feeds live queue
                # depths to the balancer and drives the breaker
                # evict/readmit funnel the chaos flags below exercise
                decode_health_poll_ms=50.0,
                decode_health_miss_threshold=2,
            )
            # pin headroom on top of the deliberately-tight paged budget:
            # the replica soak asserts the fleet HIT RATE, and a budget
            # that reclaims prefix pins as fast as groups capture would
            # fail that assert for allocator reasons, not routing ones
            ps = predictor_extra["tpu"]["decode_kv_page_size"]
            pin_pages = -(-max(1, features // 2) // ps)
            predictor_extra["tpu"]["decode_kv_pages"] += (
                4 * replicas * pin_pages + 2
            )
    if fault_spec is not None:
        # the faulted leg exercises the resilience layer end-to-end: the
        # model node gets a retry policy (absorbing injected transport
        # errors) — what survives shows up in the reported error budget
        graph["parameters"] += [
            {"name": "retry_max_attempts", "value": "3", "type": "INT"},
            {"name": "retry_backoff_ms", "value": "2", "type": "FLOAT"},
            {"name": "retry_seed", "value": str(fault_spec.seed), "type": "INT"},
        ]
    dep = SeldonDeployment.from_dict(
        {
            "spec": {
                "name": "soak",
                "predictors": [{"name": "p", "graph": graph, **predictor_extra}],
            }
        }
    )
    dep = default_deployment(dep)
    validate_deployment(dep)
    predictor = dep.spec.predictors[0]

    if trace_summary > 0:
        # fresh process-global trace store per run: --faults runs two legs
        # in one process, and the faulted leg's summary must rank ITS
        # traces, not the union of both legs
        import seldon_core_tpu.telemetry as telemetry

        telemetry.configure(telemetry.tracer_from_env())
    server, gw, oauth, _token = build_gateway_stack(
        predictor,
        deployment_name="soak",
        oauth_key="soak-key",
        oauth_secret="soak-secret",
    )
    fault_schedules = {}
    if fault_spec is not None:
        from seldon_core_tpu.engine.faults import install_faults

        fault_schedules = install_faults(server.executor, {"m": fault_spec})

    port = _free_port()
    fast = await start_fast_server(gateway_routes(gw), "127.0.0.1", port)

    # ---- seeded replica chaos (--kill-replica / --drain-replica n@t) ----
    def _parse_at(flag: str, raw: str) -> tuple[int, float]:
        try:
            n, _, t = raw.partition("@")
            arm, at_s = int(n), float(t)
        except ValueError:
            raise RuntimeError(f"soak {flag}: expected <replica>@<seconds>, got {raw!r}")
        if not (0 <= arm < max(replicas, 1)) or at_s < 0:
            raise RuntimeError(
                f"soak {flag}: replica must be in [0, {replicas}) and the "
                f"time non-negative, got {raw!r}"
            )
        return arm, at_s

    chaos_actions: list[tuple[str, int, float]] = []
    if kill_replica or drain_replica:
        if replicas <= 1:
            raise RuntimeError(
                "soak --kill-replica/--drain-replica need --replicas > 1 "
                "(a single scheduler has no surviving arm to migrate onto)"
            )
        if kill_replica:
            chaos_actions.append(("kill", *_parse_at("--kill-replica", kill_replica)))
        if drain_replica:
            chaos_actions.append(("drain", *_parse_at("--drain-replica", drain_replica)))
        chaos_actions.sort(key=lambda a: a[2])
    chaos_events: list[dict] = []

    rss_samples: list[tuple[float, float]] = []
    lag_samples: list[float] = []
    stop = asyncio.Event()

    async def sampler() -> None:
        while not stop.is_set():
            window_max_lag = 0.0
            t_end = time.perf_counter() + 1.0
            while time.perf_counter() < t_end and not stop.is_set():
                t0 = time.perf_counter()
                await asyncio.sleep(0.02)
                window_max_lag = max(
                    window_max_lag, time.perf_counter() - t0 - 0.02
                )
            rss_samples.append((time.perf_counter(), _rss_mb()))
            lag_samples.append(window_max_lag * 1e3)

    payload_fn = None
    shared_sent = {"n": 0}
    n_groups = 4 * replicas if replicas > 1 else 1
    if kv_overflow:
        # 4× the 2-entry device index: the working set of distinct shared
        # prefixes CANNOT fit on device, so overflow (and the host tier
        # underneath it) is guaranteed, not load-dependent
        n_groups = max(n_groups, 8)
    if prefix_share > 0:
        # prompt mix: `prefix_share` of requests open with a fixed system
        # prefix (half the prompt bucket) + a random tail, the rest are
        # fully random — retiring slots auto-capture full prompts, and the
        # radix index's longest-common-prefix match turns ANY captured
        # sharer into a hit for the next one; the random tails churn the
        # LRU pool so eviction runs under load too. A replicated soak uses
        # SEVERAL distinct system prefixes (4 per replica) so the affinity
        # router has a keyspace to spread — one group would just pin one
        # replica hot
        shared_len = max(1, features // 2)
        prefixes = [[7 + g] * shared_len for g in range(n_groups)]

        def payload_fn(rng):
            def tail(n):
                return [rng.randrange(64) for _ in range(n)]

            if rng.random() < prefix_share:
                shared_sent["n"] += 1
                g = rng.randrange(n_groups)
                if kv_overflow:
                    # group-DETERMINISTIC full prompts: the host tier holds
                    # whole page-aligned spans (entry must prefix the
                    # prompt), so a revisit only promotes when it replays
                    # the captured span exactly — random tails would bury
                    # the shared head inside never-rehit entries
                    prompt = [7 + g] * features
                else:
                    prompt = prefixes[g] + tail(features - shared_len)
            else:
                prompt = tail(features)
            return {"data": {"ndarray": [prompt] * batch}}

    async def chaos_driver() -> None:
        """Fire the scheduled replica chaos actions mid-load. A KILL arms a
        deterministic induced allocator-OOM on the target's very next
        decode round (engine/faults.py DecodeFaultSpec) — its loop crashes
        for real, the router force-opens the breaker, migrates the
        in-flight generations, and the health poller readmits the replica
        through the half-open probe once it answers again. A DRAIN calls
        the graceful path. Either way the load generator above must see
        ZERO errors — that is the assertion this harness exists for."""
        from seldon_core_tpu.engine.faults import DecodeFaultSpec, install_decode_faults

        t0 = time.perf_counter()
        for kind, arm, at_s in chaos_actions:
            delay = at_s - (time.perf_counter() - t0)
            if delay > 0:
                try:
                    await asyncio.wait_for(stop.wait(), timeout=delay)
                    return  # load finished before the action came due
                except asyncio.TimeoutError:
                    pass
            sched_ = getattr(server, "decode_scheduler", None)
            fleet_ = getattr(sched_, "replicas", None)
            if fleet_ is None or fleet_[arm] is None:
                continue
            ev = {"action": kind, "replica": arm, "t_s": round(at_s, 2)}
            if kind == "kill":
                install_decode_faults(fleet_[arm], DecodeFaultSpec(oom_at_round=1))
            else:
                lookups_ = sched_.stat_prefix_hits + sched_.stat_prefix_misses
                ev["hit_rate_pre_drain"] = round(
                    sched_.stat_prefix_hits / max(lookups_, 1), 3
                )
                ev["hits_pre"] = sched_.stat_prefix_hits
                ev["lookups_pre"] = lookups_
                ev.update(await sched_.drain_replica(arm))
            chaos_events.append(ev)

    sampler_task = asyncio.ensure_future(sampler())
    chaos_task = (
        asyncio.ensure_future(chaos_driver()) if chaos_actions else None
    )
    try:
        stats = await run_load(
            f"http://127.0.0.1:{port}",
            users=users,
            duration_s=duration_s,
            features=features,
            batch=batch,
            oauth_key="soak-key",
            oauth_secret="soak-secret",
            static_payload=True,
            payload_fn=payload_fn,
        )
    finally:
        stop.set()
        await sampler_task
        if chaos_task is not None:
            await chaos_task
        fast.close()
        await fast.wait_closed()
        if getattr(server, "decode_scheduler", None) is not None:
            await server.decode_scheduler.close()
        if server.batcher is not None:
            await server.batcher.close()

    s = stats.summary()
    # The in-process load GENERATOR keeps every request's latency +
    # completion time for exact percentiles (tools/loadtest.py LoadStats)
    # — that is real, expected growth of ~64 bytes/request in THIS
    # process, not a server leak. Estimate it so the net server slope is
    # the leak signal. (A measured 90 s iris soak: 45 MB raw growth,
    # ~36 MB of it the stats lists.)
    loadgen_mb = s["requests"] * 64 / 1e6
    # least-squares slope over (minute, MB) samples
    slope = 0.0
    if len(rss_samples) >= 2:
        t0 = rss_samples[0][0]
        xs = [(t - t0) / 60.0 for t, _ in rss_samples]
        ys = [m for _, m in rss_samples]
        n = len(xs)
        mx, my = sum(xs) / n, sum(ys) / n
        denom = sum((x - mx) ** 2 for x in xs)
        if denom > 0:
            slope = sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / denom
    lag_sorted = sorted(lag_samples)
    # s["requests"] counts only SUCCESSES (loadtest tallies errors apart);
    # the budget denominator is all attempts, clamped only against div-by-0
    attempts = max(int(s["requests"]) + int(s["errors"]), 1)
    traces = None
    if trace_summary > 0:
        # built-in attribution for soak/chaos runs: the slowest retained
        # traces (tail sampling keeps errors + slowest-N), each with its
        # top spans by SELF time — where the tail latency actually went
        from seldon_core_tpu.telemetry import get_tracer

        traces = get_tracer().store.slowest_summaries(n=trace_summary)
    spec_stats = None
    sched = getattr(server, "decode_scheduler", None)
    if (spec_k > 0 or spec_tree) and sched is not None:
        spec_stats = {
            **({"spec_tree": spec_tree} if spec_tree else {"spec_k": spec_k}),
            "spec_dispatches": sched.stat_spec_dispatches,
            "accept_rate": round(
                sched.stat_spec_accepted / max(sched.stat_spec_proposed, 1), 3
            ),
            "tokens_per_dispatch": round(
                sched.stat_spec_emitted / max(sched.stat_spec_dispatches, 1), 2
            ),
            "tokens_per_ride": round(
                sched.stat_spec_ride_emitted / max(sched.stat_spec_rides, 1), 2
            ),
            "recompiles_after_warmup": sched.recompiles_since_warmup(),
        }
    fleet = getattr(sched, "replicas", None) if sched is not None else None
    # drained replicas leave a None tombstone in the fleet list (positional
    # rendezvous ranks); every aggregation below reads the LIVE ones
    live_fleet = [r for r in fleet if r is not None] if fleet else None
    paged_stats = None
    if paged and sched is not None:
        pools = [r.pool for r in live_fleet] if live_fleet else [sched.pool]
        allocs = [p.alloc for p in pools]
        paged_stats = {
            "page_size": pools[0].page_size,
            "page_budget": sum(p.n_pages for p in pools),
            "peak_slots": sched.stat_peak_active,
            "pages_shared": sum(a.stat_pages_shared for a in allocs),
            "cow_copies": sum(a.stat_cow_copies for a in allocs),
            "pins_reclaimed": sum(a.stat_pin_reclaims for a in allocs),
            "pages_reclaimed": sum(a.stat_reclaimed_pages for a in allocs),
            "admit_blocked_rounds": sched.stat_admit_blocked_rounds,
            "pages_free_end": sum(a.free_pages for a in allocs),
            "pages_live_end": sum(a.live_pages for a in allocs),
            "pages_prefix_end": sum(a.prefix_pages for a in allocs),
            "recompiles_after_warmup": sched.recompiles_since_warmup(),
        }
        # end-of-run allocator audit (per replica on a fleet): a soak that
        # leaked or double-freed a page fails loudly here rather than
        # reporting a green run
        for a in allocs:
            a.check()
    tp_stats = None
    if tp > 1:
        # a --tp soak that silently fell back to single-device (mesh
        # warn-disabled, too few devices, no scheduler) would report a
        # vacuously green run with the shard audit never executed — the
        # exact failure mode a CI gate keyed on exit code must not miss
        if sched is None or sched.tp != tp:
            raise RuntimeError(
                f"soak --tp {tp}: scheduler runs at tp="
                f"{getattr(sched, 'tp', None)} — the mesh request was "
                "disabled (device count or head/ffn divisibility); the "
                "sharded geometry was NOT exercised"
            )
        # per-shard audit beside the allocator's host-side check(): every
        # pool/draft-cache buffer must be laid out across exactly the mesh
        # devices with head-sharded payloads — a soak that drifted a
        # buffer off the mesh (or silently replicated a shard) fails
        # loudly here rather than reporting a green run
        tp_stats = {
            **sched.shard_audit(),
            "requested_tp": tp,
            "recompiles_after_warmup": sched.recompiles_since_warmup(),
        }
    replica_stats = None
    if replicas > 1:
        # a --replicas soak that silently fell back to one scheduler
        # (validation refused, spec dropped) must not report green
        if fleet is None or len(fleet) < replicas:
            raise RuntimeError(
                f"soak --replicas {replicas}: replicated decode tier not "
                f"built (got {0 if fleet is None else len(fleet)} replicas)"
            )
        hits = sched.stat_prefix_hits
        misses = sched.stat_prefix_misses
        lookups = max(hits + misses, 1)
        # the analytic round-robin FLOOR for this mix, from the traffic
        # the payload generator actually sent: shared rows can hit at
        # best after their group's cold capture, and under round-robin
        # EVERY replica pays its own capture per group — so round-robin's
        # hit count is bounded by shared_rows - replicas * groups * batch
        # (batch rows per request admit and look up individually).
        # Affinity pays one capture per group fleet-wide; beating the
        # floor is the point of keying the router on the radix prefix.
        shared_rows = shared_sent["n"] * batch
        cold_rows_per_group = batch  # one cold REQUEST (batch rows)
        rr_cold = len(fleet) * n_groups * cold_rows_per_group
        rr_floor = max(0.0, (shared_rows - rr_cold) / lookups)
        agg_hit = hits / lookups
        replica_stats = {
            "replicas": len(fleet),
            "policy": sched.policy,
            "routes": dict(sched.balancer.stat_routes),
            "aggregate_hit_rate": round(agg_hit, 3),
            "rr_floor_hit_rate": round(rr_floor, 3),
            "shared_requests_sent": shared_sent["n"],
            "scale_ups": sched.stat_scale_ups,
            "per_replica": [
                {
                    "replica_id": r.replica_id,
                    "admitted": r.stat_admitted,
                    "hits": r.stat_prefix_hits,
                    "misses": r.stat_prefix_misses,
                    "queue_depth_end": r.queue_depth,
                }
                for r in live_fleet
            ],
            "recompiles_after_warmup": sched.recompiles_since_warmup(),
        }
        # fleet hit-rate above the round-robin floor — the affinity
        # contract under a sustained mixed shared/divergent stream. Only
        # judged when the mix sent enough shared traffic for the floor to
        # separate from capture-race noise (a sparse short smoke records
        # the numbers without asserting on them).
        # a chaos leg invalidates the floor: an eviction/drain re-captures
        # its groups on the surviving replicas (and again on readmission),
        # so the capture cost the floor models is paid extra times — that
        # leg is judged by its own zero-error/lifecycle asserts instead
        if shared_rows >= 4 * rr_cold and hits > 0 and not chaos_actions:
            if not agg_hit > rr_floor:
                raise RuntimeError(
                    f"soak --replicas: aggregate prefix hit rate {agg_hit:.3f} "
                    f"did not clear the round-robin floor {rr_floor:.3f} — "
                    "affinity routing is not keeping sharers co-located"
                )
    flight_stats = None
    if generative and live_fleet is not None:
        # per-replica flight summaries (each replica owns its recorder;
        # /decode/health serves the same per-replica rows live)
        per_replica = []
        for r in live_fleet:
            agg = r.flight.aggregate()
            per_replica.append(
                {
                    "name": r.flight.name,
                    "replica_id": r.replica_id,
                    "rounds": agg["rounds"],
                    "occupancy_mean": agg["occupancy_mean"],
                    "bubble_fraction": agg["bubble_fraction"],
                    "goodput": agg["goodput"],
                }
            )
        flight_stats = {"per_replica": per_replica}
    elif generative and sched is not None and getattr(sched, "flight", None):
        # the flight recorder's aggregate beside the allocator audit: the
        # same bubble/occupancy/blocked-cause read-out GET /decode/flight
        # serves live, as an end-of-run summary
        fa = sched.flight.aggregate()
        flight_stats = {
            "rounds": fa["rounds"],
            "modes": fa["modes"],
            "occupancy_mean": fa["occupancy_mean"],
            "bubble_fraction": fa["bubble_fraction"],
            # the pipelined loop's win: host work hidden under in-flight
            # dispatches, and the share of the would-be serial gap it
            # covered vs the residual still exposed as bubble
            "overlap_ms": fa["overlap_ms"],
            "overlap_of_gap": fa["overlap_of_gap"],
            "bubble_residual": fa["bubble_residual"],
            "pipelined_rounds": sched.stat_pipelined_rounds,
            "busy_ms": fa["busy_ms"],
            # the enqueue/readback split of busy_ms and the per-phase
            # decomposition of gap_ms — the host-bubble attribution the
            # pipelined decode loop spends, printed beside the aggregate
            # exactly as GET /decode/flight serves it
            "enqueue_ms": fa["enqueue_ms"],
            "readback_ms": fa["readback_ms"],
            "phase_ms": fa["phase_ms"],
            "top_gap_phase": sched.flight.top_gap_phase(),
            "gap_ms": fa["gap_ms"],
            "blocked_rounds": fa["blocked_rounds"],
            "goodput": fa["goodput"],
        }
    profile_stats = None
    if profile_out:
        # --profile: the run must have exercised the decode loop AND the
        # sampler must have caught it in the act at least once — a smoke
        # gate that fails loudly instead of writing an empty file
        from seldon_core_tpu.telemetry import profile as profile_mod

        if not generative:
            raise RuntimeError(
                "soak --profile needs a generative leg (--spec-k/"
                "--prefix-share/--paged/--tp) — the sampler targets the "
                "decode loop's thread"
            )
        prof = profile_mod.get_profiler()
        folded = prof.folded()
        if prof.samples < 1 or not folded:
            raise RuntimeError(
                "soak --profile: the sampling profiler captured no decode-"
                "loop stack (ENGINE_DECODE_PROFILE off? run shorter than "
                f"one {prof.hz} Hz sampling tick?)"
            )
        # the profile-smoke leg doubles as the pipelined-round gate: the
        # generative smoke must actually hide host work under in-flight
        # dispatches — a silently-serialized decode loop (pipeline flag
        # dropped, overlap window skipped, overlap accounting broken)
        # fails CI here instead of shipping as a quiet perf regression
        if (
            sched is not None
            and getattr(sched, "_pipeline_on", lambda: False)()
            and flight_stats is not None
            and flight_stats.get("rounds")
        ):
            # overlap_of_gap comes from flight frames — with the recorder
            # killed (ENGINE_FLIGHT=off) or no frames recorded there is
            # nothing to judge, and failing would blame the pipeline for
            # a telemetry kill switch
            ov = flight_stats.get("overlap_of_gap", 0.0)
            if not ov > 0.0:
                raise RuntimeError(
                    "soak --profile: the decode pipeline is on but "
                    "overlap_of_gap is 0 — no host work was hidden under "
                    "an in-flight dispatch (silently-serialized loop?)"
                )
        with open(profile_out, "w") as f:
            f.write("\n".join(folded) + "\n")
        rep = prof.report(n=3)
        profile_stats = {
            "samples": rep["samples"],
            "hz": rep["hz"],
            "stacks": rep["table_entries"],
            "truncated_samples": rep["truncated_samples"],
            "folded_out": profile_out,
            "top_self": [t["frame"] for t in rep["top"]],
        }
    chaos_stats = None
    if chaos_actions:
        # the fault-tolerance contract, asserted: replica death/drain
        # under load is INVISIBLE to clients — every in-flight generation
        # migrated and resumed, zero errors in the load generator's tally
        if s["errors"] > 0:
            raise RuntimeError(
                f"soak replica-chaos: {s['errors']} request error(s) leaked "
                "to clients — migration/recovery did not absorb the fault"
            )
        if not chaos_events:
            raise RuntimeError(
                "soak replica-chaos: no scheduled action actually fired "
                "(action time past --duration, or target already gone) — "
                "the run proved nothing"
            )
        killed = [e for e in chaos_events if e["action"] == "kill"]
        if killed and sched.stat_evictions < 1:
            raise RuntimeError(
                "soak --kill-replica: the induced allocator-OOM never "
                "evicted the target (no breaker-open observed) — the kill "
                "was not exercised"
            )
        # readmission via the half-open probe: only judged when the kill
        # left the poller time to recover the replica before shutdown
        if killed and sched.stat_recoveries < 1 and all(
            duration_s - e["t_s"] >= 2.0 for e in killed
        ):
            raise RuntimeError(
                "soak --kill-replica: the evicted replica was never "
                "readmitted (half-open probe did not recover it)"
            )
        for e in chaos_events:
            if e["action"] != "drain":
                continue
            lookups_post = (
                sched.stat_prefix_hits + sched.stat_prefix_misses - e["lookups_pre"]
            )
            hits_post = sched.stat_prefix_hits - e["hits_pre"]
            e["hit_rate_post_drain"] = round(hits_post / max(lookups_post, 1), 3)
            # the drain acceptance bar (warm-TTFT hit rate within 5% of
            # pre-drain) — only judged when enough post-drain traffic ran
            # for the rate to mean anything
            if (
                lookups_post >= 100
                and e["hit_rate_post_drain"] < e["hit_rate_pre_drain"] - 0.05
            ):
                raise RuntimeError(
                    f"soak --drain-replica: post-drain hit rate "
                    f"{e['hit_rate_post_drain']} fell more than 5% below "
                    f"pre-drain {e['hit_rate_pre_drain']} — the spill/"
                    "sibling-push did not keep the working set warm"
                )
        chaos_stats = {
            "events": chaos_events,
            "replica_states": sched.replica_states(),
            "evictions": sched.stat_evictions,
            "recoveries": sched.stat_recoveries,
            "migrations": sched.stat_migrations,
            "drains": sched.stat_drains,
            "health_misses": sched.stat_health_misses,
        }
    prefix_stats = None
    if prefix_share > 0 and sched is not None:
        lookups = sched.stat_prefix_hits + sched.stat_prefix_misses
        prefix_stats = {
            "prefix_share": prefix_share,
            "hit_rate": round(sched.stat_prefix_hits / max(lookups, 1), 3),
            "prefill_tokens_saved": sched.stat_prefix_tokens_saved,
            "captures": sched.stat_prefix_captures,
            "evictions": sched.stat_prefix_evictions,
            "chunk_dispatches": sched.stat_chunk_dispatches,
            "recompiles_after_warmup": sched.recompiles_since_warmup(),
        }
    kvtier_stats = None
    if kv_overflow and sched is not None:
        tier = getattr(sched, "_host_tier", None)
        kvtier_stats = {
            "groups": n_groups,
            "prefix_slots": 2,
            "demotions": sched.stat_tier_demotions,
            "promotions": sched.stat_tier_promotions,
            "promote_overlap": sched.stat_tier_promote_overlap,
            "sent_shared": shared_sent["n"],
            "recompiles_after_warmup": sched.recompiles_since_warmup(),
            **({"host_tier": tier.snapshot()} if tier is not None else {}),
        }
        # with 8 distinct groups and a 2-entry device index, every capture
        # past the second must evict-and-demote — zero demotions means the
        # tier was never wired in and the soak proved nothing
        if shared_sent["n"] >= n_groups and kvtier_stats["demotions"] < 1:
            raise RuntimeError(
                "soak --kv-overflow: the device prefix index never demoted "
                "to the host tier — overflow was not exercised"
            )
        # revisited groups must come back WARM from the host tier; enough
        # shared traffic makes a revisit-of-evicted statistically certain
        if shared_sent["n"] >= 4 * n_groups and kvtier_stats["promotions"] < 1:
            raise RuntimeError(
                "soak --kv-overflow: no evicted prefix was ever promoted "
                "back from the host tier — the ladder is one-way"
            )
        if kvtier_stats["recompiles_after_warmup"] != 0:
            raise RuntimeError(
                "soak --kv-overflow: promotion churn recompiled a decode "
                "program — tier traffic must never touch compiled signatures"
            )
    return {
        "duration_s": duration_s,
        "users": users,
        "model": "tiny_gpt" if generative else model,
        "preds_per_sec": round(s["requests_per_sec"] * batch, 2),
        "p99_ms": s["p99_ms"],
        "errors": s["errors"],
        # error budget consumed: failed fraction of all requests (the SLO
        # number the faulted leg is judged by)
        "error_rate": round(s["errors"] / attempts, 4),
        "faults_injected": (
            sum(sch.injected for sch in fault_schedules.values())
            if fault_schedules
            else 0
        ),
        "rss_start_mb": round(rss_samples[0][1], 1) if rss_samples else None,
        "rss_end_mb": round(rss_samples[-1][1], 1) if rss_samples else None,
        "rss_slope_mb_per_min": round(slope, 3),
        "loadgen_stats_mb_est": round(loadgen_mb, 1),
        # the leak signal: growth with the loadgen's own accounting removed
        "rss_slope_net_mb_per_min": round(
            slope - loadgen_mb / max(duration_s / 60.0, 1e-9), 3
        ),
        "loop_lag_p99_ms": round(
            lag_sorted[min(len(lag_sorted) - 1, int(0.99 * len(lag_sorted)))], 2
        ) if lag_sorted else None,
        "loop_lag_max_ms": round(max(lag_samples), 2) if lag_samples else None,
        **({"trace_summary": traces} if traces is not None else {}),
        **({"chaos": chaos_stats} if chaos_stats is not None else {}),
        **({"replicas": replica_stats} if replica_stats is not None else {}),
        **({"flight": flight_stats} if flight_stats is not None else {}),
        **({"profile": profile_stats} if profile_stats is not None else {}),
        **({"spec": spec_stats} if spec_stats is not None else {}),
        **({"prefix": prefix_stats} if prefix_stats is not None else {}),
        **({"paged": paged_stats} if paged_stats is not None else {}),
        **({"kv_tier": kvtier_stats} if kvtier_stats is not None else {}),
        **({"tp": tp_stats} if tp_stats is not None else {}),
    }


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--duration", type=float, default=60.0)
    ap.add_argument("--users", type=int, default=16)
    ap.add_argument("--model", default="iris_mlp")
    ap.add_argument("--features", type=int, default=4)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument(
        "--faults",
        action="store_true",
        help="run the soak twice — faults off, then a seeded fault schedule "
        "injected into the model node (retries enabled) — and report p99 + "
        "error budget for both legs side by side",
    )
    ap.add_argument(
        "--trace-summary",
        type=int,
        nargs="?",
        const=5,
        default=0,
        metavar="N",
        help="after the run, include the slowest-N retained traces (id, "
        "total ms, top-3 spans by self-time) in the report (default N=5)",
    )
    ap.add_argument(
        "--spec-k",
        type=int,
        default=0,
        help="run the soak against a generative deployment with draft-model "
        "speculative decoding (k proposals per dispatch); the report gains "
        "accept_rate / tokens_per_dispatch under 'spec'",
    )
    ap.add_argument(
        "--spec-tree",
        default="",
        metavar="B,B,...",
        help="run the soak with TREE speculation (decode_spec_tree, e.g. "
        "'2,2,1'): per-depth top-b candidate branches scored in one "
        "widened verify dispatch; the report gains accept_rate / "
        "tokens_per_ride under 'spec' and composes with --paged/--tp "
        "(same allocator + per-shard audits)",
    )
    ap.add_argument(
        "--prefix-share",
        type=float,
        default=0.0,
        help="run the soak against a generative deployment with the prefix "
        "cache enabled and shape the prompt mix so this fraction of requests "
        "share a system prefix; the report gains hit_rate / tokens_saved / "
        "evictions under 'prefix'",
    )
    ap.add_argument(
        "--paged",
        action="store_true",
        help="run the soak against a generative deployment with a TIGHT "
        "paged-KV budget and a mixed shared-prefix/divergent prompt stream "
        "so copy-on-write and LRU pin reclaim run under load; the report "
        "gains pages_shared / cow_copies / pins_reclaimed under 'paged' "
        "(implies --prefix-share 0.6 unless set)",
    )
    ap.add_argument(
        "--tp",
        type=int,
        default=0,
        help="run the soak against a generative deployment decoded "
        "tensor-parallel over an N-device mesh (decode_mesh_axes={'tp': N}; "
        "forces an N-device host platform when no accelerator provides one, "
        "implies --paged); the report gains the per-shard layout audit "
        "under 'tp' and the end-of-run allocator check runs as usual",
    )
    ap.add_argument(
        "--replicas",
        type=int,
        default=0,
        help="run the soak against a REPLICATED generative deployment: N "
        "decode-scheduler replicas behind the prefix-affinity router "
        "(decode_replicas=N; implies --paged and a multi-group shared-"
        "prefix mix, forces an N-device host platform when no accelerator "
        "provides one); the report gains per-replica admissions/hits and "
        "the routing split under 'replicas', every replica's allocator is "
        "audited, and the aggregate prefix hit rate must clear the "
        "analytic round-robin floor",
    )
    ap.add_argument(
        "--profile",
        default="",
        metavar="FILE",
        help="after a generative run, dump the decode-loop sampling "
        "profiler's folded stacks (flamegraph input) to FILE and FAIL if "
        "no stack was captured — the `make profile-smoke` gate; the "
        "report gains samples/hz/top frames under 'profile'",
    )
    ap.add_argument(
        "--kill-replica",
        default="",
        metavar="N@T",
        help="with --replicas: at T seconds into the run, arm a "
        "deterministic induced allocator-OOM on replica N's next decode "
        "round — its loop crashes for real, the router evicts it, migrates "
        "its in-flight generations, and the health poller readmits it via "
        "the half-open probe; the run FAILS unless clients saw zero "
        "errors, the eviction fired, and (time permitting) the replica "
        "was readmitted. The report gains the lifecycle counters under "
        "'chaos'",
    )
    ap.add_argument(
        "--drain-replica",
        default="",
        metavar="N@T",
        help="with --replicas: at T seconds into the run, gracefully drain "
        "replica N (stop admission, migrate stragglers, spill its prefix "
        "pages to the store + push them to their new rendezvous homes, "
        "release the device); the run FAILS unless clients saw zero "
        "errors and the post-drain warm hit rate stays within 5%% of "
        "pre-drain (when enough post-drain traffic ran to judge)",
    )
    ap.add_argument(
        "--kv-overflow",
        action="store_true",
        help="run the soak against a generative deployment whose device "
        "prefix index is squeezed to TWO entries under an 8-group shared-"
        "prefix mix, with a host-RAM KV tier hung below it — sustained "
        "evict/demote + revisit/promote churn with the allocator audit and "
        "zero-recompile gate live; the run FAILS unless demotions AND "
        "promotions both fired and no decode program recompiled; the "
        "report gains the tier counters under 'kv_tier' (implies --paged)",
    )
    ap.add_argument("--fault-seed", type=int, default=1337)
    ap.add_argument("--fault-error-rate", type=float, default=0.3)
    ap.add_argument("--fault-latency-ms", type=float, default=0.0)
    ap.add_argument(
        "--fault-flap-period",
        type=int,
        default=0,
        help="calls per unhealthy window (0 = steady error rate)",
    )
    args = ap.parse_args(argv)

    if args.tp > 1 or args.replicas > 1:
        # the host platform's device count is fixed at backend init — set
        # the flag before anything imports jax (harmless when a real
        # multi-chip backend is attached: the flag only shapes the CPU
        # platform). Replicas want one forced device each (the replica
        # factory places replica i on device i).
        import os
        import sys as _sys

        flags = os.environ.get("XLA_FLAGS", "")
        if (
            "jax" not in _sys.modules
            and "xla_force_host_platform_device_count" not in flags
        ):
            os.environ["XLA_FLAGS"] = (
                flags
                + " --xla_force_host_platform_device_count="
                + str(max(8, args.tp, args.replicas))
            ).strip()

    def _run(fault_spec=None) -> dict:
        return asyncio.run(
            soak(
                duration_s=args.duration,
                users=args.users,
                model=args.model,
                features=args.features,
                batch=args.batch,
                fault_spec=fault_spec,
                trace_summary=args.trace_summary,
                spec_k=args.spec_k,
                spec_tree=args.spec_tree,
                prefix_share=args.prefix_share,
                paged=args.paged,
                tp=args.tp,
                replicas=args.replicas,
                profile_out=args.profile,
                kill_replica=args.kill_replica,
                drain_replica=args.drain_replica,
                kv_overflow=args.kv_overflow,
            )
        )

    if not args.faults:
        out = _run()
    else:
        from seldon_core_tpu.engine.faults import FaultSpec

        spec = FaultSpec(
            error_rate=args.fault_error_rate,
            latency_ms=args.fault_latency_ms,
            flap_period=args.fault_flap_period,
            seed=args.fault_seed,
        )
        baseline = _run()
        faulted = _run(fault_spec=spec)
        out = {
            "fault_seed": args.fault_seed,
            "baseline": baseline,
            "faulted": faulted,
            # the resilience claim in one number: how much error budget the
            # injected fault rate actually burned after retries absorbed it
            "p99_delta_ms": round(faulted["p99_ms"] - baseline["p99_ms"], 2),
            "error_rate_delta": round(
                faulted["error_rate"] - baseline["error_rate"], 4
            ),
        }
    json.dump(out, sys.stdout)
    print()


if __name__ == "__main__":
    main()
