from seldon_core_tpu.training.steps import TrainState, make_sharded_train_step, make_train_step

__all__ = ["TrainState", "make_train_step", "make_sharded_train_step"]
