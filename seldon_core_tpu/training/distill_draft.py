"""Distill a speculative DRAFT against its serving target.

The ``zoo://draft`` entry ships as a seed-shared layer truncation of its
target — the untrained-weights analogue of a distilled draft (PR 4). Its
accept rate comes entirely from the shared residual prefix; nothing ever
LEARNS the target's conditionals. This module closes that gap with the
idle training machinery (training/steps.py): teacher-forced target logits
at every position (models/decoder.sequence_logits) -> KL into the draft,
on a mix of ON-POLICY sequences (prompt + the target's own greedy
continuation — the distribution verify rounds actually score the draft
on, since context during decode IS the target's accepted chain) and
uniform-random sequences (so the draft doesn't collapse off-path).

Run:

    python -m seldon_core_tpu.training.distill_draft \
        --hidden 256 --layers 4 --ffn 1024 --draft-layers 1 \
        --steps 300 --out /tmp/draft_distilled.npz

and serve the result via the checkpoint-loading draft variant:

    tpu.decode_draft_model: "zoo://draft?layers=1&...&distilled=/tmp/draft_distilled.npz"

The report prints the greedy accept-rate proxy (draft/target argmax
agreement along target-greedy trajectories — exactly the per-position
acceptance probability of the chain/tree walk) before and after, plus the
KL trajectory; the measured delta for the stock bench pair is recorded in
PARITY.md.
"""

from __future__ import annotations

import argparse
import json

import numpy as np


# ------------------------------------------------------- checkpoint format
# A flat .npz keyed by dotted tree paths ("layers.0.qkv.w", "ln_f.g", ...):
# readable with plain numpy, no pickle, geometry checked on load against
# the receiving build's own init (a distilled checkpoint can only REFILL a
# draft of the same architecture, never change it).


def flatten_params(params) -> dict:
    flat: dict = {}

    def walk(p, prefix):
        if isinstance(p, dict):
            for k, v in p.items():
                walk(v, f"{prefix}{k}.")
        elif isinstance(p, (list, tuple)):
            for i, v in enumerate(p):
                walk(v, f"{prefix}{i}.")
        else:
            flat[prefix[:-1]] = np.asarray(p)

    walk(params, "")
    return flat


def save_draft_checkpoint(path: str, params) -> None:
    np.savez(path, **flatten_params(params))


def load_draft_checkpoint(path: str, like):
    """Rebuild ``like``'s tree structure from the checkpoint, raising on
    any missing key or shape mismatch (the load is an architecture
    assertion, not a best-effort merge)."""
    data = np.load(path)

    def walk(p, prefix):
        if isinstance(p, dict):
            return {k: walk(v, f"{prefix}{k}.") for k, v in p.items()}
        if isinstance(p, (list, tuple)):
            return [walk(v, f"{prefix}{i}.") for i, v in enumerate(p)]
        key = prefix[:-1]
        if key not in data:
            raise ValueError(f"distilled checkpoint {path!r} is missing {key!r}")
        arr = data[key]
        want = np.shape(p)
        if tuple(arr.shape) != tuple(want):
            raise ValueError(
                f"distilled checkpoint {path!r} {key!r} has shape "
                f"{tuple(arr.shape)}, the draft build wants {tuple(want)} — "
                "the checkpoint was trained for a different geometry"
            )
        return arr.astype(np.asarray(p).dtype)

    return walk(like, "")


# ------------------------------------------------------------- the recipe


def greedy_accept_proxy(target, draft, prompts: np.ndarray, max_new: int) -> float:
    """Per-position greedy acceptance probability: along the TARGET's own
    greedy continuation of each prompt, the fraction of generated
    positions where the draft's argmax equals the target's. This is
    exactly what the chain walk accepts per depth (and a lower bound per
    depth for a top-b tree), so it converts directly into expected
    accepted-tokens-per-dispatch."""
    import jax.numpy as jnp

    from seldon_core_tpu.models.decoder import generate, sequence_logits

    full = np.asarray(generate(target, jnp.asarray(prompts), max_new))
    # position j's logits row predicts token j+1 — compare predictions
    # for the GENERATED span only (the prompt is given, not predicted)
    tl = np.asarray(sequence_logits(target, jnp.asarray(full[:, :-1])))
    dl = np.asarray(sequence_logits(draft, jnp.asarray(full[:, :-1])))
    gen = slice(prompts.shape[1] - 1, full.shape[1] - 1)
    return float(
        np.mean(np.argmax(tl[:, gen], -1) == np.argmax(dl[:, gen], -1))
    )


def distill(
    *,
    seed: int = 0,
    vocab: int = 512,
    hidden: int = 256,
    layers: int = 4,
    ffn: int = 1024,
    max_len: int = 80,
    resid_scale: float = 1.0,
    draft_layers: int = 1,
    seq: int = 16,
    horizon: int = 48,
    batch: int = 16,
    steps: int = 300,
    lr: float = 1e-3,
    teacher_temp: float = 0.5,
    on_policy_frac: float = 0.5,
    eval_prompts: int = 16,
    out: str = "",
    log_every: int = 50,
    data_seed: int = 1234,
) -> dict:
    """Distill the seed-shared truncation draft against its target; returns
    the report dict (accept proxy before/after, final KL) and writes the
    checkpoint to ``out`` when set."""
    import jax.numpy as jnp
    import optax

    from seldon_core_tpu.models.decoder import generate, init_decoder, sequence_logits
    from seldon_core_tpu.training.steps import init_state, make_distill_step

    target = init_decoder(
        seed, vocab=vocab, hidden=hidden, layers=layers, ffn=ffn,
        max_len=max_len, resid_scale=resid_scale,
    )
    draft = init_decoder(
        seed, vocab=vocab, hidden=hidden, layers=draft_layers, ffn=ffn,
        max_len=max_len, resid_scale=resid_scale,
    )

    rng = np.random.default_rng(data_seed)
    eval_ids = rng.integers(0, vocab, (eval_prompts, seq)).astype(np.int32)
    accept_before = greedy_accept_proxy(target, draft, eval_ids, horizon - seq)

    import jax

    opt = optax.adam(lr)
    teacher = jax.jit(lambda ids: sequence_logits(target, ids))
    step = jax.jit(make_distill_step(sequence_logits, opt, teacher_temp))
    state = init_state(draft, opt)

    # on-policy pool: target-greedy continuations of random prompts,
    # regenerated sparsely (they are the expensive half of the data).
    # The teacher is FROZEN, so pool rows' logits are computed once per
    # refresh and gathered per step — recomputing them every step would
    # spend ~half the teacher forward cost on targets that cannot change.
    def on_policy_batch(n):
        p = rng.integers(0, vocab, (n, seq)).astype(np.int32)
        ids = np.asarray(generate(target, jnp.asarray(p), horizon - seq))
        return ids, np.asarray(teacher(jnp.asarray(ids)))

    pool, pool_t = on_policy_batch(max(batch * 4, 32))
    kl = agree = float("nan")
    history = []
    for i in range(steps):
        n_on = int(round(batch * on_policy_frac))
        idx = rng.integers(0, len(pool), n_on) if n_on else None
        rand = rng.integers(0, vocab, (batch - n_on, horizon)).astype(np.int32)
        rand_t = np.asarray(teacher(jnp.asarray(rand))) if len(rand) else None
        if idx is not None:
            ids = np.concatenate([pool[idx], rand])
            t = (
                np.concatenate([pool_t[idx], rand_t])
                if rand_t is not None
                else pool_t[idx]
            )
        else:
            ids, t = rand, rand_t
        state, m = step(state, {"x": jnp.asarray(ids), "t": jnp.asarray(t)})
        kl, agree = float(m["kl"]), float(m["top1_agreement"])
        if log_every and (i + 1) % log_every == 0:
            history.append({"step": i + 1, "kl": round(kl, 4),
                            "top1": round(agree, 4)})
            print(f"step {i+1:5d}  kl {kl:.4f}  top1 {agree:.4f}", flush=True)
        if (i + 1) % max(1, steps // 4) == 0:
            pool, pool_t = on_policy_batch(len(pool))  # refresh as the draft moves

    distilled = jax.tree.map(np.asarray, state.params)
    accept_after = greedy_accept_proxy(target, distilled, eval_ids, horizon - seq)
    if out:
        save_draft_checkpoint(out, distilled)
    return {
        "accept_proxy_before": round(accept_before, 4),
        "accept_proxy_after": round(accept_after, 4),
        "final_kl": round(kl, 4),
        "final_top1": round(agree, 4),
        "steps": steps,
        "history": history,
        "checkpoint": out or None,
        "geometry": {
            "seed": seed, "vocab": vocab, "hidden": hidden, "layers": layers,
            "ffn": ffn, "max_len": max_len, "resid_scale": resid_scale,
            "draft_layers": draft_layers,
        },
    }


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--layers", type=int, default=4, help="TARGET layers")
    ap.add_argument("--ffn", type=int, default=1024)
    ap.add_argument("--max-len", type=int, default=80)
    ap.add_argument("--resid-scale", type=float, default=1.0)
    ap.add_argument("--draft-layers", type=int, default=1)
    ap.add_argument("--seq", type=int, default=16, help="prompt length")
    ap.add_argument(
        "--horizon", type=int, default=48,
        help="full training-sequence length (prompt + on-policy span)",
    )
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument(
        "--teacher-temp", type=float, default=0.5,
        help="sharpen the teacher before the KL (<1 weights its argmax; "
        "1.0 is pure distribution-matching)",
    )
    ap.add_argument(
        "--on-policy-frac", type=float, default=0.5,
        help="fraction of each batch drawn from target-greedy continuations",
    )
    ap.add_argument("--out", default="", help="checkpoint path (.npz)")
    ap.add_argument("--log-every", type=int, default=50)
    args = ap.parse_args(argv)
    report = distill(
        seed=args.seed, vocab=args.vocab, hidden=args.hidden, layers=args.layers,
        ffn=args.ffn, max_len=args.max_len, resid_scale=args.resid_scale,
        draft_layers=args.draft_layers, seq=args.seq, horizon=args.horizon,
        batch=args.batch, steps=args.steps, lr=args.lr,
        teacher_temp=args.teacher_temp,
        on_policy_frac=args.on_policy_frac, out=args.out,
        log_every=args.log_every,
    )
    print(json.dumps(report))


if __name__ == "__main__":
    main()
