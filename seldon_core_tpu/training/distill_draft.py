"""Distill a speculative DRAFT against its serving target.

The ``zoo://draft`` entry ships as a seed-shared layer truncation of its
target — the untrained-weights analogue of a distilled draft (PR 4). Its
accept rate comes entirely from the shared residual prefix; nothing ever
LEARNS the target's conditionals. This module closes that gap with the
idle training machinery (training/steps.py): teacher-forced target logits
at every position (models/decoder.sequence_logits) -> KL into the draft,
on a mix of ON-POLICY sequences (prompt + the target's own greedy
continuation — the distribution verify rounds actually score the draft
on, since context during decode IS the target's accepted chain) and
uniform-random sequences (so the draft doesn't collapse off-path).

Run:

    python -m seldon_core_tpu.training.distill_draft \
        --hidden 256 --layers 4 --ffn 1024 --draft-layers 1 \
        --steps 300 --out /tmp/draft_distilled.npz

and serve the result via the checkpoint-loading draft variant:

    tpu.decode_draft_model: "zoo://draft?layers=1&...&distilled=/tmp/draft_distilled.npz"

``--features`` trains the EAGLE-style FEATURE HEAD instead
(models/decoder.init_feature_draft): the teacher supplies per-position
hidden states beside its logits (sequence_hidden), the head runs
teacher-forced on them, and the loss adds feature-regression MSE
(--feat-weight) and input-feature noise (--feat-noise) — the two
augmentations that keep the head's serving-time feature AUTOREGRESSION
(deeper tree nodes feed on its own output) from collapsing. Serve via

    tpu.decode_draft_model: "zoo://draft?features=1&distilled=/tmp/draft_feat.npz"

The report prints the greedy accept-rate proxy (draft/target argmax
agreement along target-greedy trajectories — exactly the per-position
acceptance probability of the chain/tree walk; the feature variant runs
the head on the TRUE teacher features, the serving root's conditioning)
before and after, plus the KL trajectory; the measured deltas for the
stock bench pairs are recorded in PARITY.md.
"""

from __future__ import annotations

import argparse
import json

import numpy as np


# ------------------------------------------------------- checkpoint format
# A flat .npz keyed by dotted tree paths ("layers.0.qkv.w", "ln_f.g", ...):
# readable with plain numpy, no pickle, geometry checked on load against
# the receiving build's own init (a distilled checkpoint can only REFILL a
# draft of the same architecture, never change it).


def flatten_params(params) -> dict:
    flat: dict = {}

    def walk(p, prefix):
        if isinstance(p, dict):
            for k, v in p.items():
                walk(v, f"{prefix}{k}.")
        elif isinstance(p, (list, tuple)):
            for i, v in enumerate(p):
                walk(v, f"{prefix}{i}.")
        else:
            flat[prefix[:-1]] = np.asarray(p)

    walk(params, "")
    return flat


def save_draft_checkpoint(path: str, params) -> None:
    np.savez(path, **flatten_params(params))


def load_draft_checkpoint(path: str, like):
    """Rebuild ``like``'s tree structure from the checkpoint, raising on
    any missing key or shape mismatch (the load is an architecture
    assertion, not a best-effort merge)."""
    data = np.load(path)

    def walk(p, prefix):
        if isinstance(p, dict):
            return {k: walk(v, f"{prefix}{k}.") for k, v in p.items()}
        if isinstance(p, (list, tuple)):
            return [walk(v, f"{prefix}{i}.") for i, v in enumerate(p)]
        key = prefix[:-1]
        if key not in data:
            raise ValueError(f"distilled checkpoint {path!r} is missing {key!r}")
        arr = data[key]
        want = np.shape(p)
        if tuple(arr.shape) != tuple(want):
            raise ValueError(
                f"distilled checkpoint {path!r} {key!r} has shape "
                f"{tuple(arr.shape)}, the draft build wants {tuple(want)} — "
                "the checkpoint was trained for a different geometry"
            )
        return arr.astype(np.asarray(p).dtype)

    return walk(like, "")


# ------------------------------------------------------------- the recipe


def greedy_accept_proxy(target, draft, prompts: np.ndarray, max_new: int) -> float:
    """Per-position greedy acceptance probability: along the TARGET's own
    greedy continuation of each prompt, the fraction of generated
    positions where the draft's argmax equals the target's. This is
    exactly what the chain walk accepts per depth (and a lower bound per
    depth for a top-b tree), so it converts directly into expected
    accepted-tokens-per-dispatch."""
    import jax.numpy as jnp

    from seldon_core_tpu.models.decoder import generate, sequence_logits

    full = np.asarray(generate(target, jnp.asarray(prompts), max_new))
    # position j's logits row predicts token j+1 — compare predictions
    # for the GENERATED span only (the prompt is given, not predicted)
    tl = np.asarray(sequence_logits(target, jnp.asarray(full[:, :-1])))
    dl = np.asarray(sequence_logits(draft, jnp.asarray(full[:, :-1])))
    gen = slice(prompts.shape[1] - 1, full.shape[1] - 1)
    return float(
        np.mean(np.argmax(tl[:, gen], -1) == np.argmax(dl[:, gen], -1))
    )


def greedy_accept_proxy_features(
    target, head, prompts: np.ndarray, max_new: int
) -> float:
    """``greedy_accept_proxy`` for a FEATURE draft head: the head runs
    teacher-forced on the target's own hidden states along the target's
    greedy continuation — exactly the serving ROOT step's conditioning
    (the root always consumes the TRUE previous feature; deeper tree
    nodes autoregress on the head's own output, for which this is the
    per-depth upper-bound analogue of the chain proxy)."""
    import jax.numpy as jnp

    from seldon_core_tpu.models.decoder import (
        feature_sequence_logits, generate, sequence_hidden,
    )

    full = np.asarray(generate(target, jnp.asarray(prompts), max_new))
    tl, tf = sequence_hidden(target, jnp.asarray(full[:, :-1]))
    dl, _ = feature_sequence_logits(head, jnp.asarray(full[:, :-1]), tf)
    tl, dl = np.asarray(tl), np.asarray(dl)
    gen = slice(prompts.shape[1] - 1, full.shape[1] - 1)
    return float(
        np.mean(np.argmax(tl[:, gen], -1) == np.argmax(dl[:, gen], -1))
    )


def distill(
    *,
    seed: int = 0,
    vocab: int = 512,
    hidden: int = 256,
    layers: int = 4,
    ffn: int = 1024,
    max_len: int = 80,
    resid_scale: float = 1.0,
    draft_layers: int = 1,
    seq: int = 16,
    horizon: int = 48,
    batch: int = 16,
    steps: int = 300,
    lr: float = 1e-3,
    teacher_temp: float = 0.5,
    on_policy_frac: float = 0.5,
    eval_prompts: int = 16,
    out: str = "",
    log_every: int = 50,
    data_seed: int = 1234,
    features: bool = False,
    feat_weight: float = 1.0,
    feat_noise: float = 0.2,
    self_cond: float = 0.0,
    draft_ffn: int = 0,
) -> dict:
    """Distill a draft against its target; returns the report dict (accept
    proxy before/after, final KL) and writes the checkpoint to ``out``
    when set.

    ``features=False`` (default) trains the seed-shared layer-truncation
    draft (PR 8's recipe). ``features=True`` trains the EAGLE-style
    feature HEAD instead (models/decoder.init_feature_draft): the teacher
    supplies per-position hidden states beside its logits
    (``sequence_hidden``), the head runs teacher-forced on them, and the
    loss adds ``feat_weight`` x feature-regression MSE to the KL
    (training/steps.make_feature_distill_step) so the head's feature
    autoregression stays anchored; ``feat_noise`` perturbs the input
    features during training (the EAGLE augmentation for serving-time
    feature drift at depth — measured: without it deep-node accept
    collapses and the tree ride LOSES to the token draft). ``draft_ffn``
    sizes the head's FFN (0 = the target's ``ffn``)."""
    import jax.numpy as jnp
    import optax

    from seldon_core_tpu.models.decoder import (
        generate, init_decoder, init_feature_draft, sequence_hidden,
        sequence_logits,
    )
    from seldon_core_tpu.training.steps import (
        init_state, make_distill_step, make_feature_distill_step,
    )

    target = init_decoder(
        seed, vocab=vocab, hidden=hidden, layers=layers, ffn=ffn,
        max_len=max_len, resid_scale=resid_scale,
    )
    if features:
        draft = init_feature_draft(
            seed, vocab=vocab, hidden=hidden, ffn=draft_ffn or ffn, max_len=max_len
        )
        proxy = greedy_accept_proxy_features
    else:
        draft = init_decoder(
            seed, vocab=vocab, hidden=hidden, layers=draft_layers, ffn=ffn,
            max_len=max_len, resid_scale=resid_scale,
        )
        proxy = greedy_accept_proxy

    rng = np.random.default_rng(data_seed)
    eval_ids = rng.integers(0, vocab, (eval_prompts, seq)).astype(np.int32)
    accept_before = proxy(target, draft, eval_ids, horizon - seq)

    import jax

    opt = optax.adam(lr)
    if features:
        teacher = jax.jit(lambda ids: sequence_hidden(target, ids))
        step = jax.jit(
            make_feature_distill_step(
                opt, teacher_temp, feat_weight, feat_noise, self_cond
            )
        )
    else:
        teacher = jax.jit(lambda ids: (sequence_logits(target, ids), None))
        step = jax.jit(make_distill_step(sequence_logits, opt, teacher_temp))
    state = init_state(draft, opt)

    # on-policy pool: target-greedy continuations of random prompts,
    # regenerated sparsely (they are the expensive half of the data).
    # The teacher is FROZEN, so pool rows' logits (and, in feature mode,
    # hidden states) are computed once per refresh and gathered per step —
    # recomputing them every step would spend ~half the teacher forward
    # cost on targets that cannot change.
    def _teach(ids):
        t, f = teacher(jnp.asarray(ids))
        return np.asarray(t), (np.asarray(f) if f is not None else None)

    def on_policy_batch(n):
        p = rng.integers(0, vocab, (n, seq)).astype(np.int32)
        ids = np.asarray(generate(target, jnp.asarray(p), horizon - seq))
        return (ids,) + _teach(ids)

    pool, pool_t, pool_f = on_policy_batch(max(batch * 4, 32))
    kl = agree = fmse = float("nan")
    history = []
    for i in range(steps):
        n_on = int(round(batch * on_policy_frac))
        idx = rng.integers(0, len(pool), n_on) if n_on else None
        rand = rng.integers(0, vocab, (batch - n_on, horizon)).astype(np.int32)
        rand_t, rand_f = _teach(rand) if len(rand) else (None, None)
        if idx is not None:
            ids = np.concatenate([pool[idx], rand])
            t = (
                np.concatenate([pool_t[idx], rand_t])
                if rand_t is not None
                else pool_t[idx]
            )
            f = None
            if features:
                f = (
                    np.concatenate([pool_f[idx], rand_f])
                    if rand_f is not None
                    else pool_f[idx]
                )
        else:
            ids, t, f = rand, rand_t, rand_f
        batch_d = {"x": jnp.asarray(ids), "t": jnp.asarray(t)}
        if features:
            batch_d["f"] = jnp.asarray(f)
        state, m = step(state, batch_d)
        kl, agree = float(m["kl"]), float(m["top1_agreement"])
        if features:
            fmse = float(m["feat_mse"])
        if log_every and (i + 1) % log_every == 0:
            row = {"step": i + 1, "kl": round(kl, 4), "top1": round(agree, 4)}
            line = f"step {i+1:5d}  kl {kl:.4f}  top1 {agree:.4f}"
            if features:
                row["feat_mse"] = round(fmse, 4)
                line += f"  fmse {fmse:.4f}"
            history.append(row)
            print(line, flush=True)
        if (i + 1) % max(1, steps // 4) == 0:
            # refresh as the draft moves
            pool, pool_t, pool_f = on_policy_batch(len(pool))

    distilled = jax.tree.map(np.asarray, state.params)
    accept_after = proxy(target, distilled, eval_ids, horizon - seq)
    if out:
        save_draft_checkpoint(out, distilled)
    report = {
        "accept_proxy_before": round(accept_before, 4),
        "accept_proxy_after": round(accept_after, 4),
        "final_kl": round(kl, 4),
        "final_top1": round(agree, 4),
        "steps": steps,
        "features": bool(features),
        "history": history,
        "checkpoint": out or None,
        "geometry": {
            "seed": seed, "vocab": vocab, "hidden": hidden, "layers": layers,
            "ffn": ffn, "max_len": max_len, "resid_scale": resid_scale,
            "draft_layers": draft_layers,
        },
    }
    if features:
        report["final_feat_mse"] = round(fmse, 4)
        report["geometry"]["draft_ffn"] = draft_ffn or ffn
    return report


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--layers", type=int, default=4, help="TARGET layers")
    ap.add_argument("--ffn", type=int, default=1024)
    ap.add_argument("--max-len", type=int, default=80)
    ap.add_argument("--resid-scale", type=float, default=1.0)
    ap.add_argument("--draft-layers", type=int, default=1)
    ap.add_argument("--seq", type=int, default=16, help="prompt length")
    ap.add_argument(
        "--horizon", type=int, default=48,
        help="full training-sequence length (prompt + on-policy span)",
    )
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--steps", type=int, default=300)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument(
        "--teacher-temp", type=float, default=0.5,
        help="sharpen the teacher before the KL (<1 weights its argmax; "
        "1.0 is pure distribution-matching)",
    )
    ap.add_argument(
        "--on-policy-frac", type=float, default=0.5,
        help="fraction of each batch drawn from target-greedy continuations",
    )
    ap.add_argument("--out", default="", help="checkpoint path (.npz)")
    ap.add_argument("--log-every", type=int, default=50)
    ap.add_argument(
        "--features", action="store_true",
        help="train the EAGLE-style feature draft HEAD (target-hidden + "
        "token-embedding input) instead of the layer-truncation draft; "
        "serve via zoo://draft?features=1&distilled=...",
    )
    ap.add_argument(
        "--feat-weight", type=float, default=1.0,
        help="feature-regression MSE weight beside the KL (features mode)",
    )
    ap.add_argument(
        "--feat-noise", type=float, default=0.2,
        help="input-feature noise std fraction during training (features "
        "mode) — the EAGLE drift augmentation; 0 disables",
    )
    ap.add_argument(
        "--self-cond", type=float, default=0.0,
        help="weight of a self-conditioned second pass (features mode) — "
        "scheduled sampling in feature space. Ships DISABLED: on the "
        "bench pair it traded away depth-1 accuracy for less deep-drift "
        "than the noise augmentation already buys (PARITY r16)",
    )
    ap.add_argument(
        "--draft-ffn", type=int, default=0,
        help="feature head FFN width (0 = the target's --ffn)",
    )
    args = ap.parse_args(argv)
    report = distill(
        seed=args.seed, vocab=args.vocab, hidden=args.hidden, layers=args.layers,
        ffn=args.ffn, max_len=args.max_len, resid_scale=args.resid_scale,
        draft_layers=args.draft_layers, seq=args.seq, horizon=args.horizon,
        batch=args.batch, steps=args.steps, lr=args.lr,
        teacher_temp=args.teacher_temp,
        on_policy_frac=args.on_policy_frac, out=args.out,
        log_every=args.log_every,
        features=args.features, feat_weight=args.feat_weight,
        feat_noise=args.feat_noise, self_cond=args.self_cond,
        draft_ffn=args.draft_ffn,
    )
    print(json.dumps(report))


if __name__ == "__main__":
    main()
