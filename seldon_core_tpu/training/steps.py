"""Sharded training/fine-tuning steps over the device mesh.

Parity role: the reference is serving-only — the closest thing it has to
learning is the bandit Router feedback loop (engine/.../PredictiveUnitBean.java
sendFeedback + wrappers/python/router_microservice.py send_feedback). This
module is the TPU-native generalisation: reward/label feedback can fine-tune
the *model itself* on-device, not just a router's arm statistics.

Design:
- a train step is a pure function (state, batch) -> (state, metrics), built
  once and jitted with explicit in/out shardings over a Mesh;
- parallelism comes entirely from shardings: batch over "data", params over
  "model" (Megatron TP via the model's param_pspecs), activations' sequence
  axis over "seq" (GSPMD sequence parallelism — XLA inserts the attention
  all-gathers), so one step definition serves dp, tp, sp and combinations;
- optimizer state inherits the param shardings leaf-for-leaf (an Adam moment
  is sharded exactly like its parameter — same layout the scaling-book
  recipe prescribes), so optimizer memory also scales with 1/|model axis|.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Mapping

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

LogitsFn = Callable[[Any, jax.Array], jax.Array]


@jax.tree_util.register_pytree_node_class
@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array

    def tree_flatten(self):
        return (self.params, self.opt_state, self.step), None

    @classmethod
    def tree_unflatten(cls, _, children):
        return cls(*children)


def cross_entropy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))


def make_train_step(
    logits_fn: LogitsFn,
    optimizer: optax.GradientTransformation,
):
    """Unsharded (single-device / auto-sharded) train step."""

    def step(state: TrainState, batch: Mapping[str, jax.Array]):
        def loss_fn(p):
            logits = logits_fn(p, batch["x"])
            loss = cross_entropy(logits, batch["y"])
            acc = jnp.mean(
                (jnp.argmax(logits, axis=-1) == batch["y"]).astype(jnp.float32)
            )
            return loss, acc

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            TrainState(params, opt_state, state.step + 1),
            {"loss": loss, "accuracy": acc},
        )

    return step


def init_state(params: Any, optimizer: optax.GradientTransformation) -> TrainState:
    return TrainState(params, optimizer.init(params), jnp.zeros((), jnp.int32))


def kl_from_teacher(
    teacher_logits: jax.Array,
    student_logits: jax.Array,
    teacher_temp: float = 1.0,
) -> jax.Array:
    """Mean KL(teacher || student) over every position: the distillation
    objective — mass goes exactly where the teacher puts it, which for a
    speculative DRAFT is the quantity that becomes accept rate (greedy
    acceptance is argmax agreement; sampled acceptance is min(1, p/q)
    overlap — both are maximized by matching the teacher's distribution,
    not by one-hot cross-entropy on sampled tokens). ``teacher_temp`` < 1
    SHARPENS the teacher before the KL (τ -> 0 is cross-entropy on the
    teacher's argmax): for low-margin teachers — e.g. the depth-scaled
    resid_scale builds, whose softmax is near-uniform even where the
    argmax is stable — the unsharpened KL barely rewards ranking the
    teacher's top token first, which is exactly what greedy acceptance
    pays for."""
    t = jax.nn.log_softmax(
        teacher_logits.astype(jnp.float32) / teacher_temp, axis=-1
    )
    s = jax.nn.log_softmax(student_logits.astype(jnp.float32), axis=-1)
    return jnp.mean(jnp.sum(jnp.exp(t) * (t - s), axis=-1))


def make_distill_step(
    logits_fn: LogitsFn,
    optimizer: optax.GradientTransformation,
    teacher_temp: float = 1.0,
):
    """KL-distillation train step: batch = {"x": token ids [b, s],
    "t": teacher-forced TEACHER logits [b, s, vocab]} -> the student's
    sequence logits chase the teacher's at every position. Teacher logits
    ride the batch (computed once per batch by the caller, e.g. with
    models/decoder.sequence_logits) so the teacher itself never traces
    into the student's backward pass. Metrics: the KL itself and top-1
    agreement — the direct proxy for greedy speculative accept rate."""

    def step(state: TrainState, batch: Mapping[str, jax.Array]):
        def loss_fn(p):
            logits = logits_fn(p, batch["x"])
            loss = kl_from_teacher(batch["t"], logits, teacher_temp)
            agree = jnp.mean(
                (jnp.argmax(logits, axis=-1) == jnp.argmax(batch["t"], axis=-1))
                .astype(jnp.float32)
            )
            return loss, agree

        (loss, agree), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            TrainState(params, opt_state, state.step + 1),
            {"kl": loss, "top1_agreement": agree},
        )

    return step


def make_feature_distill_step(
    optimizer: optax.GradientTransformation,
    teacher_temp: float = 1.0,
    feat_weight: float = 1.0,
    feat_noise: float = 0.0,
    self_cond: float = 0.0,
):
    """KL + feature-regression distillation for the EAGLE-style feature
    draft head (models/decoder.init_feature_draft): batch = {"x": token
    ids [b, s], "t": teacher-forced TEACHER logits [b, s, vocab], "f":
    the teacher's final-layer hidden [b, s, d] (models/decoder.
    sequence_hidden)}. The head runs teacher-forced on the TRUE features
    (feature_sequence_logits — exactly the serving root step's
    conditioning); the loss is the same teacher-temp-sharpened KL as the
    token recipe PLUS ``feat_weight`` x MSE between the head's output
    hidden and the teacher's next feature — the regression that keeps the
    head's feature AUTOREGRESSION (deeper tree nodes feed on the head's
    own output) anchored to the target's feature manifold, per EAGLE.

    Two augmentations close the serving-time gap where deeper tree nodes
    consume the head's own APPROXIMATE features instead of the
    teacher-forced truth (without them the head overfits exact features
    and its accept collapses past depth 2 — measured): ``feat_noise``
    perturbs the INPUT features with Gaussian noise scaled by the batch's
    feature std (the regression target stays clean, the EAGLE noise
    trick), and ``self_cond`` adds a SECOND, self-conditioned forward —
    the same batch with the head's own stop-gradient feature estimates as
    inputs (scheduled sampling in feature space: exactly the depth-2
    conditioning the serving expansion runs) — whose KL+regression rides
    the loss at that weight. Metrics: kl, top1_agreement (the greedy
    accept proxy), feat_mse — both from the teacher-forced pass."""
    from seldon_core_tpu.models.decoder import feature_sequence_logits

    def step(state: TrainState, batch: Mapping[str, jax.Array]):
        f_in = batch["f"]
        if feat_noise > 0.0:
            key = jax.random.fold_in(jax.random.key(7), state.step)
            f_in = f_in + (
                feat_noise
                * jnp.std(f_in)
                * jax.random.normal(key, f_in.shape, jnp.float32)
            ).astype(f_in.dtype)

        def loss_fn(p):
            logits, head_feats = feature_sequence_logits(p, batch["x"], f_in)
            kl = kl_from_teacher(batch["t"], logits, teacher_temp)
            fmse = jnp.mean(
                (head_feats.astype(jnp.float32) - batch["f"].astype(jnp.float32))
                ** 2
            )
            agree = jnp.mean(
                (jnp.argmax(logits, axis=-1) == jnp.argmax(batch["t"], axis=-1))
                .astype(jnp.float32)
            )
            loss = kl + feat_weight * fmse
            if self_cond > 0.0:
                f_self = jax.lax.stop_gradient(head_feats)
                logits2, head_feats2 = feature_sequence_logits(
                    p, batch["x"], f_self
                )
                kl2 = kl_from_teacher(batch["t"], logits2, teacher_temp)
                fmse2 = jnp.mean(
                    (
                        head_feats2.astype(jnp.float32)
                        - batch["f"].astype(jnp.float32)
                    )
                    ** 2
                )
                loss = loss + self_cond * (kl2 + feat_weight * fmse2)
            return loss, (kl, fmse, agree)

        (_, (kl, fmse, agree)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return (
            TrainState(params, opt_state, state.step + 1),
            {"kl": kl, "top1_agreement": agree, "feat_mse": fmse},
        )

    return step


def shard_state(
    state: TrainState, mesh: Mesh, param_pspecs: Any | None
) -> tuple[TrainState, Any]:
    """device_put state with param shardings; opt-state leaves inherit the
    sharding of the parameter they track (matching pytree prefix)."""
    if param_pspecs is None:
        param_pspecs = jax.tree.map(lambda _: P(), state.params)

    def to_sharding(spec):
        return NamedSharding(mesh, spec if isinstance(spec, P) else P())

    param_sh = jax.tree.map(
        to_sharding, param_pspecs, is_leaf=lambda x: isinstance(x, P) or x is None
    )

    # broadcast param shardings onto the (possibly nested) optimizer state:
    # optax states are pytrees whose leaves either mirror params (mu, nu) or
    # are scalars (count) — match by tree structure, default replicated.
    params_treedef = jax.tree.structure(state.params)

    def opt_shardings(opt_state):
        def map_one(node):
            try:
                if jax.tree.structure(node) == params_treedef:
                    return param_sh
            except Exception:
                pass
            return jax.tree.map(lambda _: NamedSharding(mesh, P()), node)

        # optax wraps states in tuples/namedtuples; walk one level
        if isinstance(opt_state, tuple) and type(opt_state) is not tuple:
            return type(opt_state)(*(opt_shardings(s) for s in opt_state))
        if isinstance(opt_state, tuple):
            return tuple(opt_shardings(s) for s in opt_state)
        return map_one(opt_state)

    opt_sh = opt_shardings(state.opt_state)
    state_sh = TrainState(param_sh, opt_sh, NamedSharding(mesh, P()))
    sharded = jax.device_put(state, state_sh)
    return sharded, state_sh


def make_sharded_train_step(
    logits_fn: LogitsFn,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    param_pspecs: Any | None,
    *,
    batch_pspec: P = P("data"),
    label_pspec: P = P("data"),
    init_params: Any = None,
):
    """Build (jitted_step, sharded_state, shardings) for a mesh.

    batch_pspec defaults to data-parallel; pass P("data", "seq") to also
    shard the sequence axis (sequence parallelism) — XLA derives the
    attention collectives from the sharding annotations.
    """
    state = init_state(init_params, optimizer)
    sharded_state, state_sh = shard_state(state, mesh, param_pspecs)
    step = make_train_step(logits_fn, optimizer)
    batch_sh = {
        "x": NamedSharding(mesh, batch_pspec),
        "y": NamedSharding(mesh, label_pspec),
    }
    metric_sh = {"loss": NamedSharding(mesh, P()), "accuracy": NamedSharding(mesh, P())}
    jitted = jax.jit(
        step,
        in_shardings=(state_sh, batch_sh),
        out_shardings=(state_sh, metric_sh),
        donate_argnums=(0,),
    )
    return jitted, sharded_state, batch_sh
