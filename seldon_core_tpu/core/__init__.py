from seldon_core_tpu.core.message import (
    DefaultData,
    Feedback,
    Meta,
    RequestResponse,
    SeldonMessage,
    Status,
    StatusFlag,
)
from seldon_core_tpu.core.codec_json import (
    feedback_from_json,
    feedback_to_json,
    message_from_json,
    message_to_json,
)
from seldon_core_tpu.core.errors import APIException, ErrorCode
from seldon_core_tpu.core.puid import new_puid

__all__ = [
    "APIException",
    "DefaultData",
    "ErrorCode",
    "Feedback",
    "Meta",
    "RequestResponse",
    "SeldonMessage",
    "Status",
    "StatusFlag",
    "feedback_from_json",
    "feedback_to_json",
    "message_from_json",
    "message_to_json",
    "new_puid",
]
