"""Protobuf wire codec for SeldonMessage / Feedback.

Counterpart of codec_json for the gRPC edge (reference parity: the engine's
proto handling in SeldonService.java + PredictorUtils.java tensor bridge).
Wire format is compatible with the reference contract — field numbers match
(see proto/prediction.proto header).
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

import numpy as np
from google.protobuf import struct_pb2

from seldon_core_tpu.core.message import (
    DataKind,
    DefaultData,
    Feedback,
    Meta,
    RequestResponse,
    SeldonMessage,
    Status,
    StatusFlag,
)
from seldon_core_tpu.proto import prediction_pb2 as pb

DEFAULT_DTYPE = np.float32


# ---------------------------------------------------------------- value glue


def _value_to_py(v: struct_pb2.Value) -> Any:
    kind = v.WhichOneof("kind")
    if kind == "number_value":
        return v.number_value
    if kind == "string_value":
        return v.string_value
    if kind == "bool_value":
        return v.bool_value
    if kind == "list_value":
        return [_value_to_py(x) for x in v.list_value.values]
    if kind == "struct_value":
        return {k: _value_to_py(x) for k, x in v.struct_value.fields.items()}
    return None


def _py_to_value(obj: Any) -> struct_pb2.Value:
    v = struct_pb2.Value()
    if obj is None:
        v.null_value = struct_pb2.NULL_VALUE
    elif isinstance(obj, bool):
        v.bool_value = obj
    elif isinstance(obj, (int, float)):
        v.number_value = float(obj)
    elif isinstance(obj, str):
        v.string_value = obj
    elif isinstance(obj, (list, tuple)):
        v.list_value.values.extend(_py_to_value(x) for x in obj)
    elif isinstance(obj, Mapping):
        for k, x in obj.items():
            v.struct_value.fields[k].CopyFrom(_py_to_value(x))
    else:
        v.string_value = str(obj)
    return v


def _ndarray_to_listvalue(arr: np.ndarray) -> struct_pb2.ListValue:
    lv = struct_pb2.ListValue()

    def fill(target: struct_pb2.ListValue, a) -> None:
        if a.ndim == 1:
            for x in a:
                target.values.append(struct_pb2.Value(number_value=float(x)))
            return
        for row in a:
            v = target.values.add()
            fill(v.list_value, row)

    fill(lv, np.asarray(arr, dtype=np.float64))
    return lv


def _listvalue_to_ndarray(lv: struct_pb2.ListValue, dtype) -> np.ndarray:
    return np.asarray([_value_to_py(v) for v in lv.values], dtype=dtype)


# ------------------------------------------------------------------- decode


def message_from_proto(m: pb.SeldonMessage, dtype: Any = DEFAULT_DTYPE) -> SeldonMessage:
    meta = Meta(
        puid=m.meta.puid,
        tags={k: _value_to_py(v) for k, v in m.meta.tags.items()},
        routing=dict(m.meta.routing),
        request_path=dict(m.meta.requestPath),
    )
    status = None
    if m.HasField("status"):
        status = Status(
            code=m.status.code,
            info=m.status.info,
            reason=m.status.reason,
            status=StatusFlag(m.status.status),
        )
    arm = m.WhichOneof("data_oneof")
    if arm == "data":
        names = tuple(m.data.names)
        d_arm = m.data.WhichOneof("data_oneof")
        if d_arm == "tensor":
            values = np.fromiter(
                m.data.tensor.values, dtype=np.float64, count=len(m.data.tensor.values)
            ).astype(dtype)
            shape = tuple(m.data.tensor.shape)
            array = values.reshape(shape) if shape else values
            data = DefaultData(names=names, array=array, kind=DataKind.TENSOR)
        else:
            data = DefaultData(
                names=names,
                array=_listvalue_to_ndarray(m.data.ndarray, dtype),
                kind=DataKind.NDARRAY,
            )
        return SeldonMessage(data=data, meta=meta, status=status)
    if arm == "binData":
        return SeldonMessage(bin_data=m.binData, meta=meta, status=status)
    if arm == "strData":
        return SeldonMessage(str_data=m.strData, meta=meta, status=status)
    return SeldonMessage(meta=meta, status=status)


def feedback_from_proto(f: pb.Feedback, dtype: Any = DEFAULT_DTYPE) -> Feedback:
    return Feedback(
        request=message_from_proto(f.request, dtype) if f.HasField("request") else None,
        response=message_from_proto(f.response, dtype) if f.HasField("response") else None,
        reward=f.reward,
        truth=message_from_proto(f.truth, dtype) if f.HasField("truth") else None,
    )


# ------------------------------------------------------------------- encode


def message_to_proto(msg: SeldonMessage) -> pb.SeldonMessage:
    m = pb.SeldonMessage()
    m.meta.puid = msg.meta.puid
    for k, v in msg.meta.tags.items():
        m.meta.tags[k].CopyFrom(_py_to_value(v))
    for k, v in msg.meta.routing.items():
        m.meta.routing[k] = int(v)
    for k, v in msg.meta.request_path.items():
        m.meta.requestPath[k] = str(v)
    if msg.status is not None:
        m.status.code = msg.status.code
        m.status.info = msg.status.info
        m.status.reason = msg.status.reason
        m.status.status = int(msg.status.status)
    if msg.data is not None:
        m.data.names.extend(msg.data.names)
        arr = np.asarray(msg.data.array)
        if msg.data.kind == DataKind.NDARRAY:
            m.data.ndarray.CopyFrom(_ndarray_to_listvalue(arr))
        else:
            m.data.tensor.shape.extend(int(s) for s in arr.shape)
            m.data.tensor.values.extend(arr.reshape(-1).astype(np.float64).tolist())
    elif msg.bin_data is not None:
        m.binData = msg.bin_data
    elif msg.str_data is not None:
        m.strData = msg.str_data
    return m


def feedback_to_proto(fb: Feedback) -> pb.Feedback:
    f = pb.Feedback()
    if fb.request is not None:
        f.request.CopyFrom(message_to_proto(fb.request))
    if fb.response is not None:
        f.response.CopyFrom(message_to_proto(fb.response))
    f.reward = float(fb.reward)
    if fb.truth is not None:
        f.truth.CopyFrom(message_to_proto(fb.truth))
    return f


def message_list_to_proto(msgs: Sequence[SeldonMessage]) -> pb.SeldonMessageList:
    out = pb.SeldonMessageList()
    for m in msgs:
        out.seldonMessages.append(message_to_proto(m))
    return out


def message_list_from_proto(ml: pb.SeldonMessageList, dtype: Any = DEFAULT_DTYPE):
    return [message_from_proto(m, dtype) for m in ml.seldonMessages]
