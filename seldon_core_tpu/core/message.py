"""Typed message model — the framework-wide data contract.

Parity target: the reference wire contract in
``/root/reference/proto/prediction.proto:12-69`` (SeldonMessage / DefaultData /
Tensor / Meta / Status / Feedback). Design difference: instead of a protobuf
``Tensor{shape,values-as-double}`` that every hop re-serialises, ``DefaultData``
holds a live ``numpy``/``jax.Array`` so a message can flow through an in-process
graph — and onto the TPU — with zero copies. Codecs (JSON / proto) live in
``codec_json.py`` / ``codec_proto.py`` and only run at the process edge.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Mapping, Sequence

import numpy as np

Array = Any  # np.ndarray | jax.Array — kept loose so core has no jax import cost


class StatusFlag(enum.IntEnum):
    SUCCESS = 0
    FAILURE = 1


@dataclass(frozen=True)
class Status:
    """Mirrors reference Status (prediction.proto:46-57)."""

    code: int = 200
    info: str = ""
    reason: str = ""
    status: StatusFlag = StatusFlag.SUCCESS


class DataKind(enum.Enum):
    """Which wire form DefaultData serialises back to (tensor vs ndarray)."""

    TENSOR = "tensor"
    NDARRAY = "ndarray"


@dataclass(frozen=True)
class DefaultData:
    """Named tensor payload (reference prediction.proto:23-34).

    ``array`` is the single in-memory representation; ``kind`` only records
    which JSON/proto encoding the client used so responses round-trip in the
    same form (the reference keeps Tensor and ListValue as distinct oneof arms).
    """

    names: tuple[str, ...] = ()
    array: Array | None = None
    kind: DataKind = DataKind.TENSOR

    def with_array(self, array: Array, names: Sequence[str] | None = None) -> "DefaultData":
        return DefaultData(
            names=tuple(names) if names is not None else self.names,
            array=array,
            kind=self.kind,
        )

    @property
    def shape(self) -> tuple[int, ...]:
        if self.array is None:
            return ()
        return tuple(int(d) for d in self.array.shape)


@dataclass(frozen=True)
class Meta:
    """Request metadata (reference prediction.proto:36-40).

    ``routing`` records, per graph-node name, which child index a ROUTER chose
    (-1 = all children). Feedback replays down exactly this recorded path —
    the bandit-learning loop depends on it (reference
    PredictiveUnitBean.sendFeedback:126-154).
    """

    puid: str = ""
    tags: Mapping[str, Any] = field(default_factory=dict)
    routing: Mapping[str, int] = field(default_factory=dict)
    # requestPath: nodeName -> model image (we use runtime id); additive over the
    # reference's Meta, used for tracing (SURVEY §5.1: puid as trace id).
    request_path: Mapping[str, str] = field(default_factory=dict)

    def merged_with(self, other: "Meta") -> "Meta":
        """Merge rule from reference PredictiveUnitBean.mergeMeta:252-264:
        tags are union-merged (child wins on conflict), puid preserved from the
        request, routing entries accumulate.

        The no-op short-circuits matter: a graph walk merges meta at every
        node boundary and most merges carry nothing new — at serving rates
        the dict spreads below are real CPU."""
        if other is self:
            return self
        if not (other.tags or other.routing or other.request_path) and (
            self.puid or not other.puid
        ):
            return self
        if not (self.tags or self.routing or self.request_path) and not self.puid:
            return other
        return Meta(
            puid=self.puid or other.puid,
            tags={**self.tags, **other.tags},
            routing={**self.routing, **other.routing},
            request_path={**self.request_path, **other.request_path},
        )


@dataclass(frozen=True)
class SeldonMessage:
    """The one message type every graph node consumes and produces
    (reference prediction.proto:12-21). Exactly one of data/bin_data/str_data
    /json_data is set (oneof semantics); ``data`` is the TPU fast path.
    """

    data: DefaultData | None = None
    bin_data: bytes | None = None
    str_data: str | None = None
    json_data: Any | None = None  # forward-compat arm (later seldon versions)
    meta: Meta = field(default_factory=Meta)
    status: Status | None = None

    def __post_init__(self) -> None:
        set_arms = [
            x is not None for x in (self.data, self.bin_data, self.str_data, self.json_data)
        ]
        if sum(set_arms) > 1:
            raise ValueError("SeldonMessage: at most one data arm may be set (oneof)")

    # -- convenience constructors -------------------------------------------------
    @staticmethod
    def from_array(
        array: Array,
        names: Sequence[str] = (),
        meta: Meta | None = None,
        kind: DataKind = DataKind.TENSOR,
    ) -> "SeldonMessage":
        return SeldonMessage(
            data=DefaultData(names=tuple(names), array=array, kind=kind),
            meta=meta or Meta(),
        )

    @staticmethod
    def failure(code: int, reason: str, info: str = "") -> "SeldonMessage":
        return SeldonMessage(
            status=Status(code=code, info=info, reason=reason, status=StatusFlag.FAILURE)
        )

    # -- accessors ---------------------------------------------------------------
    @property
    def array(self) -> Array | None:
        return self.data.array if self.data is not None else None

    @property
    def names(self) -> tuple[str, ...]:
        return self.data.names if self.data is not None else ()

    # The with_* updates below construct via object.__new__ instead of
    # dataclasses.replace: replace() re-introspects fields and re-runs
    # __post_init__ on every call (~4 us), and these run several times per
    # request on the serving hot path. Each method sets EVERY field and
    # keeps the oneof invariant by construction (exactly one arm non-None).
    def _copy(self, data, bin_data, str_data, json_data, meta, status) -> "SeldonMessage":
        new = object.__new__(SeldonMessage)
        object.__setattr__(new, "data", data)
        object.__setattr__(new, "bin_data", bin_data)
        object.__setattr__(new, "str_data", str_data)
        object.__setattr__(new, "json_data", json_data)
        object.__setattr__(new, "meta", meta)
        object.__setattr__(new, "status", status)
        return new

    def with_array(self, array: Array, names: Sequence[str] | None = None) -> "SeldonMessage":
        """Functional update of the payload, preserving meta/kind. Setting
        the tensor arm REPLACES the payload: the other oneof arms clear (a
        unit that produces a tensor from a binData/strData request must not
        leave the stale bytes beside it)."""
        base = self.data if self.data is not None else DefaultData()
        return self._copy(
            base.with_array(array, names), None, None, None, self.meta, self.status
        )

    def with_bin_data(self, raw: bytes) -> "SeldonMessage":
        """Replace the payload with bytes (clears the other oneof arms)."""
        return self._copy(None, bytes(raw), None, None, self.meta, self.status)

    def with_str_data(self, text: str) -> "SeldonMessage":
        """Replace the payload with a string (clears the other oneof arms)."""
        return self._copy(None, None, text, None, self.meta, self.status)

    def with_meta(self, meta: Meta) -> "SeldonMessage":
        if meta is self.meta:
            return self
        return self._copy(
            self.data, self.bin_data, self.str_data, self.json_data, meta, self.status
        )

    def with_array_meta(
        self, array: Array, meta: Meta, names: Sequence[str] | None = None
    ) -> "SeldonMessage":
        """Payload + meta update in ONE copy (batch scatter paths build a
        per-request message from a merged result; two chained with_* calls
        would construct an intermediate that is immediately discarded)."""
        base = self.data if self.data is not None else DefaultData()
        return self._copy(base.with_array(array, names), None, None, None, meta, self.status)

    def is_failure(self) -> bool:
        return self.status is not None and self.status.status == StatusFlag.FAILURE


@dataclass(frozen=True)
class Feedback:
    """Reward feedback (reference prediction.proto:59-64)."""

    request: SeldonMessage | None = None
    response: SeldonMessage | None = None
    reward: float = 0.0
    truth: SeldonMessage | None = None


@dataclass(frozen=True)
class RequestResponse:
    """Audit-log pair (reference prediction.proto:66-69; Kafka payload C17)."""

    request: SeldonMessage | None = None
    response: SeldonMessage | None = None


def messages_arrays(messages: Sequence[SeldonMessage]) -> list[Array]:
    """Extract payload arrays from a list of messages (combiner input),
    failing loudly on non-tensor arms."""
    out = []
    for i, m in enumerate(messages):
        if m.array is None:
            raise ValueError(f"message {i} has no tensor payload")
        out.append(m.array)
    return out


def np_array(msg: SeldonMessage) -> np.ndarray:
    """Payload as a host numpy array (device arrays transfer)."""
    a = msg.array
    if a is None:
        raise ValueError("message has no tensor payload")
    return np.asarray(a)
