"""Prediction-UID generation.

Parity: reference engine PredictionService (engine/.../service/
PredictionService.java:52-57,71-78) generates a 130-bit SecureRandom integer
rendered in base32 and assigns it when a request has no puid. Same contract
here: 130 bits, base32 (RFC 4648 lowercase, no padding), assigned-if-missing.
"""

from __future__ import annotations

import secrets

_ALPHABET = "0123456789abcdefghijklmnopqrstuv"  # base32, matches Java BigInteger.toString(32)


def new_puid(bits: int = 130) -> str:
    n = secrets.randbits(bits)
    if n == 0:
        return "0"
    digits = []
    while n:
        digits.append(_ALPHABET[n & 31])
        n >>= 5
    return "".join(reversed(digits))
