"""Prediction-UID generation.

Parity: reference engine PredictionService (engine/.../service/
PredictionService.java:52-57,71-78) generates a 130-bit SecureRandom integer
rendered in base32 and assigns it when a request has no puid. Same entropy
and digit set here, with one deliberate format difference: the Java
BigInteger.toString(32) emits variable-length output (no leading zeros);
this implementation emits a FIXED 26-character string, leading '0' digits
included — fixed width keeps generation allocation-free and log fields
aligned, and no consumer parses the puid numerically.
"""

from __future__ import annotations

import os

_ALPHABET = "0123456789abcdefghijklmnopqrstuv"  # digit set of Java BigInteger.toString(32)


def new_puid(bits: int = 130) -> str:
    # one os.urandom read + a byte->digit map: ~3 us where
    # secrets.randbits + an int division loop costs ~12 us — puids are
    # minted once per request on the serving hot path. ceil(bits/5) digits
    # of 5 bits each = the same 130-bit entropy / 26-char base32 contract.
    n_digits = -(-bits // 5)
    raw = os.urandom(n_digits)
    return "".join([_ALPHABET[b & 31] for b in raw])
