"""Prediction-UID generation.

Parity: reference engine PredictionService (engine/.../service/
PredictionService.java:52-57,71-78) generates a 130-bit SecureRandom integer
rendered in base32 and assigns it when a request has no puid. Same contract
here: 130 bits, base32 (RFC 4648 lowercase, no padding), assigned-if-missing.
"""

from __future__ import annotations

import os

_ALPHABET = "0123456789abcdefghijklmnopqrstuv"  # base32, matches Java BigInteger.toString(32)


def new_puid(bits: int = 130) -> str:
    # one os.urandom read + a byte->digit map: ~3 us where
    # secrets.randbits + an int division loop costs ~12 us — puids are
    # minted once per request on the serving hot path. ceil(bits/5) digits
    # of 5 bits each = the same 130-bit entropy / 26-char base32 contract.
    n_digits = -(-bits // 5)
    raw = os.urandom(n_digits)
    return "".join([_ALPHABET[b & 31] for b in raw])
