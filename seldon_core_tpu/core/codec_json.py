"""JSON wire codec for SeldonMessage / Feedback.

Wire shapes match the reference's external API docs
(/root/reference/docs/reference/prediction.md and internal-api.md):

    {"meta": {"puid": ..., "tags": {...}, "routing": {...}},
     "data": {"names": [...], "tensor": {"shape": [...], "values": [...]}}}
    {"data": {"names": [...], "ndarray": [[...], ...]}}
    {"binData": "<base64>"} | {"strData": "..."}
    {"status": {"code": ..., "info": ..., "reason": ..., "status": "FAILURE"}}

The codec is the *edge only*: inside the graph a message carries a live array.
A native C++ fast path for the hot float-parsing loop lives in
seldon_core_tpu/native (used automatically when built); this module is the
always-available pure-Python implementation.
"""

from __future__ import annotations

import base64
import json
from typing import Any, Mapping

import numpy as np

from seldon_core_tpu.core.errors import APIException, ErrorCode
from seldon_core_tpu.core.message import (
    DataKind,
    DefaultData,
    Feedback,
    Meta,
    RequestResponse,
    SeldonMessage,
    Status,
    StatusFlag,
)

DEFAULT_DTYPE = np.float32  # TPU-friendly; reference wire format is float64


# ---------------------------------------------------------------- decode


def _decode_default_data(obj: Mapping[str, Any], dtype: Any) -> DefaultData:
    names = tuple(obj.get("names") or ())
    if "tensor" in obj:
        t = obj["tensor"]
        try:
            values = np.asarray(t.get("values", []), dtype=dtype)
            shape = tuple(int(s) for s in t.get("shape", []))
            array = values.reshape(shape) if shape else values
        except (ValueError, TypeError) as e:
            raise APIException(ErrorCode.ENGINE_INVALID_JSON, f"bad tensor: {e}") from e
        return DefaultData(names=names, array=array, kind=DataKind.TENSOR)
    if "ndarray" in obj:
        try:
            array = np.asarray(obj["ndarray"], dtype=dtype)
        except (ValueError, TypeError):
            # non-numeric payloads (e.g. string categoricals) keep numpy's
            # inferred dtype — the reference microservice does the same
            # (rest_datadef_to_array: plain np.array); numeric-only models
            # fail later with a clear shape/dtype error
            try:
                array = np.asarray(obj["ndarray"])
            except (ValueError, TypeError) as e:
                raise APIException(
                    ErrorCode.ENGINE_INVALID_JSON, f"bad ndarray: {e}"
                ) from e
        return DefaultData(names=names, array=array, kind=DataKind.NDARRAY)
    raise APIException(ErrorCode.ENGINE_INVALID_JSON, "data must contain tensor or ndarray")


def _decode_meta(obj: Mapping[str, Any] | None) -> Meta:
    if not obj:
        return Meta()
    return Meta(
        puid=obj.get("puid", ""),
        tags=dict(obj.get("tags") or {}),
        routing={k: int(v) for k, v in (obj.get("routing") or {}).items()},
        request_path=dict(obj.get("requestPath") or {}),
    )


def _decode_status(obj: Mapping[str, Any] | None) -> Status | None:
    if not obj:
        return None
    flag = obj.get("status", "SUCCESS")
    return Status(
        code=int(obj.get("code", 200)),
        info=obj.get("info", ""),
        reason=obj.get("reason", ""),
        status=StatusFlag.FAILURE if flag in ("FAILURE", 1) else StatusFlag.SUCCESS,
    )


def message_from_dict(obj: Mapping[str, Any], dtype: Any = DEFAULT_DTYPE) -> SeldonMessage:
    if not isinstance(obj, Mapping):
        raise APIException(ErrorCode.ENGINE_INVALID_JSON, "message must be a JSON object")
    meta = _decode_meta(obj.get("meta"))
    status = _decode_status(obj.get("status"))
    if "data" in obj:
        return SeldonMessage(data=_decode_default_data(obj["data"], dtype), meta=meta, status=status)
    if "binData" in obj:
        try:
            raw = base64.b64decode(obj["binData"])
        except Exception as e:  # noqa: BLE001 - normalise any b64 failure
            raise APIException(ErrorCode.ENGINE_INVALID_JSON, f"bad binData: {e}") from e
        return SeldonMessage(bin_data=raw, meta=meta, status=status)
    if "strData" in obj:
        return SeldonMessage(str_data=str(obj["strData"]), meta=meta, status=status)
    if "jsonData" in obj:
        return SeldonMessage(json_data=obj["jsonData"], meta=meta, status=status)
    # bare status/meta message (e.g. feedback ack) is legal
    return SeldonMessage(meta=meta, status=status)


def message_from_json(text: str | bytes, dtype: Any = DEFAULT_DTYPE) -> SeldonMessage:
    try:
        obj = json.loads(text)
    except json.JSONDecodeError as e:
        raise APIException(ErrorCode.ENGINE_INVALID_JSON, str(e)) from e
    return message_from_dict(obj, dtype)


# Below this body size the C matrix codec LOSES: the span-search + splice +
# ctypes call overhead (~20 us) dwarfs parsing a few dozen numbers in pure
# Python (~8 us). Measured crossover is around a few KB of digits.
_SMALL_BODY_BYTES = 4096


def message_from_json_fast(raw: bytes, dtype: Any = DEFAULT_DTYPE) -> SeldonMessage:
    """Hot-path decode: the ndarray number matrix (the bulk of the body)
    parses in C (native/fastcodec) and the small envelope in Python json;
    any deviation falls back to the pure-Python path, which stays the
    semantic source of truth. Small bodies skip the C path entirely."""
    if len(raw) < _SMALL_BODY_BYTES:
        return message_from_json(raw, dtype)
    if dtype is DEFAULT_DTYPE:
        from seldon_core_tpu import native

        span = native.find_ndarray_span(raw)
        if span is not None:
            s, e = span
            array = native.parse_ndarray(raw[s:e])
            if array is not None:
                try:
                    obj = json.loads(raw[:s] + b"null" + raw[e:])
                except json.JSONDecodeError as exc:
                    raise APIException(ErrorCode.ENGINE_INVALID_JSON, str(exc)) from exc
                data = obj.get("data")
                # the spliced null must be THIS message's data.ndarray (not a
                # nested request's), and tensor must not also be present (the
                # oracle prefers tensor when both exist); otherwise fall back
                if (
                    isinstance(data, Mapping)
                    and data.get("ndarray", "") is None
                    and "tensor" not in data
                ):
                    msg = message_from_dict(
                        {k: v for k, v in obj.items() if k != "data"}, dtype
                    )
                    return SeldonMessage(
                        data=DefaultData(
                            names=tuple(data.get("names") or ()),
                            array=array,
                            kind=DataKind.NDARRAY,
                        ),
                        meta=msg.meta,
                        status=msg.status,
                    )
    return message_from_json(raw, dtype)


def feedback_from_dict(obj: Mapping[str, Any], dtype: Any = DEFAULT_DTYPE) -> Feedback:
    return Feedback(
        request=message_from_dict(obj["request"], dtype) if "request" in obj else None,
        response=message_from_dict(obj["response"], dtype) if "response" in obj else None,
        reward=float(obj.get("reward", 0.0)),
        truth=message_from_dict(obj["truth"], dtype) if "truth" in obj else None,
    )


def feedback_from_json(text: str | bytes, dtype: Any = DEFAULT_DTYPE) -> Feedback:
    try:
        obj = json.loads(text)
    except json.JSONDecodeError as e:
        raise APIException(ErrorCode.ENGINE_INVALID_JSON, str(e)) from e
    return feedback_from_dict(obj, dtype)


# ---------------------------------------------------------------- encode


def _encode_array(data: DefaultData) -> dict[str, Any]:
    out: dict[str, Any] = {}
    if data.names:
        out["names"] = list(data.names)
    arr = np.asarray(data.array)
    if data.kind == DataKind.NDARRAY:
        out["ndarray"] = arr.tolist()
    else:
        out["tensor"] = {
            "shape": [int(s) for s in arr.shape],
            "values": arr.reshape(-1).astype(np.float64).tolist(),
        }
    return out


def _encode_meta(meta: Meta) -> dict[str, Any]:
    out: dict[str, Any] = {"puid": meta.puid}
    if meta.tags:
        out["tags"] = dict(meta.tags)
    if meta.routing:
        out["routing"] = dict(meta.routing)
    if meta.request_path:
        out["requestPath"] = dict(meta.request_path)
    return out


# public aliases: binary-response paths ship meta out-of-band (HTTP header)
meta_to_dict = _encode_meta
meta_from_dict = _decode_meta


def message_to_dict(msg: SeldonMessage) -> dict[str, Any]:
    out: dict[str, Any] = {"meta": _encode_meta(msg.meta)}
    if msg.status is not None:
        out["status"] = {
            "code": msg.status.code,
            "info": msg.status.info,
            "reason": msg.status.reason,
            "status": msg.status.status.name,
        }
    if msg.data is not None:
        out["data"] = _encode_array(msg.data)
    elif msg.bin_data is not None:
        out["binData"] = base64.b64encode(msg.bin_data).decode("ascii")
    elif msg.str_data is not None:
        out["strData"] = msg.str_data
    elif msg.json_data is not None:
        out["jsonData"] = msg.json_data
    return out


def message_to_json(msg: SeldonMessage) -> str:
    return json.dumps(message_to_dict(msg))


def message_to_json_fast(msg: SeldonMessage) -> bytes:
    """Hot-path encode: the response ndarray serializes in C, the envelope
    in Python json with a placeholder splice. Falls back to message_to_json
    for anything but a float 2D ndarray payload."""
    arr = np.asarray(msg.data.array) if msg.data is not None else None
    if (
        arr is not None
        and msg.data.kind == DataKind.NDARRAY
        and arr.ndim == 2
        and arr.size > 256  # small matrices: tolist+dumps beats the C call
        and arr.dtype == np.float32  # f64 would silently lose precision in C
    ):
        from seldon_core_tpu import native

        body = native.encode_ndarray(np.asarray(msg.data.array))
        if body is not None:
            # build the envelope WITHOUT ever calling arr.tolist() (that is
            # the cost this path exists to avoid)
            obj: dict[str, Any] = {"meta": _encode_meta(msg.meta)}
            if msg.status is not None:
                obj["status"] = {
                    "code": msg.status.code,
                    "info": msg.status.info,
                    "reason": msg.status.reason,
                    "status": msg.status.status.name,
                }
            data: dict[str, Any] = {}
            if msg.data.names:
                data["names"] = list(msg.data.names)
            data["ndarray"] = "\x00NDARRAY\x00"
            obj["data"] = data
            text = json.dumps(obj).encode()
            # data is inserted LAST, so its placeholder is the rightmost
            # occurrence — a client-forged copy of the sentinel in meta tags
            # or names can never be the one spliced
            head, sep, tail = text.rpartition(b'"\\u0000NDARRAY\\u0000"')
            return head + body + tail
    return message_to_json(msg).encode()


def feedback_to_dict(fb: Feedback) -> dict[str, Any]:
    out: dict[str, Any] = {"reward": fb.reward}
    if fb.request is not None:
        out["request"] = message_to_dict(fb.request)
    if fb.response is not None:
        out["response"] = message_to_dict(fb.response)
    if fb.truth is not None:
        out["truth"] = message_to_dict(fb.truth)
    return out


def feedback_to_json(fb: Feedback) -> str:
    return json.dumps(feedback_to_dict(fb))


def request_response_to_dict(rr: RequestResponse) -> dict[str, Any]:
    return {
        "request": message_to_dict(rr.request) if rr.request else None,
        "response": message_to_dict(rr.response) if rr.response else None,
    }
