"""Error taxonomy.

Parity: reference engine APIException enum
(engine/src/main/java/io/seldon/engine/exception/APIException.java) and the
api-frontend variant (APIFE_* codes), plus the Python microservice error JSON
(wrappers/python/microservice.py:29-30). The numeric codes and names are kept
so clients/dashboards written against the reference keep working.
"""

from __future__ import annotations

import enum


class ErrorCode(enum.Enum):
    # (code, http_status, message) — engine taxonomy
    ENGINE_INVALID_JSON = (101, 400, "Invalid JSON")
    ENGINE_INVALID_ENDPOINT_URL = (102, 500, "Invalid endpoint URL")
    ENGINE_MICROSERVICE_ERROR = (103, 500, "Microservice error")
    ENGINE_INVALID_ABTEST = (104, 500, "Error happened in AB Test routing")
    ENGINE_INVALID_ROUTING = (105, 500, "Invalid graph routing")
    ENGINE_INVALID_RESPONSE = (106, 500, "Invalid microservice response")
    # api-frontend taxonomy
    APIFE_INVALID_JSON = (201, 400, "Invalid JSON")
    APIFE_INVALID_ENDPOINT_URL = (202, 500, "Invalid endpoint URL")
    APIFE_MICROSERVICE_ERROR = (203, 500, "Microservice error")
    APIFE_NO_RUNNING_DEPLOYMENT = (204, 500, "No Running Deployment")
    APIFE_GRPC_NO_PRINCIPAL_FOUND = (205, 401, "No Principal found")
    # new-framework additions (outside reference ranges)
    TPU_COMPILE_ERROR = (301, 500, "XLA compilation failed")
    TPU_SHAPE_BUCKET_OVERFLOW = (302, 400, "Request exceeds largest compiled batch bucket")
    REQUEST_TIMEOUT = (303, 504, "Request timed out in batching queue")

    @property
    def code(self) -> int:
        return self.value[0]

    @property
    def http_status(self) -> int:
        return self.value[1]

    @property
    def message(self) -> str:
        return self.value[2]


class APIException(Exception):
    def __init__(self, error: ErrorCode, info: str = ""):
        self.error = error
        self.info = info
        super().__init__(f"{error.name}({error.code}): {error.message} {info}".rstrip())

    def to_status_json(self) -> dict:
        """The JSON error body shape the reference engine returns."""
        return {
            "code": self.error.code,
            "info": self.info,
            "reason": self.error.message,
            "status": "FAILURE",
        }
