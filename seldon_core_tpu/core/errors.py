"""Error taxonomy.

Parity: reference engine APIException enum
(engine/src/main/java/io/seldon/engine/exception/APIException.java) and the
api-frontend variant (APIFE_* codes), plus the Python microservice error JSON
(wrappers/python/microservice.py:29-30). The numeric codes and names are kept
so clients/dashboards written against the reference keep working.
"""

from __future__ import annotations

import enum


class ErrorCode(enum.Enum):
    # (code, http_status, message) — engine taxonomy
    ENGINE_INVALID_JSON = (101, 400, "Invalid JSON")
    ENGINE_INVALID_ENDPOINT_URL = (102, 500, "Invalid endpoint URL")
    ENGINE_MICROSERVICE_ERROR = (103, 500, "Microservice error")
    ENGINE_INVALID_ABTEST = (104, 500, "Error happened in AB Test routing")
    ENGINE_INVALID_ROUTING = (105, 500, "Invalid graph routing")
    ENGINE_INVALID_RESPONSE = (106, 500, "Invalid microservice response")
    # api-frontend taxonomy
    APIFE_INVALID_JSON = (201, 400, "Invalid JSON")
    APIFE_INVALID_ENDPOINT_URL = (202, 500, "Invalid endpoint URL")
    APIFE_MICROSERVICE_ERROR = (203, 500, "Microservice error")
    APIFE_NO_RUNNING_DEPLOYMENT = (204, 500, "No Running Deployment")
    APIFE_GRPC_NO_PRINCIPAL_FOUND = (205, 401, "No Principal found")
    # new-framework additions (outside reference ranges)
    TPU_COMPILE_ERROR = (301, 500, "XLA compilation failed")
    TPU_SHAPE_BUCKET_OVERFLOW = (302, 400, "Request exceeds largest compiled batch bucket")
    REQUEST_TIMEOUT = (303, 504, "Request timed out in batching queue")
    REQUEST_DEADLINE_EXCEEDED = (304, 504, "Request deadline budget exhausted")
    ENGINE_BREAKER_OPEN = (305, 503, "Circuit breaker open for endpoint")

    @property
    def code(self) -> int:
        return self.value[0]

    @property
    def http_status(self) -> int:
        return self.value[1]

    @property
    def message(self) -> str:
        return self.value[2]


class APIException(Exception):
    def __init__(
        self,
        error: ErrorCode,
        info: str = "",
        *,
        retry_after_s: float | None = None,
        retryable: bool | None = None,
    ):
        self.error = error
        self.info = info
        # when set (open circuit breaker), the wire layers emit it as an
        # HTTP Retry-After header so clients can back off instead of hammer
        self.retry_after_s = retry_after_s
        # explicit retryability override for the resilience layer: a remote
        # 4xx is normalised to ENGINE_MICROSERVICE_ERROR for wire compat but
        # is DETERMINISTIC — replaying it or counting it against the
        # endpoint's breaker would punish a healthy backend. None = classify
        # by error code (engine/resilience.is_retryable).
        self.retryable = retryable
        super().__init__(f"{error.name}({error.code}): {error.message} {info}".rstrip())

    def retry_after_header(self) -> str | None:
        """Value for the HTTP Retry-After header, or None. One place for
        the rounding policy (ceil, floor 1 s) so the aiohttp and fast-
        ingress wire layers cannot drift."""
        if self.retry_after_s is None:
            return None
        return str(max(1, int(self.retry_after_s + 0.999)))

    def to_status_json(self) -> dict:
        """The JSON error body shape the reference engine returns."""
        return {
            "code": self.error.code,
            "info": self.info,
            "reason": self.error.message,
            "status": "FAILURE",
        }
