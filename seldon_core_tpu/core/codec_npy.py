"""Binary tensor codec: npy bytes <-> numpy arrays.

TPU-native wire fast path. The reference moves every tensor as JSON text
(engine form-encoded ``json=`` hops, ~8-18 bytes per value) and treats the
proto ``binData`` arm as opaque passthrough bytes (prediction.proto:12-21 —
no codec anywhere consumes it). For image-scale payloads the text encoding
is the bottleneck: a 224x224x3 float32 image is ~1.2 MB as JSON but 588 KB
as npy float32 and 147 KB as npy uint8.

Format: the standard npy container (numpy.lib.format) — self-describing
dtype/shape/order header + raw buffer. Chosen over a bespoke header because
every numpy/jax client can produce it with ``np.save`` and it decodes
zero-copy for C-contiguous arrays.

Ingress rule (serving/service.py): a request whose ``binData`` arm starts
with the npy magic is decoded into the tensor ``data`` arm before the
micro-batcher, and the response tensor is encoded back to npy ``binData``
(mirrored kind). Non-npy binData stays opaque passthrough, preserving the
reference semantics. REST also accepts the raw body directly with
``Content-Type: application/x-npy`` (serving/rest.py) — no JSON envelope,
no base64 inflation.
"""

from __future__ import annotations

import io

import numpy as np

from seldon_core_tpu.core.errors import APIException, ErrorCode

NPY_MAGIC = b"\x93NUMPY"


def is_npy(raw: bytes | None) -> bool:
    return raw is not None and raw[: len(NPY_MAGIC)] == NPY_MAGIC


def array_from_npy(raw: bytes) -> np.ndarray:
    """Decode npy bytes. allow_pickle stays False: object-dtype payloads
    would otherwise be arbitrary-code-execution on the serving path."""
    try:
        arr = np.load(io.BytesIO(raw), allow_pickle=False)
    except Exception as e:  # noqa: BLE001 - wire input, map to error taxonomy
        raise APIException(
            ErrorCode.ENGINE_INVALID_JSON, f"bad npy payload: {e}"
        ) from e
    if arr.dtype == object:  # defense in depth; np.load refuses already
        raise APIException(ErrorCode.ENGINE_INVALID_JSON, "object npy refused")
    return arr


def npy_from_array(array) -> bytes:
    arr = np.asarray(array)
    if arr.dtype.kind == "V" or not arr.dtype.isnative or arr.dtype.hasobject:
        # ml_dtypes (bfloat16 etc.) serialize as opaque void in npy — no
        # client could decode them; float32 is the interoperable form
        arr = arr.astype(np.float32)
    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
    return buf.getvalue()
