"""Tensor bridge: host numpy <-> TPU device arrays, dtype policy, padding.

Parity role: the reference's only numeric kernel is ND4J conversion glue
(engine/.../predictors/PredictorUtils.java — Tensor<->ndarray<->INDArray).
Here the equivalent is numpy<->jax with an explicit TPU dtype policy and a
zero-ish-copy device path (np.frombuffer on the wire buffer -> device_put).

TPU notes: float64 (the reference wire dtype) is emulated and slow on TPU;
we compute in float32 (or bfloat16 where the model opts in) and only widen
back to float64 at the JSON edge.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

_JAX = None


def _jax():
    global _JAX
    if _JAX is None:
        import jax

        _JAX = jax
    return _JAX


def to_device(array: np.ndarray, sharding: Any | None = None) -> Any:
    """Host array -> device, optionally with a NamedSharding (multi-chip)."""
    jax = _jax()
    if sharding is not None:
        return jax.device_put(array, sharding)
    return jax.device_put(array)


def to_host(array: Any) -> np.ndarray:
    return np.asarray(array)


def cast_policy(array: np.ndarray, dtype: Any = np.float32) -> np.ndarray:
    if array.dtype == dtype:
        return array
    return array.astype(dtype)


def pad_batch(array: np.ndarray, target_batch: int, axis: int = 0) -> tuple[np.ndarray, int]:
    """Pad ``axis`` up to ``target_batch`` with zeros; returns (padded, valid_n).

    Shape bucketing is the TPU answer to variable request sizes: XLA compiles
    one program per bucket instead of one per observed shape (SURVEY §7 hard
    parts: 'variable batch ... on TPU they are the problem')."""
    n = array.shape[axis]
    if n > target_batch:
        raise ValueError(f"batch {n} exceeds bucket {target_batch}")
    if n == target_batch:
        return array, n
    # zeros + slice-assign instead of np.pad: same result, ~20x less Python
    # overhead (np.pad's generic machinery costs ~20 us per call — real
    # money at thousands of requests/sec on the serving path)
    shape = list(array.shape)
    shape[axis] = target_batch
    out = np.zeros(shape, dtype=array.dtype)
    sl = [slice(None)] * array.ndim
    sl[axis] = slice(0, n)
    out[tuple(sl)] = array
    return out, n


def bucket_for(n: int, buckets: Sequence[int]) -> int | None:
    """Smallest bucket >= n, or None if n exceeds the largest bucket."""
    for b in buckets:
        if n <= b:
            return b
    return None


def default_buckets(max_batch: int) -> tuple[int, ...]:
    """Power-of-two buckets up to max_batch: 1,2,4,...  At most
    log2(max)+1 compiled programs per model."""
    out = []
    b = 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return tuple(out)
