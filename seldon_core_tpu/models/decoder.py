"""GPT-style causal decoder with KV-cache generation — the generative
serving tier.

Greenfield vs the reference (SURVEY §2: classifiers/regressors only); the
TPU-native pieces are exactly the ones a naive port gets wrong:

- ONE compiled program per (batch bucket, prompt length): prefill computes
  every prompt position's K/V in one causal-attention pass (the same
  length-adaptive policy BERT serving uses — naive < 1024, blockwise, the
  Pallas causal kernel on TPU at long prompts), writes them into a
  [b, h, max_ctx, d] cache, then a ``lax.scan`` runs ``max_new_tokens``
  greedy steps — static shapes throughout, no Python loop, no recompiles.
- per-step attention is one [b, h, 1, d] query against the cache with a
  position mask (cache slots beyond the current length contribute zero
  mass), K/V written in place via ``lax.dynamic_update_slice``.
- outputs are int32 token ids (the serving wire keeps integer dtypes
  exact; float32 readback holds every id < 2^24).

Serving contract: apply(params, ids[b, s]) -> [b, s + max_new_tokens]
(prompt echoed, generated ids appended) — max_new_tokens is a DEPLOYMENT
parameter (static at trace time), the zoo entry is ``tiny_gpt``.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


def _dense(rng: np.random.Generator, n_in: int, n_out: int) -> dict:
    scale = (2.0 / (n_in + n_out)) ** 0.5
    return {
        "w": (rng.standard_normal((n_in, n_out)) * scale).astype(np.float32),
        "b": np.zeros((n_out,), np.float32),
    }


def _ln_init(d: int) -> dict:
    return {"scale": np.ones((d,), np.float32), "bias": np.zeros((d,), np.float32)}


def _ln(p: dict, x: jax.Array) -> jax.Array:
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mu) * lax.rsqrt(var + jnp.asarray(1e-5, x.dtype))
    return y * p["scale"].astype(x.dtype) + p["bias"].astype(x.dtype)


def init_decoder(
    seed: int = 0,
    vocab: int = 512,
    hidden: int = 128,
    layers: int = 2,
    ffn: int = 256,
    max_len: int = 128,
    resid_scale: float = 1.0,
) -> dict:
    """``resid_scale`` scales the residual-branch output projections
    (attn_out, mlp_out) after drawing them — GPT-2/µP-style depth-scaled
    init. At 1.0 (default) the params are bit-identical to earlier builds.
    Scaling happens AFTER the rng draws, so two builds that differ only in
    ``layers`` share their embedding + leading-layer weights verbatim (the
    generator stream is positional): a fewer-layers build IS the deeper
    build's prefix — what makes a seed-shared truncated draft model a
    faithful early-exit approximation of its target for speculative
    decoding (serving/decode_scheduler.py)."""
    heads = _heads_for(hidden)
    if hidden % heads:
        raise ValueError(
            f"hidden={hidden} not divisible by its derived head count "
            f"{heads} (head_dim-64 convention) — a cryptic reshape error "
            "at first trace otherwise"
        )
    rng = np.random.default_rng(seed)

    def _resid(p: dict) -> dict:
        if resid_scale != 1.0:
            p["w"] = (p["w"] * np.float32(resid_scale)).astype(np.float32)
        return p

    return {
        "tok_emb": (rng.standard_normal((vocab, hidden)) * 0.02).astype(np.float32),
        "pos_emb": (rng.standard_normal((max_len, hidden)) * 0.02).astype(np.float32),
        "layers": [
            {
                "ln1": _ln_init(hidden),
                "qkv": _dense(rng, hidden, 3 * hidden),
                "attn_out": _resid(_dense(rng, hidden, hidden)),
                "ln2": _ln_init(hidden),
                "mlp_in": _dense(rng, hidden, ffn),
                "mlp_out": _resid(_dense(rng, ffn, hidden)),
            }
            for _ in range(layers)
        ],
        "ln_f": _ln_init(hidden),
        # lm head reuses tok_emb^T (weight tying, the standard decoder move)
    }


def _heads_for(hidden: int) -> int:
    return max(1, hidden // 64) if hidden >= 64 else 2


def _heads(params: dict) -> int:
    return _heads_for(params["layers"][0]["qkv"]["w"].shape[0])


def _split_heads(t: jax.Array, h: int) -> jax.Array:
    b, s, d = t.shape
    return t.reshape(b, s, h, d // h).transpose(0, 2, 1, 3)


def _merge_heads(t: jax.Array) -> jax.Array:
    b, h, s, hd = t.shape
    return t.transpose(0, 2, 1, 3).reshape(b, s, h * hd)


def _causal_attention(q, k, v):
    """Prefill attention: the shared backend-adaptive causal policy
    (ops/attention.causal_attention_auto — Pallas kernel on TPU at long
    prompts, pure JAX elsewhere)."""
    from seldon_core_tpu.ops.attention import causal_attention_auto

    return causal_attention_auto(q, k, v)


def _layer_prefill(p, x, h):
    """Returns (x_out, k[b,h,s,hd], v[b,h,s,hd]) for the cache."""
    normed = _ln(p["ln1"], x)
    qkv = normed @ p["qkv"]["w"].astype(x.dtype) + p["qkv"]["b"].astype(x.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q, k, v = _split_heads(q, h), _split_heads(k, h), _split_heads(v, h)
    ctx = _merge_heads(_causal_attention(q, k, v))
    x = x + ctx @ p["attn_out"]["w"].astype(x.dtype) + p["attn_out"]["b"].astype(x.dtype)
    normed2 = _ln(p["ln2"], x)
    hdn = jax.nn.gelu(
        normed2 @ p["mlp_in"]["w"].astype(x.dtype) + p["mlp_in"]["b"].astype(x.dtype),
        approximate=False,
    )
    x = x + hdn @ p["mlp_out"]["w"].astype(x.dtype) + p["mlp_out"]["b"].astype(x.dtype)
    return x, k, v


def _layer_step(p, x, cache_k, cache_v, pos, h):
    """One token through one layer against the cache. x: [b, 1, d]; cache
    [b, h, max_ctx, hd]; pos: scalar current position (tokens < pos are
    valid). Returns (x_out, cache_k, cache_v) with the new K/V written at
    ``pos``."""
    normed = _ln(p["ln1"], x)
    qkv = normed @ p["qkv"]["w"].astype(x.dtype) + p["qkv"]["b"].astype(x.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = _split_heads(q, h)  # [b, h, 1, hd]
    k = _split_heads(k, h)
    v = _split_heads(v, h)
    cache_k = lax.dynamic_update_slice(cache_k, k, (0, 0, pos, 0))
    cache_v = lax.dynamic_update_slice(cache_v, v, (0, 0, pos, 0))
    # masked dot attention over the whole (static) cache: slots > pos get
    # -inf, so their mass is exactly zero — no dynamic shapes
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), cache_k.astype(jnp.float32)) * scale
    valid = jnp.arange(cache_k.shape[2]) <= pos  # [max_ctx]
    s = jnp.where(valid[None, None, None, :], s, -1e30)
    p_attn = jax.nn.softmax(s, axis=-1)
    ctx = jnp.einsum("bhqk,bhkd->bhqd", p_attn, cache_v.astype(jnp.float32))
    ctx = _merge_heads(ctx.astype(x.dtype))
    x = x + ctx @ p["attn_out"]["w"].astype(x.dtype) + p["attn_out"]["b"].astype(x.dtype)
    normed2 = _ln(p["ln2"], x)
    hdn = jax.nn.gelu(
        normed2 @ p["mlp_in"]["w"].astype(x.dtype) + p["mlp_in"]["b"].astype(x.dtype),
        approximate=False,
    )
    x = x + hdn @ p["mlp_out"]["w"].astype(x.dtype) + p["mlp_out"]["b"].astype(x.dtype)
    return x, cache_k, cache_v


def _embed(params, ids, pos_offset: int = 0):
    # jnp.asarray: params may be host numpy on the direct (un-device_put)
    # call path, and numpy arrays cannot be indexed by tracers
    h = jnp.asarray(params["tok_emb"])[ids]
    return h + jnp.asarray(params["pos_emb"])[
        pos_offset : pos_offset + ids.shape[1]
    ][None, :, :]


def _logits(params, x):
    x = _ln(params["ln_f"], x)
    return x @ jnp.asarray(params["tok_emb"]).T.astype(x.dtype)  # weight-tied head


def generate(params: dict, ids: jax.Array, max_new_tokens: int) -> jax.Array:
    """Greedy decode: ids[b, s] int -> [b, s + max_new_tokens] int32.

    Prefill fills the KV caches in one causal pass; a lax.scan then runs
    ``max_new_tokens`` single-token steps. max_ctx = s + max_new_tokens is
    static, so one XLA program serves every request of this bucket."""
    ids = ids.astype(jnp.int32)
    b, s = ids.shape
    heads = _heads(params)
    max_ctx = s + max_new_tokens
    max_len = params["pos_emb"].shape[0]
    if max_ctx > max_len:
        raise ValueError(
            f"prompt {s} + max_new_tokens {max_new_tokens} exceeds the "
            f"position table ({max_len}) — raise max_len"
        )

    # ---- prefill
    x = _embed(params, ids)
    caches = []
    hd = x.shape[-1] // heads
    for lp in params["layers"]:
        x, k, v = _layer_prefill(lp, x, heads)
        ck = jnp.zeros((b, heads, max_ctx, hd), x.dtype)
        cv = jnp.zeros((b, heads, max_ctx, hd), x.dtype)
        ck = lax.dynamic_update_slice(ck, k, (0, 0, 0, 0))
        cv = lax.dynamic_update_slice(cv, v, (0, 0, 0, 0))
        caches.append((ck, cv))
    first_tok = jnp.argmax(_logits(params, x[:, -1:, :]), axis=-1)  # [b, 1]

    # ---- decode scan: carry = (token, pos, caches)
    cache_k = jnp.stack([c[0] for c in caches])  # [L, b, h, max_ctx, hd]
    cache_v = jnp.stack([c[1] for c in caches])

    def step(carry, _):
        tok, pos, ck_all, cv_all = carry
        x = _embed_one(params, tok, pos)
        new_k, new_v = [], []
        for li, lp in enumerate(params["layers"]):
            x, ck, cv = _layer_step(lp, x, ck_all[li], cv_all[li], pos, heads)
            new_k.append(ck)
            new_v.append(cv)
        nxt = jnp.argmax(_logits(params, x), axis=-1)  # [b, 1]
        return (nxt, pos + 1, jnp.stack(new_k), jnp.stack(new_v)), tok

    # max_new - 1 steps: each step consumes one already-chosen token and
    # chooses the next, and first_tok came from prefill — a full step for
    # the token after the last would be paid-for-then-discarded compute
    (last, _, _, _), toks = lax.scan(
        step, (first_tok, jnp.int32(s), cache_k, cache_v), None,
        length=max_new_tokens - 1,
    )
    # toks: the token CONSUMED by each step (first_tok first); `last` is
    # the final chosen token — together exactly max_new generated ids
    gen = jnp.concatenate(
        [toks[:, :, 0].T.reshape(b, -1), last], axis=1
    )
    return jnp.concatenate([ids, gen.astype(jnp.int32)], axis=1)


def _embed_one(params, tok: jax.Array, pos) -> jax.Array:
    """tok: [b, 1] -> [b, 1, d] with the position-``pos`` embedding."""
    h = jnp.asarray(params["tok_emb"])[tok]
    return h + lax.dynamic_slice_in_dim(
        jnp.asarray(params["pos_emb"]), pos, 1, axis=0
    )[None, :, :]


# --------------------------------------------------------------------------
# Continuous-batching building blocks (serving/decode_scheduler.py).
#
# The fused ``generate`` above runs one whole batch to completion inside a
# single lax.scan — the correctness oracle. The functions below split that
# program into the three pieces iteration-level scheduling needs:
#   prefill()      one causal pass over a prompt -> per-sequence K/V + the
#                  last-position logits (the first generated token's logits)
#   init_slot_cache / write_prefill  a STATIC [L, n_slots, h, max_ctx, hd]
#                  cache, sequences scattered into slots
#   decode_step()  one token for EVERY slot at per-slot positions — batch
#                  composition changes between steps without shape changes
#   sample_tokens  per-slot temperature/top-k sampling, greedy at temp<=0
#   draft_propose / verify_step / speculative_accept
#                  draft-model speculation: k proposed tokens per slot and
#                  their one-dispatch verification against the same cache
# All shapes are static in (n_slots, max_ctx), so one XLA program per
# function serves every batch composition (zero recompiles after warmup).


def decoder_dims(params: dict) -> dict:
    """Static geometry the scheduler sizes its cache from."""
    hidden = params["layers"][0]["qkv"]["w"].shape[0]
    heads = _heads(params)
    return {
        "layers": len(params["layers"]),
        "heads": heads,
        "hidden": hidden,
        "head_dim": hidden // heads,
        "vocab": params["tok_emb"].shape[0],
        "max_len": params["pos_emb"].shape[0],
    }


def prefill(params: dict, ids: jax.Array) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One causal pass over prompts ids[b, s] -> (logits[b, vocab],
    k[L, b, h, s, hd], v[L, b, h, s, hd]).

    Same math as the fused generate's prefill phase (shared _layer_prefill /
    causal-attention policy), but the K/V comes back to the caller to be
    scattered into slots instead of being written into a private cache."""
    ids = ids.astype(jnp.int32)
    heads = _heads(params)
    x = _embed(params, ids)
    ks, vs = [], []
    for lp in params["layers"]:
        x, k, v = _layer_prefill(lp, x, heads)
        ks.append(k)
        vs.append(v)
    logits = _logits(params, x[:, -1:, :])[:, 0, :]
    return logits, jnp.stack(ks), jnp.stack(vs)


def init_slot_cache(
    params: dict, n_slots: int, max_ctx: int, dtype=jnp.float32
) -> tuple[jax.Array, jax.Array]:
    """Zeroed slot KV cache pair, each [L, n_slots, heads, max_ctx, hd]."""
    d = decoder_dims(params)
    shape = (d["layers"], n_slots, d["heads"], max_ctx, d["head_dim"])
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def write_prefill(
    cache_k: jax.Array, cache_v: jax.Array, k: jax.Array, v: jax.Array, slot
) -> tuple[jax.Array, jax.Array]:
    """Scatter one prefilled sequence's K/V (k[L, 1, h, s, hd]) into ``slot``
    positions 0..s-1 via lax.dynamic_update_slice. Jitted by the scheduler
    with cache donation, so the update is in-place in HBM."""
    cache_k = lax.dynamic_update_slice(cache_k, k, (0, slot, 0, 0, 0))
    cache_v = lax.dynamic_update_slice(cache_v, v, (0, slot, 0, 0, 0))
    return cache_k, cache_v


def _layer_step_slots(p, x, cache_k, cache_v, positions, h, counts=None, starts=None):
    """_layer_step generalized to PER-SLOT positions and m queries per
    slot. x: [n, m, d]; cache [n, h, max_ctx, hd]; positions: [n] — slot
    i's query j sits at positions[i] + j, writes its K/V there, and
    attends to cache entries <= positions[i] + j (the in-block causal
    mask: speculative query j sees the keys queries 0..j-1 of the same
    dispatch just wrote). The serving decode step is the m=1 case.

    ``counts`` (optional, [n]): per-slot WRITE masks for chunked prefill —
    slot i persists only its first counts[i] K/V entries and leaves the
    rest of its cache byte-identical (a select against the current block,
    so a counts-0 slot riding the static-shape dispatch mutates nothing).
    None keeps the unconditional m-wide write (decode/verify paths, where
    junk beyond a slot's limit lands ahead of its cursor by design).

    ``starts`` (optional, [n]): per-slot attention LOWER bound — cache
    entries before starts[i] are masked out. The feature draft uses this
    on warm (prefix-reuse) admissions: positions the target mapped from
    the prefix pool have no draft-side K/V (the draft cache is populated
    by the chunk rounds, which only compute the uncovered suffix), so the
    draft's window opens at the suffix instead of attending to zeroed
    rows. None keeps the full [0, pos] window (target paths — the pool
    is always complete there)."""
    normed = _ln(p["ln1"], x)
    qkv = normed @ p["qkv"]["w"].astype(x.dtype) + p["qkv"]["b"].astype(x.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = _split_heads(q, h)  # [n, h, m, hd]
    k = _split_heads(k, h)
    v = _split_heads(v, h)
    # per-slot scatter: vmap over the slot axis turns the per-sequence
    # dynamic_update_slice into one batched scatter — no host loop, no
    # per-slot programs; the m-wide K/V block lands at positions[i]..+m-1
    if counts is None:
        write = jax.vmap(lambda c, kk, pos: lax.dynamic_update_slice(c, kk, (0, pos, 0)))
        cache_k = write(cache_k, k, positions)
        cache_v = write(cache_v, v, positions)
    else:
        m_w = k.shape[2]

        def _masked(c, kk, pos, cnt):
            cur = lax.dynamic_slice(c, (0, pos, 0), kk.shape)
            blk = jnp.where((jnp.arange(m_w) < cnt)[None, :, None], kk, cur)
            return lax.dynamic_update_slice(c, blk, (0, pos, 0))

        write = jax.vmap(_masked)
        cache_k = write(cache_k, k, positions, counts)
        cache_v = write(cache_v, v, positions, counts)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum(
        "nhqd,nhkd->nhqk", q.astype(jnp.float32), cache_k.astype(jnp.float32)
    ) * scale
    m = x.shape[1]
    q_pos = positions[:, None] + jnp.arange(m)[None, :]  # [n, m]
    valid = jnp.arange(cache_k.shape[2])[None, None, :] <= q_pos[:, :, None]
    if starts is not None:
        valid = valid & (
            jnp.arange(cache_k.shape[2])[None, None, :] >= starts[:, None, None]
        )
    s = jnp.where(valid[:, None, :, :], s, -1e30)
    p_attn = jax.nn.softmax(s, axis=-1)
    ctx = jnp.einsum("nhqk,nhkd->nhqd", p_attn, cache_v.astype(jnp.float32))
    ctx = _merge_heads(ctx.astype(x.dtype))
    x = x + ctx @ p["attn_out"]["w"].astype(x.dtype) + p["attn_out"]["b"].astype(x.dtype)
    normed2 = _ln(p["ln2"], x)
    hdn = jax.nn.gelu(
        normed2 @ p["mlp_in"]["w"].astype(x.dtype) + p["mlp_in"]["b"].astype(x.dtype),
        approximate=False,
    )
    x = x + hdn @ p["mlp_out"]["w"].astype(x.dtype) + p["mlp_out"]["b"].astype(x.dtype)
    return x, cache_k, cache_v


def decode_step(
    params: dict,
    cache_k: jax.Array,
    cache_v: jax.Array,
    tokens: jax.Array,
    positions: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One decode step for every slot: consume tokens[n] sitting at
    positions[n], return (logits[n, vocab], cache_k, cache_v) with each
    slot's K/V written at its own position.

    Free slots step too (their compute is the price of static shapes); the
    scheduler passes position 0 for them and their garbage K/V is
    overwritten by the next admission's prefill scatter."""
    heads = _heads(params)
    x = jnp.asarray(params["tok_emb"])[tokens][:, None, :]
    x = x + jnp.asarray(params["pos_emb"])[positions][:, None, :]
    new_k, new_v = [], []
    for li, lp in enumerate(params["layers"]):
        x, ck, cv = _layer_step_slots(lp, x, cache_k[li], cache_v[li], positions, heads)
        new_k.append(ck)
        new_v.append(cv)
    logits = _logits(params, x)[:, 0, :]
    return logits, jnp.stack(new_k), jnp.stack(new_v)


def _transform_logits(logits: jax.Array, temperature, top_k) -> jax.Array:
    """The per-row sampling transform shared by ``sample_tokens`` and the
    speculative acceptance rule (both MUST agree, or the draft's proposal
    distribution q would differ from the one acceptance corrects against):
    top_k restriction (<= 0 = full vocabulary) then temperature scaling.
    ``temperature``/``top_k`` broadcast against logits' leading axes;
    top_k is data, not shape — the cutoff is looked up in the sorted
    logits, so one compiled program serves every per-request k."""
    vocab = logits.shape[-1]
    temperature = jnp.broadcast_to(temperature, logits.shape[:-1])
    top_k = jnp.broadcast_to(top_k, logits.shape[:-1])
    sorted_desc = jnp.flip(jnp.sort(logits, axis=-1), axis=-1)
    k_idx = jnp.clip(top_k - 1, 0, vocab - 1)
    thresh = jnp.take_along_axis(sorted_desc, k_idx[..., None], axis=-1)  # [..., 1]
    restricted = jnp.where(logits < thresh, -jnp.inf, logits)
    masked = jnp.where(top_k[..., None] > 0, restricted, logits)
    return masked / jnp.maximum(temperature, 1e-6)[..., None].astype(logits.dtype)


def sample_tokens(
    logits: jax.Array,
    temperature: jax.Array,
    top_k: jax.Array,
    key: jax.Array,
) -> jax.Array:
    """Per-row sampling: greedy argmax where temperature <= 0 (the serving
    default — what the fused oracle computes), else temperature-scaled
    categorical restricted to the top_k logits (top_k <= 0 means the full
    vocabulary)."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = _transform_logits(logits, temperature, top_k)
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)


# ----------------------------------------------------- speculative decoding
# Draft-model speculation (Leviathan et al.; Chen et al.): a cheap draft
# decoder proposes k tokens per slot in ONE dispatch, the target model
# scores all k+1 queries against the same slot cache in ONE widened
# dispatch, and the longest valid prefix is accepted — amortizing the
# per-dispatch cost over several emitted tokens. Speculative cache writes
# need no rollback copy: positions only advance by the ACCEPTED length, so
# rejected entries sit beyond every later attention mask until the next
# consumed token overwrites them.


def verify_step(
    params: dict,
    cache_k: jax.Array,
    cache_v: jax.Array,
    tokens: jax.Array,
    positions: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """decode_step widened to m queries per slot: consume tokens[n, m]
    (the last emitted token + the m-1 draft proposals) with slot i's query
    j at positions[i] + j, return (logits[n, m, vocab], cache_k, cache_v)
    with every query's K/V written at its own position.

    logits[i, j] is the target's next-token distribution AFTER consuming
    query j — exactly what j sequential decode_step calls would produce
    for the same prefix, which is what makes greedy acceptance bit-exact.
    Junk queries (beyond a slot's accept limit, or free slots) may index
    the position table out of range; the lookup clips and their logits are
    never used."""
    heads = _heads(params)
    m = tokens.shape[1]
    max_len = params["pos_emb"].shape[0]
    x = jnp.asarray(params["tok_emb"])[tokens]  # [n, m, d]
    pidx = jnp.clip(positions[:, None] + jnp.arange(m)[None, :], 0, max_len - 1)
    x = x + jnp.asarray(params["pos_emb"])[pidx]
    new_k, new_v = [], []
    for li, lp in enumerate(params["layers"]):
        x, ck, cv = _layer_step_slots(lp, x, cache_k[li], cache_v[li], positions, heads)
        new_k.append(ck)
        new_v.append(cv)
    logits = _logits(params, x)  # [n, m, vocab]
    return logits, jnp.stack(new_k), jnp.stack(new_v)


def chunk_prefill(
    params: dict,
    cache_k: jax.Array,
    cache_v: jax.Array,
    tokens: jax.Array,
    positions: jax.Array,
    counts: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One prefill CHUNK for every slot: consume tokens[n, c] with slot
    i's token j at positions[i] + j, persisting only the first counts[i]
    K/V entries per slot (counts-0 slots — generating, free — ride the
    static-shape dispatch without touching their cache). Returns
    (logits[n, c, vocab], cache_k, cache_v); logits[i, counts[i] - 1] is
    the next-token distribution after slot i's last consumed token — the
    first generated token's logits when the chunk completes a prompt.

    This is the incremental prefill building block behind both prefix
    reuse (only the suffix a cached prefix doesn't cover is computed) and
    Sarathi-style chunked prefill (a long prompt spreads over several
    scheduler rounds interleaved with decode steps). Same per-position
    K/V math as verify_step/_layer_step_slots: each query attends to
    cache entries <= its own position through the in-block causal mask,
    so a prompt prefilled in ANY chunk partition yields the same K/V as
    one computed in a single pass over the same cache layout."""
    heads = _heads(params)
    m = tokens.shape[1]
    max_len = params["pos_emb"].shape[0]
    x = jnp.asarray(params["tok_emb"])[tokens]  # [n, m, d]
    # junk queries (beyond a slot's count) may index past the position
    # table; clip like verify_step — their logits are never used and
    # their K/V writes are masked off
    pidx = jnp.clip(positions[:, None] + jnp.arange(m)[None, :], 0, max_len - 1)
    x = x + jnp.asarray(params["pos_emb"])[pidx]
    new_k, new_v = [], []
    for li, lp in enumerate(params["layers"]):
        x, ck, cv = _layer_step_slots(
            lp, x, cache_k[li], cache_v[li], positions, heads, counts=counts
        )
        new_k.append(ck)
        new_v.append(cv)
    logits = _logits(params, x)  # [n, m, vocab]
    return logits, jnp.stack(new_k), jnp.stack(new_v)


# ------------------------------------------------------------- paged KV
# Block-table KV memory (serving/kv_pool.py owns the allocator): instead of
# one contiguous [L, n_slots, h, max_ctx, hd] row per slot, K/V lives in a
# shared page pool [L, n_pages, h, page_size, hd] and each slot carries a
# static-shape block table [max_pages] of physical page ids. The attention
# building blocks below mirror decode_step / verify_step / chunk_prefill
# exactly — same masks, same einsums, same f32 accumulation — but read the
# cache through a pool gather and write through a per-token page/offset
# scatter, so two slots sharing a system prompt REFERENCE the same pages
# (vLLM's PagedAttention memory model) instead of each holding a copy.
#
# Conventions the scheduler relies on:
# - physical page 0 is a reserved junk sink: free slots' block tables are
#   all-zero and masked-off writes (beyond a slot's chunk count, past the
#   virtual length) are redirected there, so a static-shape dispatch can
#   never corrupt a live page;
# - the gathered virtual cache is [max_pages * page_size] long; positions
#   beyond a query's own position contribute exactly zero attention mass
#   (the same -1e30 masking the flat path uses), so greedy output stays
#   bit-identical to the contiguous layout and the scan oracle;
# - pool state is a flat tuple pytree: (k, v) in fp mode, or
#   (k_q, k_scale, k_zp, v_q, v_scale, v_zp) with int8 payloads and ONE
#   (scale, zero-point) pair per page row (= per cached token, shared
#   across heads) stored page-resident beside the payload — copy-on-write
#   and sharing move the scales with their page, and dequantization fuses
#   into the attention gather.


def paged_kv_init(
    params: dict, n_pages: int, page_size: int, dtype=jnp.float32, kv_dtype: str = ""
) -> tuple:
    """Zeroed page pool state tuple (see module comment for the layout)."""
    d = decoder_dims(params)
    shape = (d["layers"], n_pages, d["heads"], page_size, d["head_dim"])
    if kv_dtype == "int8":
        sshape = (d["layers"], n_pages, page_size)
        # scale 1 / zp 0: dequantized junk pages read back as exact zeros,
        # matching the fp pool's init
        return (
            jnp.zeros(shape, jnp.int8),
            jnp.ones(sshape, jnp.float32),
            jnp.zeros(sshape, jnp.float32),
            jnp.zeros(shape, jnp.int8),
            jnp.ones(sshape, jnp.float32),
            jnp.zeros(sshape, jnp.float32),
        )
    if kv_dtype:
        raise ValueError(f"unsupported kv_dtype {kv_dtype!r} (want '' or 'int8')")
    return (jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


def paged_copy(pool: tuple, src: jax.Array, dst: jax.Array) -> tuple:
    """Copy pool pages src[i] -> dst[i] across every state component (the
    copy-on-write primitive). Padding entries use src=dst=0: page 0 is the
    junk sink, so rewriting it with its own bytes is a no-op by design."""
    return tuple(a.at[:, dst].set(jnp.take(a, src, axis=1)) for a in pool)


def _quant_rows(x: jax.Array) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Per-row asymmetric int8: x[N, h, hd] -> (q[N, h, hd] int8, scale[N],
    zp[N]) with q = round((x - zp) / scale) in [-127, 127]."""
    lo = jnp.min(x, axis=(1, 2))
    hi = jnp.max(x, axis=(1, 2))
    zp = (hi + lo) * 0.5
    scale = jnp.maximum((hi - lo) / 254.0, 1e-8)
    q = jnp.clip(
        jnp.round((x - zp[:, None, None]) / scale[:, None, None]), -127, 127
    ).astype(jnp.int8)
    return q, scale, zp


def _paged_write(kv: tuple, k, v, bt, positions, counts):
    """Scatter the dispatch's new K/V (k, v: [n, h, m, hd], slot i's entry j
    at positions[i] + j) into the per-layer pool slices through the block
    tables. Invalid entries — beyond counts[i], or past the virtual length
    — are redirected to junk page 0 instead of masked in place, which is
    what lets free/prefilling slots ride static-shape dispatches without
    owning writable pages."""
    n, h, m, hd = k.shape
    ps = kv[0].shape[2]
    n_log = bt.shape[1]
    gp = positions[:, None] + jnp.arange(m)[None, :]  # [n, m] global positions
    lp = jnp.clip(gp // ps, 0, n_log - 1)
    phys = jnp.take_along_axis(bt, lp, axis=1)  # [n, m] physical pages
    ok = (gp >= 0) & (gp < n_log * ps)
    if counts is not None:
        ok = ok & (jnp.arange(m)[None, :] < counts[:, None])
    phys = jnp.where(ok, phys, 0)
    pf = phys.reshape(-1)
    of = (gp % ps).reshape(-1)
    kt = k.transpose(0, 2, 1, 3).reshape(n * m, h, hd)  # per-token rows
    vt = v.transpose(0, 2, 1, 3).reshape(n * m, h, hd)
    if len(kv) == 2:
        pk, pv = kv
        return (
            pk.at[pf, :, of, :].set(kt.astype(pk.dtype)),
            pv.at[pf, :, of, :].set(vt.astype(pv.dtype)),
        )
    kq, sk, zk, vq, sv, zv = kv
    qk, sck, zpk = _quant_rows(kt.astype(jnp.float32))
    qv, scv, zpv = _quant_rows(vt.astype(jnp.float32))
    return (
        kq.at[pf, :, of, :].set(qk),
        sk.at[pf, of].set(sck),
        zk.at[pf, of].set(zpk),
        vq.at[pf, :, of, :].set(qv),
        sv.at[pf, of].set(scv),
        zv.at[pf, of].set(zpv),
    )


def _paged_gather(kv: tuple, bt) -> tuple[jax.Array, jax.Array]:
    """Gather each slot's pages into a virtual contiguous cache
    [n, h, max_pages * page_size, hd] in f32 (the flat path's attention
    accumulation dtype). int8 mode fuses the per-page-row dequant here."""
    if len(kv) == 2:
        k = jnp.take(kv[0], bt, axis=0).astype(jnp.float32)  # [n, P, h, ps, hd]
        v = jnp.take(kv[1], bt, axis=0).astype(jnp.float32)
    else:
        kq, sk, zk, vq, sv, zv = kv
        k = jnp.take(kq, bt, axis=0).astype(jnp.float32)
        v = jnp.take(vq, bt, axis=0).astype(jnp.float32)
        k = k * jnp.take(sk, bt, axis=0)[:, :, None, :, None] + jnp.take(
            zk, bt, axis=0
        )[:, :, None, :, None]
        v = v * jnp.take(sv, bt, axis=0)[:, :, None, :, None] + jnp.take(
            zv, bt, axis=0
        )[:, :, None, :, None]
    n, p, h, ps, hd = k.shape
    k = k.transpose(0, 2, 1, 3, 4).reshape(n, h, p * ps, hd)
    v = v.transpose(0, 2, 1, 3, 4).reshape(n, h, p * ps, hd)
    return k, v


def _layer_step_paged(p, x, kv, bt, positions, h, counts=None):
    """_layer_step_slots reworked onto the page pool: same math, but the
    new K/V scatters through the block tables first and attention reads
    the pool back through a page gather (so in-dispatch queries see the
    keys earlier queries of the same dispatch just wrote, exactly like the
    flat path's write-then-read). Returns (x_out, new per-layer kv)."""
    normed = _ln(p["ln1"], x)
    qkv = normed @ p["qkv"]["w"].astype(x.dtype) + p["qkv"]["b"].astype(x.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = _split_heads(q, h)  # [n, h, m, hd]
    k = _split_heads(k, h)
    v = _split_heads(v, h)
    kv = _paged_write(kv, k, v, bt, positions, counts)
    cache_k, cache_v = _paged_gather(kv, bt)  # f32 virtual caches
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("nhqd,nhkd->nhqk", q.astype(jnp.float32), cache_k) * scale
    m = x.shape[1]
    q_pos = positions[:, None] + jnp.arange(m)[None, :]  # [n, m]
    valid = jnp.arange(cache_k.shape[2])[None, None, :] <= q_pos[:, :, None]
    s = jnp.where(valid[:, None, :, :], s, -1e30)
    p_attn = jax.nn.softmax(s, axis=-1)
    ctx = jnp.einsum("nhqk,nhkd->nhqd", p_attn, cache_v)
    ctx = _merge_heads(ctx.astype(x.dtype))
    x = x + ctx @ p["attn_out"]["w"].astype(x.dtype) + p["attn_out"]["b"].astype(x.dtype)
    normed2 = _ln(p["ln2"], x)
    hdn = jax.nn.gelu(
        normed2 @ p["mlp_in"]["w"].astype(x.dtype) + p["mlp_in"]["b"].astype(x.dtype),
        approximate=False,
    )
    x = x + hdn @ p["mlp_out"]["w"].astype(x.dtype) + p["mlp_out"]["b"].astype(x.dtype)
    return x, kv


def _paged_forward(params, pool, bt, tokens, positions, counts=None):
    """Shared body of the paged decode/verify/chunk programs: tokens[n, m]
    with slot i's query j at positions[i] + j; returns (logits[n, m, vocab],
    hidden[n, m, d], new pool state) — ``hidden`` is the final layer's
    residual-stream output (pre-``ln_f``), the per-position FEATURE an
    EAGLE-style draft head conditions on (data-only: same static shapes,
    and XLA dead-code-eliminates the extra output inside fused programs
    that drop it). Junk queries clip the position table like the flat
    verify/chunk paths — their logits are never read and their writes are
    junk-redirected."""
    heads = _heads(params)
    m = tokens.shape[1]
    max_len = params["pos_emb"].shape[0]
    x = jnp.asarray(params["tok_emb"])[tokens]  # [n, m, d]
    pidx = jnp.clip(positions[:, None] + jnp.arange(m)[None, :], 0, max_len - 1)
    x = x + jnp.asarray(params["pos_emb"])[pidx]
    per_comp: list[list] = [[] for _ in pool]
    for li, lp in enumerate(params["layers"]):
        layer_kv = tuple(a[li] for a in pool)
        x, layer_kv = _layer_step_paged(lp, x, layer_kv, bt, positions, heads, counts)
        for acc, a in zip(per_comp, layer_kv):
            acc.append(a)
    logits = _logits(params, x)  # [n, m, vocab]
    return logits, x, tuple(jnp.stack(acc) for acc in per_comp)


def paged_decode_step(params, pool, bt, tokens, positions):
    """decode_step over the page pool: consume tokens[n] at positions[n],
    return (logits[n, vocab], hidden[n, d], pool) — K/V written through
    block tables; ``hidden`` is the consumed position's final-layer
    feature (what a feature-level draft conditions the next round on)."""
    logits, hidden, pool = _paged_forward(params, pool, bt, tokens[:, None], positions)
    return logits[:, 0, :], hidden[:, 0, :], pool


def paged_verify_step(params, pool, bt, tokens, positions):
    """verify_step over the page pool: m queries per slot, logits[i, j]
    scored AFTER consuming query j — the widened speculative verify.
    Returns (logits, hidden[n, m, d], pool)."""
    return _paged_forward(params, pool, bt, tokens, positions)


def paged_chunk_prefill(params, pool, bt, tokens, positions, counts):
    """chunk_prefill over the page pool: persist only the first counts[i]
    K/V entries per slot (counts-0 slots ride the static-shape dispatch
    with their writes junk-redirected, touching no live page). Returns
    (logits, hidden[n, c, d], pool)."""
    return _paged_forward(params, pool, bt, tokens, positions, counts)


def draft_propose(
    params: dict,
    cache_k: jax.Array,
    cache_v: jax.Array,
    tokens: jax.Array,
    positions: jax.Array,
    temperature: jax.Array,
    top_k: jax.Array,
    key: jax.Array,
    k: int,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """k autoregressive draft steps in ONE program: starting from the last
    emitted token of every slot, propose (draft_tokens[n, k],
    draft_logits[n, k, vocab], cache_k, cache_v). ``k`` is static (the
    deployment's decode_spec_k), so the loop unrolls at trace time and the
    whole proposal chain costs one dispatch. Greedy rows (temperature <=
    0) propose argmax; sampled rows propose from the same transformed
    distribution sample_tokens serves — the q the acceptance rule corrects
    against."""
    toks = tokens
    drafts, logit_steps = [], []
    for j in range(k):
        logits, cache_k, cache_v = decode_step(
            params, cache_k, cache_v, toks, positions + j
        )
        toks = sample_tokens(logits, temperature, top_k, jax.random.fold_in(key, j))
        drafts.append(toks)
        logit_steps.append(logits)
    # one extra cache-fill step consuming the LAST proposal at pos+k
    # (logits discarded): a fully-accepted round advances the slot past
    # pos+k without ever consuming d_k here, and without this write the
    # draft cache keeps a permanent zero/stale hole inside every later
    # attention mask — accept rate silently decays. On partial accepts
    # the entry is junk-then-overwritten like every speculative write.
    _, cache_k, cache_v = decode_step(params, cache_k, cache_v, toks, positions + k)
    return (
        jnp.stack(drafts, axis=1),
        jnp.stack(logit_steps, axis=1),
        cache_k,
        cache_v,
    )


def speculative_accept(
    target_logits: jax.Array,
    draft_tokens: jax.Array,
    draft_logits: jax.Array,
    limits: jax.Array,
    temperature: jax.Array,
    top_k: jax.Array,
    key: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """The acceptance rule: given the widened target logits [n, k+1, V]
    (position j scored AFTER consuming query j), the draft's proposals
    [n, k] and raw logits [n, k, V], and per-slot accept limits [n]
    (0..k — the tighten-only spec_k override and the remaining token
    budget), return (out_tokens [n, k+1], n_accepted [n]): slot i emits
    out_tokens[i, :n_accepted[i] + 1].

    Greedy rows (temperature <= 0) accept the longest draft prefix that
    matches the target's own argmax chain and emit the target argmax at
    the first mismatch — bit-identical to sequential greedy decoding by
    induction (query 0 consumed the true last token, so a match at j
    makes query j+1's context exact too). Sampled rows use standard
    speculative sampling: accept d_j with probability min(1, p(d_j) /
    q(d_j)) and resample a TRUE rejection from the residual
    max(p - q, 0) — the emitted distribution is exactly the target's
    (Leviathan et al. Thm 1). A limit clamp is NOT a rejection (nothing
    was proposed there): its bonus token samples p directly."""
    n, kp1, vocab = target_logits.shape
    k = kp1 - 1
    rows = jnp.arange(n)
    greedy_t = jnp.argmax(target_logits, axis=-1).astype(jnp.int32)  # [n, k+1]
    p = jax.nn.softmax(
        _transform_logits(target_logits, temperature[:, None], top_k[:, None]), axis=-1
    )
    greedy_ok = draft_tokens == greedy_t[:, :k]  # [n, k]
    q = jax.nn.softmax(
        _transform_logits(draft_logits, temperature[:, None], top_k[:, None]), axis=-1
    )
    p_d = jnp.take_along_axis(p[:, :k], draft_tokens[..., None], axis=-1)[..., 0]
    q_d = jnp.take_along_axis(q, draft_tokens[..., None], axis=-1)[..., 0]
    key_u, key_b = jax.random.split(key)
    u = jax.random.uniform(key_u, (n, k))
    sampled_ok = u * q_d < p_d  # u < p/q without the division
    ok = jnp.where(temperature[:, None] > 0, sampled_ok, greedy_ok)
    ok = ok & (jnp.arange(k)[None, :] < limits[:, None])
    n_acc = jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)
    # bonus token at index n_acc
    p_a = p[rows, n_acc]  # [n, vocab]
    q_a = jnp.where(
        (n_acc < k)[:, None], q[rows, jnp.minimum(n_acc, k - 1)], jnp.float32(0.0)
    )
    true_reject = n_acc < limits  # a draft existed here and lost
    residual = jnp.maximum(p_a - q_a, 0.0)
    rsum = jnp.sum(residual, axis=-1, keepdims=True)
    residual = jnp.where(rsum > 1e-9, residual / jnp.maximum(rsum, 1e-9), p_a)
    dist = jnp.where(true_reject[:, None], residual, p_a)
    bonus_sampled = jax.random.categorical(
        key_b, jnp.log(dist + 1e-38), axis=-1
    ).astype(jnp.int32)
    bonus = jnp.where(temperature > 0, bonus_sampled, greedy_t[rows, n_acc])
    out = jnp.concatenate([draft_tokens, jnp.zeros((n, 1), jnp.int32)], axis=1)
    out = out.at[rows, n_acc].set(bonus)
    return out, n_acc.astype(jnp.int32)


# ------------------------------------------------------- tree speculation
# Multi-candidate (tree) speculation (SpecInfer; Medusa; EAGLE): instead of
# one k-token chain, the draft proposes a token TREE — ``branching[d]``
# candidates per depth under every surviving branch (models/spec_tree.py
# owns the static layout) — and the target scores the whole flattened tree
# in ONE widened dispatch. Acceptance walks the longest valid PATH, so
# accepted-tokens-per-dispatch rises at the same 2-dispatch round cost:
# where a chain dies at the first mismatch, a tree usually has a sibling
# candidate covering the target's actual choice.
#
# Cache discipline differs from the chain on purpose: sibling nodes at one
# depth would collide on the same (page, offset), so the tree forward
# NEVER writes speculative K/V — in-dispatch queries read their ancestors
# through the ancestor mask (the in-block causal mask generalized), and
# only the ACCEPTED path is committed afterwards, every other column
# junk-redirected. The pool never holds speculative garbage.


def sequence_hidden(params: dict, ids: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Teacher-forced (logits, hidden) at every position: ids[b, s] ->
    ([b, s, vocab], [b, s, d]). ``hidden`` is the final layer's
    residual-stream output (pre-``ln_f``) — the same FEATURE definition
    the paged serving programs thread out, so the feature-conditioned
    distillation recipe (training/distill_draft.py) trains on exactly
    what the serving draft head will be fed."""
    ids = ids.astype(jnp.int32)
    heads = _heads(params)
    x = _embed(params, ids)
    for lp in params["layers"]:
        x, _, _ = _layer_prefill(lp, x, heads)
    return _logits(params, x), x


def sequence_logits(params: dict, ids: jax.Array) -> jax.Array:
    """Teacher-forced logits at every position: ids[b, s] -> [b, s, vocab]
    (position j's row is the next-token distribution after consuming
    tokens 0..j). One causal pass — the signal both sides of the draft
    KL-distillation recipe (training/distill_draft.py) train on."""
    return sequence_hidden(params, ids)[0]


def _layer_tree_flat(p, x, cache_k, cache_v, positions, h, ek, ev, sub_mask, starts=None):
    """One layer of a draft tree-expansion step over the FLAT draft cache:
    x [n, c, d] carries one depth's nodes; attention reads the cache at
    entries <= positions[i] (prompt + committed tokens + the root's fresh
    write) PLUS the in-register K/V of every node proposed so far this
    round (``ek``/``ev`` [n, h, E, hd], grown per depth — speculative
    draft K/V is never written to the cache; the verify dispatch commits
    the accepted path). ``sub_mask`` [c, E + c] is the ancestor-or-self
    mask over those in-flight nodes. Returns (x_out, ek', ev') with this
    depth's K/V appended."""
    normed = _ln(p["ln1"], x)
    qkv = normed @ p["qkv"]["w"].astype(x.dtype) + p["qkv"]["b"].astype(x.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = _split_heads(q, h)  # [n, h, c, hd]
    k = _split_heads(k, h)
    v = _split_heads(v, h)
    ek = k if ek is None else jnp.concatenate([ek, k], axis=2)
    ev = v if ev is None else jnp.concatenate([ev, v], axis=2)
    scale = 1.0 / (q.shape[-1] ** 0.5)
    qf = q.astype(jnp.float32)
    s_cache = jnp.einsum("nhqd,nhkd->nhqk", qf, cache_k.astype(jnp.float32)) * scale
    valid = jnp.arange(cache_k.shape[2])[None, None, None, :] <= positions[:, None, None, None]
    if starts is not None:
        # per-slot attention lower bound (see _layer_step_slots): the
        # feature draft's warm-admit window opens at the computed suffix
        valid = valid & (
            jnp.arange(cache_k.shape[2])[None, None, None, :]
            >= starts[:, None, None, None]
        )
    s_cache = jnp.where(valid, s_cache, -1e30)
    s_ext = jnp.einsum("nhqd,nhkd->nhqk", qf, ek.astype(jnp.float32)) * scale
    s_ext = jnp.where(sub_mask[None, None, :, :], s_ext, -1e30)
    p_attn = jax.nn.softmax(jnp.concatenate([s_cache, s_ext], axis=-1), axis=-1)
    c_len = cache_k.shape[2]
    ctx = jnp.einsum(
        "nhqk,nhkd->nhqd", p_attn[..., :c_len], cache_v.astype(jnp.float32)
    ) + jnp.einsum("nhqk,nhkd->nhqd", p_attn[..., c_len:], ev.astype(jnp.float32))
    ctx = _merge_heads(ctx.astype(x.dtype))
    x = x + ctx @ p["attn_out"]["w"].astype(x.dtype) + p["attn_out"]["b"].astype(x.dtype)
    normed2 = _ln(p["ln2"], x)
    hdn = jax.nn.gelu(
        normed2 @ p["mlp_in"]["w"].astype(x.dtype) + p["mlp_in"]["b"].astype(x.dtype),
        approximate=False,
    )
    x = x + hdn @ p["mlp_out"]["w"].astype(x.dtype) + p["mlp_out"]["b"].astype(x.dtype)
    return x, ek, ev


def _tree_candidates(parent_logits, temperature, top_k, key, d: int, b: int):
    """One depth's candidate tokens [n, c_prev * b] in parent-major block
    order, from the parents' logits [n, c_prev, V] — THE candidate rule
    both tree drafts share (token-level ``draft_propose_tree`` and the
    feature head ``draft_propose_features``; extracting it is what keeps
    their RNG streams and block layouts identical by construction).
    Greedy rows take the top-b DISTINCT tokens (branch 0 is the chain's
    argmax proposal); sampled rows draw b i.i.d. tokens from the
    transformed distribution ``sample_tokens`` serves — i.i.d. candidates
    are what make the per-depth recursive rejection resampling in
    ``speculative_accept_tree`` exact."""
    n, c_prev, _ = parent_logits.shape
    _, top_idx = lax.top_k(parent_logits, b)  # [n, c_prev, b]
    flat_parent = parent_logits.reshape(n * c_prev, -1)
    scaled = _transform_logits(
        flat_parent, jnp.repeat(temperature, c_prev), jnp.repeat(top_k, c_prev)
    )
    samp = [
        jax.random.categorical(
            jax.random.fold_in(jax.random.fold_in(key, d), bi), scaled, axis=-1
        ).astype(jnp.int32)
        for bi in range(b)
    ]
    sampled = jnp.stack(samp, axis=-1).reshape(n, c_prev, b)
    cand = jnp.where(
        (temperature > 0)[:, None, None], sampled, top_idx.astype(jnp.int32)
    )
    return cand.reshape(n, c_prev * b)  # parent-major: the block layout


def draft_propose_tree(
    params: dict,
    cache_k: jax.Array,
    cache_v: jax.Array,
    tokens: jax.Array,
    positions: jax.Array,
    temperature: jax.Array,
    top_k: jax.Array,
    key: jax.Array,
    tree,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Grow the whole proposal tree in ONE program: a root decode step
    (consume the last emitted token at ``pos``, write its K/V — always
    consumed, so the write is never speculative), then ``tree.depth``
    unrolled widened expansions, each proposing ``branching[d]`` children
    per surviving node. Greedy rows take the top-b distinct tokens of the
    parent's raw logits (branch 0 IS the chain's argmax proposal); sampled
    rows draw b i.i.d. tokens from the transformed distribution
    ``sample_tokens`` serves — i.i.d. candidates are what make the
    per-depth recursive rejection resampling in ``speculative_accept_tree``
    exact.

    Returns (node_tokens [n, n_tree], block_logits [n, width, V],
    node_k [L, n, h, n_tree, hd], node_v, cache_k, cache_v): block j's
    logits are the draft's next-token distribution AFTER consuming block
    j's token along its path (block 0 = the root) — the q each node's
    children are corrected against. Speculative node K/V comes back
    in-register for the verify dispatch to commit (``draft_tree_commit``);
    the cache itself only gains the root's entry."""
    heads = _heads(params)
    max_len = params["pos_emb"].shape[0]
    n = tokens.shape[0]
    logits0, cache_k, cache_v = decode_step(params, cache_k, cache_v, tokens, positions)
    block_logits = [logits0[:, None, :]]
    node_tokens = []
    ek: list = [None] * len(params["layers"])
    ev: list = [None] * len(params["layers"])
    parent_logits = logits0[:, None, :]  # [n, 1, V]
    mask_np = tree.ancestor_mask
    for d in range(1, tree.depth + 1):
        b = tree.branching[d - 1]
        c_d = tree.level_counts[d - 1]
        toks_d = _tree_candidates(parent_logits, temperature, top_k, key, d, b)
        node_tokens.append(toks_d)
        x = jnp.asarray(params["tok_emb"])[toks_d]
        pidx = jnp.clip(positions + d, 0, max_len - 1)
        x = x + jnp.asarray(params["pos_emb"])[pidx][:, None, :]
        start = tree.level_starts[d - 1]
        sub_mask = jnp.asarray(mask_np[start : start + c_d, 1 : start + c_d])
        for li, lp in enumerate(params["layers"]):
            x, ek[li], ev[li] = _layer_tree_flat(
                lp, x, cache_k[li], cache_v[li], positions, heads,
                ek[li], ev[li], sub_mask,
            )
        depth_logits = _logits(params, x)  # [n, c_d, V]
        block_logits.append(depth_logits)
        parent_logits = depth_logits
    return (
        jnp.concatenate(node_tokens, axis=1),
        jnp.concatenate(block_logits, axis=1),
        jnp.stack(ek),
        jnp.stack(ev),
        cache_k,
        cache_v,
    )


def _layer_tree_paged(p, x, kv, bt, positions, h, mask):
    """One layer of the widened TARGET tree verify over the page pool:
    all ``width`` blocks at once, attention over the gathered cache
    (entries strictly before ``pos`` — nothing speculative lives there)
    plus the dispatch's own fresh K/V under the ancestor mask. The pool
    is NOT written (``paged_tree_commit`` writes the accepted path after
    acceptance). int8 pools round-trip the fresh K/V through the same
    per-page-row quantizer the commit will apply, so every value a query
    reads is bit-identical to what the sequential plain path would have
    read back from the pool. Returns (x_out, k, v) with the RAW fresh
    K/V for the commit."""
    normed = _ln(p["ln1"], x)
    qkv = normed @ p["qkv"]["w"].astype(x.dtype) + p["qkv"]["b"].astype(x.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = _split_heads(q, h)  # [n, h, m, hd]
    k = _split_heads(k, h)
    v = _split_heads(v, h)
    n, hh, m, hd = k.shape
    if len(kv) == 6:
        # int8 pool: quantize-dequantize the in-block K/V per token row —
        # the exact transform _paged_write/_paged_gather would apply
        def _rt(t):
            rows = t.transpose(0, 2, 1, 3).reshape(n * m, hh, hd).astype(jnp.float32)
            qr, sc, zp = _quant_rows(rows)
            deq = qr.astype(jnp.float32) * sc[:, None, None] + zp[:, None, None]
            return deq.reshape(n, m, hh, hd).transpose(0, 2, 1, 3)

        k_att, v_att = _rt(k), _rt(v)
    else:
        # fp pool: round-trip through the pool dtype (no-op at float32)
        k_att = k.astype(kv[0].dtype).astype(jnp.float32)
        v_att = v.astype(kv[0].dtype).astype(jnp.float32)
    cache_k, cache_v = _paged_gather(kv, bt)  # f32 virtual caches
    scale = 1.0 / (q.shape[-1] ** 0.5)
    qf = q.astype(jnp.float32)
    s_cache = jnp.einsum("nhqd,nhkd->nhqk", qf, cache_k) * scale
    valid = jnp.arange(cache_k.shape[2])[None, None, None, :] < positions[:, None, None, None]
    s_cache = jnp.where(valid, s_cache, -1e30)
    s_blk = jnp.einsum("nhqd,nhkd->nhqk", qf, k_att) * scale
    s_blk = jnp.where(jnp.asarray(mask)[None, None, :, :], s_blk, -1e30)
    p_attn = jax.nn.softmax(jnp.concatenate([s_cache, s_blk], axis=-1), axis=-1)
    c_len = cache_k.shape[2]
    ctx = jnp.einsum("nhqk,nhkd->nhqd", p_attn[..., :c_len], cache_v) + jnp.einsum(
        "nhqk,nhkd->nhqd", p_attn[..., c_len:], v_att
    )
    ctx = _merge_heads(ctx.astype(x.dtype))
    x = x + ctx @ p["attn_out"]["w"].astype(x.dtype) + p["attn_out"]["b"].astype(x.dtype)
    normed2 = _ln(p["ln2"], x)
    hdn = jax.nn.gelu(
        normed2 @ p["mlp_in"]["w"].astype(x.dtype) + p["mlp_in"]["b"].astype(x.dtype),
        approximate=False,
    )
    x = x + hdn @ p["mlp_out"]["w"].astype(x.dtype) + p["mlp_out"]["b"].astype(x.dtype)
    return x, k, v


def paged_tree_verify(
    params: dict, pool: tuple, bt: jax.Array, tokens: jax.Array,
    positions: jax.Array, tree,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Score the flattened tree in ONE widened dispatch: tokens [n, width]
    (block 0 = the last emitted token, blocks 1.. = tree nodes), block j
    at position ``pos + depth(j)``. logits[i, j] is the target's
    next-token distribution AFTER consuming block j's token along its
    path — exactly what sequential decoding down that path would produce,
    which is what keeps greedy path acceptance bit-exact. Returns
    (logits [n, width, V], hidden [n, width, d], new_k
    [L, n, h, width, hd], new_v); ``hidden`` is each block's final-layer
    feature — the accepted path's last entry seeds the NEXT round's
    feature-draft root. The pool is untouched — ``paged_tree_commit``
    writes the accepted path."""
    heads = _heads(params)
    max_len = params["pos_emb"].shape[0]
    x = jnp.asarray(params["tok_emb"])[tokens]  # [n, width, d]
    pidx = jnp.clip(
        positions[:, None] + jnp.asarray(tree.block_depth)[None, :], 0, max_len - 1
    )
    x = x + jnp.asarray(params["pos_emb"])[pidx]
    mask = tree.ancestor_mask
    nk, nv = [], []
    for li, lp in enumerate(params["layers"]):
        layer_kv = tuple(a[li] for a in pool)
        x, k, v = _layer_tree_paged(lp, x, layer_kv, bt, positions, heads, mask)
        nk.append(k)
        nv.append(v)
    logits = _logits(params, x)  # [n, width, V]
    return logits, x, jnp.stack(nk), jnp.stack(nv)


def paged_tree_commit(
    pool: tuple, bt: jax.Array, new_k: jax.Array, new_v: jax.Array,
    path_idx: jax.Array, positions: jax.Array, n_acc: jax.Array,
) -> tuple:
    """Write the ACCEPTED path's K/V — the root block plus the chosen
    node at depths 1..n_acc — through the block tables at
    ``pos..pos+n_acc``; every column beyond ``n_acc + 1`` is
    junk-redirected by the counts mask, so the pool holds exactly what
    sequential decoding would have written and no speculative garbage."""
    L = new_k.shape[0]
    idx = jnp.broadcast_to(
        path_idx[None, :, None, :, None],
        new_k.shape[:3] + (path_idx.shape[1], new_k.shape[4]),
    )
    k_sel = jnp.take_along_axis(new_k, idx, axis=3)  # [L, n, h, D+1, hd]
    v_sel = jnp.take_along_axis(new_v, idx, axis=3)
    counts = n_acc + 1
    per_comp: list[list] = [[] for _ in pool]
    for li in range(L):
        layer_kv = _paged_write(
            tuple(a[li] for a in pool), k_sel[li], v_sel[li], bt, positions, counts
        )
        for acc, a in zip(per_comp, layer_kv):
            acc.append(a)
    return tuple(jnp.stack(acc) for acc in per_comp)


def draft_tree_commit(
    cache_k: jax.Array, cache_v: jax.Array, node_k: jax.Array, node_v: jax.Array,
    path_idx: jax.Array, positions: jax.Array, n_acc: jax.Array,
) -> tuple[jax.Array, jax.Array]:
    """The draft-side twin of ``paged_tree_commit``: write the accepted
    path's draft K/V into the FLAT draft cache at ``pos+1..pos+n_acc``
    (the root's entry at ``pos`` was written by the draft dispatch
    itself). node_k/node_v [L, n, h, n_tree, hd] are in block order, so
    ``path_idx[:, 1:] - 1`` selects the chosen node per depth; columns
    beyond ``n_acc`` keep the cache's current bytes (a masked select, so
    a zero-accept slot mutates nothing)."""
    D = path_idx.shape[1] - 1
    nidx = jnp.maximum(path_idx[:, 1:] - 1, 0)  # [n, D] node indices
    idx = jnp.broadcast_to(
        nidx[None, :, None, :, None], node_k.shape[:3] + (D, node_k.shape[4])
    )
    k_sel = jnp.take_along_axis(node_k, idx, axis=3)  # [L, n, h, D, hd]
    v_sel = jnp.take_along_axis(node_v, idx, axis=3)

    def upd(c, r, pos, cnt):  # c [h, ctx, hd]; r [h, D, hd]
        cur = lax.dynamic_slice(c, (0, pos, 0), r.shape)
        blk = jnp.where((jnp.arange(D) < cnt)[None, :, None], r, cur)
        return lax.dynamic_update_slice(c, blk, (0, pos, 0))

    write = jax.vmap(jax.vmap(upd), in_axes=(0, 0, None, None))
    cache_k = write(cache_k, k_sel.astype(cache_k.dtype), positions + 1, n_acc)
    cache_v = write(cache_v, v_sel.astype(cache_v.dtype), positions + 1, n_acc)
    return cache_k, cache_v


def speculative_accept_tree(
    target_logits: jax.Array,
    block_tokens: jax.Array,
    draft_logits: jax.Array,
    width_limits: jax.Array,
    temperature: jax.Array,
    top_k: jax.Array,
    key: jax.Array,
    tree,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Longest-accepted-PATH walk over the scored tree. Per depth, the
    current node's children (in branch order, gated by ``width_limits
    [n, depth]`` — the per-slot tighten/adapt mask; width 0 at a depth
    ends that slot's walk as a limit clamp, not a rejection) are tried:

    - greedy rows (temperature <= 0) accept the child matching the
      target's own argmax at the current node — bit-identical to
      sequential greedy decoding by induction, for ANY draft, since a
      match at depth d makes depth d+1's scored context exact too;
    - sampled rows run recursive rejection resampling (SpecInfer): each
      candidate c_i (i.i.d. from the draft's q) accepts with probability
      min(1, r(c_i)/q(c_i)) against the running residual r (r starts at
      the target's p; every rejection folds q out: r <- norm(max(r - q,
      0))), so the emitted marginal at every position is exactly the
      target's.

    The bonus token at the final node samples the target's p directly —
    or, after a TRUE rejection (candidates existed and all lost), the
    final residual, which is what preserves the distribution. Returns
    (out_tokens [n, depth+1] — slot i emits out[:n_acc[i]+1], n_acc [n],
    path_idx [n, depth+1] block indices, path_idx[:, 0] = 0)."""
    n, width, vocab = target_logits.shape
    D = tree.depth
    rows = jnp.arange(n)
    greedy_t = jnp.argmax(target_logits, axis=-1).astype(jnp.int32)  # [n, width]
    p_all = jax.nn.softmax(
        _transform_logits(target_logits, temperature[:, None], top_k[:, None]), axis=-1
    )
    q_all = jax.nn.softmax(
        _transform_logits(draft_logits, temperature[:, None], top_k[:, None]), axis=-1
    )
    child_tab = jnp.asarray(tree.child_table)  # [width, max_b]
    sampled_row = temperature > 0
    cur = jnp.zeros(n, jnp.int32)
    alive = jnp.ones(n, bool)
    n_acc = jnp.zeros(n, jnp.int32)
    rejected = jnp.zeros(n, bool)
    rej_dist = jnp.zeros((n, vocab), jnp.float32)
    path_blocks = []
    for d in range(1, D + 1):
        b = tree.branching[d - 1]
        kd = jax.random.fold_in(key, d)
        ch = child_tab[cur][:, :b]  # [n, b] candidate block indices
        ch_tok = jnp.take_along_axis(block_tokens, ch, axis=1)  # [n, b]
        p_cur = p_all[rows, cur]  # [n, V]
        q_cur = q_all[rows, cur]
        gt = greedy_t[rows, cur]  # [n]
        wl = width_limits[:, d - 1]
        step_ok = alive & (wl > 0)
        in_w = jnp.arange(b)[None, :] < wl[:, None]
        # greedy arm: at most one candidate can match (top-b is distinct)
        g_match = (ch_tok == gt[:, None]) & in_w
        g_any = jnp.any(g_match, axis=1)
        g_sel = jnp.argmax(g_match, axis=1).astype(jnp.int32)
        # sampled arm: recursive rejection over the i.i.d. candidates
        r = p_cur
        s_acc = jnp.zeros(n, bool)
        s_sel = jnp.zeros(n, jnp.int32)
        for bi in range(b):
            c_tok = ch_tok[:, bi]
            r_c = jnp.take_along_axis(r, c_tok[:, None], axis=1)[:, 0]
            q_c = jnp.take_along_axis(q_cur, c_tok[:, None], axis=1)[:, 0]
            u = jax.random.uniform(jax.random.fold_in(kd, bi), (n,))
            considered = in_w[:, bi] & ~s_acc
            ok_bi = considered & (u * q_c < r_c)  # u < r/q without dividing
            s_sel = jnp.where(ok_bi, bi, s_sel)
            s_acc = s_acc | ok_bi
            # a rejected candidate folds its proposal out of the residual
            upd = considered & ~ok_bi
            r_new = jnp.maximum(r - q_cur, 0.0)
            rs = jnp.sum(r_new, axis=-1, keepdims=True)
            r_new = jnp.where(rs > 1e-9, r_new / jnp.maximum(rs, 1e-9), p_cur)
            r = jnp.where(upd[:, None], r_new, r)
        acc_d = jnp.where(sampled_row, s_acc, g_any) & step_ok
        sel = jnp.where(sampled_row, s_sel, g_sel)
        new_cur = ch[rows, sel]
        # a TRUE rejection (candidates existed, all lost) pins the final
        # residual as this slot's bonus distribution; a limit clamp does
        # not (nothing was proposed there — bonus samples p directly)
        rej_now = step_ok & ~acc_d
        rej_dist = jnp.where((rej_now & ~rejected)[:, None], r, rej_dist)
        rejected = rejected | rej_now
        cur = jnp.where(acc_d, new_cur, cur)
        n_acc = n_acc + acc_d.astype(jnp.int32)
        alive = alive & acc_d
        path_blocks.append(cur)
    p_fin = p_all[rows, cur]
    dist = jnp.where(rejected[:, None], rej_dist, p_fin)
    bonus_sampled = jax.random.categorical(
        jax.random.fold_in(key, 0), jnp.log(dist + 1e-38), axis=-1
    ).astype(jnp.int32)
    bonus = jnp.where(sampled_row, bonus_sampled, greedy_t[rows, cur])
    path_idx = jnp.concatenate(
        [jnp.zeros((n, 1), jnp.int32), jnp.stack(path_blocks, axis=1)], axis=1
    )
    out = jnp.take_along_axis(block_tokens, path_idx[:, 1:], axis=1)  # [n, D]
    out = jnp.concatenate([out, jnp.zeros((n, 1), jnp.int32)], axis=1)
    out = out.at[rows, n_acc].set(bonus)
    return out, n_acc.astype(jnp.int32), path_idx


# ------------------------------------------------------ feature-level draft
# EAGLE-style feature drafting (Li et al., EAGLE): instead of a truncated-
# layer decoder re-embedding TOKENS, the draft head conditions on the
# TARGET's last hidden state — the final layer's residual-stream output,
# which the paged programs above already compute per committed position
# and thread out as ``hidden``. The head is ONE transformer layer plus a
# weight-tied LM head; its input at position j is
# ``fc([target_feature_{j-1} ; tok_emb(token_j)])`` (position 0 pads the
# feature with zeros), and during tree expansion the head autoregresses in
# FEATURE space: a depth-d node's input feature is its parent node's own
# output hidden (the draft's approximation of the target feature the
# target would have produced there). The target feature summarizes the
# whole prefix through the target's own stack, so acceptance beats any
# token-only draft of the same depth — the accept-rate headroom PR 8
# noted.
#
# Cache discipline is the tree draft's, unchanged: the head keeps a flat
# per-slot K/V cache ([1, n_slots, h, ctx, hd] — ``init_slot_cache`` on
# the head's one layer), the root step's write is never speculative,
# expansion K/V stays in-register, and only the accepted path commits
# (``draft_tree_commit`` with L=1). On warm (prefix-reuse) admissions the
# reused span has no draft K/V; ``starts`` opens the head's attention
# window at the computed suffix instead of reading zeroed rows.


def is_feature_draft(params) -> bool:
    """Whether a draft param tree is the feature-head layout (the ``fc``
    feature+embedding fuse marks it — a truncated-layer decoder has none)."""
    return isinstance(params, dict) and "fc" in params


def init_feature_draft(
    seed: int = 0, vocab: int = 512, hidden: int = 128, ffn: int = 256,
    max_len: int = 128,
) -> dict:
    """Feature-draft head params: the ``fc`` [2*hidden -> hidden] fuse, one
    decoder layer (same block structure as the target's, so every slot/tree
    building block above applies verbatim with L=1), own position table and
    a weight-tied LM head. ``hidden`` MUST equal the target's — the fuse
    consumes the target's feature vector directly.

    The rng draws follow ``init_decoder``'s positional order (tok_emb,
    pos_emb, the layer's qkv/attn_out/mlp_in/mlp_out; ``fc`` drawn LAST):
    built with the target's seed/vocab/hidden/ffn the head starts with
    the target's embeddings, weight-tied LM head, AND leading layer
    verbatim — the same stream-sharing trick the truncation draft rides,
    so distillation only has to learn the feature path, not re-derive the
    output geometry from scratch."""
    heads = _heads_for(hidden)
    if hidden % heads:
        raise ValueError(
            f"hidden={hidden} not divisible by its derived head count {heads}"
        )
    rng = np.random.default_rng(seed)
    return {
        "tok_emb": (rng.standard_normal((vocab, hidden)) * 0.02).astype(np.float32),
        "pos_emb": (rng.standard_normal((max_len, hidden)) * 0.02).astype(np.float32),
        "layers": [
            {
                "ln1": _ln_init(hidden),
                "qkv": _dense(rng, hidden, 3 * hidden),
                "attn_out": _dense(rng, hidden, hidden),
                "ln2": _ln_init(hidden),
                "mlp_in": _dense(rng, hidden, ffn),
                "mlp_out": _dense(rng, ffn, hidden),
            }
        ],
        "ln_f": _ln_init(hidden),
        "fc": _dense(rng, 2 * hidden, hidden),
    }


def _feature_fuse(params: dict, feats, tokens, pidx) -> jax.Array:
    """The head's input embedding: ``fc([feature ; tok_emb(token)])`` plus
    the position embedding. feats [n, m, d] aligned with tokens [n, m];
    pidx broadcastable position indices (already clipped)."""
    emb = jnp.asarray(params["tok_emb"])[tokens]  # [n, m, d]
    z = jnp.concatenate([feats.astype(emb.dtype), emb], axis=-1)
    x = z @ params["fc"]["w"].astype(emb.dtype) + params["fc"]["b"].astype(emb.dtype)
    return x + jnp.asarray(params["pos_emb"])[pidx]


def feature_sequence_logits(
    params: dict, ids: jax.Array, feats: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Teacher-forced head forward for distillation: ids[b, s] with the
    TARGET's aligned features feats[b, s, d] (``sequence_hidden``'s second
    output) -> (logits[b, s, vocab], head_hidden[b, s, d]). Input at
    position j fuses feature j-1 with token j (feature -1 = zeros), so
    logits[j] predicts token j+1 and head_hidden[j] is the head's
    approximation of feature j — the KL and feature-regression targets of
    the distillation recipe, and exactly the serving root step's
    conditioning (the root consumes the TRUE previous feature)."""
    ids = ids.astype(jnp.int32)
    heads = _heads(params)
    s = ids.shape[1]
    fin = jnp.concatenate(
        [jnp.zeros_like(feats[:, :1]), feats[:, :-1]], axis=1
    )
    x = _feature_fuse(params, fin, ids, jnp.arange(s)[None, :])
    for lp in params["layers"]:
        x, _, _ = _layer_prefill(lp, x, heads)
    return _logits(params, x), x


def feature_chunk_prefill(
    params: dict, cache_k, cache_v, tokens, target_hidden, prev_feat,
    positions, counts, starts,
) -> tuple[jax.Array, jax.Array]:
    """Teacher-forced head-side chunk prefill, fused into the target's
    chunk round: tokens[n, c] (the chunk's prompt ids), the target's fresh
    per-position hidden for the SAME chunk, and ``prev_feat[n, d]`` — the
    carried feature at position positions[i]-1 (the previous chunk's last
    hidden; zeroed when positions == starts, i.e. the slot's first chunk,
    matching the recipe's zero pad at position 0). Writes the head's K/V
    under the same ``counts`` mask the target chunk uses (counts-0 slots
    mutate nothing) with the ``starts`` attention window."""
    m = tokens.shape[1]
    heads = _heads(params)
    max_len = params["pos_emb"].shape[0]
    fin = jnp.concatenate([prev_feat[:, None, :], target_hidden[:, :-1, :]], axis=1)
    first = positions == starts
    fin = fin.at[:, 0, :].set(
        jnp.where(first[:, None], jnp.zeros_like(prev_feat), fin[:, 0, :])
    )
    pidx = jnp.clip(positions[:, None] + jnp.arange(m)[None, :], 0, max_len - 1)
    x = _feature_fuse(params, fin, tokens, pidx)
    new_k, new_v = [], []
    for li, lp in enumerate(params["layers"]):
        x, ck, cv = _layer_step_slots(
            lp, x, cache_k[li], cache_v[li], positions, heads,
            counts=counts, starts=starts,
        )
        new_k.append(ck)
        new_v.append(cv)
    return jnp.stack(new_k), jnp.stack(new_v)


def draft_propose_features(
    params: dict,
    cache_k: jax.Array,
    cache_v: jax.Array,
    feats: jax.Array,
    tokens: jax.Array,
    positions: jax.Array,
    starts: jax.Array,
    temperature: jax.Array,
    top_k: jax.Array,
    key: jax.Array,
    tree,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """``draft_propose_tree`` with the feature head: the root step fuses
    the slot's carried TARGET feature (``feats[n, d]`` — position
    ``pos - 1``'s final-layer hidden, threaded out of the previous verify
    / plain step / chunk round) with the last emitted token; each
    expansion depth fuses the PARENT NODE's own head hidden with the
    candidate token — autoregression in feature space, per EAGLE. Same
    candidate rule, RNG stream, block layout, in-register node K/V, and
    return shape as the token tree draft, so the scheduler's verify /
    accept / commit round half is shared unchanged."""
    heads = _heads(params)
    max_len = params["pos_emb"].shape[0]
    n = tokens.shape[0]
    # root step: consume the last emitted token at ``pos`` (its write is
    # never speculative) conditioned on the carried target feature
    pidx0 = jnp.clip(positions, 0, max_len - 1)[:, None]
    x = _feature_fuse(params, feats[:, None, :], tokens[:, None], pidx0)
    new_k, new_v = [], []
    for li, lp in enumerate(params["layers"]):
        x, ck, cv = _layer_step_slots(
            lp, x, cache_k[li], cache_v[li], positions, heads, starts=starts
        )
        new_k.append(ck)
        new_v.append(cv)
    cache_k, cache_v = jnp.stack(new_k), jnp.stack(new_v)
    logits0 = _logits(params, x)[:, 0, :]
    block_logits = [logits0[:, None, :]]
    node_tokens = []
    ek: list = [None] * len(params["layers"])
    ev: list = [None] * len(params["layers"])
    parent_logits = logits0[:, None, :]  # [n, 1, V]
    parent_feats = x  # [n, 1, d] — the head's own hidden, root block
    mask_np = tree.ancestor_mask
    for d in range(1, tree.depth + 1):
        b = tree.branching[d - 1]
        c_d = tree.level_counts[d - 1]
        toks_d = _tree_candidates(parent_logits, temperature, top_k, key, d, b)
        pf = jnp.repeat(parent_feats, b, axis=1)  # [n, c_d, d] parent-major
        pidx = jnp.clip(positions + d, 0, max_len - 1)[:, None]
        x = _feature_fuse(params, pf, toks_d, pidx)
        node_tokens.append(toks_d)
        start = tree.level_starts[d - 1]
        sub_mask = jnp.asarray(mask_np[start : start + c_d, 1 : start + c_d])
        for li, lp in enumerate(params["layers"]):
            x, ek[li], ev[li] = _layer_tree_flat(
                lp, x, cache_k[li], cache_v[li], positions, heads,
                ek[li], ev[li], sub_mask, starts=starts,
            )
        depth_logits = _logits(params, x)  # [n, c_d, V]
        block_logits.append(depth_logits)
        parent_logits = depth_logits
        parent_feats = x
    return (
        jnp.concatenate(node_tokens, axis=1),
        jnp.concatenate(block_logits, axis=1),
        jnp.stack(ek),
        jnp.stack(ev),
        cache_k,
        cache_v,
    )


def reference_generate(params: dict, ids: np.ndarray, max_new_tokens: int) -> np.ndarray:
    """Cache-less reference: full forward per step (the slow obvious
    implementation the scan version must match token-for-token)."""
    ids = np.asarray(ids, dtype=np.int32)
    heads = _heads(params)
    for _ in range(max_new_tokens):
        x = _embed(params, jnp.asarray(ids))
        for lp in params["layers"]:
            x, _, _ = _layer_prefill(lp, x, heads)
        nxt = np.asarray(jnp.argmax(_logits(params, x[:, -1:, :]), axis=-1))
        ids = np.concatenate([ids, nxt.astype(np.int32)], axis=1)
    return ids
