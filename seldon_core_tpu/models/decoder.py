"""GPT-style causal decoder with KV-cache generation — the generative
serving tier.

Greenfield vs the reference (SURVEY §2: classifiers/regressors only); the
TPU-native pieces are exactly the ones a naive port gets wrong:

- ONE compiled program per (batch bucket, prompt length): prefill computes
  every prompt position's K/V in one causal-attention pass (the same
  length-adaptive policy BERT serving uses — naive < 1024, blockwise, the
  Pallas causal kernel on TPU at long prompts), writes them into a
  [b, h, max_ctx, d] cache, then a ``lax.scan`` runs ``max_new_tokens``
  greedy steps — static shapes throughout, no Python loop, no recompiles.
- per-step attention is one [b, h, 1, d] query against the cache with a
  position mask (cache slots beyond the current length contribute zero
  mass), K/V written in place via ``lax.dynamic_update_slice``.
- outputs are int32 token ids (the serving wire keeps integer dtypes
  exact; float32 readback holds every id < 2^24).

Serving contract: apply(params, ids[b, s]) -> [b, s + max_new_tokens]
(prompt echoed, generated ids appended) — max_new_tokens is a DEPLOYMENT
parameter (static at trace time), the zoo entry is ``tiny_gpt``.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


def _dense(rng: np.random.Generator, n_in: int, n_out: int) -> dict:
    scale = (2.0 / (n_in + n_out)) ** 0.5
    return {
        "w": (rng.standard_normal((n_in, n_out)) * scale).astype(np.float32),
        "b": np.zeros((n_out,), np.float32),
    }


def _ln_init(d: int) -> dict:
    return {"scale": np.ones((d,), np.float32), "bias": np.zeros((d,), np.float32)}


def _ln(p: dict, x: jax.Array) -> jax.Array:
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mu) * lax.rsqrt(var + jnp.asarray(1e-5, x.dtype))
    return y * p["scale"].astype(x.dtype) + p["bias"].astype(x.dtype)


def init_decoder(
    seed: int = 0,
    vocab: int = 512,
    hidden: int = 128,
    layers: int = 2,
    ffn: int = 256,
    max_len: int = 128,
) -> dict:
    heads = _heads_for(hidden)
    if hidden % heads:
        raise ValueError(
            f"hidden={hidden} not divisible by its derived head count "
            f"{heads} (head_dim-64 convention) — a cryptic reshape error "
            "at first trace otherwise"
        )
    rng = np.random.default_rng(seed)
    return {
        "tok_emb": (rng.standard_normal((vocab, hidden)) * 0.02).astype(np.float32),
        "pos_emb": (rng.standard_normal((max_len, hidden)) * 0.02).astype(np.float32),
        "layers": [
            {
                "ln1": _ln_init(hidden),
                "qkv": _dense(rng, hidden, 3 * hidden),
                "attn_out": _dense(rng, hidden, hidden),
                "ln2": _ln_init(hidden),
                "mlp_in": _dense(rng, hidden, ffn),
                "mlp_out": _dense(rng, ffn, hidden),
            }
            for _ in range(layers)
        ],
        "ln_f": _ln_init(hidden),
        # lm head reuses tok_emb^T (weight tying, the standard decoder move)
    }


def _heads_for(hidden: int) -> int:
    return max(1, hidden // 64) if hidden >= 64 else 2


def _heads(params: dict) -> int:
    return _heads_for(params["layers"][0]["qkv"]["w"].shape[0])


def _split_heads(t: jax.Array, h: int) -> jax.Array:
    b, s, d = t.shape
    return t.reshape(b, s, h, d // h).transpose(0, 2, 1, 3)


def _merge_heads(t: jax.Array) -> jax.Array:
    b, h, s, hd = t.shape
    return t.transpose(0, 2, 1, 3).reshape(b, s, h * hd)


def _causal_attention(q, k, v):
    """Prefill attention: the shared backend-adaptive causal policy
    (ops/attention.causal_attention_auto — Pallas kernel on TPU at long
    prompts, pure JAX elsewhere)."""
    from seldon_core_tpu.ops.attention import causal_attention_auto

    return causal_attention_auto(q, k, v)


def _layer_prefill(p, x, h):
    """Returns (x_out, k[b,h,s,hd], v[b,h,s,hd]) for the cache."""
    normed = _ln(p["ln1"], x)
    qkv = normed @ p["qkv"]["w"].astype(x.dtype) + p["qkv"]["b"].astype(x.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q, k, v = _split_heads(q, h), _split_heads(k, h), _split_heads(v, h)
    ctx = _merge_heads(_causal_attention(q, k, v))
    x = x + ctx @ p["attn_out"]["w"].astype(x.dtype) + p["attn_out"]["b"].astype(x.dtype)
    normed2 = _ln(p["ln2"], x)
    hdn = jax.nn.gelu(
        normed2 @ p["mlp_in"]["w"].astype(x.dtype) + p["mlp_in"]["b"].astype(x.dtype),
        approximate=False,
    )
    x = x + hdn @ p["mlp_out"]["w"].astype(x.dtype) + p["mlp_out"]["b"].astype(x.dtype)
    return x, k, v


def _layer_step(p, x, cache_k, cache_v, pos, h):
    """One token through one layer against the cache. x: [b, 1, d]; cache
    [b, h, max_ctx, hd]; pos: scalar current position (tokens < pos are
    valid). Returns (x_out, cache_k, cache_v) with the new K/V written at
    ``pos``."""
    normed = _ln(p["ln1"], x)
    qkv = normed @ p["qkv"]["w"].astype(x.dtype) + p["qkv"]["b"].astype(x.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = _split_heads(q, h)  # [b, h, 1, hd]
    k = _split_heads(k, h)
    v = _split_heads(v, h)
    cache_k = lax.dynamic_update_slice(cache_k, k, (0, 0, pos, 0))
    cache_v = lax.dynamic_update_slice(cache_v, v, (0, 0, pos, 0))
    # masked dot attention over the whole (static) cache: slots > pos get
    # -inf, so their mass is exactly zero — no dynamic shapes
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), cache_k.astype(jnp.float32)) * scale
    valid = jnp.arange(cache_k.shape[2]) <= pos  # [max_ctx]
    s = jnp.where(valid[None, None, None, :], s, -1e30)
    p_attn = jax.nn.softmax(s, axis=-1)
    ctx = jnp.einsum("bhqk,bhkd->bhqd", p_attn, cache_v.astype(jnp.float32))
    ctx = _merge_heads(ctx.astype(x.dtype))
    x = x + ctx @ p["attn_out"]["w"].astype(x.dtype) + p["attn_out"]["b"].astype(x.dtype)
    normed2 = _ln(p["ln2"], x)
    hdn = jax.nn.gelu(
        normed2 @ p["mlp_in"]["w"].astype(x.dtype) + p["mlp_in"]["b"].astype(x.dtype),
        approximate=False,
    )
    x = x + hdn @ p["mlp_out"]["w"].astype(x.dtype) + p["mlp_out"]["b"].astype(x.dtype)
    return x, cache_k, cache_v


def _embed(params, ids, pos_offset: int = 0):
    # jnp.asarray: params may be host numpy on the direct (un-device_put)
    # call path, and numpy arrays cannot be indexed by tracers
    h = jnp.asarray(params["tok_emb"])[ids]
    return h + jnp.asarray(params["pos_emb"])[
        pos_offset : pos_offset + ids.shape[1]
    ][None, :, :]


def _logits(params, x):
    x = _ln(params["ln_f"], x)
    return x @ jnp.asarray(params["tok_emb"]).T.astype(x.dtype)  # weight-tied head


def generate(params: dict, ids: jax.Array, max_new_tokens: int) -> jax.Array:
    """Greedy decode: ids[b, s] int -> [b, s + max_new_tokens] int32.

    Prefill fills the KV caches in one causal pass; a lax.scan then runs
    ``max_new_tokens`` single-token steps. max_ctx = s + max_new_tokens is
    static, so one XLA program serves every request of this bucket."""
    ids = ids.astype(jnp.int32)
    b, s = ids.shape
    heads = _heads(params)
    max_ctx = s + max_new_tokens
    max_len = params["pos_emb"].shape[0]
    if max_ctx > max_len:
        raise ValueError(
            f"prompt {s} + max_new_tokens {max_new_tokens} exceeds the "
            f"position table ({max_len}) — raise max_len"
        )

    # ---- prefill
    x = _embed(params, ids)
    caches = []
    hd = x.shape[-1] // heads
    for lp in params["layers"]:
        x, k, v = _layer_prefill(lp, x, heads)
        ck = jnp.zeros((b, heads, max_ctx, hd), x.dtype)
        cv = jnp.zeros((b, heads, max_ctx, hd), x.dtype)
        ck = lax.dynamic_update_slice(ck, k, (0, 0, 0, 0))
        cv = lax.dynamic_update_slice(cv, v, (0, 0, 0, 0))
        caches.append((ck, cv))
    first_tok = jnp.argmax(_logits(params, x[:, -1:, :]), axis=-1)  # [b, 1]

    # ---- decode scan: carry = (token, pos, caches)
    cache_k = jnp.stack([c[0] for c in caches])  # [L, b, h, max_ctx, hd]
    cache_v = jnp.stack([c[1] for c in caches])

    def step(carry, _):
        tok, pos, ck_all, cv_all = carry
        x = _embed_one(params, tok, pos)
        new_k, new_v = [], []
        for li, lp in enumerate(params["layers"]):
            x, ck, cv = _layer_step(lp, x, ck_all[li], cv_all[li], pos, heads)
            new_k.append(ck)
            new_v.append(cv)
        nxt = jnp.argmax(_logits(params, x), axis=-1)  # [b, 1]
        return (nxt, pos + 1, jnp.stack(new_k), jnp.stack(new_v)), tok

    # max_new - 1 steps: each step consumes one already-chosen token and
    # chooses the next, and first_tok came from prefill — a full step for
    # the token after the last would be paid-for-then-discarded compute
    (last, _, _, _), toks = lax.scan(
        step, (first_tok, jnp.int32(s), cache_k, cache_v), None,
        length=max_new_tokens - 1,
    )
    # toks: the token CONSUMED by each step (first_tok first); `last` is
    # the final chosen token — together exactly max_new generated ids
    gen = jnp.concatenate(
        [toks[:, :, 0].T.reshape(b, -1), last], axis=1
    )
    return jnp.concatenate([ids, gen.astype(jnp.int32)], axis=1)


def _embed_one(params, tok: jax.Array, pos) -> jax.Array:
    """tok: [b, 1] -> [b, 1, d] with the position-``pos`` embedding."""
    h = jnp.asarray(params["tok_emb"])[tok]
    return h + lax.dynamic_slice_in_dim(
        jnp.asarray(params["pos_emb"]), pos, 1, axis=0
    )[None, :, :]


def reference_generate(params: dict, ids: np.ndarray, max_new_tokens: int) -> np.ndarray:
    """Cache-less reference: full forward per step (the slow obvious
    implementation the scan version must match token-for-token)."""
    ids = np.asarray(ids, dtype=np.int32)
    heads = _heads(params)
    for _ in range(max_new_tokens):
        x = _embed(params, jnp.asarray(ids))
        for lp in params["layers"]:
            x, _, _ = _layer_prefill(lp, x, heads)
        nxt = np.asarray(jnp.argmax(_logits(params, x[:, -1:, :]), axis=-1))
        ids = np.concatenate([ids, nxt.astype(np.int32)], axis=1)
    return ids
