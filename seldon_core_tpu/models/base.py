"""TPU model runtime: params resident in HBM, jitted apply, shape buckets.

This is the TPU replacement for the reference's model microservice
(wrappers/python/model_microservice.py): instead of a Flask/gRPC process per
model whose predict() runs wherever the container lands, a ModelRuntime keeps
the weights on device (replicated or sharded over a Mesh) and serves predict
as a jit-compiled XLA call per batch bucket.

XLA notes:
- one compiled program per (bucket, dtype) — buckets bound recompilation;
- params are device_put once with a NamedSharding (replicated by default,
  tensor-parallel if the model provides a param_sharding rule);
- inputs are padded host-side to the bucket then device_put with the batch
  axis sharded over the mesh "data" axis — on v5e-8 a bucket-512 ResNet batch
  lands 64-per-chip with XLA inserting no collectives until the loss-less
  output gather.
"""

from __future__ import annotations

import logging
import threading
import time
from functools import partial
from typing import Any, Callable, Mapping, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from seldon_core_tpu.core.message import SeldonMessage
from seldon_core_tpu.core.tensor import bucket_for, default_buckets, pad_batch
from seldon_core_tpu.engine.units import Unit
from seldon_core_tpu.graph.spec import PredictiveUnit

# host-backend forwards at or above this stall the event loop enough to
# tax other tenants' latency; offload_compute="auto" moves them to the
# worker pool at warmup. (The r4 bench's 73 ms multi-tenant lag spikes
# turned out to be gen-2 GC pauses, fixed by serving/gc_policy.py — this
# guard covers the genuinely-compute-bound case: any model whose measured
# forward exceeds the threshold.)
OFFLOAD_MIN_FORWARD_MS = 3.0

_COMPUTE_POOL = None
_COMPUTE_POOL_LOCK = threading.Lock()


def compute_pool():
    """Shared worker pool for offloaded model forwards. Small on purpose:
    XLA CPU execution already parallelizes internally and releases the GIL;
    the pool exists for loop isolation, not throughput."""
    global _COMPUTE_POOL
    if _COMPUTE_POOL is None:
        with _COMPUTE_POOL_LOCK:
            if _COMPUTE_POOL is None:
                from concurrent.futures import ThreadPoolExecutor

                _COMPUTE_POOL = ThreadPoolExecutor(
                    max_workers=2, thread_name_prefix="seldon-compute"
                )
    return _COMPUTE_POOL

log = logging.getLogger(__name__)

ApplyFn = Callable[[Any, jax.Array], jax.Array]


class ModelRuntime:
    """One model loaded onto the device mesh.

    apply_fn(params, x[batch, ...]) -> y[batch, ...] must be pure/jittable.
    """

    def __init__(
        self,
        apply_fn: ApplyFn,
        params: Any,
        *,
        mesh: Mesh | None = None,
        data_axis: str = "data",
        param_pspecs: Any | None = None,  # pytree of PartitionSpec for TP models
        buckets: Sequence[int] = (),
        max_batch: int = 64,
        dtype: Any = jnp.float32,
        class_names: Sequence[str] = (),
        donate: bool = True,
        int_inputs: str = "cast",
        weight_quant: str = "",
        offload_compute: str = "auto",
    ):
        self.apply_fn = apply_fn
        self.mesh = mesh
        self.data_axis = data_axis
        self.dtype = dtype
        if weight_quant not in ("", "int8"):
            raise ValueError(f"weight_quant must be '' or 'int8', got {weight_quant!r}")
        self.weight_quant = weight_quant
        if int_inputs not in ("cast", "ids"):
            raise ValueError(f"int_inputs must be 'cast' or 'ids', got {int_inputs!r}")
        # "cast": integer payloads are VALUES (images/tabular) — normalize to
        # the model dtype. "ids": integers are token ids — normalize to int32
        # so every id stays exact (casting ids through bf16 corrupts >= 257).
        self.int_inputs = int_inputs
        self.class_names = tuple(class_names)
        self._host_backend = all(d.platform == "cpu" for d in jax.devices())
        self._donate = donate  # donation invalidates caller-held input
        # buffers, so the device-array fast path must not feed them through
        self.stat_device_fastpath = 0
        if offload_compute not in ("auto", "always", "never"):
            raise ValueError(
                "offload_compute must be 'auto', 'always' or 'never', got "
                f"{offload_compute!r}"
            )
        # event-loop guard (VERDICT r4 Weak #6): on the host CPU backend a
        # wide model's forward runs synchronously and stalls the shared
        # serving loop for every tenant. "auto" resolves at warmup() from a
        # measured forward time; until then only "always" offloads.
        self.offload_compute_mode = offload_compute
        self.offload_compute = offload_compute == "always"
        # generative decode geometry ({"seq", "max_new_tokens"}) — set by
        # the zoo factory for decoder models; consumed by the decode
        # scheduler opt-in (serving/decode_scheduler.scheduler_for_executor)
        self.generative: dict | None = None
        self.stat_forward_ms: float | None = None
        self.buckets = tuple(buckets) if buckets else default_buckets(max_batch)
        if mesh is not None and data_axis in mesh.axis_names:
            # batch shards over the data axis, so every compiled bucket must
            # be divisible by its size — a bucket-1 program on a data=8 mesh
            # is not shardable. Round buckets up to the axis multiple (padding
            # covers the difference, exactly as for non-power-of-two batches).
            d = int(mesh.shape[data_axis])
            self.buckets = tuple(sorted({((b + d - 1) // d) * d for b in self.buckets}))
        self._lock = threading.Lock()

        if weight_quant == "int8":
            # weight-only int8 (models/quant.py): quantize from the original
            # precision, keep scales float32, dequantize INSIDE the jitted
            # program where XLA fuses it into the matmul operand read
            from seldon_core_tpu.models.quant import (
                dequantize,
                is_quantized_leaf,
                quantize_params,
                quantized_pspecs,
            )

            params = quantize_params(params)

            def _place(x):
                if is_quantized_leaf(x):
                    # int8 payload as-is; scales STAY float32 (casting the
                    # scale to bf16 would waste the per-channel precision)
                    return {
                        k: jnp.asarray(v) for k, v in x.items()
                    }
                return jnp.asarray(x, dtype=self._param_dtype(x))

            params = jax.tree.map(_place, params, is_leaf=is_quantized_leaf)
            if param_pspecs is not None:
                param_pspecs = quantized_pspecs(param_pspecs, params)
            inner_apply = apply_fn
            compute_dtype = self.dtype  # capture the value, not self: the
            # closure escapes via as_pure_fn into fused runtimes, and
            # capturing self would pin this runtime's params + executables

            def apply_fn(p, x):  # noqa: F811 - deliberate wrap
                return inner_apply(dequantize(p, compute_dtype), x)

            # expose the wrapped apply: as_pure_fn consumers (graph fusion)
            # must pair self.params (quantized) with an apply that dequantizes
            self.apply_fn = apply_fn
        else:
            from seldon_core_tpu.models.quant import is_quantized_leaf

            def _place_plain(a):
                if is_quantized_leaf(a):
                    # params may arrive ALREADY quantized (e.g. a fused graph
                    # rebuilding a runtime from a quantized member): keep the
                    # int8 payload and the f32 scale exactly as stored —
                    # _param_dtype would silently downcast the scales
                    return {k: jnp.asarray(v) for k, v in a.items()}
                return jnp.asarray(a, dtype=self._param_dtype(a))

            params = jax.tree.map(_place_plain, params, is_leaf=is_quantized_leaf)

        # Wire-dtype policy, enforced at the jit boundary:
        # - uint8 inputs (the binary image wire dtype) cast to the model
        #   dtype ON DEVICE — the uint8 batch crosses host->device at 1
        #   byte/value and the cast fuses into the first op. Other integer
        #   dtypes pass through untouched: they are token ids, and casting
        #   ids to bf16 would corrupt every id >= 257 (bf16 has an 8-bit
        #   mantissa); models that take ids cast to int32 themselves.
        # - outputs come back float32: bf16 is a compute/storage dtype, not
        #   a wire dtype — clients can't decode it (npy has no bf16) and
        #   bf16 device->host readback pays a slow conversion fallback
        #   (measured ~5x the f32 readback on this harness). The cast runs
        #   inside jit, fused into the last op; integer outputs pass through.
        low_precision = jnp.dtype(self.dtype).itemsize < 4
        self._low_precision = low_precision

        def serving_fn(p, x):
            if x.dtype == jnp.uint8:
                x = x.astype(self.dtype)
            elif low_precision and x.dtype == jnp.float32:
                # graph-internal hops deliver float32 (outputs below are
                # cast to f32 inside jit); low-precision models take them
                # device-side and cast here, fused into the first op —
                # otherwise every bf16 model->model hop would bounce
                # through the host for a dtype normalization
                x = x.astype(self.dtype)
            y = apply_fn(p, x)
            if low_precision:
                y = jax.tree.map(
                    lambda a: a.astype(jnp.float32)
                    if jnp.issubdtype(a.dtype, jnp.floating)
                    else a,
                    y,
                )
            return y

        if mesh is not None:
            pspecs = param_pspecs if param_pspecs is not None else jax.tree.map(
                lambda _: P(), params
            )

            dropped_axes: set[str] = set()

            def to_mesh_spec(s) -> P:
                # a model's PartitionSpecs may name axes this mesh doesn't
                # have (TP specs on a data/seq-only mesh): those dimensions
                # degrade to replicated instead of erroring
                if not isinstance(s, P):
                    return P()
                axes = set(mesh.axis_names)

                def keep(entry):
                    if entry is None:
                        return None
                    if isinstance(entry, (tuple, list)):
                        kept = tuple(a for a in entry if a in axes)
                        dropped_axes.update(a for a in entry if a not in axes)
                        return kept if kept else None
                    if entry in axes:
                        return entry
                    dropped_axes.add(entry)
                    return None

                return P(*(keep(e) for e in s))

            shardings = jax.tree.map(
                lambda s: NamedSharding(mesh, to_mesh_spec(s)),
                pspecs,
                is_leaf=lambda x: isinstance(x, P) or x is None,
            )
            if dropped_axes:
                # a misspelled TP axis silently replicating every weight is
                # an HBM multiplier the operator should know about
                log.warning(
                    "param shardings name axes %s missing from mesh %s — "
                    "those dimensions are now REPLICATED (full param copy "
                    "per device along the missing axis)",
                    sorted(dropped_axes),
                    dict(mesh.shape),
                )
            self.params = jax.device_put(params, shardings)
            # batch axis shards over "data" when the mesh has it; a mesh
            # without it (e.g. pure seq-parallel serving) replicates the
            # batch and lets the apply's own collectives do the work
            batch_spec = P(data_axis) if data_axis in mesh.axis_names else P()
            self._in_sharding = NamedSharding(mesh, batch_spec)
            self._out_sharding = NamedSharding(mesh, batch_spec)
            self._jit = jax.jit(
                serving_fn,
                in_shardings=(shardings, self._in_sharding),
                out_shardings=self._out_sharding,
                donate_argnums=(1,) if donate else (),
            )
        else:
            self.params = jax.device_put(params)
            self._in_sharding = None
            self._jit = jax.jit(serving_fn, donate_argnums=(1,) if donate else ())
        # where the params live — the device-array fast path must not feed
        # a jit an input committed elsewhere (jax raises incompatible-devices
        # where the old host round-trip re-placed it). Param-less models
        # (test stubs) get None, which disables the unsharded fast path.
        leaves = jax.tree.leaves(self.params)
        self._param_devices = leaves[0].devices() if leaves else None

    def _param_dtype(self, a) -> Any:
        a = jnp.asarray(a)
        return self.dtype if jnp.issubdtype(a.dtype, jnp.floating) else a.dtype

    # -------------------------------------------------------------- predict
    def predict(self, x: np.ndarray) -> np.ndarray:
        """Host-in host-out batched predict with bucket padding."""
        y = self.predict_device(x)
        return np.asarray(y)

    def predict_device(self, x: np.ndarray) -> jax.Array:
        """Like predict but leaves the result on device (graph-internal hops
        between JAX nodes never touch the host)."""
        if (
            isinstance(x, jax.Array)
            and not self._host_backend
            and not self._donate
            # fast path only for signatures warmup compiled: the model's
            # input dtype — or float32 for low-precision models, since
            # graph-internal hops deliver f32 (serving_fn casts in-jit and
            # warmup compiles that signature) — and the batch exactly a
            # bucket. Anything else falls through to the host normalization
            # below (np.asarray on a device array is a READBACK; skipping
            # it is the whole point of this branch)
            and (
                x.dtype == jnp.int32
                if self.int_inputs == "ids"
                else (
                    x.dtype == jnp.dtype(self.dtype)
                    or (self._low_precision and x.dtype == jnp.float32)
                )
            )
            and bucket_for(int(x.shape[0]), self.buckets) == int(x.shape[0])
            # placement: with a mesh, device_put below reshards any input;
            # without one, only accept inputs already on the params' device
            # (a different-device input would make the jit raise where the
            # old host round-trip silently re-placed it)
            and (self._in_sharding is not None or x.devices() == self._param_devices)
        ):
            self.stat_device_fastpath += 1
            if self._in_sharding is not None:
                x = jax.device_put(x, self._in_sharding)  # no-op if placed
            return self._jit(self.params, x)
        x = np.asarray(x)
        # Dtype normalization: every wire form maps onto exactly the
        # signatures warmup compiled (a live request must never hit a fresh
        # XLA compile).
        if self.int_inputs == "ids":
            # ids models consume int32 whatever the wire encoding — the
            # JSON wire delivers floats, and float32 holds every id < 2^24
            # exactly, so this round-trip is lossless (casting ids through
            # bf16 would corrupt >= 257)
            x = np.asarray(x, dtype=np.int32)
        elif x.dtype == np.uint8 and self._uint8_wire():
            pass  # binary image wire dtype: 1 byte/value over the wire,
            # cast to model dtype INSIDE jit (serving_fn); warmed
        else:
            # floats (f64 json, f32/f16 npy) and value-like ints normalize
            # to the model dtype
            x = np.asarray(x, dtype=self.dtype)
        n = x.shape[0]
        bucket = bucket_for(n, self.buckets)
        if bucket is None:
            # larger than the biggest bucket: split into max-bucket chunks
            outs = []
            step = self.buckets[-1]
            for i in range(0, n, step):
                outs.append(self.predict_device(x[i : i + step]))
            return jnp.concatenate(outs, axis=0)
        padded, valid = pad_batch(x, bucket)
        if self._in_sharding is not None:
            padded = jax.device_put(padded, self._in_sharding)
        y = self._jit(self.params, padded)
        if valid == bucket:
            return y
        if self._host_backend:
            # CPU jax arrays view into host memory: numpy slice is free
            # (~1 us) where the jnp getitem path pays ~95 us of eager
            # dispatch per call
            return np.asarray(y)[:valid]
        # accelerator: keep the result ON DEVICE for graph-internal hops
        # (readback here would pay host transfer per node); lax.slice_in_dim
        # skips the generic jnp indexing rewrite (~3x cheaper dispatch)
        return jax.lax.slice_in_dim(y, 0, valid, axis=0)

    def _uint8_wire(self) -> bool:
        """uint8 rides to the device raw only for image-shaped value models
        — exactly the signature set warmup compiles. Unknown feature shape
        (no warmup ran) means no warmed uint8 program, so cast on host."""
        if self.int_inputs != "cast":
            return False
        shape = getattr(self, "feature_shape", None)
        return shape is not None and len(tuple(shape)) >= 2

    def warmup(self) -> None:
        """Compile every bucket ahead of traffic (first XLA compile is tens
        of seconds on TPU; serving must not pay that on a live request).

        Signatures warmed per bucket mirror predict_device's normalization
        exactly: ids models compile int32 only (every wire form maps to
        it); value models compile the model float dtype, plus uint8 for
        image-shaped inputs (rank >= 2 features — tabular payloads always
        normalize to the float form), plus float32 for low-precision
        models (graph-internal hops deliver f32 device arrays; the fast
        path feeds them to the f32-input program, cast in-jit)."""
        feat_shape = self._example_feature_shape()
        if self.int_inputs == "ids":
            wire_dtypes = [np.int32]
        elif self._uint8_wire():
            wire_dtypes = [self.dtype, np.uint8]
        else:
            wire_dtypes = [self.dtype]
        first = True
        for b in self.buckets:
            for dt in wire_dtypes:
                x = np.zeros((b, *feat_shape), dtype=dt)
                _ = self.predict(x[:1]) if first else self.predict(x)
                first = False
            if (
                self._low_precision
                and self.int_inputs != "ids"
                and not self._host_backend
                and not self._donate
            ):
                # the f32 graph-hop signature must be warmed THROUGH the
                # device fast path: the host path would normalize f32 to
                # the model dtype and compile the wrong program
                y = self.predict_device(
                    jnp.asarray(np.zeros((b, *feat_shape), np.float32))
                )
                jax.block_until_ready(y)
        if self.offload_compute_mode == "auto" and self._host_backend:
            # measure the LARGEST bucket (the one that stalls the loop):
            # all buckets are compiled by now, so this is pure execution
            x = np.zeros((max(self.buckets), *feat_shape), dtype=wire_dtypes[0])
            self.stat_forward_ms = self._measure_forward_ms(x)
            self.offload_compute = self.stat_forward_ms >= OFFLOAD_MIN_FORWARD_MS

    def _measure_forward_ms(self, x: np.ndarray, runs: int = 3) -> float:
        """Median synchronous forward time — the per-batch stall a host-
        backend model imposes on the event loop (patchable in tests)."""
        times = []
        for _ in range(runs):
            t0 = time.perf_counter()
            self.predict(x)
            times.append(time.perf_counter() - t0)
        times.sort()
        return times[len(times) // 2] * 1e3

    def _example_feature_shape(self) -> tuple[int, ...]:
        shape = getattr(self, "feature_shape", None)
        if shape is None:
            raise ValueError("set runtime.feature_shape before warmup()")
        return tuple(shape)


class JaxModelUnit(Unit):
    """Graph unit backed by a ModelRuntime (MODEL node, TPU-resident)."""

    def __init__(self, spec: PredictiveUnit, runtime: ModelRuntime):
        super().__init__(spec)
        self.runtime = runtime

    async def transform_input(self, msg: SeldonMessage) -> SeldonMessage:
        if msg.data is None:
            # opaque binData/strData reached a tensor model: reject with the
            # reference error taxonomy instead of np.asarray(None) blowing
            # up into a bare 500 (npy binData was already decoded at the
            # serving ingress; anything left here is undecodable)
            from seldon_core_tpu.core.errors import APIException, ErrorCode

            raise APIException(
                ErrorCode.ENGINE_INVALID_JSON,
                f"MODEL node '{self.spec.name}' needs tensor data; opaque "
                "binData/strData is not a tensor (use npy binData or the "
                "data arm)",
            )
        x = msg.array
        if not isinstance(x, jax.Array):
            # lists / numpy normalize on host; device arrays pass through so
            # predict_device's fast path can keep graph-internal hops
            # on-device (np.asarray here would force a readback)
            x = np.asarray(x)
        if self.runtime.offload_compute:
            # event-loop guard: slow host-backend forwards run on the worker
            # pool (XLA releases the GIL during execution) so this tenant's
            # compute cannot add tens of ms of scheduling lag to every other
            # tenant sharing the serving loop
            import asyncio

            y = await asyncio.get_running_loop().run_in_executor(
                compute_pool(), self.runtime.predict_device, x
            )
        else:
            y = self.runtime.predict_device(x)
        return msg.with_array(y, self.runtime.class_names or msg.names)

    def as_pure_fn(self):
        return self.runtime.apply_fn, self.runtime.params
