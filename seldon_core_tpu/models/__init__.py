from seldon_core_tpu.models.base import JaxModelUnit, ModelRuntime
from seldon_core_tpu.models.zoo import get_model, list_models, register_model

__all__ = ["JaxModelUnit", "ModelRuntime", "get_model", "list_models", "register_model"]
