"""BERT-base encoder in pure JAX with tensor-parallel PartitionSpecs.

Parity role: BASELINE.json's "Full DAG: input Transformer -> epsilon-greedy
Router -> BERT-base models -> Combiner" config. The reference would run each
BERT as its own GPU container; here it is a params pytree whose attention/MLP
weights carry PartitionSpecs so ModelRuntime can shard them over the mesh
"model" axis (Megatron-style TP: qkv column-split, output row-split — the
all-reduce after the row-split matmul is inserted by XLA from the shardings,
never hand-written).

Serving contract: apply(params, x) where x is int token ids [batch, seq]
(arriving as the SeldonMessage float tensor; cast inside — TPU serving keeps
one input dtype at the edge). Output: [batch, num_classes] probabilities.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from seldon_core_tpu.models.zoo import ModelSpec, register_model


# Host-side numpy init (see models/resnet.py): one device_put instead of one
# compiled rng program per tensor — matters on tunneled/remote devices.
import numpy as np


def _dense_init(rng: np.random.Generator, n_in, n_out):
    scale = (2.0 / (n_in + n_out)) ** 0.5
    return {
        "w": (rng.standard_normal((n_in, n_out)) * scale).astype(np.float32),
        "b": np.zeros((n_out,), np.float32),
    }


def _ln_init(d):
    return {"scale": np.ones((d,), np.float32), "bias": np.zeros((d,), np.float32)}


def _ln(p, x, eps=1e-6):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    y = (x - mu) * jax.lax.rsqrt(var + jnp.asarray(eps, x.dtype))
    return y * p["scale"].astype(x.dtype) + p["bias"].astype(x.dtype)


def _layer_init(rng, hidden, ffn):
    return {
        "qkv": _dense_init(rng, hidden, 3 * hidden),
        "attn_out": _dense_init(rng, hidden, hidden),
        "ln1": _ln_init(hidden),
        "mlp_in": _dense_init(rng, hidden, ffn),
        "mlp_out": _dense_init(rng, ffn, hidden),
        "ln2": _ln_init(hidden),
    }


def _pallas_eligible(k: jax.Array) -> bool:
    """The hand-tiled kernel needs the KV axis to divide its 128 block (no
    in-kernel masking) and a jax build with pltpu types (interpret mode
    included). Shapes are static at trace time so this resolves during
    compilation, never per request."""
    from seldon_core_tpu.ops.pallas_flash import pallas_available

    return pallas_available() and k.shape[2] % 128 == 0


def _default_attention(q, k, v):
    """seq-length-adaptive: dense einsum below FLASH_MIN_SEQ; above it, the
    Pallas flash kernel (ops/pallas_flash — VMEM-streamed online softmax on
    the MXU) on the TPU backend, pure-JAX blockwise elsewhere. The length
    policy constant lives in ops/attention so seq-parallel local bodies
    can't drift from it."""
    from seldon_core_tpu.ops.attention import FLASH_MIN_SEQ, PALLAS_MIN_SEQ

    if q.shape[2] >= FLASH_MIN_SEQ:
        if (
            q.shape[2] >= PALLAS_MIN_SEQ
            and jax.default_backend() == "tpu"
            and _pallas_eligible(k)
        ):
            from seldon_core_tpu.ops.pallas_flash import flash_attention

            return flash_attention(q, k, v)
        from seldon_core_tpu.ops.attention import blockwise_attention

        return blockwise_attention(q, k, v, block_size=512)
    from seldon_core_tpu.ops.attention import naive_attention

    return naive_attention(q, k, v)


def _pallas_attention(q, k, v):
    """Forced-Pallas impl (attn_kernel=pallas): interpret mode off-TPU, so a
    CI deployment on the CPU mesh exercises the same kernel code path the
    chip compiles with Mosaic. Falls back to blockwise only when the kernel
    is not viable (pltpu-less build, or a static KV length its block sizes
    can't tile), mirroring _default_attention. Short sequences (<= one KV
    block) tile trivially — _kv_block caps the block at the sequence."""
    from seldon_core_tpu.ops.pallas_flash import (
        DEFAULT_BLOCK_K,
        flash_attention,
        pallas_available,
    )

    sk = k.shape[2]
    # sublane alignment (16 for bf16) + either the 128-lane tiling or a
    # single-KV-block fit (the kernel caps its block at the sequence)
    if pallas_available() and sk % 16 == 0 and (
        sk % 128 == 0 or sk <= DEFAULT_BLOCK_K
    ):
        return flash_attention(q, k, v)
    from seldon_core_tpu.ops.attention import blockwise_attention

    return blockwise_attention(q, k, v, block_size=512)


def _blockwise_only_attention(q, k, v):
    """attn_kernel=blockwise: the pure-JAX path at any length — the control
    leg the bench compares the Pallas kernel against."""
    from seldon_core_tpu.ops.attention import blockwise_attention

    return blockwise_attention(q, k, v, block_size=512)


# attn_kernel knob -> attention impl for the NON-seq-parallel path. Values
# are module-level functions (not per-build closures) so two builds of the
# same config share apply-fn identity — what lets engine/fused.py stack a
# homogeneous ensemble and vmap once.
_KERNEL_IMPLS = {
    "auto": None,  # _default_attention policy
    "pallas": _pallas_attention,
    "blockwise": _blockwise_only_attention,
}


def make_ring_attention(mesh, seq_axis: str = "seq"):
    """Sequence-parallel attention impl for serving long contexts over a
    mesh: K/V shards rotate over ICI (ops/ring_attention.py) so each device
    holds O(seq/ring) of the sequence. Plug into build_bert_* via
    attn_impl."""

    def impl(q, k, v):
        ring = mesh.shape[seq_axis]
        if q.shape[2] % ring != 0:
            # shapes are static at trace time: lengths the ring can't split
            # evenly fall back to the length-adaptive single-device path
            # instead of erroring the request
            return _default_attention(q, k, v)
        from seldon_core_tpu.ops.ring_attention import ring_attention

        return ring_attention(q, k, v, mesh, seq_axis=seq_axis)

    return impl


def make_ulysses_attention_impl(mesh, seq_axis: str = "seq"):
    """The all-to-all (Ulysses-style) seq-parallel twin of
    make_ring_attention: heads scatter / sequence gathers for the attention
    op, then reverses (ops/ulysses.py). Same graceful fallback to the
    single-device path when shapes don't divide the mesh axis."""

    def impl(q, k, v):
        n = mesh.shape[seq_axis]
        if q.shape[2] % n != 0 or q.shape[1] % n != 0:
            return _default_attention(q, k, v)
        from seldon_core_tpu.ops.ulysses import ulysses_attention

        return ulysses_attention(q, k, v, mesh, seq_axis=seq_axis)

    return impl


def _attention(p, x, num_heads, attn_impl=None):
    b, s, d = x.shape
    head = d // num_heads
    qkv = x @ p["qkv"]["w"].astype(x.dtype) + p["qkv"]["b"].astype(x.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(b, s, num_heads, head).transpose(0, 2, 1, 3)

    q, k, v = heads(q), heads(k), heads(v)
    ctx = (attn_impl or _default_attention)(q, k, v)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, d)
    return ctx @ p["attn_out"]["w"].astype(x.dtype) + p["attn_out"]["b"].astype(x.dtype)


def _layer_apply(p, x, num_heads, attn_impl=None):
    x = _ln(p["ln1"], x + _attention(p, x, num_heads, attn_impl))
    # erf gelu, not the tanh approximation: BERT (paper and HF) uses the
    # exact form, so imported checkpoints reproduce their torch logits
    h = jax.nn.gelu(
        x @ p["mlp_in"]["w"].astype(x.dtype) + p["mlp_in"]["b"].astype(x.dtype),
        approximate=False,
    )
    h = h @ p["mlp_out"]["w"].astype(x.dtype) + p["mlp_out"]["b"].astype(x.dtype)
    return _ln(p["ln2"], x + h)


def init_bert(
    seed: int = 0,
    vocab: int = 30522,
    hidden: int = 768,
    layers: int = 12,
    ffn: int = 3072,
    max_len: int = 512,
    num_classes: int = 2,
) -> dict:
    """Head count is hidden//64 by convention (head_dim 64, BERT-base
    geometry) — see _infer_heads; it is derived from the params at apply
    time, never stored."""
    rng = np.random.default_rng(seed)
    params: dict[str, Any] = {
        "tok_emb": (rng.standard_normal((vocab, hidden)) * 0.02).astype(np.float32),
        "pos_emb": (rng.standard_normal((max_len, hidden)) * 0.02).astype(np.float32),
        "ln_emb": _ln_init(hidden),
        "layers": [_layer_init(rng, hidden, ffn) for _ in range(layers)],
        "head": _dense_init(rng, hidden, num_classes),
    }
    return params


def bert_pspecs(params: dict) -> dict:
    """Megatron-style TP over the mesh 'model' axis:
    qkv / mlp_in column-parallel, attn_out / mlp_out row-parallel;
    embeddings + layernorms + head replicated. XLA inserts the row-parallel
    all-reduce from these shardings."""

    def layer_spec(_):
        return {
            "qkv": {"w": P(None, "model"), "b": P("model")},
            "attn_out": {"w": P("model", None), "b": P()},
            "ln1": {"scale": P(), "bias": P()},
            "mlp_in": {"w": P(None, "model"), "b": P("model")},
            "mlp_out": {"w": P("model", None), "b": P()},
            "ln2": {"scale": P(), "bias": P()},
        }

    specs = {
        "tok_emb": P(),
        "pos_emb": P(),
        "ln_emb": {"scale": P(), "bias": P()},
        "layers": [layer_spec(l) for l in params["layers"]],
        "head": {"w": P(), "b": P()},
    }
    if "pooler" in params:  # imported checkpoints carry the HF tanh pooler
        specs["pooler"] = {"w": P(), "b": P()}
    return specs


def bert_logits(params: dict, x: jax.Array, attn_impl=None) -> jax.Array:
    """x: token ids [batch, seq] (any numeric dtype) -> logits [batch, classes]."""
    ids = x.astype(jnp.int32)
    num_heads = _infer_heads(params)
    compute_dtype = params["tok_emb"].dtype
    h = params["tok_emb"][ids] + params["pos_emb"][: ids.shape[1]][None, :, :]
    h = _ln(params["ln_emb"], h.astype(compute_dtype))
    for lp in params["layers"]:
        h = _layer_apply(lp, h, num_heads, attn_impl)
    cls = h[:, 0, :]  # [CLS] pooling
    pooler = params.get("pooler")
    if pooler is not None:
        # HF/original BERT classification head: tanh pooler before the
        # classifier (BertPooler) — present only on imported checkpoints,
        # init_bert's native head classifies [CLS] directly
        cls = jnp.tanh(
            cls @ pooler["w"].astype(cls.dtype) + pooler["b"].astype(cls.dtype)
        )
    return cls @ params["head"]["w"].astype(cls.dtype) + params["head"]["b"].astype(
        cls.dtype
    )


def apply_bert(params: dict, x: jax.Array) -> jax.Array:
    """Serving entrypoint: softmax probabilities."""
    return jax.nn.softmax(bert_logits(params, x), axis=-1)


def make_apply_bert(attn_impl):
    """apply_bert with a custom attention impl (e.g. make_ring_attention)."""

    def apply(params, x):
        return jax.nn.softmax(bert_logits(params, x, attn_impl), axis=-1)

    return apply


def _infer_heads(params: dict) -> int:
    hidden = params["layers"][0]["qkv"]["w"].shape[0]
    return max(1, hidden // 64)


# memoized per (mesh, strategy) / per kernel: fused.py detects homogeneous
# ensembles by apply-fn IDENTITY, so two builds of the same config must get
# the same function object
_RING_APPLY_CACHE: dict = {}
_KERNEL_APPLY_CACHE: dict = {}


def _apply_for_kernel(attn_kernel: str):
    """Single-device/no-seq-mesh apply for an attn_kernel knob value."""
    if attn_kernel not in _KERNEL_IMPLS:
        raise ValueError(
            f"attn_kernel must be one of {sorted(_KERNEL_IMPLS)}, got "
            f"{attn_kernel!r}"
        )
    if attn_kernel == "auto":
        return apply_bert
    fn = _KERNEL_APPLY_CACHE.get(attn_kernel)
    if fn is None:
        fn = make_apply_bert(_KERNEL_IMPLS[attn_kernel])
        _KERNEL_APPLY_CACHE[attn_kernel] = fn
    return fn


def _bert_apply_factory(
    mesh,
    seq_parallel: str = "ring",
    num_heads: int | None = None,
    attn_kernel: str = "auto",
):
    """Mesh-aware serving apply: a mesh with a "seq" axis turns on sequence
    parallelism automatically — ring attention by default, or the
    all-to-all (Ulysses) strategy when the deployment asks for it
    (``seq_parallel`` model parameter); otherwise the default
    length-adaptive attention runs under whatever data/TP sharding the mesh
    provides.

    ``num_heads`` (static model config, known at build time) lets ulysses
    fail the DEPLOYMENT when heads don't divide the seq axis — heads are
    the all-to-all resharding currency, and silently serving unsharded
    attention would defeat the knob exactly at the long contexts that
    motivated it. (Ring's seq-length fallback stays dynamic: request
    lengths vary per bucket and must not error.)"""
    if mesh is not None and "seq" in getattr(mesh, "shape", {}):
        if seq_parallel == "ulysses" and num_heads is not None:
            n = int(mesh.shape["seq"])
            if num_heads % n != 0:
                raise ValueError(
                    f"seq_parallel=ulysses needs attention heads divisible "
                    f"by the seq-axis size: {num_heads} heads vs seq={n} — "
                    "use a smaller seq axis or seq_parallel=ring"
                )
        key = (mesh, seq_parallel)
        fn = _RING_APPLY_CACHE.get(key)
        if fn is None:
            if seq_parallel == "ulysses":
                impl = make_ulysses_attention_impl(mesh)
            elif seq_parallel == "ring":
                impl = make_ring_attention(mesh)
            else:
                raise ValueError(
                    f"seq_parallel must be 'ring' or 'ulysses', got {seq_parallel!r}"
                )
            fn = make_apply_bert(impl)
            _RING_APPLY_CACHE[key] = fn
        return fn
    return _apply_for_kernel(attn_kernel)


@register_model("bert_base")
def build_bert_base(
    seed: int = 0,
    num_classes: int = 2,
    max_len: int = 512,
    seq: int = 128,
    seq_parallel: str = "ring",
    attn_kernel: str = "auto",
    **_,
) -> ModelSpec:
    from functools import partial

    if seq > max_len:
        raise ValueError(
            f"seq={seq} exceeds max_len={max_len} (position table size) — "
            "raise max_len for long-context deployments"
        )
    params = init_bert(seed, num_classes=num_classes, max_len=max_len)
    return ModelSpec(
        # attn_kernel is a deployment knob (auto|pallas|blockwise): auto
        # routes long sequences to the Pallas flash kernel on the TPU
        # backend and blockwise elsewhere; pallas forces the kernel
        # (interpret mode off-TPU) so CI serving configs reach it
        _apply_for_kernel(attn_kernel),
        params,
        (seq,),  # serving seq length (buckets handle the batch axis)
        tuple(f"class_{i}" for i in range(num_classes)),
        param_pspecs=bert_pspecs(params),
        # seq-parallel strategy is a deployment knob: a "seq" mesh axis plus
        # model parameter seq_parallel=ring|ulysses picks the collective;
        # num_heads lets ulysses reject undivisible meshes at BUILD time
        # (derived by the SAME rule attention itself uses)
        apply_factory=partial(
            _bert_apply_factory,
            seq_parallel=seq_parallel,
            num_heads=_infer_heads(params),
            attn_kernel=attn_kernel,
        ),
        int_inputs="ids",
    )


@register_model("bert_tiny")
def build_bert_tiny(
    seed: int = 0,
    vocab: int = 1024,
    hidden: int = 128,
    layers: int = 2,
    ffn: int = 256,
    max_len: int = 128,
    num_classes: int = 2,
    seq: int = 16,
    seq_parallel: str = "ring",
    attn_kernel: str = "auto",
    **_,
) -> ModelSpec:
    """Shrunk config for tests / virtual-mesh dryruns."""
    from functools import partial

    if seq > max_len:
        raise ValueError(f"seq={seq} exceeds max_len={max_len}")
    params = init_bert(
        seed,
        vocab=vocab,
        hidden=hidden,
        layers=layers,
        ffn=ffn,
        max_len=max_len,
        num_classes=num_classes,
    )
    return ModelSpec(
        _apply_for_kernel(attn_kernel),
        params,
        (seq,),
        tuple(f"class_{i}" for i in range(num_classes)),
        param_pspecs=bert_pspecs(params),
        apply_factory=partial(
            _bert_apply_factory,
            seq_parallel=seq_parallel,
            num_heads=_infer_heads(params),
            attn_kernel=attn_kernel,
        ),
        int_inputs="ids",
    )
