"""Framework adapters: serve non-JAX models as graph nodes.

Parity: the reference wraps sklearn/TF/Keras/H2O models by putting them in a
container behind the duck-typed predict contract (wrappers/python). Here the
same duck-typed contract exists in-process (engine/units.py PythonClassUnit),
and these adapters produce such objects from common frameworks:

- TorchModelAdapter: torch.nn.Module -> predict() on host CPU (torch-cpu
  tier; the model joins the graph next to TPU-resident JAX nodes);
- FunctionModelAdapter: any f(np.ndarray) -> np.ndarray;
- SklearnModelAdapter: estimator with predict_proba/predict.

For TPU-resident serving of foreign weights, convert the weights into a zoo
ModelSpec (pure JAX apply + params pytree) and load via JAX_MODEL — the
adapters here are the compatibility tier, not the fast path.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import numpy as np


class FunctionModelAdapter:
    """Wrap a plain function as a duck-typed model."""

    def __init__(self, fn: Callable[[np.ndarray], np.ndarray], class_names: Sequence[str] = ()):
        self._fn = fn
        if class_names:
            self.class_names = list(class_names)

    def predict(self, X: np.ndarray, feature_names) -> np.ndarray:
        return np.asarray(self._fn(np.asarray(X)))


class TorchModelAdapter:
    """Wrap a torch.nn.Module (eval mode, CPU) as a duck-typed model."""

    def __init__(self, module: Any, class_names: Sequence[str] = (), softmax: bool = False):
        import torch

        self._torch = torch
        self._module = module.eval()
        self._softmax = softmax
        if class_names:
            self.class_names = list(class_names)

    def predict(self, X: np.ndarray, feature_names) -> np.ndarray:
        torch = self._torch
        with torch.no_grad():
            t = torch.as_tensor(np.asarray(X, dtype=np.float32))
            out = self._module(t)
            if self._softmax:
                out = torch.softmax(out, dim=-1)
        return out.cpu().numpy()


class SklearnModelAdapter:
    """Wrap an sklearn-style estimator (predict_proba preferred, reference
    IrisClassifier.py pattern)."""

    def __init__(self, estimator: Any, class_names: Sequence[str] = ()):
        self._est = estimator
        if class_names:
            self.class_names = list(class_names)
        elif hasattr(estimator, "classes_"):
            self.class_names = [str(c) for c in estimator.classes_]

    def predict(self, X: np.ndarray, feature_names) -> np.ndarray:
        if hasattr(self._est, "predict_proba"):
            return np.asarray(self._est.predict_proba(np.asarray(X)))
        out = np.asarray(self._est.predict(np.asarray(X)))
        return out if out.ndim == 2 else out[:, None]
