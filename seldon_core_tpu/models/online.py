"""Online fine-tuning from serving feedback.

The reference's only learning loop is bandit arm statistics (router
send_feedback). Here labeled feedback can update the MODEL ITSELF: a
JAX_MODEL unit with ``finetune: true`` buffers (features, truth) pairs from
/api/v0.1/feedback and, once ``finetune_batch`` examples accumulate, runs
one jitted SGD/Adam step on-device and swaps the updated params into the
serving runtime — predictions immediately reflect the new weights.

Design constraints honored (SURVEY §7 hard parts):
- predict stays pure/compiled; training happens OUTSIDE the request path,
  triggered host-side from feedback events;
- the optimizer step is jitted once per batch shape and reuses the serving
  params pytree (no copy of HBM weights beyond optimizer moments);
- the buffer and optimizer state are host-side unit state, picklable, so
  persistence/ can snapshot learning progress like any stateful unit.

Loss: cross-entropy on log(serving probabilities) — the zoo serving contract
returns probabilities, and log-of-softmax is numerically adequate at
fine-tuning learning rates.
"""

from __future__ import annotations

import logging
import threading
from typing import Any

import numpy as np

import jax
import jax.numpy as jnp

from seldon_core_tpu.core.message import Feedback, SeldonMessage
from seldon_core_tpu.graph.spec import PredictiveUnit
from seldon_core_tpu.models.base import JaxModelUnit, ModelRuntime

log = logging.getLogger(__name__)


class OnlineFinetuneModelUnit(JaxModelUnit):
    """JaxModelUnit that learns from labeled feedback.

    Unit parameters: ``finetune`` (bool, enables this wrapper),
    ``finetune_lr`` (default 1e-3), ``finetune_batch`` (examples per step,
    default 32), ``finetune_optimizer`` ("sgd" | "adam", default "adam").
    """

    def __init__(self, spec: PredictiveUnit, runtime: ModelRuntime):
        super().__init__(spec, runtime)
        import optax

        self.lr = float(self.params.get("finetune_lr", 1e-3))
        self.batch = int(self.params.get("finetune_batch", 32))
        opt_name = str(self.params.get("finetune_optimizer", "adam"))
        self._optimizer = (
            optax.sgd(self.lr) if opt_name == "sgd" else optax.adam(self.lr)
        )
        # optimizer moments allocate lazily on the first train step — an
        # Adam state doubles the model's HBM and is wasted if feedback
        # never arrives
        self._opt_state = None
        self._buffer_x: list[np.ndarray] = []
        self._buffer_y: list[int] = []
        self._steps_taken = 0
        self._lock = threading.Lock()
        self._jit_step = None

    # ------------------------------------------------------------- learning
    def _make_step(self):
        optimizer = self._optimizer
        apply_fn = self.runtime.apply_fn

        def step(params, opt_state, x, y):
            def loss_fn(p):
                probs = apply_fn(p, x)
                logp = jnp.log(probs.astype(jnp.float32) + 1e-9)
                return -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=-1))

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            import optax

            return optax.apply_updates(params, updates), opt_state, loss

        return jax.jit(step)

    async def send_feedback(self, feedback: Feedback, routing: int) -> None:
        """Buffer (request features, truth label); train when full."""
        if feedback.request is None or feedback.truth is None:
            return
        x = feedback.request.array
        t = feedback.truth.array
        if x is None or t is None:
            return
        x = np.atleast_2d(np.asarray(x, np.float32))
        t = np.asarray(t)
        # truth may be class indices [n] / [n,1] or one-hot rows [n,classes]
        if t.ndim >= 2 and t.shape[-1] > 1:
            y = np.argmax(t, axis=-1).reshape(-1)
        else:
            y = t.reshape(-1).astype(np.int64)
        if x.shape[0] != y.shape[0]:
            return
        import asyncio

        batches = []
        with self._lock:
            self._buffer_x.extend(x)
            self._buffer_y.extend(int(v) for v in y)
            # drain EVERY full batch, or payloads larger than the batch size
            # grow the buffer without bound
            while len(self._buffer_y) >= self.batch:
                batches.append(
                    (
                        np.stack(self._buffer_x[: self.batch]),
                        np.asarray(self._buffer_y[: self.batch], np.int32),
                    )
                )
                del self._buffer_x[: self.batch]
                del self._buffer_y[: self.batch]
        for bx, by in batches:
            # off the event loop: the first step pays XLA compilation and
            # every step synchronizes the device — serving must not stall
            await asyncio.to_thread(self._train, bx, by)

    def _train(self, x: np.ndarray, y: np.ndarray) -> None:
        if self._jit_step is None:
            self._jit_step = self._make_step()
        if self._opt_state is None:
            self._opt_state = self._optimizer.init(self.runtime.params)
        params, opt_state, loss = self._jit_step(
            self.runtime.params, self._opt_state, jnp.asarray(x), jnp.asarray(y)
        )
        with self._lock:
            # atomic reference swap: in-flight predicts finish on the old
            # params, subsequent ones see the fine-tuned weights
            self.runtime.params = params
            self._opt_state = opt_state
            self._steps_taken += 1
        log.info(
            "online finetune '%s': step %d, loss %.4f",
            self.name,
            self._steps_taken,
            float(loss),
        )

    # ---------------------------------------------------------- persistence
    def __getstate__(self):
        # the persister snapshots from its own daemon thread while feedback
        # mutates the buffers — hold the lock so (x, y) pairs stay aligned
        with self._lock:
            return {
                "buffer_x": [np.asarray(a) for a in self._buffer_x],
                "buffer_y": list(self._buffer_y),
                "steps_taken": self._steps_taken,
                "params": jax.tree.map(np.asarray, self.runtime.params),
                "opt_state": None
                if self._opt_state is None
                else jax.tree.map(np.asarray, self._opt_state),
            }

    def __setstate__(self, state):
        self._lock = threading.Lock()
        with self._lock:
            self._buffer_x = [np.asarray(a) for a in state.get("buffer_x", [])]
            self._buffer_y = list(state.get("buffer_y", []))
            self._steps_taken = int(state.get("steps_taken", 0))
            if "params" in state:
                self.runtime.params = jax.device_put(state["params"])
            if state.get("opt_state") is not None:
                self._opt_state = jax.tree.map(jnp.asarray, state["opt_state"])
        self._jit_step = None
