"""Model zoo: named model builders -> (apply_fn, params, metadata).

Parity role: the reference's examples/models/* (sklearn_iris, deep_mnist,
keras_mnist, mean_classifier, ...) are user containers; here the equivalents
are JAX builders that the JAX_MODEL graph unit loads straight into HBM.
``model_uri`` schemes understood by unit_from_container:
    zoo://<name>[?k=v...]   build from this registry (fresh deterministic init)
    file://<path>           orbax checkpoint dir (params restored to device)
    hf-bert://<path>[?seq=N]  local HF BertForSequenceClassification dir
                            (save_pretrained), mapped via models/hf_import
"""

from __future__ import annotations

import inspect
import threading
import urllib.parse
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from seldon_core_tpu.graph.spec import ContainerSpec, PredictiveUnit
from seldon_core_tpu.models.base import JaxModelUnit, ModelRuntime


@dataclass
class ModelSpec:
    """What a builder returns: everything needed to instantiate a runtime."""

    apply_fn: Callable[[Any, jax.Array], jax.Array]
    params: Any
    feature_shape: tuple[int, ...]
    class_names: tuple[str, ...] = ()
    param_pspecs: Any | None = None  # PartitionSpec pytree for tensor parallelism
    # optional mesh-aware apply: called with the predictor's Mesh to build a
    # sharded apply (e.g. ring attention over the "seq" axis); apply_fn
    # remains the single-device/no-mesh path
    apply_factory: Callable[[Any], Callable] | None = None
    # integer-payload semantics: "cast" = integers are values (images,
    # tabular) and normalize to the model dtype; "ids" = integers are token
    # ids and stay exact int32 (ModelRuntime wire-dtype policy)
    int_inputs: str = "cast"
    # generative decoders (models/decoder.py layout) advertise their decode
    # geometry here ({"seq": prompt bucket, "max_new_tokens": cap}) so the
    # serving layer can offer the continuous-batching decode scheduler
    # (tpu.decode_slots) as an alternative to the fused whole-batch apply
    generative: dict | None = None


Builder = Callable[..., ModelSpec]
_REGISTRY: dict[str, Builder] = {}


def register_model(name: str):
    def deco(fn: Builder) -> Builder:
        _REGISTRY[name] = fn
        return fn

    return deco


# Heavy builds are memoized per (name, builder-relevant kwargs): same-seed
# builds are deterministic, params are treated as immutable downstream
# (ModelRuntime casts/quantizes into NEW arrays; online fine-tuning rebinds
# runtime.params, never writes through), so sharing the pytree is safe — and
# re-initializing a ResNet50/BERT for every deployment of the same spec
# costs tens of seconds of device time (e.g. an ensemble CR + its bench
# rerun). Bounded LRU: the admission estimator also builds via get_model,
# and an unbounded cache would retain every rejected spec's params forever.
_HEAVY_CACHE: OrderedDict[tuple, ModelSpec] = OrderedDict()
_HEAVY_CACHE_MAX = 4
_CACHEABLE = frozenset({"resnet50", "bert_base"})
# the admission estimator and operator reconcile both build via get_model
# from different threads: the lock serializes the OrderedDict check/insert/
# evict (a concurrent popitem interleaving could KeyError), and the
# in-flight table de-dups concurrent FIRST builds of the same key —
# a duplicated resnet50/bert build costs tens of seconds of device time and
# 2x peak params memory. Builds themselves run OUTSIDE the lock.
_HEAVY_CACHE_LOCK = threading.Lock()
_HEAVY_BUILDING: dict[tuple, threading.Event] = {}


def _heavy_cache_key(name: str, kwargs: dict) -> tuple | None:
    """(name, kwargs restricted to the builder's own parameters) — callers
    forward EVERY unit parameter (finetune_lr etc.) as builder kwargs and
    the builders swallow unknowns via **_, so keying on the full dict would
    duplicate bit-identical builds. None when any relevant value is
    unhashable (build uncached)."""
    sig = inspect.signature(_REGISTRY[name])
    relevant = {
        k: v
        for k, v in kwargs.items()
        if k in sig.parameters
        and sig.parameters[k].kind is not inspect.Parameter.VAR_KEYWORD
    }
    # normalize defaults so zoo://resnet50?space_to_depth=1 and
    # zoo://resnet50?seed=0&space_to_depth=1 (bit-identical builds) share a
    # key instead of occupying two LRU slots
    bound = sig.bind_partial(**relevant)
    bound.apply_defaults()
    args = {
        k: v
        for k, v in bound.arguments.items()
        if sig.parameters[k].kind is not inspect.Parameter.VAR_KEYWORD
    }
    key = (name, tuple(sorted(args.items())))
    try:
        hash(key)
    except TypeError:
        return None
    return key


def get_model(name: str, **kwargs) -> ModelSpec:
    if name not in _REGISTRY:
        _register_heavy_models()
    if name not in _REGISTRY:
        raise KeyError(f"unknown model '{name}'; known: {sorted(_REGISTRY)}")
    if name in _CACHEABLE:
        key = _heavy_cache_key(name, kwargs)
        if key is None:
            return _REGISTRY[name](**kwargs)
        with _HEAVY_CACHE_LOCK:
            if key in _HEAVY_CACHE:
                _HEAVY_CACHE.move_to_end(key)
                return _HEAVY_CACHE[key]
            in_flight = _HEAVY_BUILDING.get(key)
            if in_flight is None:
                in_flight = threading.Event()
                _HEAVY_BUILDING[key] = in_flight
                am_builder = True
            else:
                am_builder = False
        if not am_builder:
            in_flight.wait()
            with _HEAVY_CACHE_LOCK:
                if key in _HEAVY_CACHE:
                    _HEAVY_CACHE.move_to_end(key)
                    return _HEAVY_CACHE[key]
            # the builder raised — build for ourselves (uncached; a broken
            # spec must not poison the cache for later callers)
            return _REGISTRY[name](**kwargs)
        try:
            spec = _REGISTRY[name](**kwargs)
            with _HEAVY_CACHE_LOCK:
                _HEAVY_CACHE[key] = spec
                while len(_HEAVY_CACHE) > _HEAVY_CACHE_MAX:
                    _HEAVY_CACHE.popitem(last=False)
            return spec
        finally:
            with _HEAVY_CACHE_LOCK:
                _HEAVY_BUILDING.pop(key, None)
            in_flight.set()
    return _REGISTRY[name](**kwargs)


def list_models() -> list[str]:
    return sorted(_REGISTRY)


# ------------------------------------------------------------------ builders


def _dense_init(key, n_in: int, n_out: int):
    wkey, _ = jax.random.split(key)
    scale = (2.0 / n_in) ** 0.5
    return {
        "w": jax.random.normal(wkey, (n_in, n_out), dtype=jnp.float32) * scale,
        "b": jnp.zeros((n_out,), dtype=jnp.float32),
    }


# apply fns are MODULE-LEVEL (not per-build closures) so two builds of the
# same architecture share function identity — that is what lets the fused
# ensemble compiler (engine/fused.py) stack their params and vmap once.


def _apply_logistic(p, x):
    return jax.nn.softmax(x @ p["w"] + p["b"], axis=-1)


def _apply_mlp2(p, x):
    h = jax.nn.relu(x @ p["l1"]["w"] + p["l1"]["b"])
    return jax.nn.softmax(h @ p["l2"]["w"] + p["l2"]["b"], axis=-1)


def _apply_mean_sigmoid(p, x):
    return jax.nn.sigmoid(jnp.mean(x, axis=-1, keepdims=True))


def _apply_mlp3_flat(p, x):
    x = x.reshape((x.shape[0], -1))
    h = jax.nn.relu(x @ p["l1"]["w"] + p["l1"]["b"])
    h = jax.nn.relu(h @ p["l2"]["w"] + p["l2"]["b"])
    return jax.nn.softmax(h @ p["l3"]["w"] + p["l3"]["b"], axis=-1)


@register_model("iris_logistic")
def build_iris_logistic(seed: int = 0, **_) -> ModelSpec:
    """Logistic head, 4 features -> 3 classes — the sklearn-iris-equivalent
    (reference examples/models/sklearn_iris/IrisClassifier.py)."""
    params = _dense_init(jax.random.key(seed), 4, 3)
    return ModelSpec(_apply_logistic, params, (4,), ("setosa", "versicolor", "virginica"))


@register_model("iris_mlp")
def build_iris_mlp(seed: int = 0, hidden: int = 32, **_) -> ModelSpec:
    k1, k2 = jax.random.split(jax.random.key(seed))
    params = {"l1": _dense_init(k1, 4, hidden), "l2": _dense_init(k2, hidden, 3)}
    return ModelSpec(_apply_mlp2, params, (4,), ("setosa", "versicolor", "virginica"))


@register_model("mean_classifier")
def build_mean_classifier(**_) -> ModelSpec:
    """Parity with reference examples/models/mean_classifier/MeanClassifier.py:
    sigmoid of the feature mean -> single score."""
    return ModelSpec(_apply_mean_sigmoid, {}, (4,), ("proba",))


@register_model("mnist_mlp")
def build_mnist_mlp(seed: int = 0, hidden: int = 512, **_) -> ModelSpec:
    """Deep-MNIST-equivalent (reference examples/models/deep_mnist): flat 784
    input -> 10 softmax. MLP keeps the matmuls MXU-shaped."""
    keys = jax.random.split(jax.random.key(seed), 3)
    params = {
        "l1": _dense_init(keys[0], 784, hidden),
        "l2": _dense_init(keys[1], hidden, hidden),
        "l3": _dense_init(keys[2], hidden, 10),
    }
    return ModelSpec(_apply_mlp3_flat, params, (784,), tuple(str(i) for i in range(10)))


def _pipe_stage_fn(p, h):
    """One pipeline stage: residual tanh block, [mb, d] -> [mb, d] (the
    uniform signature pipeline_apply requires)."""
    return h + jnp.tanh(h @ p["w"] + p["b"])


def _apply_pipe_tower_seq(p, x):
    """Single-device reference path: stages run sequentially via scan over
    the stacked [S, ...] stage params — bitwise the same math the pipelined
    path computes, so serving equivalence is testable."""
    from jax import lax

    h = x @ p["embed"]["w"] + p["embed"]["b"]

    def body(h, stage_p):
        return _pipe_stage_fn(stage_p, h), None

    h, _ = lax.scan(body, h, p["stages"])
    return jax.nn.softmax(h @ p["head"]["w"] + p["head"]["b"], axis=-1)


@register_model("pipe_mlp")
def build_pipe_mlp(
    seed: int = 0, n_in: int = 16, d: int = 64, stages: int = 4, classes: int = 3, **_
) -> ModelSpec:
    """Pipeline-parallel SERVING model (VERDICT r2 item 6): a residual MLP
    tower whose stages shard one-per-device over a "pipe" mesh axis.

    With ``tpu.mesh: {"pipe": S}`` the apply_factory wraps
    parallel/pipeline.pipeline_apply — each device holds ONE stage's
    params, activations flow stage-to-stage over ICI (ppermute), and the
    micro-batched GPipe schedule hides the per-stage latency. Without a
    pipe axis the same stacked params run as a sequential scan, so the
    deployment spec alone decides the execution strategy (the SURVEY §7
    inversion: the CR compiles onto the slice)."""
    keys = jax.random.split(jax.random.key(seed), 3)
    scale = (1.0 / d) ** 0.5
    params = {
        "embed": _dense_init(keys[0], n_in, d),
        "stages": {
            "w": jax.random.normal(keys[1], (stages, d, d), jnp.float32) * scale,
            "b": jnp.zeros((stages, d), jnp.float32),
        },
        "head": _dense_init(keys[2], d, classes),
    }
    from jax.sharding import PartitionSpec as P

    pspecs = {
        "embed": {"w": P(), "b": P()},
        # one stage per device along the pipe axis
        "stages": {"w": P("pipe"), "b": P("pipe")},
        "head": {"w": P(), "b": P()},
    }

    def apply_factory(mesh):
        if "pipe" not in mesh.axis_names:
            return _apply_pipe_tower_seq
        from seldon_core_tpu.parallel.pipeline import pipeline_apply

        n_stages = int(mesh.shape["pipe"])

        def apply_pipelined(p, x):
            h = x @ p["embed"]["w"] + p["embed"]["b"]
            batch = h.shape[0]
            # microbatch count: S microbatches fill the pipe (bubble
            # fraction (S-1)/(2S-1)); shapes are static per bucket so this
            # branch resolves at trace time, and power-of-two buckets are
            # always divisible by a power-of-two stage count
            m = n_stages if batch % n_stages == 0 else 1
            h_micro = h.reshape(m, batch // m, h.shape[-1])
            out = pipeline_apply(_pipe_stage_fn, p["stages"], h_micro, mesh)
            h2 = out.reshape(batch, h.shape[-1])
            return jax.nn.softmax(h2 @ p["head"]["w"] + p["head"]["b"], axis=-1)

        return apply_pipelined

    return ModelSpec(
        _apply_pipe_tower_seq,
        params,
        (n_in,),
        tuple(f"c{i}" for i in range(classes)),
        param_pspecs=pspecs,
        apply_factory=apply_factory,
    )


def _apply_moe_mlp(p, x):
    """[batch, features] -> class probabilities through a top-1 MoE FFN
    (ops/moe.py): embed -> residual MoE block (seq length 1) -> softmax
    head. Module-level for fused-ensemble apply-fn identity."""
    from seldon_core_tpu.ops.moe import moe_ffn

    h = x @ p["embed"]["w"] + p["embed"]["b"]
    h = h[:, None, :]  # [b, 1, d_model] — moe_ffn's token axis
    h = h + moe_ffn(p["moe"], h)
    h = h[:, 0, :]
    return jax.nn.softmax(h @ p["head"]["w"] + p["head"]["b"], axis=-1)


@register_model("moe_mlp")
def build_moe_mlp(
    seed: int = 0,
    n_in: int = 16,
    d_model: int = 64,
    d_ff: int = 128,
    n_experts: int = 8,
    classes: int = 3,
    **_,
) -> ModelSpec:
    """Expert-parallel SERVING model (VERDICT r4 Next #5): a mixture-of-
    experts classifier whose expert weights shard over the mesh "expert"
    axis (ops/moe.moe_pspecs) — with ``tpu.mesh: {"data": D, "expert": E}``
    each device computes only its local experts' slab and XLA inserts the
    one psum the gate-weighted reduction needs. Without a mesh the same
    params serve dense on one device, so the deployment spec alone decides
    the strategy (same inversion as pipe_mlp). No reference analogue
    (SURVEY §2: no expert parallelism exists there).
    """
    from jax.sharding import PartitionSpec as P

    from seldon_core_tpu.ops.moe import init_moe, moe_pspecs

    k1, k2 = jax.random.split(jax.random.key(seed))
    params = {
        "embed": _dense_init(k1, n_in, d_model),
        "moe": init_moe(seed, d_model=d_model, d_ff=d_ff, n_experts=n_experts),
        "head": _dense_init(k2, d_model, classes),
    }
    pspecs = {
        "embed": {"w": P(), "b": P()},
        "moe": moe_pspecs("expert"),
        "head": {"w": P(), "b": P()},
    }
    return ModelSpec(
        _apply_moe_mlp,
        params,
        (n_in,),
        tuple(f"c{i}" for i in range(classes)),
        param_pspecs=pspecs,
    )


@register_model("tiny_gpt")
def build_tiny_gpt(
    seed: int = 0,
    vocab: int = 512,
    hidden: int = 128,
    layers: int = 2,
    ffn: int = 256,
    max_len: int = 128,
    seq: int = 32,
    max_new_tokens: int = 16,
    resid_scale: float = 1.0,
    **_,
) -> ModelSpec:
    """Generative SERVING model (greenfield tier — the reference serves no
    autoregressive models): GPT-style causal decoder, greedy KV-cache
    decode inside one compiled program (models/decoder.py — prefill
    through the causal-attention policy incl. the Pallas kernel on TPU,
    then a lax.scan of single-token steps). ``max_new_tokens`` and the
    prompt bucket are deployment parameters, so every request of a bucket
    reuses one XLA program. Wire: int token ids in, ids out
    ([b, seq + max_new_tokens], exact int32 through the serving dtype
    policy)."""
    from functools import partial

    from seldon_core_tpu.models.decoder import init_decoder

    if seq + max_new_tokens > max_len:
        raise ValueError(
            f"seq={seq} + max_new_tokens={max_new_tokens} exceeds "
            f"max_len={max_len} — raise max_len"
        )
    params = init_decoder(
        seed, vocab=vocab, hidden=hidden, layers=layers, ffn=ffn, max_len=max_len,
        resid_scale=resid_scale,
    )
    return ModelSpec(
        partial(_apply_tiny_gpt, max_new_tokens=max_new_tokens),
        params,
        (seq,),
        (),
        int_inputs="ids",
        generative={"seq": seq, "max_new_tokens": max_new_tokens},
    )


@register_model("draft")
def build_draft(
    seed: int = 0,
    vocab: int = 512,
    hidden: int = 128,
    layers: int = 1,
    ffn: int = 256,
    max_len: int = 128,
    resid_scale: float = 1.0,
    seq: int = 32,
    max_new_tokens: int = 16,
    distilled: str = "",
    features: int = 0,
    **_,
) -> ModelSpec:
    """Draft decoder for speculative decoding (tpu.decode_draft_model):
    the same GPT-style architecture as tiny_gpt, defaulting to ONE layer.
    Because init_decoder draws weights positionally from a single seeded
    generator, a draft built with the target's seed/vocab/hidden/ffn/
    max_len (the decode scheduler injects vocab and max_len from the
    target automatically) IS the target's embeddings + leading layers
    verbatim — early-exit self-speculation, the untrained-weights
    analogue of a distilled draft. With the default depth-unscaled init
    the truncated layers dominate the logits and the accept rate is low;
    builds meant as drafts should set resid_scale (on BOTH target and
    draft) so the shared prefix carries the prediction — see
    docs/generative.md. Serves standalone like any other zoo entry —
    it IS tiny_gpt with a 1-layer default, so it delegates (any change to
    the target's ModelSpec wiring automatically carries to the draft,
    which the truncation property depends on).

    ``distilled=/path/to.npz`` refills the build's weights from a
    KL-distillation checkpoint (training/distill_draft.py) trained
    against the target — acceptance from LEARNING the target's
    conditionals instead of seed-shared layer truncation alone. The
    checkpoint must match this build's geometry exactly (the loader
    asserts every leaf's shape), so the URI still carries the full
    architecture and ``distilled`` only swaps the values.

    ``features=1`` builds the EAGLE-style feature-draft HEAD instead
    (models/decoder.init_feature_draft): one transformer layer whose
    input fuses the TARGET's last hidden state with the token embedding.
    ``hidden`` must equal the target's (the decode scheduler injects it
    from the target automatically); ``layers``/``resid_scale`` do not
    apply. A feature head is not a standalone decoder — it serves ONLY
    through ``tpu.decode_draft_model``, and its apply raises to say so.
    Distill it with ``python -m seldon_core_tpu.training.distill_draft
    --features`` and load via
    ``zoo://draft?features=1&distilled=/path.npz``."""
    if features:
        from seldon_core_tpu.models.decoder import init_feature_draft

        params = init_feature_draft(
            seed, vocab=vocab, hidden=hidden, ffn=ffn, max_len=max_len
        )
        if distilled:
            from seldon_core_tpu.training.distill_draft import load_draft_checkpoint

            params = load_draft_checkpoint(str(distilled), params)
        return ModelSpec(
            _feature_draft_apply,
            params,
            (seq,),
            (),
            int_inputs="ids",
        )
    ms = build_tiny_gpt(
        seed=seed, vocab=vocab, hidden=hidden, layers=layers, ffn=ffn,
        max_len=max_len, seq=seq, max_new_tokens=max_new_tokens,
        resid_scale=resid_scale,
    )
    if distilled:
        from seldon_core_tpu.training.distill_draft import load_draft_checkpoint

        ms.params = load_draft_checkpoint(str(distilled), ms.params)
    return ms


def _feature_draft_apply(p, x):
    raise ValueError(
        "a feature-draft head (zoo://draft?features=1) conditions on the "
        "target's hidden states and cannot serve standalone — point "
        "tpu.decode_draft_model at it instead"
    )


def _apply_tiny_gpt(p, x, *, max_new_tokens: int):
    from seldon_core_tpu.models.decoder import generate

    return generate(p, x, max_new_tokens)


def _register_heavy_models() -> None:
    """resnet50 / bert_base import lazily — they pull flax."""
    from seldon_core_tpu.models import resnet as _resnet  # noqa: F401
    from seldon_core_tpu.models import bert as _bert  # noqa: F401


# ------------------------------------------------------------- unit factory


def _runtime_from_modelspec(ms: ModelSpec, tpu_cfg, mesh=None) -> ModelRuntime:
    import jax.numpy as jnp

    dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16, "float16": jnp.float16}[
        getattr(tpu_cfg, "dtype", "float32")
    ]
    apply_fn = ms.apply_fn
    if mesh is not None and ms.apply_factory is not None:
        apply_fn = ms.apply_factory(mesh)
    rt = ModelRuntime(
        apply_fn,
        ms.params,
        mesh=mesh,
        param_pspecs=ms.param_pspecs,
        buckets=tuple(getattr(tpu_cfg, "batch_buckets", ()) or ()),
        max_batch=getattr(tpu_cfg, "max_batch", 64),
        dtype=dtype,
        class_names=ms.class_names,
        donate=getattr(tpu_cfg, "donate_input", True),
        int_inputs=ms.int_inputs,
        weight_quant=getattr(tpu_cfg, "weight_quant", ""),
        offload_compute=getattr(tpu_cfg, "offload_compute", "auto"),
    )
    rt.feature_shape = ms.feature_shape
    rt.generative = ms.generative
    return rt


def _parse_zoo_uri(uri: str) -> tuple[str, dict]:
    parsed = urllib.parse.urlparse(uri)
    name = parsed.netloc or parsed.path.lstrip("/")
    kwargs: dict[str, Any] = {}
    for k, v in urllib.parse.parse_qsl(parsed.query):
        try:
            kwargs[k] = int(v)
        except ValueError:
            try:
                kwargs[k] = float(v)
            except ValueError:
                kwargs[k] = v
    return name, kwargs


def build_runtime_from_uri(uri: str, tpu_cfg, mesh=None, extra_params: dict | None = None) -> ModelRuntime:
    """``extra_params``: unit parameters beyond model/model_uri (typed by
    the CR) — merged as builder kwargs under the URI's own query string, so
    ``model_uri`` deployments get the same knobs (seq_parallel etc.) as the
    ``model`` shorthand."""
    extra_params = extra_params or {}
    if uri.startswith("zoo://"):
        name, kwargs = _parse_zoo_uri(uri)
        kwargs = {**extra_params, **kwargs}  # the uri's own query wins
        ms = get_model(name, **kwargs)  # lazy-registers heavy models itself
        return _runtime_from_modelspec(ms, tpu_cfg, mesh)
    if uri.startswith("file://"):
        if extra_params:
            import logging

            logging.getLogger(__name__).warning(
                "file:// checkpoints ignore extra unit parameters %s (the "
                "builder and its kwargs are baked into the checkpoint)",
                sorted(extra_params),
            )
        from seldon_core_tpu.persistence.checkpoint import restore_model

        ms = restore_model(uri[len("file://") :])
        return _runtime_from_modelspec(ms, tpu_cfg, mesh)
    if uri.startswith("hf-bert://"):
        # a LOCAL Hugging Face BertForSequenceClassification checkpoint dir
        # (from save_pretrained): trained torch weights map into the
        # jit-compiled BERT (models/hf_import.py) — torch leaves the loop
        import transformers

        from seldon_core_tpu.models.bert import (
            _apply_for_kernel,
            _bert_apply_factory,
            _infer_heads,
            bert_pspecs,
        )
        from seldon_core_tpu.models.hf_import import bert_params_from_hf

        rest = uri[len("hf-bert://") :]
        path, _, query = rest.partition("?")
        kwargs = {
            **{k: str(v) for k, v in extra_params.items()},
            **dict(urllib.parse.parse_qsl(query)),  # the uri's query wins
        }
        hf = transformers.BertForSequenceClassification.from_pretrained(path)
        params = bert_params_from_hf(hf.eval())
        id2label = getattr(hf.config, "id2label", None) or {}
        class_names = tuple(
            str(id2label[i]) for i in sorted(id2label)
        ) or tuple(f"class_{i}" for i in range(params["head"]["w"].shape[1]))
        seq = int(kwargs.get("seq", 128))
        max_len = int(params["pos_emb"].shape[0])
        if seq > max_len:
            raise ValueError(
                f"hf-bert seq={seq} exceeds the checkpoint's "
                f"max_position_embeddings={max_len} — failing fast instead "
                "of an opaque XLA broadcast error at warmup"
            )
        from functools import partial

        ms = ModelSpec(
            _apply_for_kernel(str(kwargs.get("attn_kernel", "auto"))),
            params,
            (seq,),
            class_names,
            param_pspecs=bert_pspecs(params),
            # same mesh-aware apply as zoo bert builders: a 'seq' mesh axis
            # turns on sequence parallelism for imported checkpoints too,
            # with the same ring|ulysses strategy knob (?seq_parallel=) and
            # attention-kernel knob (?attn_kernel=auto|pallas|blockwise)
            apply_factory=partial(
                _bert_apply_factory,
                seq_parallel=str(kwargs.get("seq_parallel", "ring")),
                num_heads=_infer_heads(params),
                attn_kernel=str(kwargs.get("attn_kernel", "auto")),
            ),
            int_inputs="ids",
        )
        return _runtime_from_modelspec(ms, tpu_cfg, mesh)
    raise ValueError(f"unsupported model_uri '{uri}'")


def make_jax_model_unit(spec: PredictiveUnit, context: dict) -> JaxModelUnit:
    """Factory for implementation=JAX_MODEL units: model name/uri comes from a
    unit parameter ``model_uri`` (or ``model`` shorthand)."""
    from seldon_core_tpu.graph.spec import parameters_dict

    params = parameters_dict(spec.parameters)
    uri = params.get("model_uri") or (
        f"zoo://{params['model']}" if "model" in params else None
    )
    # every OTHER unit parameter forwards as a builder kwarg, so CR
    # parameters like seq_parallel/num_classes reach the builder on every
    # URI scheme instead of being silently dropped
    extra = {
        k: v for k, v in params.items() if k not in ("model", "model_uri", "finetune")
    }
    if uri is None:
        container = (context.get("containers") or {}).get(spec.name)
        uri = getattr(container, "model_uri", "") or None
    if uri is None:
        raise ValueError(f"JAX_MODEL unit '{spec.name}' needs a model_uri parameter")
    from seldon_core_tpu.graph.spec import bool_param

    finetune = bool_param(params.get("finetune", False))
    # invalid config fails BEFORE any params are built or device_put —
    # admission-protected HBM must not be touched for a doomed deployment
    if finetune and getattr(context.get("tpu"), "weight_quant", "") == "int8":
        raise ValueError(
            f"unit '{spec.name}': finetune=true cannot combine with "
            "tpu.weight_quant='int8' — gradients over int8 weight payloads "
            "are undefined and updates would corrupt the frozen per-channel "
            "scales; serve the finetuning replica unquantized"
        )
    runtime = build_runtime_from_uri(
        uri, context.get("tpu"), context.get("mesh"), extra_params=extra
    )

    if finetune:
        from seldon_core_tpu.graph.spec import TYPE_METHODS, PredictiveUnitMethod
        from seldon_core_tpu.models.online import OnlineFinetuneModelUnit

        effective = tuple(spec.methods) or TYPE_METHODS.get(spec.type, ())
        if PredictiveUnitMethod.SEND_FEEDBACK not in effective:
            import logging

            logging.getLogger(__name__).warning(
                "finetune=true on unit '%s' but SEND_FEEDBACK is not in its "
                "methods — feedback will never reach it (run the spec "
                "through defaulting, or add the method explicitly)",
                spec.name,
            )
        return OnlineFinetuneModelUnit(spec, runtime)
    return JaxModelUnit(spec, runtime)


def unit_from_container(spec: PredictiveUnit, container: ContainerSpec, context: dict):
    runtime = build_runtime_from_uri(
        container.model_uri, context.get("tpu"), context.get("mesh")
    )
    return JaxModelUnit(spec, runtime)
