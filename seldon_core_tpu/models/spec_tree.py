"""Static token-tree layout for multi-candidate (tree) speculation.

A speculation round generalizes from a single k-token chain to a token
TREE (Medusa / EAGLE / SpecInfer style): the draft proposes ``branching[d]``
candidate continuations at each depth ``d`` under every surviving branch,
the flattened tree is scored in ONE widened target dispatch, and
acceptance walks the longest valid PATH. This module owns the pure-host
structure: parsing the ``tpu.decode_spec_tree`` knob (``"4,2,1"`` —
per-depth top-b branching), the flattened block layout, the ancestor
mask the widened attention uses as its in-block causal mask, and the
child tables the acceptance walk gathers through.

Block-index convention (shared by every tree program): the widened
dispatch carries ``width = 1 + n_tree`` queries per slot; block 0 is the
round's root (the slot's last emitted token, exactly the chain verify's
query 0) and block ``1 + i`` is flattened tree node ``i``. Nodes are laid
out depth-major, parent-major: depth-1 nodes first (the root's
``branching[0]`` children in branch order), then each depth advances with
every depth-(d-1) node's ``branching[d-1]`` children contiguous. A node
at depth ``d`` sits at position ``pos + d`` (position EMBEDDING — its
cache address is only decided after acceptance, when the chosen path is
committed to ``pos+1..pos+n_acc`` and every other node's write is
redirected to the junk page).

The dataclass is frozen and hashable on ``branching`` alone, so it rides
``jax.jit`` static args: ONE compiled draft/verify program pair per
deployment tree shape.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import cached_property

import numpy as np

# Verify-width headroom: the widened target dispatch materializes
# [n_slots, 1 + n_tree, vocab] logits and an O(width^2) in-block ancestor
# mask — past this many flattened nodes the dispatch stops amortizing and
# the config is almost certainly a typo'd branching ("44" for "4,4").
# Validation rejects larger trees at CR time; the scheduler ctor enforces
# it as a hard error (the serving builder pre-checks and warn-disables).
MAX_TREE_NODES = 64


def parse_spec_tree(text: str, min_branch: int = 1) -> tuple[int, ...]:
    """Parse a ``decode_spec_tree`` knob (``"4,2,1"``) into a per-depth
    branching tuple. Raises ValueError with the CR-validation wording on
    anything malformed — both validation.py and the scheduler call this,
    so the two layers cannot drift. ``min_branch=0`` relaxes the floor
    for the per-request TIGHTEN string (``meta.tags.spec_tree``), where
    a 0 width is the documented opt-out — the deployment knob itself
    must describe a real tree (every depth >= 1)."""
    parts = [p.strip() for p in str(text).split(",") if p.strip()]
    if not parts:
        raise ValueError("decode_spec_tree must name at least one depth (e.g. '4,2,1')")
    branching = []
    for p in parts:
        try:
            b = int(p)
        except ValueError:
            raise ValueError(
                f"decode_spec_tree entry {p!r} is not an integer (want e.g. '4,2,1')"
            ) from None
        if b < min_branch:
            raise ValueError(
                f"decode_spec_tree branching must be >= {min_branch}, got {b}"
            )
        branching.append(b)
    return tuple(branching)


@dataclass(frozen=True)
class SpecTree:
    """One deployment's speculation tree shape. ``branching[d]`` is the
    number of candidate children every depth-``d`` node proposes (the
    root counts as depth 0). All derived tables are cached numpy — they
    close over jit traces as static structure."""

    branching: tuple[int, ...]

    @staticmethod
    def from_text(text: str) -> "SpecTree":
        return SpecTree(parse_spec_tree(text))

    @staticmethod
    def chain(k: int) -> "SpecTree":
        """The degenerate tree the chain path is a special case of:
        ``k`` depths of branching 1."""
        return SpecTree((1,) * int(k))

    @property
    def depth(self) -> int:
        return len(self.branching)

    @cached_property
    def level_counts(self) -> tuple[int, ...]:
        """Nodes per depth: cumulative branching products."""
        counts, c = [], 1
        for b in self.branching:
            c *= b
            counts.append(c)
        return tuple(counts)

    @property
    def n_tree(self) -> int:
        """Flattened tree node count (blocks 1..n_tree)."""
        return sum(self.level_counts)

    @property
    def width(self) -> int:
        """Widened verify dispatch width: root block + every tree node."""
        return 1 + self.n_tree

    @cached_property
    def level_starts(self) -> tuple[int, ...]:
        """Block index of each depth's first node (depth d -> blocks
        ``level_starts[d-1] .. level_starts[d-1] + level_counts[d-1])``)."""
        starts, s = [], 1
        for c in self.level_counts:
            starts.append(s)
            s += c
        return tuple(starts)

    @cached_property
    def parent_block(self) -> np.ndarray:
        """``parent_block[j]`` for block j: 0 for depth-1 nodes (the
        root), else the parent node's block index; ``parent_block[0]=0``."""
        parent = np.zeros(self.width, np.int32)
        for d in range(2, self.depth + 1):
            start = self.level_starts[d - 1]
            pstart = self.level_starts[d - 2]
            b = self.branching[d - 1]
            for g in range(self.level_counts[d - 1]):
                parent[start + g] = pstart + g // b
        return parent

    @cached_property
    def block_depth(self) -> np.ndarray:
        """Position offset of each block: 0 for the root, else the node's
        tree depth (a depth-d node embeds at ``pos + d``)."""
        depth = np.zeros(self.width, np.int32)
        for d in range(1, self.depth + 1):
            start = self.level_starts[d - 1]
            depth[start : start + self.level_counts[d - 1]] = d
        return depth

    @cached_property
    def ancestor_mask(self) -> np.ndarray:
        """``[width, width]`` bool: ``mask[q, j]`` — may block-query q
        attend to block j's fresh K/V? True iff j is q's ancestor-or-self
        (the root is everyone's ancestor). This is the in-block causal
        mask of the widened dispatch: composed with the strictly-before-
        ``pos`` cache mask it makes every tree query see exactly its own
        path's context, and reduces to the lower-triangular chain mask on
        a branching-1 tree."""
        m = np.zeros((self.width, self.width), bool)
        parent = self.parent_block
        for q in range(self.width):
            j = q
            m[q, j] = True
            while j != 0:
                j = int(parent[j])
                m[q, j] = True
        return m

    @cached_property
    def child_table(self) -> np.ndarray:
        """``[width, max_branching]`` int32: block j's children's block
        indices in branch order, padded with 0 (never read past a depth's
        true branching — the acceptance walk slices ``[:branching[d]]``
        statically per depth)."""
        table = np.zeros((self.width, max(self.branching)), np.int32)
        nxt = {j: 0 for j in range(self.width)}
        parent = self.parent_block
        for j in range(1, self.width):
            p = int(parent[j])
            table[p, nxt[p]] = j
            nxt[p] += 1
        return table

    def nodes_for_widths(self, widths) -> int:
        """Flattened node count of the SUB-TREE a per-depth width mask
        induces (``sum_d prod_{e<=d} min(widths[e], branching[e])``; a 0
        width truncates the depths below it) — the effective tree the
        acceptance walk can actually traverse. The verify dispatch still
        scores the full static layout (widths are data, not shape); this
        is the observability number: how much of the scored width the
        auto-tuner's current mask keeps reachable
        (``/decode/health`` ``spec.nodes``)."""
        total, level = 0, 1
        for d, b in enumerate(self.branching):
            w = min(int(widths[d]), b) if d < len(widths) else 0
            if w <= 0:
                break
            level *= w
            total += level
        return total

    def tighten(self, widths) -> tuple[int, ...]:
        """Element-wise clamp of a per-request branching request against
        this (deployment) tree: per depth ``min(req, deployment)``, depths
        the request omits get width 0 (= depth tightening). Tighten-only:
        a request can narrow or shorten the tree, never widen it."""
        widths = tuple(int(w) for w in widths)
        return tuple(
            min(max(widths[d], 0), self.branching[d]) if d < len(widths) else 0
            for d in range(self.depth)
        )
