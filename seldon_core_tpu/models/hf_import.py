"""Import Hugging Face / torch BERT checkpoints into the TPU-resident BERT.

Closes the real-weights path for the flagship transformer: the reference
serves foreign-framework models behind container RPC (its keras/TF examples,
SURVEY C25); here trained weights map INTO the jit-compiled serving program,
so an HF ``BertForSequenceClassification`` checkpoint runs on the MXU with
bucketed batching, TP shardings (bert_pspecs), and optional ring attention —
no torch in the serving loop.

Numerics parity with the torch forward is exact up to layernorm-eps rounding
(HF 1e-12 vs 1e-6 here) and verified by tests/test_hf_import.py; the model
uses erf gelu and the tanh pooler precisely so this mapping is lossless.

Constraints (asserted): head_dim must be 64 (BERT geometry — head count is
inferred as hidden//64 at apply time) and inputs are single-segment
(token_type_ids = 0; the segment-0 embedding row is folded into pos_emb,
exact for every single-sequence request).
"""

from __future__ import annotations

from typing import Any

import numpy as np


def _t(state: dict, key: str) -> np.ndarray:
    """Fetch a tensor from a torch state_dict as float32 numpy."""
    t = state[key]
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t, np.float32)


def bert_params_from_hf(model: Any) -> dict:
    """Map a ``transformers`` BERT classifier (or its ``state_dict()``) onto
    the params pytree bert_logits consumes.

    Accepts a ``BertForSequenceClassification`` instance or a raw
    state_dict with the standard HF key names. torch Linear weights are
    [out, in] and transpose to the [in, out] layout used here; per-layer
    Q/K/V concatenate into the fused qkv projection.
    """
    state = model if isinstance(model, dict) else model.state_dict()
    state = {k.removeprefix("bert."): v for k, v in state.items()}

    hidden = _t(state, "embeddings.word_embeddings.weight").shape[1]
    if hidden % 64 != 0:
        raise ValueError(
            f"hidden={hidden} is not a multiple of 64: head count is "
            "inferred as hidden//64 (head_dim 64, BERT geometry)"
        )
    n_layers = 0
    while f"encoder.layer.{n_layers}.attention.self.query.weight" in state:
        n_layers += 1
    if n_layers == 0:
        raise ValueError("no encoder layers found — not a BERT state_dict?")

    def dense(prefix: str) -> dict:
        return {
            "w": _t(state, f"{prefix}.weight").T.copy(),
            "b": _t(state, f"{prefix}.bias"),
        }

    def ln(prefix: str) -> dict:
        return {
            "scale": _t(state, f"{prefix}.weight"),
            "bias": _t(state, f"{prefix}.bias"),
        }

    layers = []
    for i in range(n_layers):
        a = f"encoder.layer.{i}.attention"
        qkv_w = np.concatenate(
            [_t(state, f"{a}.self.{m}.weight").T for m in ("query", "key", "value")],
            axis=1,
        )
        qkv_b = np.concatenate(
            [_t(state, f"{a}.self.{m}.bias") for m in ("query", "key", "value")]
        )
        layers.append(
            {
                "qkv": {"w": qkv_w.copy(), "b": qkv_b},
                "attn_out": dense(f"{a}.output.dense"),
                "ln1": ln(f"{a}.output.LayerNorm"),
                "mlp_in": dense(f"encoder.layer.{i}.intermediate.dense"),
                "mlp_out": dense(f"encoder.layer.{i}.output.dense"),
                "ln2": ln(f"encoder.layer.{i}.output.LayerNorm"),
            }
        )

    # single-segment serving: the segment-0 embedding joins every position,
    # so folding it into pos_emb is exact (HF adds tok + pos + type then LN)
    pos = _t(state, "embeddings.position_embeddings.weight")
    type0 = _t(state, "embeddings.token_type_embeddings.weight")[0]
    params: dict = {
        "tok_emb": _t(state, "embeddings.word_embeddings.weight"),
        "pos_emb": pos + type0[None, :],
        "ln_emb": ln("embeddings.LayerNorm"),
        "layers": layers,
    }
    if "pooler.dense.weight" in state:
        params["pooler"] = dense("pooler.dense")
    if "classifier.weight" in state:
        params["head"] = dense("classifier")
    else:  # headless encoder: identity head keeps bert_logits callable
        params["head"] = {
            "w": np.eye(hidden, dtype=np.float32),
            "b": np.zeros((hidden,), np.float32),
        }
    return params
