"""Weight-only int8 quantization for serving (opt-in, per-channel).

TPU-native perf lever with no reference analogue: transformer/MLP serving at
small batch is WEIGHT-bandwidth bound — every forward streams the full
parameter set from HBM while activations stay small. Storing weights as int8
with per-output-channel float scales halves that traffic (vs bf16; 4x vs
f32) and halves the HBM a deployment holds (multi-tenancy admission,
operator/reconciler.py accounting reads the actual array bytes). The
dequantize (scale * int8) runs INSIDE the jitted program, where XLA fuses it
into the matmul operand load — the full-precision weight matrix is never
materialized in HBM.

Scheme: symmetric per-channel (last axis) int8 — ``w ≈ q * scale`` with
``scale = max|w| / 127`` per output column. Quantized: floating leaves with
ndim >= 2 and a leading dim <= 8192 (matmul/conv kernels). Exact: biases
and norm vectors (ndim 1), and big gathered tables (vocab embeddings) —
a gather from a fused dequant would MATERIALIZE the whole dequantized
table per call, spending the bandwidth the scheme saves. Worst-case
relative weight error is 1/254 per channel; classification outputs
typically move < 1e-2.

Enable per predictor with ``tpu: {weight_quant: "int8"}``.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

_QKEY = "__int8_weight__"


_MAX_LEAD_DIM = 8192  # above this the leaf is a gathered table, not a kernel


def _eligible(a: np.ndarray) -> bool:
    a = np.asarray(a)
    return a.ndim >= 2 and a.dtype.kind == "f" and a.shape[0] <= _MAX_LEAD_DIM


def quantize_params(params: Any) -> Any:
    """float pytree -> pytree where eligible leaves become
    {_QKEY: int8[...,], "scale": f32[out]} marker dicts (tree structure of
    everything else unchanged)."""

    def quant(a):
        if is_quantized_leaf(a):
            return a  # idempotent: already-quantized leaves pass through
        a = np.asarray(a)
        if not _eligible(a):
            return a
        amax = np.max(np.abs(a), axis=tuple(range(a.ndim - 1)), keepdims=True)
        scale = (amax / 127.0 + 1e-30).astype(np.float32)
        q = np.clip(np.round(a / scale), -127, 127).astype(np.int8)
        return {_QKEY: q, "scale": scale.astype(np.float32)}

    return jax.tree.map(quant, params, is_leaf=is_quantized_leaf)


def is_quantized_leaf(x: Any) -> bool:
    return isinstance(x, dict) and _QKEY in x


def dequantize(params: Any, dtype: Any = jnp.bfloat16) -> Any:
    """Inverse transform, for use INSIDE jit: marker dicts -> dtype arrays.
    XLA fuses the convert+multiply into the consuming matmul's operand
    read, so the dequantized matrix never lands in HBM."""

    def dequant(x):
        if is_quantized_leaf(x):
            # multiply in float32 THEN cast: rounding the f32 scale to bf16
            # first would add error of the same magnitude as the int8 step
            return (x[_QKEY].astype(jnp.float32) * x["scale"]).astype(dtype)
        return x

    return jax.tree.map(dequant, params, is_leaf=is_quantized_leaf)


def quantized_nbytes(leaf: Any, nonquant_factor: float = 1.0) -> int:
    """Residency of one param leaf under this scheme, for pre-build HBM
    admission estimates (operator/reconciler.py): eligible kernels store
    int8 payload + per-channel f32 scales; non-eligible leaves follow the
    predictor's compute dtype (``nonquant_factor``, e.g. 0.5 for bf16).
    Lives HERE so the estimator can never drift from the actual scheme."""
    a = np.asarray(leaf)
    if _eligible(a):
        return int(a.size + a.shape[-1] * 4)
    return int(a.nbytes * nonquant_factor)


def quantized_pspecs(pspecs: Any, params: Any) -> Any:
    """Mirror a PartitionSpec tree onto the quantized structure: a leaf's
    spec applies to its int8 payload; scales are tiny and replicate.
    PartitionSpec is itself a tuple-pytree, so it must be declared a leaf."""
    from jax.sharding import PartitionSpec as P

    def expand(spec, leaf):
        if is_quantized_leaf(leaf):
            return {_QKEY: spec, "scale": P()}
        return spec

    return jax.tree.map(
        expand,
        pspecs,
        params,
        is_leaf=lambda x: isinstance(x, P) or x is None,
    )
