"""ResNet-50 in pure JAX (NHWC) — the flagship image model of the zoo.

Parity role: the reference's benchmark configs call for "Average Combiner
ensemble: 3x ResNet50 image models" (BASELINE.json) served as CUDA/TF
containers behind per-request RPC. Here ResNet50 is a params-pytree + pure
apply function loaded straight into TPU HBM by ModelRuntime.

TPU design notes:
- NHWC layout with HWIO kernels — the layout XLA's TPU conv emitter expects;
  channels land on the 128-wide lane dimension of the MXU.
- BatchNorm is inference-mode (running stats are parameters). The functional
  training path (batch stats computed in-graph) lives in
  seldon_core_tpu/training/steps.py so serving apply stays a single pure fn.
- All FLOPs are convs/matmuls; elementwise (BN, relu, add) fuses into the
  preceding conv under XLA. bfloat16 params/activations are one dtype flag
  away (ModelRuntime dtype policy).
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from seldon_core_tpu.models.zoo import ModelSpec, register_model

# stage depths for the resnet family
_DEPTHS = {18: (2, 2, 2, 2), 34: (3, 4, 6, 3), 50: (3, 4, 6, 3), 101: (3, 4, 23, 3)}
_BOTTLENECK = {50: True, 101: True, 18: False, 34: False}


# Param init is HOST-side numpy on purpose: jax.random on a tunneled/remote
# device pays one compile + round-trip per tensor (~50 s for all of ResNet50);
# numpy init + one device_put is ~1 s. Determinism comes from the seeded rng.


def _conv_init(rng: np.random.Generator, h, w, c_in, c_out):
    fan_in = h * w * c_in
    scale = (2.0 / fan_in) ** 0.5
    return (rng.standard_normal((h, w, c_in, c_out)) * scale).astype(np.float32)


def _bn_init(c):
    return {
        "scale": np.ones((c,), np.float32),
        "bias": np.zeros((c,), np.float32),
        "mean": np.zeros((c,), np.float32),
        "var": np.ones((c,), np.float32),
    }


def _conv(x, kernel, stride=1):
    return jax.lax.conv_general_dilated(
        x,
        kernel.astype(x.dtype),
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _bn(x, p, eps=1e-5):
    # inference-mode batchnorm; folds to scale*x+shift, fused by XLA
    inv = jax.lax.rsqrt(p["var"].astype(x.dtype) + jnp.asarray(eps, x.dtype))
    scale = p["scale"].astype(x.dtype) * inv
    shift = p["bias"].astype(x.dtype) - p["mean"].astype(x.dtype) * scale
    return x * scale + shift


def _bottleneck_init(rng, c_in, c_mid, stride):
    c_out = c_mid * 4
    p = {
        "conv1": _conv_init(rng, 1, 1, c_in, c_mid),
        "bn1": _bn_init(c_mid),
        "conv2": _conv_init(rng, 3, 3, c_mid, c_mid),
        "bn2": _bn_init(c_mid),
        "conv3": _conv_init(rng, 1, 1, c_mid, c_out),
        "bn3": _bn_init(c_out),
    }
    if stride != 1 or c_in != c_out:
        p["proj"] = _conv_init(rng, 1, 1, c_in, c_out)
        p["bn_proj"] = _bn_init(c_out)
    return p


def _bottleneck_apply(p, x, stride):
    y = jax.nn.relu(_bn(_conv(x, p["conv1"]), p["bn1"]))
    y = jax.nn.relu(_bn(_conv(y, p["conv2"], stride), p["bn2"]))
    y = _bn(_conv(y, p["conv3"]), p["bn3"])
    if "proj" in p:
        x = _bn(_conv(x, p["proj"], stride), p["bn_proj"])
    return jax.nn.relu(x + y)


def _basic_init(rng, c_in, c_out, stride):
    p = {
        "conv1": _conv_init(rng, 3, 3, c_in, c_out),
        "bn1": _bn_init(c_out),
        "conv2": _conv_init(rng, 3, 3, c_out, c_out),
        "bn2": _bn_init(c_out),
    }
    if stride != 1 or c_in != c_out:
        p["proj"] = _conv_init(rng, 1, 1, c_in, c_out)
        p["bn_proj"] = _bn_init(c_out)
    return p


def _basic_apply(p, x, stride):
    y = jax.nn.relu(_bn(_conv(x, p["conv1"], stride), p["bn1"]))
    y = _bn(_conv(y, p["conv2"]), p["bn2"])
    if "proj" in p:
        x = _bn(_conv(x, p["proj"], stride), p["bn_proj"])
    return jax.nn.relu(x + y)


def init_resnet(
    seed: int = 0,
    depth: int = 50,
    num_classes: int = 1000,
    width: int = 64,
    image_size: int = 224,
) -> dict:
    rng = np.random.default_rng(seed)
    depths = _DEPTHS[depth]
    bottleneck = _BOTTLENECK[depth]
    expansion = 4 if bottleneck else 1
    block_init = _bottleneck_init if bottleneck else _basic_init

    params: dict[str, Any] = {
        "stem": {"conv": _conv_init(rng, 7, 7, 3, width), "bn": _bn_init(width)},
    }
    c_in = width
    for stage, n_blocks in enumerate(depths):
        c_mid = width * (2**stage)
        stride = 1 if stage == 0 else 2
        blocks = []
        for b in range(n_blocks):
            blocks.append(block_init(rng, c_in, c_mid, stride if b == 0 else 1))
            c_in = c_mid * expansion
        params[f"stage{stage}"] = blocks
    scale = (1.0 / c_in) ** 0.5
    params["head"] = {
        "w": (rng.standard_normal((c_in, num_classes)) * scale).astype(np.float32),
        "b": np.zeros((num_classes,), np.float32),
    }
    return params


def resnet_logits(params: dict, x: jax.Array) -> jax.Array:
    """x: [batch, H, W, 3] float -> logits [batch, num_classes]."""
    # pytree structure (not traced values) decides the block type, so this
    # branch is resolved at trace time — no dynamic control flow under jit
    bottleneck = "conv3" in params["stage0"][0]
    block_apply = _bottleneck_apply if bottleneck else _basic_apply

    h = _conv(x, params["stem"]["conv"], stride=2)
    h = jax.nn.relu(_bn(h, params["stem"]["bn"]))
    h = jax.lax.reduce_window(
        h, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )
    stage = 0
    while f"stage{stage}" in params:
        for b, bp in enumerate(params[f"stage{stage}"]):
            stride = 2 if (stage > 0 and b == 0) else 1
            h = block_apply(bp, h, stride)
        stage += 1
    h = jnp.mean(h, axis=(1, 2))  # global average pool
    return h @ params["head"]["w"].astype(h.dtype) + params["head"]["b"].astype(h.dtype)


def apply_resnet(params: dict, x: jax.Array) -> jax.Array:
    """Serving entrypoint: softmax probabilities."""
    return jax.nn.softmax(resnet_logits(params, x), axis=-1)


@register_model("resnet50")
def build_resnet50(
    seed: int = 0,
    num_classes: int = 1000,
    depth: int = 50,
    width: int = 64,
    image_size: int = 224,
    **_,
) -> ModelSpec:
    params = init_resnet(seed, depth=depth, num_classes=num_classes, width=width)
    return ModelSpec(
        apply_resnet,
        params,
        (image_size, image_size, 3),
        tuple(f"class_{i}" for i in range(num_classes)),
        param_pspecs=None,  # resnet serves data-parallel; weights replicate
    )


@register_model("resnet_tiny")
def build_resnet_tiny(seed: int = 0, num_classes: int = 10, **_) -> ModelSpec:
    """Small resnet (depth-18, width-16, 32x32) for tests and CI."""
    params = init_resnet(seed, depth=18, num_classes=num_classes, width=16)
    return ModelSpec(
        apply_resnet,
        params,
        (32, 32, 3),
        tuple(f"class_{i}" for i in range(num_classes)),
    )
