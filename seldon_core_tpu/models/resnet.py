"""ResNet-50 in pure JAX (NHWC) — the flagship image model of the zoo.

Parity role: the reference's benchmark configs call for "Average Combiner
ensemble: 3x ResNet50 image models" (BASELINE.json) served as CUDA/TF
containers behind per-request RPC. Here ResNet50 is a params-pytree + pure
apply function loaded straight into TPU HBM by ModelRuntime.

TPU design notes:
- NHWC layout with HWIO kernels — the layout XLA's TPU conv emitter expects;
  channels land on the 128-wide lane dimension of the MXU.
- BatchNorm is inference-mode (running stats are parameters) and is FOLDED
  into the preceding conv's weights at model-build time (fold_batchnorm) —
  each conv+BN pair serves as conv+bias, removing the per-channel
  scale/shift chain and the BN stats from HBM. The functional training path
  (batch stats computed in-graph) lives in seldon_core_tpu/training/steps.py
  so serving apply stays a single pure fn.
- All FLOPs are convs/matmuls; elementwise (BN, relu, add) fuses into the
  preceding conv under XLA. bfloat16 params/activations are one dtype flag
  away (ModelRuntime dtype policy).
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from seldon_core_tpu.models.zoo import ModelSpec, register_model

# stage depths for the resnet family
_DEPTHS = {18: (2, 2, 2, 2), 34: (3, 4, 6, 3), 50: (3, 4, 6, 3), 101: (3, 4, 23, 3)}
_BOTTLENECK = {50: True, 101: True, 18: False, 34: False}


# Param init is HOST-side numpy on purpose: jax.random on a tunneled/remote
# device pays one compile + round-trip per tensor (~50 s for all of ResNet50);
# numpy init + one device_put is ~1 s. Determinism comes from the seeded rng.


def _conv_init(rng: np.random.Generator, h, w, c_in, c_out):
    fan_in = h * w * c_in
    scale = (2.0 / fan_in) ** 0.5
    return (rng.standard_normal((h, w, c_in, c_out)) * scale).astype(np.float32)


def _bn_init(c):
    return {
        "scale": np.ones((c,), np.float32),
        "bias": np.zeros((c,), np.float32),
        "mean": np.zeros((c,), np.float32),
        "var": np.ones((c,), np.float32),
    }


def _conv(x, kernel, stride=1):
    return jax.lax.conv_general_dilated(
        x,
        kernel.astype(x.dtype),
        window_strides=(stride, stride),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _bn(x, p, eps=1e-5):
    # inference-mode batchnorm; folds to scale*x+shift, fused by XLA
    inv = jax.lax.rsqrt(p["var"].astype(x.dtype) + jnp.asarray(eps, x.dtype))
    scale = p["scale"].astype(x.dtype) * inv
    shift = p["bias"].astype(x.dtype) - p["mean"].astype(x.dtype) * scale
    return x * scale + shift


def _norm(x, p, bn_key, bias_key):
    """Post-conv normalisation: BN when unfolded, plain bias when folded.

    Which branch runs is decided by pytree structure at trace time, so both
    folded and unfolded params share the same jitted apply code.
    """
    if bn_key in p:
        return _bn(x, p[bn_key])
    return x + p[bias_key].astype(x.dtype)


# (conv key, unfolded bn key, folded bias key) triples for one block
_FOLD_KEYS = (
    ("conv1", "bn1", "bias1"),
    ("conv2", "bn2", "bias2"),
    ("conv3", "bn3", "bias3"),
    ("proj", "bn_proj", "bias_proj"),
)


def fold_batchnorm(params: dict, eps: float = 1e-5) -> dict:
    """Fold inference-mode BN into the preceding conv's weights (host-side).

    conv(x, W)*s + t  ==  conv(x, W*s) + t  for the per-output-channel BN
    affine s = scale/sqrt(var+eps), t = bias - mean*s, so each conv+BN pair
    becomes conv + bias — one fewer elementwise chain per conv at serving
    time and no BN stats in HBM. Equivalent to the unfolded path up to
    float rounding (folding is computed in float64 and cast to float32).
    Idempotent: already-folded params pass through unchanged.
    """

    def fold(kernel, bn):
        inv = np.asarray(bn["scale"], np.float64) / np.sqrt(
            np.asarray(bn["var"], np.float64) + eps
        )
        w = (np.asarray(kernel, np.float64) * inv).astype(np.float32)
        b = (
            np.asarray(bn["bias"], np.float64)
            - np.asarray(bn["mean"], np.float64) * inv
        ).astype(np.float32)
        return w, b

    out: dict[str, Any] = {"head": params["head"]}
    stem = params["stem"]
    if "bn" in stem:
        w, b = fold(stem["conv"], stem["bn"])
        out["stem"] = {"conv": w, "bias": b}
    else:
        out["stem"] = stem
    stage = 0
    while f"stage{stage}" in params:
        blocks = []
        for bp in params[f"stage{stage}"]:
            nb: dict[str, Any] = {}
            for conv_key, bn_key, bias_key in _FOLD_KEYS:
                if conv_key not in bp:
                    continue
                if bn_key in bp:
                    nb[conv_key], nb[bias_key] = fold(bp[conv_key], bp[bn_key])
                else:  # already folded
                    nb[conv_key] = bp[conv_key]
                    nb[bias_key] = bp[bias_key]
            blocks.append(nb)
        out[f"stage{stage}"] = blocks
        stage += 1
    return out


def _bottleneck_init(rng, c_in, c_mid, stride):
    c_out = c_mid * 4
    p = {
        "conv1": _conv_init(rng, 1, 1, c_in, c_mid),
        "bn1": _bn_init(c_mid),
        "conv2": _conv_init(rng, 3, 3, c_mid, c_mid),
        "bn2": _bn_init(c_mid),
        "conv3": _conv_init(rng, 1, 1, c_mid, c_out),
        "bn3": _bn_init(c_out),
    }
    if stride != 1 or c_in != c_out:
        p["proj"] = _conv_init(rng, 1, 1, c_in, c_out)
        p["bn_proj"] = _bn_init(c_out)
    return p


def _bottleneck_apply(p, x, stride):
    y = jax.nn.relu(_norm(_conv(x, p["conv1"]), p, "bn1", "bias1"))
    y = jax.nn.relu(_norm(_conv(y, p["conv2"], stride), p, "bn2", "bias2"))
    y = _norm(_conv(y, p["conv3"]), p, "bn3", "bias3")
    if "proj" in p:
        x = _norm(_conv(x, p["proj"], stride), p, "bn_proj", "bias_proj")
    return jax.nn.relu(x + y)


def _basic_init(rng, c_in, c_out, stride):
    p = {
        "conv1": _conv_init(rng, 3, 3, c_in, c_out),
        "bn1": _bn_init(c_out),
        "conv2": _conv_init(rng, 3, 3, c_out, c_out),
        "bn2": _bn_init(c_out),
    }
    if stride != 1 or c_in != c_out:
        p["proj"] = _conv_init(rng, 1, 1, c_in, c_out)
        p["bn_proj"] = _bn_init(c_out)
    return p


def _basic_apply(p, x, stride):
    y = jax.nn.relu(_norm(_conv(x, p["conv1"], stride), p, "bn1", "bias1"))
    y = _norm(_conv(y, p["conv2"]), p, "bn2", "bias2")
    if "proj" in p:
        x = _norm(_conv(x, p["proj"], stride), p, "bn_proj", "bias_proj")
    return jax.nn.relu(x + y)


def init_resnet(
    seed: int = 0,
    depth: int = 50,
    num_classes: int = 1000,
    width: int = 64,
    image_size: int = 224,
) -> dict:
    rng = np.random.default_rng(seed)
    depths = _DEPTHS[depth]
    bottleneck = _BOTTLENECK[depth]
    expansion = 4 if bottleneck else 1
    block_init = _bottleneck_init if bottleneck else _basic_init

    params: dict[str, Any] = {
        "stem": {"conv": _conv_init(rng, 7, 7, 3, width), "bn": _bn_init(width)},
    }
    c_in = width
    for stage, n_blocks in enumerate(depths):
        c_mid = width * (2**stage)
        stride = 1 if stage == 0 else 2
        blocks = []
        for b in range(n_blocks):
            blocks.append(block_init(rng, c_in, c_mid, stride if b == 0 else 1))
            c_in = c_mid * expansion
        params[f"stage{stage}"] = blocks
    scale = (1.0 / c_in) ** 0.5
    params["head"] = {
        "w": (rng.standard_normal((c_in, num_classes)) * scale).astype(np.float32),
        "b": np.zeros((num_classes,), np.float32),
    }
    return params


def space_to_depth_stem(params: dict) -> dict:
    """Re-express the 7x7/stride-2 stem conv as 4x4/stride-1 on a
    space-to-depth input (host-side, one-time, exact).

    The stem conv reads a 3-channel image — 3 of the MXU's 128 lanes do
    work, so the op is ~2% efficient and dominates wall time. Folding a
    2x2 space-to-depth into the weights turns it into a 12-channel conv:
      y[i,j,o] = sum_{p,q,c} w[p,q,c,o] x[2i+p-2, 2j+q-2, c]
    with x[2I+a, 2J+b, c] = X[I, J, (a,b,c)] becomes a 4x4 conv over X
    where w'[P,Q,(a,b,c),o] = w[2P+a, 2Q+b, c, o] (zero where 2P+a > 6)
    and explicit padding (1,2) replaces SAME's pixel-space (2,3).
    apply_resnet performs the matching input reshape at trace time when it
    sees a 12-channel stem kernel. Requires a folded stem (run
    fold_batchnorm first); no-op if already transformed.
    """
    stem = params["stem"]
    if "bn" in stem:
        raise ValueError("space_to_depth_stem requires fold_batchnorm first")
    w = np.asarray(stem["conv"], np.float32)
    if w.shape[:3] == (4, 4, 12):  # already transformed
        return params
    if w.shape[:3] != (7, 7, 3):
        raise ValueError(f"unexpected stem kernel shape {w.shape}")
    c_out = w.shape[3]
    w2 = np.zeros((4, 4, 12, c_out), np.float32)
    for big_p in range(4):
        for big_q in range(4):
            for a in range(2):
                for b in range(2):
                    p, q = 2 * big_p + a, 2 * big_q + b
                    if p > 6 or q > 6:
                        continue
                    for c in range(3):
                        w2[big_p, big_q, a * 6 + b * 3 + c] = w[p, q, c]
    out = dict(params)
    out["stem"] = {"conv": w2, "bias": stem["bias"]}
    return out


def _space_to_depth(x):
    """[N, 2H, 2W, C] -> [N, H, W, 4C] matching space_to_depth_stem's
    (a, b, c) channel order. Even H and W required — the transformed stem's
    explicit (1,2) block padding equals SAME's (2,3) pixel padding only
    then (shapes are static under jit, so this raises at trace time)."""
    n, h, w, c = x.shape
    if h % 2 or w % 2:
        raise ValueError(
            f"space-to-depth stem requires even spatial dims, got {h}x{w}; "
            "build the model with space_to_depth=False for odd image sizes"
        )
    x = x.reshape(n, h // 2, 2, w // 2, 2, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(n, h // 2, w // 2, 4 * c)


def resnet_logits(params: dict, x: jax.Array) -> jax.Array:
    """x: [batch, H, W, 3] float -> logits [batch, num_classes]."""
    # pytree structure (not traced values) decides the block type, so this
    # branch is resolved at trace time — no dynamic control flow under jit
    bottleneck = "conv3" in params["stage0"][0]
    block_apply = _bottleneck_apply if bottleneck else _basic_apply

    stem_kernel = params["stem"]["conv"]
    if stem_kernel.shape[2] == 12:  # space-to-depth stem (trace-time branch)
        h = jax.lax.conv_general_dilated(
            _space_to_depth(x),
            stem_kernel.astype(x.dtype),
            window_strides=(1, 1),
            padding=((1, 2), (1, 2)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
    else:
        h = _conv(x, stem_kernel, stride=2)
    h = jax.nn.relu(_norm(h, params["stem"], "bn", "bias"))
    h = jax.lax.reduce_window(
        h, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME"
    )
    stage = 0
    while f"stage{stage}" in params:
        for b, bp in enumerate(params[f"stage{stage}"]):
            stride = 2 if (stage > 0 and b == 0) else 1
            h = block_apply(bp, h, stride)
        stage += 1
    h = jnp.mean(h, axis=(1, 2))  # global average pool
    return h @ params["head"]["w"].astype(h.dtype) + params["head"]["b"].astype(h.dtype)


def apply_resnet(params: dict, x: jax.Array) -> jax.Array:
    """Serving entrypoint: softmax probabilities."""
    return jax.nn.softmax(resnet_logits(params, x), axis=-1)


@register_model("resnet50")
def build_resnet50(
    seed: int = 0,
    num_classes: int = 1000,
    depth: int = 50,
    width: int = 64,
    image_size: int = 224,
    fold_bn: bool = True,
    space_to_depth: bool = False,
    **_,
) -> ModelSpec:
    params = init_resnet(seed, depth=depth, num_classes=num_classes, width=width)
    if fold_bn:
        params = fold_batchnorm(params)
    if space_to_depth:
        params = space_to_depth_stem(params)
    return ModelSpec(
        apply_resnet,
        params,
        (image_size, image_size, 3),
        tuple(f"class_{i}" for i in range(num_classes)),
        param_pspecs=None,  # resnet serves data-parallel; weights replicate
    )


@register_model("resnet_tiny")
def build_resnet_tiny(
    seed: int = 0,
    num_classes: int = 10,
    fold_bn: bool = True,
    space_to_depth: bool = False,
    **_,
) -> ModelSpec:
    """Small resnet (depth-18, width-16, 32x32) for tests and CI."""
    params = init_resnet(seed, depth=18, num_classes=num_classes, width=16)
    if fold_bn:
        params = fold_batchnorm(params)
    if space_to_depth:
        params = space_to_depth_stem(params)
    return ModelSpec(
        apply_resnet,
        params,
        (32, 32, 3),
        tuple(f"class_{i}" for i in range(num_classes)),
    )
