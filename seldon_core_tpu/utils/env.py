"""Env-var config hand-off, reference-compatible.

The reference's load-bearing config mechanism is base64-JSON-in-env
(SURVEY §5.6): the operator injects ``ENGINE_PREDICTOR`` = b64(json(
PredictorSpec)) into the engine container (SeldonDeploymentOperatorImpl
.java:100-103) and the engine decodes it at boot (EnginePredictor.java:56-117).
Same contract here, same var names.
"""

from __future__ import annotations

import base64
import json
import os
from typing import Any

ENGINE_PREDICTOR = "ENGINE_PREDICTOR"
ENGINE_SELDON_DEPLOYMENT = "ENGINE_SELDON_DEPLOYMENT"
ENGINE_SERVER_PORT = "ENGINE_SERVER_PORT"  # default 8000 (CustomizationBean.java)
ENGINE_SERVER_GRPC_PORT = "ENGINE_SERVER_GRPC_PORT"  # default 5000 (SeldonGrpcServer.java:33)
ENGINE_DRAIN_SECONDS = "ENGINE_DRAIN_SECONDS"  # graceful-drain window, default 5
PREDICTIVE_UNIT_PARAMETERS = "PREDICTIVE_UNIT_PARAMETERS"
PREDICTIVE_UNIT_ID = "PREDICTIVE_UNIT_ID"
PREDICTIVE_UNIT_SERVICE_PORT = "PREDICTIVE_UNIT_SERVICE_PORT"  # default 5000
SELDON_DEPLOYMENT_ID = "SELDON_DEPLOYMENT_ID"
# state persistence for wrapped user objects (serving/microservice.py):
# store URL consumed by persistence/state.make_state_store
PERSISTENCE_STORE = "PERSISTENCE_STORE"  # default file://./.seldon_state
# redis state-store socket budget (persistence/state.RedisStateStore):
# connect AND per-op timeout in ms. A hung Redis must never wedge the
# serving loop mid-spill/preseed — operations past the budget degrade to
# skip-store (save dropped, load misses), matching the spill path's
# "store outage degrades, never aborts" contract.
PERSISTENCE_REDIS_TIMEOUT_MS = "PERSISTENCE_REDIS_TIMEOUT_MS"  # default 2000
# control-plane / tooling (not injected by the operator; read by humans'
# shells and CI): kubectl-proxy style API endpoint for the k8s watcher,
# the PYTHON_CLASS capability gate, and the release registry prefix
SELDON_TPU_K8S_API = "SELDON_TPU_K8S_API"
SELDON_TPU_ALLOW_PYTHON_CLASS = "SELDON_TPU_ALLOW_PYTHON_CLASS"
SELDON_TPU_REGISTRY = "SELDON_TPU_REGISTRY"
# loadtest/soak credentials (tools/loadtest.py; install.py wires them from
# a Secret in the rendered bundle) and the reference's test-client backdoor
# (gateway/app.py — AuthorizationServerConfiguration.java:78-96)
LOADTEST_OAUTH_KEY = "LOADTEST_OAUTH_KEY"
LOADTEST_OAUTH_SECRET = "LOADTEST_OAUTH_SECRET"
TEST_CLIENT_KEY = "TEST_CLIENT_KEY"
TEST_CLIENT_SECRET = "TEST_CLIENT_SECRET"
# RemoteUnit REST transport timeouts (engine/remote._RestSession). The
# reference bakes one 5 s total deadline into every call
# (InternalPredictionService.java:77); here connect and total are separate —
# a connect hang should fail in ~1 s while a legitimately slow model may use
# the whole total budget — and both are tunable without a rebuild.
ENGINE_REST_CONNECT_TIMEOUT_S = "ENGINE_REST_CONNECT_TIMEOUT_S"  # default 1.0
ENGINE_REST_TOTAL_TIMEOUT_S = "ENGINE_REST_TOTAL_TIMEOUT_S"  # default 5.0
# telemetry (telemetry/tracer.py reads these): process-wide tracing toggle,
# tail-sampling pool bounds, optional OTLP-JSON trace export, and the
# structured access log gate (telemetry/access_log.py)
ENGINE_TELEMETRY = "ENGINE_TELEMETRY"  # "off" disables tracing (default on)
ENGINE_TRACE_MAX_ERRORS = "ENGINE_TRACE_MAX_ERRORS"  # default 128
ENGINE_TRACE_SLOW_KEEP = "ENGINE_TRACE_SLOW_KEEP"  # default 32
ENGINE_TRACE_MAX_SAMPLED = "ENGINE_TRACE_MAX_SAMPLED"  # default 64
ENGINE_TRACE_SAMPLE_RATE = "ENGINE_TRACE_SAMPLE_RATE"  # default 0.05
ENGINE_OTLP_FILE = "ENGINE_OTLP_FILE"  # path; unset = no export
ENGINE_ACCESS_LOG = "ENGINE_ACCESS_LOG"  # "json" enables; default off
# decode-loop flight recorder (telemetry/flight.py reads these): per-round
# ring buffer kill switch + capacity. On by default — the measured append
# cost is single-digit µs/round (PARITY.md "Flight recorder overhead").
ENGINE_FLIGHT = "ENGINE_FLIGHT"  # "off" disables the recorder
ENGINE_FLIGHT_FRAMES = "ENGINE_FLIGHT_FRAMES"  # ring capacity, default 2048
# "on" forces per-dispatch completion (block_until_ready after every fused
# program) so each family's flight column is ground-truth device wall —
# calibration runs only; default off (async dispatch stays pipelined)
ENGINE_FLIGHT_SYNC_TIMING = "ENGINE_FLIGHT_SYNC_TIMING"
# decode-round pipelining kill switch (serving/decode_scheduler.py): "off"
# forces the SERIAL round loop — round N+1's host phases wait for round N's
# readback instead of running under the in-flight dispatch. Default on;
# ENGINE_FLIGHT_SYNC_TIMING=on also forces serial (ground-truth timing
# needs the unpipelined loop).
ENGINE_DECODE_PIPELINE = "ENGINE_DECODE_PIPELINE"
# decode-loop sampling profiler (telemetry/profile.py reads these):
# always-on low-rate folded-stack sampler over the decode loop's thread,
# served by GET /decode/profile. "off" disables; rate default 19 Hz;
# folded-stack table bound default 512 entries (overflow counts, not grows)
ENGINE_DECODE_PROFILE = "ENGINE_DECODE_PROFILE"
ENGINE_DECODE_PROFILE_HZ = "ENGINE_DECODE_PROFILE_HZ"
ENGINE_DECODE_PROFILE_TABLE = "ENGINE_DECODE_PROFILE_TABLE"
# multi-replica decode scale-out (serving/affinity_router.py): "off"
# disables warm pre-seeding of scale-up replicas from spilled prefix-pool
# pages — new replicas then boot cold (diagnosis lever: isolates a preseed
# regression from the routing policy). Default on.
ENGINE_DECODE_REPLICA_PRESEED = "ENGINE_DECODE_REPLICA_PRESEED"


def rest_timeouts(env: dict | None = None) -> tuple[float, float]:
    """(connect_s, total_s) for the pooled REST session, env-tunable.
    Falls back to the defaults on unset OR unparsable values — a typo'd
    timeout must not take the data plane down at boot."""
    env = env if env is not None else os.environ
    out = []
    for key, default in (
        (ENGINE_REST_CONNECT_TIMEOUT_S, 1.0),
        (ENGINE_REST_TOTAL_TIMEOUT_S, 5.0),
    ):
        try:
            value = float(env.get(key, default))
        except (TypeError, ValueError):
            value = default
        out.append(value if value > 0 else default)
    return out[0], out[1]


def redis_timeout_s(env: dict | None = None) -> float:
    """Redis socket/connect timeout in SECONDS (redis-py's unit), from the
    PERSISTENCE_REDIS_TIMEOUT_MS env var. Falls back to the 2000 ms default
    on unset OR unparsable values — a typo'd timeout must not take state
    persistence down at boot."""
    env = env if env is not None else os.environ
    try:
        ms = float(env.get(PERSISTENCE_REDIS_TIMEOUT_MS, 2000.0))
    except (TypeError, ValueError):
        ms = 2000.0
    if ms <= 0:
        ms = 2000.0
    return ms / 1000.0


def encode_b64_json(obj: Any) -> str:
    return base64.b64encode(json.dumps(obj).encode()).decode("ascii")


def decode_b64_json(value: str) -> Any:
    return json.loads(base64.b64decode(value))


def predictor_from_env(env: dict | None = None):
    """Decode a PredictorSpec (or the first predictor of a full deployment)
    from the environment; returns (predictor_spec, deployment_name) or None.
    Mirrors EnginePredictor.init precedence: ENGINE_PREDICTOR, then
    ENGINE_SELDON_DEPLOYMENT, then ./deploymentdef.json, else None (caller
    falls back to the default SIMPLE_MODEL graph)."""
    from seldon_core_tpu.graph.spec import PredictorSpec, SeldonDeployment

    env = env if env is not None else dict(os.environ)
    raw = env.get(ENGINE_PREDICTOR)
    if raw:
        return PredictorSpec.model_validate(decode_b64_json(raw)), env.get(
            SELDON_DEPLOYMENT_ID, ""
        )
    raw = env.get(ENGINE_SELDON_DEPLOYMENT)
    if raw:
        dep = SeldonDeployment.from_dict(decode_b64_json(raw))
        if dep.spec.predictors:
            return dep.spec.predictors[0], dep.spec.name
    if os.path.exists("deploymentdef.json"):
        with open("deploymentdef.json") as f:
            dep = SeldonDeployment.from_dict(json.load(f))
        if dep.spec.predictors:
            return dep.spec.predictors[0], dep.spec.name
    return None


def default_predictor():
    """The reference's fallback graph when no config is present
    (EnginePredictor.java:131-150): a single SIMPLE_MODEL unit."""
    from seldon_core_tpu.graph.spec import PredictiveUnit, PredictorSpec

    return PredictorSpec(
        name="default",
        graph=PredictiveUnit.model_validate(
            {"name": "simple-model", "type": "MODEL", "implementation": "SIMPLE_MODEL"}
        ),
    )
