"""Remote-unit escape hatch: call an external microservice for a graph node.

Parity: reference engine InternalPredictionService.java:90-285 — dispatch of
transform_input/route/aggregate/transform_output/send_feedback to a per-node
container over REST (form-encoded ``json=`` payload, :216-285) or gRPC.
Differences by design: connections are pooled and channels cached per
endpoint (the reference creates a NEW gRPC ManagedChannel per call, :211-214 —
SURVEY flags it as a perf hazard not to replicate), and the whole thing is
asyncio instead of blocking RestTemplate.

Internal REST API paths/payloads match docs/reference/internal-api.md so an
unmodified reference model container (wrappers/python) plugs in directly.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any, Sequence

import numpy as np

from seldon_core_tpu.core.codec_json import (
    feedback_to_dict,
    message_from_dict,
    message_to_dict,
)
from seldon_core_tpu.core.errors import APIException, ErrorCode
from seldon_core_tpu.core.message import Feedback, SeldonMessage
from seldon_core_tpu.engine.resilience import call_timeout, current_deadline
from seldon_core_tpu.engine.units import ROUTE_ALL, Unit
from seldon_core_tpu import telemetry
from seldon_core_tpu.graph.spec import EndpointType, PredictiveUnit
from seldon_core_tpu.utils.env import rest_timeouts

GRPC_DEADLINE_S = 5.0  # reference InternalPredictionService.java:77 (default
# only: a request carrying a deadline budget uses its REMAINING budget as
# the per-call timeout instead — engine/resilience.call_timeout)


class _RestSession:
    """Shared pooled aiohttp session (lazy, one per event loop).

    Guarded by a per-loop lock: a ``close()`` overlapping a ``get()`` used
    to race (get() could return the session close() was about to tear down,
    or resurrect a half-closed one); now create/close are serialized and
    the session is re-created if it was built on a previous (dead) loop.
    Connect and total timeouts are split and env-tunable (utils/env
    .rest_timeouts); per-call deadline budgets override total per request.
    """

    _session = None
    _session_loop = None
    _lock: asyncio.Lock | None = None
    _lock_loop = None

    @classmethod
    def _get_lock(cls) -> asyncio.Lock:
        loop = asyncio.get_running_loop()
        if cls._lock is None or cls._lock_loop is not loop:
            cls._lock = asyncio.Lock()
            cls._lock_loop = loop
        return cls._lock

    @classmethod
    async def get(cls):
        import aiohttp

        loop = asyncio.get_running_loop()
        async with cls._get_lock():
            if (
                cls._session is None
                or cls._session.closed
                or cls._session_loop is not loop
            ):
                stale = cls._session
                if stale is not None and not stale.closed:
                    # a session left over from a previous (dead) event loop:
                    # close its connector best-effort instead of leaking the
                    # sockets until GC ("Unclosed client session")
                    try:
                        await stale.close()
                    except Exception:  # noqa: BLE001 - cross-loop teardown
                        pass
                connect_s, total_s = rest_timeouts()
                cls._session = aiohttp.ClientSession(
                    timeout=aiohttp.ClientTimeout(total=total_s, connect=connect_s),
                    connector=aiohttp.TCPConnector(limit=150),  # reference pool size
                )
                cls._session_loop = loop
            return cls._session

    @classmethod
    async def close(cls):
        async with cls._get_lock():
            session, cls._session = cls._session, None
            cls._session_loop = None
            if session is not None and not session.closed:
                try:
                    await session.close()
                except Exception:  # noqa: BLE001 - cross-loop teardown (a
                    # session built on a previous, now-dead loop) must not
                    # abort the caller's shutdown path
                    pass


class RemoteUnit(Unit):
    """Graph unit whose methods execute in an external service."""

    def __init__(self, spec: PredictiveUnit):
        super().__init__(spec)
        ep = spec.endpoint
        if ep is None or not ep.service_port:
            raise ValueError(f"RemoteUnit '{spec.name}' needs an endpoint")
        self.endpoint = ep
        self._grpc_channel = None  # cached (never per-call)
        self._stub_cache: dict[str, object] = {}

    # ----------------------------------------------------------- REST path
    async def _rest_call(self, path: str, payload: dict) -> SeldonMessage:
        session = await _RestSession.get()
        url = f"http://{self.endpoint.service_host}:{self.endpoint.service_port}{path}"
        # reference wire quirk kept for compatibility: body is form-encoded
        # with the message under a `json=` field (microservice.py:44-52)
        data = {"json": json.dumps(payload)}
        # a stamped request deadline REPLACES the session's default total
        # timeout with the remaining budget (connect stays bounded by the
        # session default); unbudgeted requests ride the session default
        # without paying a per-call ClientTimeout construction
        kwargs = {}
        # trace propagation: the server side extracts traceparent and
        # continues this request's trace, so the hop's server-side spans
        # stitch under the unit-call span that dispatched it
        tp = telemetry.traceparent()
        if tp is not None:
            kwargs["headers"] = {"traceparent": tp}
        if current_deadline() is not None:
            import aiohttp

            connect_s, total_s = rest_timeouts()
            kwargs["timeout"] = aiohttp.ClientTimeout(
                total=call_timeout(total_s), connect=connect_s
            )
        try:
            async with session.post(url, data=data, **kwargs) as resp:
                body = await resp.text()
                if resp.status != 200:
                    # 4xx is a DETERMINISTIC answer from a healthy backend:
                    # never retried, never counted against its breaker
                    raise APIException(
                        ErrorCode.ENGINE_MICROSERVICE_ERROR,
                        f"{url} -> {resp.status}: {body[:300]}",
                        retryable=resp.status >= 500,
                    )
        except APIException:
            raise
        except Exception as e:  # noqa: BLE001 - network errors normalised
            self._raise_if_deadline(e, url)
            raise APIException(ErrorCode.ENGINE_MICROSERVICE_ERROR, f"{url}: {e}") from e
        try:
            return message_from_dict(json.loads(body))
        except (json.JSONDecodeError, APIException) as e:
            raise APIException(ErrorCode.ENGINE_INVALID_RESPONSE, str(e)) from e

    # ----------------------------------------------------------- gRPC path
    def _grpc_service_for(self, method: str) -> str:
        """Pick the per-unit-type service a reference container actually
        serves (prediction.proto:84-103): MODEL containers register
        Model.Predict, routers Router.Route, etc. Our own grpc_server also
        registers Generic, but reference wrappers do not."""
        from seldon_core_tpu.graph.spec import PredictiveUnitType

        t = self.spec.type
        if method == "Predict" or (method == "TransformInput" and t == PredictiveUnitType.MODEL):
            return "Model"
        if method in ("Route", "SendFeedback") and t == PredictiveUnitType.ROUTER:
            return "Router"
        if method == "TransformInput":
            return "Transformer"
        if method == "TransformOutput":
            return "OutputTransformer"
        if method == "Aggregate":
            return "Combiner"
        return "Generic"

    @staticmethod
    def _raise_if_deadline(e: Exception, where: str) -> None:
        """A transport timeout on a request whose budget has run out IS the
        deadline firing — surface it as 504 budget exhaustion, not as a
        retryable 5xx transport error."""
        from seldon_core_tpu.engine.resilience import current_deadline, deadline_exceeded

        d = current_deadline()
        if d is not None and d.expired():
            raise deadline_exceeded(where) from e

    @staticmethod
    def _is_transport_failure(e: Exception) -> bool:
        """gRPC failures that indict the CHANNEL (connect refused / backend
        gone / TLS reset) rather than the request: the cached channel must
        be rebuilt so a restarted backend recovers without a process
        bounce. Application-level statuses keep the channel."""
        code = getattr(e, "code", None)
        if not callable(code):
            return isinstance(e, (ConnectionError, OSError))
        try:
            import grpc

            return code() is grpc.StatusCode.UNAVAILABLE
        except Exception:  # noqa: BLE001 - classification must never raise
            return False

    @staticmethod
    def _grpc_retryable(e: Exception) -> bool | None:
        """Explicit retryability for gRPC statuses: deterministic
        request-level codes (INVALID_ARGUMENT and friends) must not be
        replayed or counted against the endpoint's breaker. None = let the
        resilience layer classify by error code (default retryable, since
        the failure normalises to ENGINE_MICROSERVICE_ERROR)."""
        code = getattr(e, "code", None)
        if not callable(code):
            return None
        try:
            import grpc

            deterministic = (
                grpc.StatusCode.INVALID_ARGUMENT,
                grpc.StatusCode.NOT_FOUND,
                grpc.StatusCode.ALREADY_EXISTS,
                grpc.StatusCode.PERMISSION_DENIED,
                grpc.StatusCode.UNAUTHENTICATED,
                grpc.StatusCode.FAILED_PRECONDITION,
                grpc.StatusCode.OUT_OF_RANGE,
                grpc.StatusCode.UNIMPLEMENTED,
            )
            return False if code() in deterministic else None
        except Exception:  # noqa: BLE001 - classification must never raise
            return None

    async def _invalidate_channel(self, channel) -> None:
        """Drop (and close) the cached channel IF it is still the one that
        failed — a concurrent call may already have rebuilt it."""
        if self._grpc_channel is not channel:
            return
        self._grpc_channel = None
        self._stub_cache.clear()
        try:
            await channel.close()
        except Exception:  # noqa: BLE001 - teardown of a dead channel
            pass

    async def _grpc_call(self, method: str, request_pb) -> SeldonMessage:
        import grpc

        from seldon_core_tpu.proto.services import ServiceStub
        from seldon_core_tpu.core.codec_proto import message_from_proto

        if self._grpc_channel is None:
            target = f"{self.endpoint.service_host}:{self.endpoint.service_port}"
            self._grpc_channel = grpc.aio.insecure_channel(target)
        channel = self._grpc_channel
        service = self._grpc_service_for(method)
        # stub per service, cached — the reference's perf hazard is a new
        # ManagedChannel per call (InternalPredictionService.java:211-214);
        # we reuse both the channel and the per-service stub.
        # reference containers serve package seldon.protos; wire format is
        # identical, so address them under that package
        stub = self._stub_cache.get(service)
        if stub is None:
            stub = ServiceStub(channel, service, package="seldon.protos")
            self._stub_cache[service] = stub
        rpc_method = "Predict" if service == "Model" else method
        # trace propagation over gRPC: same W3C traceparent, as metadata
        tp = telemetry.traceparent()
        metadata = (("traceparent", tp),) if tp is not None else None
        try:
            reply = await getattr(stub, rpc_method)(
                request_pb,
                timeout=call_timeout(GRPC_DEADLINE_S),
                metadata=metadata,
            )
        except APIException:
            raise
        except Exception as e:  # noqa: BLE001
            if self._is_transport_failure(e):
                # a channel that failed at the transport layer was cached
                # forever before this: every later call kept failing even
                # after the backend came back
                await self._invalidate_channel(channel)
            self._raise_if_deadline(e, f"gRPC {service}.{rpc_method}")
            raise APIException(
                ErrorCode.ENGINE_MICROSERVICE_ERROR,
                f"gRPC {service}.{rpc_method}: {e}",
                retryable=self._grpc_retryable(e),
            ) from e
        return message_from_proto(reply)

    def _to_proto(self, msg: SeldonMessage):
        from seldon_core_tpu.core.codec_proto import message_to_proto

        return message_to_proto(msg)

    # ------------------------------------------------------------- methods
    async def transform_input(self, msg: SeldonMessage) -> SeldonMessage:
        if self.endpoint.type == EndpointType.GRPC:
            return await self._grpc_call("TransformInput", self._to_proto(msg))
        # MODEL containers expose /predict; TRANSFORMER ones /transform-input.
        # The reference tries per unit type (InternalPredictionService:132-161);
        # we use the unit type to pick the path.
        from seldon_core_tpu.graph.spec import PredictiveUnitType

        path = "/predict" if self.spec.type == PredictiveUnitType.MODEL else "/transform-input"
        return await self._rest_call(path, message_to_dict(msg))

    async def transform_output(self, msg: SeldonMessage) -> SeldonMessage:
        if self.endpoint.type == EndpointType.GRPC:
            return await self._grpc_call("TransformOutput", self._to_proto(msg))
        return await self._rest_call("/transform-output", message_to_dict(msg))

    async def route(self, msg: SeldonMessage) -> int:
        if self.endpoint.type == EndpointType.GRPC:
            reply = await self._grpc_call("Route", self._to_proto(msg))
        else:
            reply = await self._rest_call("/route", message_to_dict(msg))
        arr = reply.array
        if arr is None:
            raise APIException(ErrorCode.ENGINE_INVALID_RESPONSE, "router returned no data")
        return int(np.asarray(arr).reshape(-1)[0])

    async def aggregate(self, msgs: Sequence[SeldonMessage]) -> SeldonMessage:
        if self.endpoint.type == EndpointType.GRPC:
            from seldon_core_tpu.core.codec_proto import message_list_to_proto

            return await self._grpc_call("Aggregate", message_list_to_proto(msgs))
        payload = {"seldonMessages": [message_to_dict(m) for m in msgs]}
        return await self._rest_call("/aggregate", payload)

    async def send_feedback(self, feedback: Feedback, routing: int) -> None:
        if self.endpoint.type == EndpointType.GRPC:
            from seldon_core_tpu.core.codec_proto import feedback_to_proto

            await self._grpc_call("SendFeedback", feedback_to_proto(feedback))
            return
        await self._rest_call("/send-feedback", feedback_to_dict(feedback))

    async def close(self) -> None:
        if self._grpc_channel is not None:
            await self._grpc_channel.close()
