"""Remote-unit escape hatch: call an external microservice for a graph node.

Parity: reference engine InternalPredictionService.java:90-285 — dispatch of
transform_input/route/aggregate/transform_output/send_feedback to a per-node
container over REST (form-encoded ``json=`` payload, :216-285) or gRPC.
Differences by design: connections are pooled and channels cached per
endpoint (the reference creates a NEW gRPC ManagedChannel per call, :211-214 —
SURVEY flags it as a perf hazard not to replicate), and the whole thing is
asyncio instead of blocking RestTemplate.

Internal REST API paths/payloads match docs/reference/internal-api.md so an
unmodified reference model container (wrappers/python) plugs in directly.
"""

from __future__ import annotations

import json
from typing import Any, Sequence

import numpy as np

from seldon_core_tpu.core.codec_json import (
    feedback_to_dict,
    message_from_dict,
    message_to_dict,
)
from seldon_core_tpu.core.errors import APIException, ErrorCode
from seldon_core_tpu.core.message import Feedback, SeldonMessage
from seldon_core_tpu.engine.units import ROUTE_ALL, Unit
from seldon_core_tpu.graph.spec import EndpointType, PredictiveUnit

GRPC_DEADLINE_S = 5.0  # reference InternalPredictionService.java:77


class _RestSession:
    """Shared pooled aiohttp session (lazy, one per process)."""

    _session = None

    @classmethod
    async def get(cls):
        import aiohttp

        if cls._session is None or cls._session.closed:
            cls._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=GRPC_DEADLINE_S),
                connector=aiohttp.TCPConnector(limit=150),  # reference pool size
            )
        return cls._session

    @classmethod
    async def close(cls):
        if cls._session is not None and not cls._session.closed:
            await cls._session.close()
        cls._session = None


class RemoteUnit(Unit):
    """Graph unit whose methods execute in an external service."""

    def __init__(self, spec: PredictiveUnit):
        super().__init__(spec)
        ep = spec.endpoint
        if ep is None or not ep.service_port:
            raise ValueError(f"RemoteUnit '{spec.name}' needs an endpoint")
        self.endpoint = ep
        self._grpc_channel = None  # cached (never per-call)
        self._stub_cache: dict[str, object] = {}

    # ----------------------------------------------------------- REST path
    async def _rest_call(self, path: str, payload: dict) -> SeldonMessage:
        session = await _RestSession.get()
        url = f"http://{self.endpoint.service_host}:{self.endpoint.service_port}{path}"
        # reference wire quirk kept for compatibility: body is form-encoded
        # with the message under a `json=` field (microservice.py:44-52)
        data = {"json": json.dumps(payload)}
        try:
            async with session.post(url, data=data) as resp:
                body = await resp.text()
                if resp.status != 200:
                    raise APIException(
                        ErrorCode.ENGINE_MICROSERVICE_ERROR,
                        f"{url} -> {resp.status}: {body[:300]}",
                    )
        except APIException:
            raise
        except Exception as e:  # noqa: BLE001 - network errors normalised
            raise APIException(ErrorCode.ENGINE_MICROSERVICE_ERROR, f"{url}: {e}") from e
        try:
            return message_from_dict(json.loads(body))
        except (json.JSONDecodeError, APIException) as e:
            raise APIException(ErrorCode.ENGINE_INVALID_RESPONSE, str(e)) from e

    # ----------------------------------------------------------- gRPC path
    def _grpc_service_for(self, method: str) -> str:
        """Pick the per-unit-type service a reference container actually
        serves (prediction.proto:84-103): MODEL containers register
        Model.Predict, routers Router.Route, etc. Our own grpc_server also
        registers Generic, but reference wrappers do not."""
        from seldon_core_tpu.graph.spec import PredictiveUnitType

        t = self.spec.type
        if method == "Predict" or (method == "TransformInput" and t == PredictiveUnitType.MODEL):
            return "Model"
        if method in ("Route", "SendFeedback") and t == PredictiveUnitType.ROUTER:
            return "Router"
        if method == "TransformInput":
            return "Transformer"
        if method == "TransformOutput":
            return "OutputTransformer"
        if method == "Aggregate":
            return "Combiner"
        return "Generic"

    async def _grpc_call(self, method: str, request_pb) -> SeldonMessage:
        import grpc

        from seldon_core_tpu.proto.services import ServiceStub
        from seldon_core_tpu.core.codec_proto import message_from_proto

        if self._grpc_channel is None:
            target = f"{self.endpoint.service_host}:{self.endpoint.service_port}"
            self._grpc_channel = grpc.aio.insecure_channel(target)
        service = self._grpc_service_for(method)
        # stub per service, cached — the reference's perf hazard is a new
        # ManagedChannel per call (InternalPredictionService.java:211-214);
        # we reuse both the channel and the per-service stub.
        # reference containers serve package seldon.protos; wire format is
        # identical, so address them under that package
        stub = self._stub_cache.get(service)
        if stub is None:
            stub = ServiceStub(self._grpc_channel, service, package="seldon.protos")
            self._stub_cache[service] = stub
        rpc_method = "Predict" if service == "Model" else method
        try:
            reply = await getattr(stub, rpc_method)(request_pb, timeout=GRPC_DEADLINE_S)
        except Exception as e:  # noqa: BLE001
            raise APIException(
                ErrorCode.ENGINE_MICROSERVICE_ERROR, f"gRPC {service}.{rpc_method}: {e}"
            ) from e
        return message_from_proto(reply)

    def _to_proto(self, msg: SeldonMessage):
        from seldon_core_tpu.core.codec_proto import message_to_proto

        return message_to_proto(msg)

    # ------------------------------------------------------------- methods
    async def transform_input(self, msg: SeldonMessage) -> SeldonMessage:
        if self.endpoint.type == EndpointType.GRPC:
            return await self._grpc_call("TransformInput", self._to_proto(msg))
        # MODEL containers expose /predict; TRANSFORMER ones /transform-input.
        # The reference tries per unit type (InternalPredictionService:132-161);
        # we use the unit type to pick the path.
        from seldon_core_tpu.graph.spec import PredictiveUnitType

        path = "/predict" if self.spec.type == PredictiveUnitType.MODEL else "/transform-input"
        return await self._rest_call(path, message_to_dict(msg))

    async def transform_output(self, msg: SeldonMessage) -> SeldonMessage:
        if self.endpoint.type == EndpointType.GRPC:
            return await self._grpc_call("TransformOutput", self._to_proto(msg))
        return await self._rest_call("/transform-output", message_to_dict(msg))

    async def route(self, msg: SeldonMessage) -> int:
        if self.endpoint.type == EndpointType.GRPC:
            reply = await self._grpc_call("Route", self._to_proto(msg))
        else:
            reply = await self._rest_call("/route", message_to_dict(msg))
        arr = reply.array
        if arr is None:
            raise APIException(ErrorCode.ENGINE_INVALID_RESPONSE, "router returned no data")
        return int(np.asarray(arr).reshape(-1)[0])

    async def aggregate(self, msgs: Sequence[SeldonMessage]) -> SeldonMessage:
        if self.endpoint.type == EndpointType.GRPC:
            from seldon_core_tpu.core.codec_proto import message_list_to_proto

            return await self._grpc_call("Aggregate", message_list_to_proto(msgs))
        payload = {"seldonMessages": [message_to_dict(m) for m in msgs]}
        return await self._rest_call("/aggregate", payload)

    async def send_feedback(self, feedback: Feedback, routing: int) -> None:
        if self.endpoint.type == EndpointType.GRPC:
            from seldon_core_tpu.core.codec_proto import feedback_to_proto

            await self._grpc_call("SendFeedback", feedback_to_proto(feedback))
            return
        await self._rest_call("/send-feedback", feedback_to_dict(feedback))

    async def close(self) -> None:
        if self._grpc_channel is not None:
            await self._grpc_channel.close()
