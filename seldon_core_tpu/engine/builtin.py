"""Built-in graph units (no container needed).

Parity: reference in-engine implementations (SURVEY C5) —
SimpleModelUnit.java (constant logits test stub), SimpleRouterUnit.java
(always child 0), RandomABTestUnit.java (seeded A/B split, param ``ratioA``,
seed 1337), AverageCombinerUnit.java (element-wise mean ensemble) — plus two
TPU-native additions: EPSILON_GREEDY bandit router (BASELINE full-DAG config)
and JAX_MODEL (a model-zoo model resident in HBM).

The AverageCombiner is where TPU-first pays: in the reference an N-model
ensemble is N containers + N RPCs + a Java mean; here the combiner is
``jnp.mean(stack, 0)`` and — via engine/fused.py — the whole ensemble
compiles into ONE XLA program with the models' matmuls batched for the MXU.
"""

from __future__ import annotations

import dataclasses
import random
import threading
from typing import Sequence

import numpy as np

from seldon_core_tpu.core.errors import APIException, ErrorCode
from seldon_core_tpu.core.message import Feedback, SeldonMessage
from seldon_core_tpu.engine.units import ROUTE_ALL, Unit, UnitRegistry
from seldon_core_tpu.graph.spec import PredictiveUnit, PredictiveUnitImplementation


def _seeded_rng(seed) -> random.Random:
    """seed=None -> OS entropy; any explicit seed (including 0) is honored."""
    return random.Random(int(seed)) if seed is not None else random.Random()


def _parse_float_vec(unit_label: str, key: str, raw) -> np.ndarray:
    """Comma-separated float vector parameter (a single value broadcasts)."""
    try:
        return np.asarray([float(v) for v in str(raw).strip().split(",")], np.float32)
    except ValueError as e:
        raise ValueError(f"{unit_label} bad '{key}' parameter: {e}") from e


class SimpleModelUnit(Unit):
    """Constant-output test model (reference SimpleModelUnit.java:24-53:
    values [[0.1, 0.9, 0.5]], classNames c0,c1,c2; its 20 ms sleep is exposed
    as an optional `delay_ms` parameter instead of being hard-coded)."""

    VALUES = np.asarray([[0.1, 0.9, 0.5]], dtype=np.float32)
    CLASS_NAMES = ("c0", "c1", "c2")

    async def transform_input(self, msg: SeldonMessage) -> SeldonMessage:
        delay_ms = float(self.params.get("delay_ms", 0.0))
        if delay_ms > 0:
            import asyncio

            await asyncio.sleep(delay_ms / 1000.0)
        batch = 1
        if msg.array is not None and np.asarray(msg.array).ndim >= 1:
            batch = int(np.asarray(msg.array).shape[0])
        out = np.repeat(self.VALUES, batch, axis=0)
        return msg.with_array(out, self.CLASS_NAMES)


class SimpleRouterUnit(Unit):
    """Always routes to child 0 (reference SimpleRouterUnit.java)."""

    async def route(self, msg: SeldonMessage) -> int:
        return 0


class MeanTransformerUnit(Unit):
    """Input-centering transformer (reference ships the same as a container:
    examples/transformers/mean_transformer/MeanTransformer.py subtracts a
    STORED mean vector). Required parameter ``means`` — comma-separated
    floats (a single value broadcasts). Deliberately no per-batch fallback:
    centering a batch of one would zero the request."""

    def __init__(self, spec: PredictiveUnit):
        super().__init__(spec)
        raw = str(self.params.get("means", "")).strip()
        if not raw:
            raise ValueError(
                f"MEAN_TRANSFORMER '{spec.name}' requires a 'means' parameter"
            )
        self.means = _parse_float_vec(f"MEAN_TRANSFORMER '{spec.name}'", "means", raw)

    def _center(self, msg: SeldonMessage) -> SeldonMessage:
        if msg.array is None:
            raise APIException(
                ErrorCode.ENGINE_INVALID_RESPONSE,
                f"unit '{self.name}' needs tensor data",
            )
        x = np.asarray(msg.array, dtype=np.float32)
        if self.means.size not in (1, x.shape[-1]):
            raise APIException(
                ErrorCode.ENGINE_MICROSERVICE_ERROR,
                f"unit '{self.name}': means has {self.means.size} values "
                f"but input has {x.shape[-1]} features",
            )
        return msg.with_array(x - self.means, msg.names)

    async def transform_input(self, msg: SeldonMessage) -> SeldonMessage:
        return self._center(msg)

    # the same container serves either endpoint in the reference — which one
    # runs is picked by the NODE type (PredictorConfigBean type->methods
    # map:44-72), so an OUTPUT_TRANSFORMER-typed MEAN_TRANSFORMER centers
    # the model output instead of the input
    async def transform_output(self, msg: SeldonMessage) -> SeldonMessage:
        return self._center(msg)

    def _pure_center(self):
        name = self.name

        def fn(means, x):
            # shapes are static under jit, so this check runs at trace time
            # (first predict per bucket) and surfaces the same structured
            # error the unfused walker raises
            if means.shape[0] not in (1, x.shape[-1]):
                raise APIException(
                    ErrorCode.ENGINE_MICROSERVICE_ERROR,
                    f"unit '{name}': means has {means.shape[0]} values "
                    f"but input has {x.shape[-1]} features",
                )
            return x - means.astype(x.dtype)

        return fn, self.means

    def as_pure_input_fn(self):
        return self._pure_center()

    def as_pure_output_fn(self):
        return self._pure_center()


class RandomABTestUnit(Unit):
    """Seeded A/B split (reference RandomABTestUnit.java:29-53).

    Parameter ``ratioA`` = probability of child 0; RNG seeded 1337 so the
    routing sequence is deterministic and testable (reference
    RandomABTestUnitInternalTest asserts routes 1,0,1 under the seed).
    The reference has a known latent bug it FIXMEs at :46 (unordered keySet);
    we index children positionally, which fixes it while keeping behavior.
    """

    SEED = 1337

    def __init__(self, spec: PredictiveUnit):
        super().__init__(spec)
        self.ratio_a = float(self.params.get("ratioA", 0.5))
        self._rng = random.Random(self.SEED)
        self._lock = threading.Lock()

    async def route(self, msg: SeldonMessage) -> int:
        if len(self.spec.children) < 2:
            raise APIException(
                ErrorCode.ENGINE_INVALID_ABTEST,
                f"RANDOM_ABTEST '{self.name}' needs 2 children, has {len(self.spec.children)}",
            )
        with self._lock:
            draw = self._rng.random()
        return 0 if draw < self.ratio_a else 1


class ShadowRouterUnit(Unit):
    """Traffic shadowing (TPU-native addition; no reference analogue):
    child 0 is the PRIMARY and serves the response; every other child is a
    SHADOW that receives a COPY of the same input fire-and-forget — its
    latency and failures never touch the caller, but its unit timers
    (prometheus) tick, so a candidate model can be validated under real
    production traffic before an A/B test sends it live requests. Routing
    records branch 0, so feedback replays down the primary only. The detached fan-out itself
    lives in the executor (GraphExecutor._spawn_shadow), keyed off
    ``shadow_fanout``."""

    shadow_fanout = True

    def __init__(self, spec: PredictiveUnit):
        super().__init__(spec)
        if len(spec.children) < 2:
            raise APIException(
                ErrorCode.ENGINE_INVALID_ROUTING,
                f"SHADOW '{self.name}' needs >= 2 children "
                f"(primary + shadows), has {len(spec.children)}",
            )

    async def route(self, msg: SeldonMessage) -> int:
        return 0  # the primary; shadows are mirrored by the executor


class EpsilonGreedyRouter(Unit):
    """Multi-armed bandit router (TPU-native addition; the BASELINE 'full DAG'
    config calls for an epsilon-greedy router, which the reference only ships
    as an example container image, not in-engine).

    Parameters: ``epsilon`` (exploration rate, default 0.1), ``seed``.
    State (per-arm pull counts + mean rewards) is host-side and mutated by
    send_feedback — deliberately OUTSIDE the jitted graph (SURVEY §7 hard
    parts: bandit state mutates while predict is pure/compiled). State is
    picklable so persistence/ can checkpoint it (reference C19 contract).
    """

    def __init__(self, spec: PredictiveUnit):
        super().__init__(spec)
        self.epsilon = float(self.params.get("epsilon", 0.1))
        self._rng = _seeded_rng(self.params.get("seed"))
        n = max(len(spec.children), 1)
        self.counts = [0] * n
        self.rewards = [0.0] * n
        self._lock = threading.Lock()

    async def route(self, msg: SeldonMessage) -> int:
        n = len(self.spec.children)
        if n == 0:
            raise APIException(ErrorCode.ENGINE_INVALID_ROUTING, "router has no children")
        with self._lock:
            if self._rng.random() < self.epsilon:
                return self._rng.randrange(n)
            means = [
                self.rewards[i] / self.counts[i] if self.counts[i] else float("inf")
                for i in range(n)
            ]
            return int(max(range(n), key=means.__getitem__))

    async def send_feedback(self, feedback: Feedback, routing: int) -> None:
        if routing < 0 or routing >= len(self.counts):
            return
        with self._lock:
            self.counts[routing] += 1
            self.rewards[routing] += feedback.reward

    # persistence hooks (persistence/persister.py)
    def __getstate__(self):
        d = self.__dict__.copy()
        d.pop("_lock", None)
        return d

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()


class PrefixAffinityRouterUnit(Unit):
    """Generative replica router (TPU-native; the source system's ROUTER +
    bandit-router pattern pointed at decode replicas): requests whose
    prompts share a leading token block rendezvous-hash to the same child,
    so prefix sharers keep hitting the child whose prefix pool is warm for
    them; prompts with no affinity signal (shorter than one block, or no
    tensor payload) ride reward-driven per-child bandit arms. When the
    affinity winner's observed queue depth runs past the bounded-load
    factor, the pick sheds power-of-two-style to the second rendezvous
    rank. The policy engine is serving/affinity_router.AffinityBalancer —
    the same one the in-process replicated scheduler uses, so in-graph and
    in-scheduler routing share one behavior.

    Rewards arrive through the Feedback API (send_feedback replays down
    ``meta.routing`` exactly like the EpsilonGreedy router), and the
    serving layer closes the loop automatically: responses carrying
    ``meta.tags.slo`` verdicts (PR 9) are fed back as rewards with no
    client change (``consumes_slo_feedback``). Child queue depths are
    ingested via ``observe_depth`` (an operator poll of each child's
    ``GET /decode/health`` ``queue_depth`` field).

    Parameters: ``block`` (affinity key length in tokens, default 16 — one
    KV page), ``fallback`` ("epsilon_greedy" | "thompson"), ``epsilon``,
    ``load_factor`` (bounded-load shed threshold, default 1.25), ``seed``.
    State is picklable so persistence/ checkpoints the learned arms
    (reference C19 contract, same as EpsilonGreedyRouter)."""

    # the serving layer feeds meta.tags.slo verdicts back as rewards to
    # graphs containing this unit (serving/service.py auto SLO sink)
    consumes_slo_feedback = True

    def __init__(self, spec: PredictiveUnit):
        super().__init__(spec)
        from seldon_core_tpu.serving.affinity_router import (
            DEFAULT_AFFINITY_BLOCK,
            AffinityBalancer,
        )

        if not spec.children:
            raise APIException(
                ErrorCode.ENGINE_INVALID_ROUTING,
                f"PREFIX_AFFINITY '{spec.name}' needs children to route over",
            )
        self.block = int(self.params.get("block", DEFAULT_AFFINITY_BLOCK))
        self.balancer = AffinityBalancer(
            len(spec.children),
            policy="affinity",
            fallback=str(self.params.get("fallback", "epsilon_greedy")),
            epsilon=float(self.params.get("epsilon", 0.1)),
            load_factor=float(self.params.get("load_factor", 1.25)),
            seed=self.params.get("seed"),
        )

    def observe_depth(self, child: int, depth: int) -> None:
        """Ingest one child's polled queue depth (``GET /decode/health``
        -> ``queue_depth``) for the bounded-load shed."""
        self.balancer.observe_depth(child, depth)

    async def route(self, msg: SeldonMessage) -> int:
        from seldon_core_tpu.serving.affinity_router import prefix_route_key

        key = ()
        if msg.array is not None:
            arr = np.atleast_2d(np.asarray(msg.array))
            if arr.size and np.issubdtype(arr.dtype, np.number):
                # batched requests route on row 0's prompt: the micro-batch
                # already groups one request's rows together, and a ROUTER
                # decides per request
                key = prefix_route_key(arr[0], block=self.block)
        arm, _reason = self.balancer.pick(key)
        return arm

    async def send_feedback(self, feedback: Feedback, routing: int) -> None:
        self.balancer.reward(routing, feedback.reward)

    # persistence hooks (persistence/persister.py)
    def __getstate__(self):
        return {"block": self.block, "balancer": self.balancer}

    def __setstate__(self, state):
        # restore is called on a unit ALREADY built for the current CR
        # (persistence/state.py attach): keep THIS graph's arm count and
        # copy the learned estimates over for arms that still exist — a
        # pickled 3-child balancer must not make a now-2-child router
        # route to a removed branch
        self.block = state["block"]
        restored = state["balancer"]
        bal = self.balancer
        n = min(bal.n_arms, restored.n_arms)
        for i in range(n):
            bal.counts[i] = restored.counts[i]
            bal.rewards[i] = restored.rewards[i]
            bal.alpha[i] = restored.alpha[i]
            bal.beta[i] = restored.beta[i]


class FaultInjectorUnit(Unit):
    """Chaos-testing transformer (no reference analogue — SURVEY §5.3 notes
    'Fault injection: none'). Fails a configurable fraction of requests or
    injects latency, so retry paths, alerts, and SLO dashboards can be
    exercised without breaking a real model.

    Parameters: ``fail_rate`` (0..1, default 0), ``delay_ms`` (fixed added
    latency, default 0), ``seed``."""

    def __init__(self, spec: PredictiveUnit):
        super().__init__(spec)
        self.fail_rate = float(self.params.get("fail_rate", 0.0))
        self.delay_ms = float(self.params.get("delay_ms", 0.0))
        self._rng = _seeded_rng(self.params.get("seed"))
        self._lock = threading.Lock()

    async def transform_input(self, msg: SeldonMessage) -> SeldonMessage:
        if self.delay_ms > 0:
            import asyncio

            await asyncio.sleep(self.delay_ms / 1000.0)
        with self._lock:
            fail = self._rng.random() < self.fail_rate
        if fail:
            raise APIException(
                ErrorCode.ENGINE_MICROSERVICE_ERROR,
                f"fault injected by unit '{self.name}'",
            )
        return msg


class ZScoreOutlierUnit(Unit):
    """Built-in outlier detector: scores each request by the max absolute
    z-score of its features against stored training stats and writes
    ``meta.tags.outlierScore`` (+ ``outlier`` bool when ``threshold`` is set),
    passing the data through unchanged.

    Parity: the reference's outlier tier is container-only — a transformer
    microservice whose /transform-input calls user score() and tags the
    request (wrappers/python/outlier_detector_microservice.py:40-50). This
    builtin gives the engine an in-process detector for graphs that don't
    need custom scoring code; custom scorers use the OUTLIER_DETECTOR
    service type of serving/microservice.py instead.

    Parameters: ``means``/``stds`` (comma-separated floats, broadcastable;
    default 0/1), ``threshold`` (optional outlier cutoff)."""

    def __init__(self, spec: PredictiveUnit):
        super().__init__(spec)

        label = f"OUTLIER_DETECTOR '{spec.name}'"
        self.means = _parse_float_vec(label, "means", self.params.get("means", "0"))
        self.stds = _parse_float_vec(label, "stds", self.params.get("stds", "1"))
        if np.any(self.stds <= 0):
            raise ValueError(
                f"OUTLIER_DETECTOR '{spec.name}': stds must be positive"
            )
        self.threshold = (
            float(self.params["threshold"]) if "threshold" in self.params else None
        )

    async def transform_input(self, msg: SeldonMessage) -> SeldonMessage:
        if msg.array is None:
            raise APIException(
                ErrorCode.ENGINE_INVALID_RESPONSE,
                f"unit '{self.name}' needs tensor data",
            )
        x = np.asarray(msg.array, dtype=np.float32)
        for name, vec in (("means", self.means), ("stds", self.stds)):
            if vec.size not in (1, x.shape[-1]):
                raise APIException(
                    ErrorCode.ENGINE_MICROSERVICE_ERROR,
                    f"unit '{self.name}': {name} has {vec.size} values "
                    f"but input has {x.shape[-1]} features",
                )
        score = float(np.max(np.abs((x - self.means) / self.stds)))
        tags = {**msg.meta.tags, "outlierScore": score}
        if self.threshold is not None:
            tags["outlier"] = score > self.threshold
        return msg.with_meta(dataclasses.replace(msg.meta, tags=tags))


class AverageCombinerUnit(Unit):
    """Element-wise mean ensemble (reference AverageCombinerUnit.java:53-76).
    Shape mismatch across children is an error (reference AverageCombinerTest
    asserts this)."""

    async def aggregate(self, msgs: Sequence[SeldonMessage]) -> SeldonMessage:
        if not msgs:
            raise APIException(ErrorCode.ENGINE_INVALID_RESPONSE, "combiner got no inputs")
        arrays = []
        shape = None
        for m in msgs:
            if m.array is None:
                raise APIException(
                    ErrorCode.ENGINE_INVALID_RESPONSE, "combiner child returned no tensor"
                )
            a = np.asarray(m.array)
            if shape is None:
                shape = a.shape
            elif a.shape != shape:
                raise APIException(
                    ErrorCode.ENGINE_INVALID_RESPONSE,
                    f"combiner shape mismatch: {a.shape} vs {shape}",
                )
            arrays.append(a)
        mean = np.mean(np.stack(arrays, axis=0), axis=0)
        return msgs[0].with_array(mean)

    def as_pure_fn(self):
        import jax.numpy as jnp

        def fn(params, xs):  # xs: tuple of child outputs
            return jnp.mean(jnp.stack(xs, axis=0), axis=0)

        return fn, None


def make_python_class_unit(spec: PredictiveUnit, context: dict):
    """PYTHON_CLASS: load a duck-typed user class in-process from the CR.

    Parameters: ``module`` (module name == class name, the reference
    wrappers/python convention), optional ``model_dir`` added to sys.path,
    and every remaining parameter is passed to the class constructor. This
    is the single-host platform inversion of the reference's
    container-endpoint mechanism — the user class joins the executor's
    process instead of sitting behind an RPC hop. Only use with CRs you
    trust: the CR names code that runs in the platform process.
    """
    from seldon_core_tpu.engine.units import PythonClassUnit
    from seldon_core_tpu.graph.spec import parameters_dict
    from seldon_core_tpu.serving.microservice import load_user_object

    # Declarative ingestion paths (reconciler watchers / control API) pass
    # allow_python_class=False unless the operator opted in — a CR author
    # with only CR-create rights must not gain code execution here. Direct
    # build_executor embedders (already code) default to allowed.
    if not context.get("allow_python_class", True):
        raise APIException(
            ErrorCode.ENGINE_MICROSERVICE_ERROR,
            f"PYTHON_CLASS unit '{spec.name}' refused: this platform was not "
            "started with allow_python_class (start with "
            "--allow-python-class, set SELDON_TPU_ALLOW_PYTHON_CLASS=1, or "
            "DeploymentManager(allow_python_class=True) to let CRs load "
            "local code in-process)",
        )
    params = parameters_dict(spec.parameters)
    try:
        module = params.pop("module")
    except KeyError:
        raise APIException(
            ErrorCode.ENGINE_MICROSERVICE_ERROR,
            f"PYTHON_CLASS unit '{spec.name}' needs a 'module' parameter",
        )
    model_dir = params.pop("model_dir", None)
    user = load_user_object(str(module), model_dir, params)
    return PythonClassUnit(spec, user)


def register_builtins(registry: UnitRegistry) -> None:
    registry.register(
        PredictiveUnitImplementation.SIMPLE_MODEL, lambda spec, ctx: SimpleModelUnit(spec)
    )
    registry.register(
        PredictiveUnitImplementation.SIMPLE_ROUTER, lambda spec, ctx: SimpleRouterUnit(spec)
    )
    registry.register(
        PredictiveUnitImplementation.RANDOM_ABTEST, lambda spec, ctx: RandomABTestUnit(spec)
    )
    registry.register(
        PredictiveUnitImplementation.AVERAGE_COMBINER, lambda spec, ctx: AverageCombinerUnit(spec)
    )
    registry.register(
        PredictiveUnitImplementation.EPSILON_GREEDY, lambda spec, ctx: EpsilonGreedyRouter(spec)
    )
    registry.register(
        PredictiveUnitImplementation.MEAN_TRANSFORMER,
        lambda spec, ctx: MeanTransformerUnit(spec),
    )
    registry.register(
        PredictiveUnitImplementation.FAULT_INJECTOR,
        lambda spec, ctx: FaultInjectorUnit(spec),
    )
    registry.register(
        PredictiveUnitImplementation.OUTLIER_DETECTOR,
        lambda spec, ctx: ZScoreOutlierUnit(spec),
    )
    registry.register(
        PredictiveUnitImplementation.PYTHON_CLASS, make_python_class_unit
    )
    registry.register(
        PredictiveUnitImplementation.SHADOW, lambda spec, ctx: ShadowRouterUnit(spec)
    )
    registry.register(
        PredictiveUnitImplementation.PREFIX_AFFINITY,
        lambda spec, ctx: PrefixAffinityRouterUnit(spec),
    )
    # JAX_MODEL is registered by models/zoo.py (needs the model registry).
    from seldon_core_tpu.models.zoo import make_jax_model_unit

    registry.register(PredictiveUnitImplementation.JAX_MODEL, make_jax_model_unit)
