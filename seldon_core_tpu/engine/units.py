"""Graph-unit runtime interface + adapters.

Parity: the reference's unit abstraction is PredictiveUnitImpl
(engine/.../predictors/PredictiveUnitImpl.java) with five methods dispatched
either to built-ins or over RPC to a per-node container
(InternalPredictionService.java:90-214). Here a unit is an in-process object;
the RPC hop exists only as the RemoteUnit escape hatch (engine/remote.py) for
non-TPU nodes.

Default method semantics (reference PredictiveUnitBean.java:174-221):
- transform_input/transform_output: identity unless the unit implements them
  (for MODEL units transform_input IS predict);
- route: -1 = fan out to all children;
- aggregate: pass-through for a single child output, error for many (only
  COMBINERs aggregate);
- send_feedback: no-op unless the unit learns (routers).
"""

from __future__ import annotations

import inspect
from typing import Any, Awaitable, Callable, Sequence

import numpy as np

from seldon_core_tpu.core.errors import APIException, ErrorCode
from seldon_core_tpu.core.message import Feedback, Meta, SeldonMessage
from seldon_core_tpu.graph.spec import (
    Parameter,
    PredictiveUnit,
    PredictiveUnitImplementation,
    parameters_dict,
)

ROUTE_ALL = -1


async def _maybe_await(value):
    if inspect.isawaitable(value):
        return await value
    return value


class Unit:
    """Base graph unit: identity transforms, fan-out routing, no learning."""

    def __init__(self, spec: PredictiveUnit):
        self.spec = spec
        self.name = spec.name
        self.params: dict[str, Any] = parameters_dict(spec.parameters)
        # what serves this unit — container image when one exists, else the
        # implementation name; reported in meta.requestPath (reference
        # PredictiveUnitState image tracking)
        self.image: str = spec.implementation.value if spec.implementation else ""

    # readiness — aggregated into the server /ready (reference engine boots
    # models at container start; our models may load weights lazily)
    def ready(self) -> bool:
        return True

    async def transform_input(self, msg: SeldonMessage) -> SeldonMessage:
        return msg

    async def transform_output(self, msg: SeldonMessage) -> SeldonMessage:
        return msg

    async def route(self, msg: SeldonMessage) -> int:
        return ROUTE_ALL

    async def aggregate(self, msgs: Sequence[SeldonMessage]) -> SeldonMessage:
        if len(msgs) == 1:
            return msgs[0]
        raise APIException(
            ErrorCode.ENGINE_INVALID_ROUTING,
            f"unit '{self.name}' received {len(msgs)} child outputs but does not aggregate",
        )

    async def send_feedback(self, feedback: Feedback, routing: int) -> None:
        return None

    # hooks for the fused compiler (engine/fused.py): a unit that can express
    # itself as a pure jax function returns (fn, params_pytree); others None.
    # as_pure_fn: combiner aggregate — fn(params, [child_outputs]) -> y
    def as_pure_fn(self):
        return None

    # as_pure_input_fn: transform_input equivalent — fn(params, x) -> x'
    def as_pure_input_fn(self):
        return None

    # as_pure_output_fn: transform_output equivalent — fn(params, y) -> y'
    def as_pure_output_fn(self):
        return None


class PythonClassUnit(Unit):
    """Adapter for duck-typed user model classes — the reference's
    wrappers/python contract (microservice.py / model_microservice.py etc.):

        class MyModel:
            def predict(self, X, feature_names): ...
            def route(self, X, feature_names): ...
            def aggregate(self, Xs, feature_names_list): ...
            def transform_input/transform_output(self, X, feature_names): ...
            def send_feedback(self, X, feature_names, routing, reward, truth): ...
            class_names / feature_names attributes optional

    Methods may be sync or async. Arrays in/out are numpy (host) — this is the
    compatibility tier; TPU-resident models use models/base.JaxModelUnit.
    """

    def __init__(self, spec: PredictiveUnit, user_object: Any):
        super().__init__(spec)
        self.user = user_object

    def _names_out(self, fallback: Sequence[str]) -> tuple[str, ...]:
        cn = getattr(self.user, "class_names", None)
        return tuple(cn) if cn is not None else tuple(fallback)

    @staticmethod
    def _payload(msg: SeldonMessage):
        """What the user method receives: the tensor when the data arm is
        set, else the raw bytes/str payload — the reference microservice
        hands binData/strData to user predict() as-is
        (wrappers/python/microservice.py get_data_from_json semantics)."""
        if msg.data is not None:
            return np.asarray(msg.array)
        if msg.bin_data is not None:
            return msg.bin_data
        return msg.str_data

    def _wrap_output(self, msg: SeldonMessage, out) -> SeldonMessage:
        """Mirror the user's return type onto the oneof: bytes -> binData,
        str -> strData, everything else the tensor arm — the other half of
        the reference binData contract (a bytes-in bytes-out transformer
        responds with binData, not a mangled |S numpy array)."""
        if isinstance(out, (bytes, bytearray)):
            return msg.with_bin_data(out)
        if isinstance(out, str):
            return msg.with_str_data(out)
        return msg.with_array(np.asarray(out), self._names_out(msg.names))

    async def transform_input(self, msg: SeldonMessage) -> SeldonMessage:
        fn = getattr(self.user, "predict", None) or getattr(self.user, "transform_input", None)
        if fn is None:
            return msg
        out = await _maybe_await(fn(self._payload(msg), list(msg.names)))
        return self._wrap_output(msg, out)

    async def transform_output(self, msg: SeldonMessage) -> SeldonMessage:
        fn = getattr(self.user, "transform_output", None)
        if fn is None:
            return msg
        out = await _maybe_await(fn(self._payload(msg), list(msg.names)))
        return self._wrap_output(msg, out)

    async def route(self, msg: SeldonMessage) -> int:
        fn = getattr(self.user, "route", None)
        if fn is None:
            return ROUTE_ALL
        out = await _maybe_await(fn(self._payload(msg), list(msg.names)))
        arr = np.asarray(out)
        return int(arr.reshape(-1)[0])

    async def aggregate(self, msgs: Sequence[SeldonMessage]) -> SeldonMessage:
        fn = getattr(self.user, "aggregate", None)
        if fn is None:
            return await super().aggregate(msgs)
        xs = [self._payload(m) for m in msgs]
        names = [list(m.names) for m in msgs]
        out = await _maybe_await(fn(xs, names))
        return self._wrap_output(msgs[0], out)

    async def send_feedback(self, feedback: Feedback, routing: int) -> None:
        fn = getattr(self.user, "send_feedback", None)
        if fn is None:
            return
        req = feedback.request
        # same payload semantics as predict: tensor, else raw bytes/str
        x = self._payload(req) if req is not None else None
        names = list(req.names) if req is not None else []
        truth = (
            np.asarray(feedback.truth.array)
            if feedback.truth is not None and feedback.truth.array is not None
            else None
        )
        await _maybe_await(fn(x, names, routing, feedback.reward, truth))


class OutlierDetectorUnit(PythonClassUnit):
    """Adapter for outlier-scoring user classes — the reference's fourth
    microservice flavor (wrappers/python/outlier_detector_microservice.py:
    40-50): the user class exposes ``score(X, feature_names)`` returning a
    single float; transform_input passes the data through unchanged and
    writes the score into ``meta.tags.outlierScore``. A per-row array score
    is also accepted (stored as a list) — additive over the reference."""

    async def transform_input(self, msg: SeldonMessage) -> SeldonMessage:
        fn = getattr(self.user, "score", None)
        if fn is None:
            return msg
        if msg.array is None:
            raise APIException(
                ErrorCode.ENGINE_INVALID_RESPONSE,
                f"unit '{self.name}' needs tensor data",
            )
        x = np.asarray(msg.array)
        out = await _maybe_await(fn(x, list(msg.names)))
        score = np.asarray(out, dtype=np.float64)
        value: Any = (
            float(score.reshape(-1)[0])
            if score.size == 1
            else [float(v) for v in score.reshape(-1)]
        )
        import dataclasses

        tags = {**msg.meta.tags, "outlierScore": value}
        return msg.with_meta(dataclasses.replace(msg.meta, tags=tags))


UnitFactory = Callable[[PredictiveUnit, dict], Unit]


class UnitRegistry:
    """implementation -> factory map (reference PredictorConfigBean
    nodeImplementationMap:77-83), extensible with user implementations."""

    def __init__(self) -> None:
        self._factories: dict[str, UnitFactory] = {}

    def register(self, impl: PredictiveUnitImplementation | str, factory: UnitFactory) -> None:
        key = impl.value if isinstance(impl, PredictiveUnitImplementation) else impl
        self._factories[key] = factory

    def create(self, spec: PredictiveUnit, context: dict) -> Unit | None:
        if spec.implementation is None:
            return None
        key = spec.implementation.value
        factory = self._factories.get(key)
        if factory is None:
            return None
        return factory(spec, context)


_default_registry: UnitRegistry | None = None


def default_registry() -> UnitRegistry:
    global _default_registry
    if _default_registry is None:
        from seldon_core_tpu.engine import builtin  # late import: avoids cycle

        _default_registry = UnitRegistry()
        builtin.register_builtins(_default_registry)
    return _default_registry
