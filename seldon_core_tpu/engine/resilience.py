"""Data-plane resilience primitives: deadline budgets, retries, breakers.

The reference engine walks the graph with one hardcoded 5 s per-call
deadline (InternalPredictionService.java:77) and no retry, breaker, or
degradation story — one slow or flapping node stalls or fails the whole
request. At serving scale partial failure is the steady state, so the
primitives live here as first-class objects:

- ``Deadline`` — a per-request budget stamped at the serving entrypoint and
  carried through the walk via a contextvar (tasks spawned during the walk
  inherit it; the micro-batcher re-stamps the LOOSEST of its batch-mates'
  budgets around the merged walk — each request's own budget is enforced
  at its ingress, so a tight mate cannot cancel the shared walk). Every
  node call checks the remaining budget; remote REST/gRPC calls use it as
  their timeout instead of the fixed default.
- ``RetryPolicy`` — per-node max attempts + jittered exponential backoff.
  The executor retries only idempotent methods (never send_feedback) on
  transport/5xx-class failures, and never sleeps past the deadline.
- ``CircuitBreaker`` — per-endpoint closed -> open (consecutive-failure or
  windowed error-rate threshold) -> half-open probe state machine. Open
  breakers fail fast with a 503 carrying Retry-After; routers with a
  configured fallback branch degrade around them instead of failing.

All knobs ride the deployment CR as unit parameters — see
graph/spec.py ResilienceSpec for the names.
"""

from __future__ import annotations

import contextvars
import random
import time
from typing import Callable

from seldon_core_tpu.core.errors import APIException, ErrorCode

# ------------------------------------------------------------------ deadline


class Deadline:
    """Absolute per-request budget against an injectable monotonic clock."""

    __slots__ = ("expires_at", "_clock")

    def __init__(self, budget_s: float, *, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self.expires_at = clock() + budget_s

    def remaining(self) -> float:
        return self.expires_at - self._clock()

    def expired(self) -> bool:
        return self.remaining() <= 0.0


# The carrier: set by the serving entrypoint (PredictionService) or the
# batcher's merged walk; read at every node-call boundary and by remote
# transports. A contextvar (not a threaded parameter) so detached helpers
# (shadow walks, offloaded compute) inherit it for free — asyncio copies the
# context into every task it spawns.
DEADLINE: contextvars.ContextVar[Deadline | None] = contextvars.ContextVar(
    "seldon_tpu_deadline", default=None
)


def current_deadline() -> Deadline | None:
    return DEADLINE.get()


def call_timeout(default_s: float) -> float:
    """Timeout for one remote call: the request's remaining budget when a
    deadline is stamped (replacing the fixed per-call default), else
    ``default_s``. Raises deadline-exceeded instead of dispatching a call
    whose budget is already gone."""
    d = DEADLINE.get()
    if d is None:
        return default_s
    remaining = d.remaining()
    if remaining <= 0.0:
        raise deadline_exceeded("remote call")
    return remaining


def deadline_exceeded(where: str) -> APIException:
    return APIException(
        ErrorCode.REQUEST_DEADLINE_EXCEEDED, f"budget exhausted at {where}"
    )


# -------------------------------------------------------------------- retry

# Methods safe to re-dispatch: inference-path calls are read-only over model
# state. send_feedback mutates learner state (bandit counts) and must never
# be replayed.
IDEMPOTENT_METHODS = frozenset(
    {"transform_input", "transform_output", "route", "aggregate", "predict"}
)

# Transport/5xx-class failures worth a retry. ENGINE_MICROSERVICE_ERROR is
# the code every normalised transport error (connect refused, reset, HTTP
# 5xx, gRPC UNAVAILABLE) surfaces as. Malformed-response and routing errors
# are deterministic — retrying replays the same failure.
RETRYABLE_CODES = frozenset({ErrorCode.ENGINE_MICROSERVICE_ERROR})


def is_retryable(exc: BaseException) -> bool:
    if isinstance(exc, APIException):
        # explicit flag wins: remote transports mark deterministic backend
        # 4xx (and gRPC INVALID_ARGUMENT-class statuses) non-retryable even
        # though they normalise to ENGINE_MICROSERVICE_ERROR on the wire
        if exc.retryable is not None:
            return exc.retryable
        return exc.error in RETRYABLE_CODES
    return isinstance(exc, (ConnectionError, TimeoutError, OSError))


class RetryState:
    """Runtime retry engine for one node (seeded RNG so backoff jitter — and
    therefore tests and fault-harness runs — is deterministic)."""

    def __init__(self, spec):
        self.max_attempts = max(int(spec.max_attempts), 1)
        self.backoff_s = float(spec.backoff_ms) / 1000.0
        self.backoff_mult = float(spec.backoff_mult)
        self.jitter = float(spec.jitter)
        self._rng = random.Random(spec.seed) if spec.seed is not None else random.Random()

    def backoff(self, attempt: int) -> float:
        """Sleep before retry number ``attempt`` (1-based), jittered. Draws
        the RNG once — callers pass the SAME value to should_retry and to
        the sleep, so the duration validated against the deadline is the
        duration actually slept."""
        base = self.backoff_s * (self.backoff_mult ** (attempt - 1))
        if self.jitter > 0:
            base *= self._rng.uniform(max(0.0, 1.0 - self.jitter), 1.0 + self.jitter)
        return base

    def should_retry(
        self, method: str, attempt: int, exc: BaseException, backoff_s: float
    ) -> bool:
        """Retry iff the method is idempotent, attempts remain, the failure
        is transport/5xx-class, and ``backoff_s`` (the exact duration the
        caller will sleep) fits the remaining budget — never sleep past the
        deadline."""
        if attempt >= self.max_attempts:
            return False
        if method not in IDEMPOTENT_METHODS:
            return False
        if not is_retryable(exc):
            return False
        d = DEADLINE.get()
        if d is not None and d.remaining() <= backoff_s:
            return False
        return True


# ------------------------------------------------------------------ breaker

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

_STATE_GAUGE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


def breaker_state_value(state: str) -> int:
    """Numeric encoding for the prometheus state gauge."""
    return _STATE_GAUGE[state]


class CircuitBreaker:
    """closed -> open -> half-open state machine for one endpoint.

    Opens on EITHER ``failure_threshold`` consecutive failures OR a windowed
    error rate >= ``error_rate`` once ``window`` outcomes have been seen.
    After ``reset_ms`` an open breaker admits ``half_open_probes`` probe
    calls; one success closes it, one failure re-opens it. The clock is
    injectable so the state machine is unit-testable without sleeping.
    """

    def __init__(
        self,
        spec,
        *,
        clock: Callable[[], float] = time.monotonic,
        on_transition: Callable[[str], None] | None = None,
    ):
        self.failure_threshold = int(spec.failure_threshold)
        self.error_rate = float(spec.error_rate)
        self.window = max(int(spec.window), 1)
        self.reset_s = float(spec.reset_ms) / 1000.0
        self.half_open_probes = max(int(spec.half_open_probes), 1)
        self._clock = clock
        self._on_transition = on_transition
        self.state = CLOSED
        self._consecutive_failures = 0
        self._outcomes: list[bool] = []  # sliding window, True = failure
        self._opened_at = 0.0
        self._probes_in_flight = 0

    # ------------------------------------------------------------- internals
    def _transition(self, state: str) -> None:
        if state == self.state:
            return
        self.state = state
        if self._on_transition is not None:
            self._on_transition(state)

    def _maybe_half_open(self) -> None:
        if self.state == OPEN and self._clock() - self._opened_at >= self.reset_s:
            self._probes_in_flight = 0
            self._transition(HALF_OPEN)

    # ------------------------------------------------------------------ API
    def allow(self) -> bool:
        """Gate one call. Consumes a probe slot in half-open state."""
        self._maybe_half_open()
        if self.state == CLOSED:
            return True
        if self.state == HALF_OPEN:
            if self._probes_in_flight < self.half_open_probes:
                self._probes_in_flight += 1
                return True
            return False
        return False

    def is_open(self) -> bool:
        """Non-consuming peek (router fallback checks): True only while
        firmly open — a reset-elapsed breaker reads half-open so the probe
        traffic that would recover it is not diverted to the fallback."""
        self._maybe_half_open()
        return self.state == OPEN

    def release_probe(self) -> None:
        """Un-consume a half-open probe whose call produced NO verdict
        (cancelled, or the request's deadline fired) — without this the
        slot leaks and the breaker wedges in half-open with zero free
        probes, never able to recover."""
        if self.state == HALF_OPEN and self._probes_in_flight > 0:
            self._probes_in_flight -= 1

    def record_success(self) -> None:
        self._consecutive_failures = 0
        self._push(False)
        if self.state == HALF_OPEN:
            self._outcomes.clear()
            self._transition(CLOSED)

    def record_failure(self) -> None:
        self._consecutive_failures += 1
        self._push(True)
        if self.state == HALF_OPEN:
            self._open()
            return
        if self.state != CLOSED:
            return
        if self._consecutive_failures >= self.failure_threshold:
            self._open()
            return
        if (
            len(self._outcomes) >= self.window
            and sum(self._outcomes) / len(self._outcomes) >= self.error_rate
        ):
            self._open()

    def _open(self) -> None:
        self._opened_at = self._clock()
        self._consecutive_failures = 0
        self._transition(OPEN)

    def _push(self, failed: bool) -> None:
        self._outcomes.append(failed)
        if len(self._outcomes) > self.window:
            del self._outcomes[0]

    def retry_after_s(self) -> float:
        """How long until the next probe could be admitted."""
        if self.state != OPEN:
            return 0.0
        return max(0.0, self.reset_s - (self._clock() - self._opened_at))


def breaker_open_error(endpoint: str, breaker: CircuitBreaker) -> APIException:
    e = APIException(
        ErrorCode.ENGINE_BREAKER_OPEN,
        f"circuit breaker open for '{endpoint}'",
        retry_after_s=breaker.retry_after_s(),
    )
    return e


def is_breaker_open_error(exc: BaseException) -> bool:
    return isinstance(exc, APIException) and exc.error is ErrorCode.ENGINE_BREAKER_OPEN


# -------------------------------------------------------------- event sinks


class ResilienceEvents:
    """No-op event sink. The executor reports every resilience action here;
    servers substitute a recorder that forwards to the metrics registry
    (metrics/registry.MetricsResilienceEvents), tests substitute lists."""

    def retry(self, unit: str, attempt: int) -> None:
        pass

    def breaker_transition(self, endpoint: str, state: str) -> None:
        pass

    def deadline_exceeded(self, unit: str) -> None:
        pass

    def degraded(self, unit: str, mode: str) -> None:
        pass

    def fault_injected(self, unit: str, kind: str) -> None:
        pass


NULL_EVENTS = ResilienceEvents()
