from seldon_core_tpu.engine.units import Unit, PythonClassUnit, UnitRegistry, default_registry
from seldon_core_tpu.engine.executor import GraphExecutor, build_executor

__all__ = [
    "GraphExecutor",
    "PythonClassUnit",
    "Unit",
    "UnitRegistry",
    "build_executor",
    "default_registry",
]
