"""In-process graph executor — the data-plane core.

Parity: reference engine PredictiveUnitBean.java getOutput/getOutputAsync
(:58-124) and sendFeedback (:126-164). Same walk semantics:

    1. transform_input            (MODEL units: this IS predict)
    2. leaf -> return
    3. route                      (-1 = fan out to all children)
    4. children, concurrently     (asyncio.gather ~ Spring @Async futures)
    5. aggregate                  (COMBINER; pass-through for single child)
    6. transform_output
    meta/tags merged per mergeMeta:252-264; ROUTER choices recorded in
    meta.routing so feedback replays down the taken branch (:131-154).

Design difference vs the reference: node "calls" are in-process awaits (the
RPC mesh is gone), and a pure all-JAX subtree can be compiled into one XLA
program by engine/fused.py — the executor is the always-correct fallback and
the host of stateful/routing nodes that cannot live inside jit.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
from typing import Any, Callable, Sequence

import numpy as np

log = logging.getLogger(__name__)

from seldon_core_tpu.core.errors import APIException, ErrorCode
from seldon_core_tpu.core.message import Feedback, Meta, SeldonMessage
from seldon_core_tpu.engine.resilience import (
    HALF_OPEN,
    NULL_EVENTS,
    CircuitBreaker,
    DEADLINE,
    ResilienceEvents,
    RetryState,
    breaker_open_error,
    current_deadline,
    deadline_exceeded,
    is_breaker_open_error,
    is_retryable,
)
from seldon_core_tpu.engine.units import ROUTE_ALL, Unit, UnitRegistry, default_registry
from seldon_core_tpu import telemetry
from seldon_core_tpu.graph.spec import (
    PredictiveUnit,
    PredictiveUnitMethod,
    PredictiveUnitType,
    PredictorSpec,
    ResilienceSpec,
)

# degradation marker written into meta.tags when a request was served by a
# fallback branch / partial quorum instead of its nominal path
DEGRADED_TAG = "degraded"


@dataclasses.dataclass
class Node:
    """Runtime tree node (reference PredictiveUnitState.java equivalent)."""

    spec: PredictiveUnit
    unit: Unit
    children: list["Node"]
    # per-node resilience knobs parsed off the CR parameters (retry/breaker/
    # fallback_child/quorum) — runtime state (breaker state machines, retry
    # RNGs) lives on the executor, keyed by node name
    policy: ResilienceSpec = dataclasses.field(default_factory=ResilienceSpec)

    @property
    def name(self) -> str:
        return self.spec.name

    def walk(self):
        yield self
        for c in self.children:
            yield from c.walk()


def _has_method(node: Node, method: PredictiveUnitMethod) -> bool:
    spec = node.spec
    if spec.methods:
        return method in spec.methods
    from seldon_core_tpu.graph.spec import TYPE_METHODS

    if spec.type is not None:
        return method in TYPE_METHODS.get(spec.type, ())
    # implementation-only node (e.g. bare AVERAGE_COMBINER): allow everything
    # the unit object actually implements.
    return True


async def _gather_settled(*aws):
    """gather that lets every sibling SETTLE before failing: with plain
    gather a raising branch returns control while its siblings keep running
    detached, so side-effectful units (feedback state, user classes,
    metrics) could still execute for a request whose response is already an
    error. All-settle-then-reraise keeps a failed walk atomic."""
    results = await asyncio.gather(*aws, return_exceptions=True)
    for r in results:
        if isinstance(r, BaseException):
            raise r
    return results


class GraphExecutor:
    """Executes one predictor graph. One instance per predictor per process —
    the reference runs one engine pod per predictor; we run one executor
    object, many deployments per host (SURVEY §7 multi-tenancy)."""

    def __init__(
        self,
        root: Node,
        feedback_metrics_hook: Callable[[str, float], None] | None = None,
        unit_call_hook: Callable[[str, str, float], None] | None = None,
        shadow_compare_hook: Callable[[str, bool], None] | None = None,
        resilience_events: ResilienceEvents | None = None,
    ):
        self.root = root
        self._feedback_hook = feedback_metrics_hook
        # (unit_name, method, duration_s) per unit invocation — C10 parity:
        # the reference timers every engine->microservice call
        # (SeldonRestTemplateExchangeTagsProvider); here calls are in-process
        # but the observability contract survives
        self._unit_hook = unit_call_hook
        # in-flight SHADOW mirror walks (fire-and-forget by design; tracked
        # so tests/shutdown can drain them)
        self._shadow_tasks: set = set()
        # (shadow_unit_name, agree: bool) per mirrored prediction — feeds
        # seldon_tpu_shadow_comparisons so a candidate's agreement rate with
        # production is a dashboard number, not a log-diving exercise
        self._shadow_hook = shadow_compare_hook
        # resilience runtime: event sink + per-node retry RNGs + breakers.
        # Breakers are keyed per ENDPOINT (host:port for remote nodes, node
        # name for in-process ones) and shared by nodes on the same
        # endpoint, so a backend's health is tracked once per backend.
        self._events = resilience_events or NULL_EVENTS
        self._retries: dict[str, RetryState] = {}
        self._breakers: dict[str, CircuitBreaker] = {}
        self._breaker_keys: dict[str, str] = {}
        shared: dict[str, CircuitBreaker] = {}
        shared_spec: dict[str, Any] = {}
        for n in root.walk():
            if n.policy.retry is not None:
                self._retries[n.name] = RetryState(n.policy.retry)
            if n.policy.breaker is None:
                continue
            ep = n.spec.endpoint
            key = (
                f"{ep.service_host}:{ep.service_port}"
                if ep is not None and ep.service_port
                else n.name
            )
            cb = shared.get(key)
            if cb is None:
                cb = CircuitBreaker(
                    n.policy.breaker,
                    on_transition=lambda state, k=key: self._on_breaker_transition(
                        k, state
                    ),
                )
                shared[key] = cb
                shared_spec[key] = n.policy.breaker
            elif n.policy.breaker != shared_spec.get(key):
                # first-walked node's spec governs the shared breaker; a
                # conflicting spec on a later node would otherwise be
                # silently dropped
                log.warning(
                    "node '%s': breaker config conflicts with the one already "
                    "governing endpoint '%s' (first-declared wins)",
                    n.name,
                    key,
                )
            self._breakers[n.name] = cb
            self._breaker_keys[n.name] = key

    def _on_breaker_transition(self, key: str, state: str) -> None:
        """Breaker state changes feed the metrics sink AND the trace of the
        request that witnessed them (transitions fire inside record_failure/
        record_success, i.e. within some request's walk)."""
        self._events.breaker_transition(key, state)
        telemetry.add_event("breaker_transition", {"endpoint": key, "state": state})

    def breaker_for(self, node_name: str) -> CircuitBreaker | None:
        """The breaker guarding a node's endpoint, if one is configured
        (tests and the router fallback check read state through this)."""
        return self._breakers.get(node_name)

    def units(self):
        """All runtime units in the graph, pre-order (used by persistence,
        warmup, readiness aggregation)."""
        return (n.unit for n in self.root.walk())

    # ------------------------------------------------------------- predict
    async def execute(self, msg: SeldonMessage) -> SeldonMessage:
        # request tracing: spans are recorded through the ambient telemetry
        # context (the serving ingress opens it). A request tagged
        # {"trace": ...} executed WITHOUT an ambient trace (direct executor
        # use) still gets per-unit spans back in tags["trace"] via a local
        # store-less trace — the legacy opt-in contract.
        if "trace" in msg.meta.tags and not telemetry.active():
            with telemetry.local_trace(puid=msg.meta.puid) as buf:
                out = await self._get_output(self.root, msg)
            return out.with_meta(
                out.meta.merged_with(Meta(tags={"trace": buf.tag_spans()}))
            )
        return await self._get_output(self.root, msg)

    # ------------------------------------------------- split-batch execution
    async def execute_many(self, msgs: list[SeldonMessage]) -> list[SeldonMessage]:
        """Vectorized walk for a coalesced batch of requests (SURVEY §7 hard
        parts — routing under batching): data nodes (transform / model /
        aggregate) run ONCE on the row-merged batch, while ROUTE nodes decide
        PER REQUEST and the batch regroups by branch, so an A/B router splits
        traffic per request exactly like the reference engine even with
        micro-batching on. Each returned message carries its own meta.routing
        — feedback replays down each request's actual branch.

        Requirements: every message has a tensor payload with equal non-batch
        shape (the micro-batcher's pending key guarantees this); anything
        else falls back to per-message walks."""
        if not msgs:
            return []
        arrays = [m.array for m in msgs]
        if len(msgs) == 1 or any(a is None for a in arrays):
            return [await self.execute(m) for m in msgs]
        shapes = {tuple(np.asarray(a).shape[1:]) for a in arrays}
        if len(shapes) != 1:
            return [await self.execute(m) for m in msgs]
        tagged = [i for i, m in enumerate(msgs) if "trace" in m.meta.tags]
        if tagged and not telemetry.active():
            # direct batched call with trace-tagged requests: give each its
            # own local trace so the vectorized walk reports the SAME spans
            # the scalar walk would (this used to silently drop tracing)
            with telemetry.local_traces(
                [msgs[i].meta.puid for i in tagged]
            ) as bufs:
                outs = await self._get_output_many(self.root, list(msgs))
            for buf, i in zip(bufs, tagged):
                outs[i] = outs[i].with_meta(
                    outs[i].meta.merged_with(Meta(tags={"trace": buf.tag_spans()}))
                )
            return outs
        return await self._get_output_many(self.root, list(msgs))

    @staticmethod
    def _merge_rows(msgs: list[SeldonMessage]) -> SeldonMessage:
        merged = np.concatenate([np.asarray(m.array) for m in msgs], axis=0)
        return msgs[0].with_array(merged)

    @staticmethod
    def _scatter_rows(
        msgs: list[SeldonMessage], out: SeldonMessage
    ) -> list[SeldonMessage]:
        """Give each request its own row slice of a merged result, each with
        its own meta (puid + per-request routing) merged with the unit's
        additions (tags etc. are shared by batch-mates, as documented)."""
        rows = [int(np.atleast_2d(np.asarray(m.array)).shape[0]) for m in msgs]
        out_arr = None if out.array is None else np.asarray(out.array)
        splittable = out_arr is not None and out_arr.shape[0] == sum(rows)
        om = out.meta
        result = []
        offset = 0
        for m, r in zip(msgs, rows):
            mm = m.meta
            # merge rule per request: the merged call's meta derives from
            # batch-mate 0 (_merge_rows), so on conflict the request's OWN
            # puid and routing must win — feedback replays down meta.routing
            # and must follow the branch THIS request actually took; tags /
            # requestPath follow the normal child-wins merge (mergeMeta)
            meta = Meta(
                puid=mm.puid or om.puid,
                tags={**mm.tags, **om.tags},
                routing={**om.routing, **mm.routing},
                request_path={**mm.request_path, **om.request_path},
            )
            if splittable:
                result.append(out.with_array_meta(out_arr[offset : offset + r], meta))
                offset += r
            else:  # graph changed the batch dim (global aggregate): share it
                result.append(out.with_meta(meta))
        return result

    @staticmethod
    async def _settle_to_host(out: SeldonMessage) -> SeldonMessage:
        """Read an accelerator-resident result back to host OFF the event
        loop before row-scattering it. _scatter_rows's np.asarray on a
        device array is a BLOCKING readback (device compute + transfer);
        run on the loop it would stall the ingress, the batcher's timers,
        and every concurrent branch group for the whole device latency of
        each batch — measured as the full_dag leg's p99 blowup (PARITY
        "full_dag attribution"). XLA releases the GIL during the copy, so
        the worker-pool overlap is real. CPU-backend arrays view host
        memory (readback is free) and skip the hop."""
        arr = out.array
        if arr is None:
            return out
        import jax  # lazy: the executor itself has no jax dependency

        if not isinstance(arr, jax.Array):
            return out
        if all(d.platform == "cpu" for d in arr.devices()):
            return out
        from seldon_core_tpu.models.base import compute_pool

        host = await asyncio.get_running_loop().run_in_executor(
            compute_pool(), np.asarray, arr
        )
        return out.with_array(host)

    async def _merged_call(self, node, method_name, method, msgs):
        merged = self._merge_rows(msgs)
        out = await self._call(node, method_name, method, merged)
        out = await self._settle_to_host(out)
        return self._scatter_rows(msgs, out)

    async def _get_output_many(
        self, node: Node, msgs: list[SeldonMessage]
    ) -> list[SeldonMessage]:
        unit = node.unit
        msgs = [
            m.with_meta(m.meta.merged_with(Meta(request_path={node.name: unit.image})))
            for m in msgs
        ]

        if _has_method(node, PredictiveUnitMethod.TRANSFORM_INPUT):
            msgs = await self._merged_call(
                node, "transform_input", unit.transform_input, msgs
            )

        if not node.children:
            return msgs

        shadow = getattr(unit, "shadow_fanout", False)
        if _has_method(node, PredictiveUnitMethod.ROUTE):
            branches = []
            for m in msgs:
                b = await self._call(node, "route", unit.route, m)
                if shadow and b == ROUTE_ALL:
                    b = 0  # shadow default primary (matches the single path)
                if b != ROUTE_ALL and not (0 <= b < len(node.children)):
                    raise APIException(
                        ErrorCode.ENGINE_INVALID_ROUTING,
                        f"unit '{node.name}' routed to {b} with {len(node.children)} children",
                    )
                branches.append(b)
            shadow_spawned = []
            if shadow:
                # mirror every message to each child that is NOT its
                # primary, detached (same SHADOW semantics as _get_output)
                for i, child in enumerate(node.children):
                    mirror_idxs = [j for j, b in enumerate(branches) if b != i]
                    if mirror_idxs:
                        task = self._spawn_shadow(
                            child, [msgs[j] for j in mirror_idxs]
                        )
                        shadow_spawned.append((child.name, task, mirror_idxs))
            msgs = [
                m.with_meta(m.meta.merged_with(Meta(routing={node.name: b})))
                for m, b in zip(msgs, branches)
            ]
            groups: dict[int, list[int]] = {}
            for i, b in enumerate(branches):
                groups.setdefault(b, []).append(i)

            # branch groups are disjoint request sets: walk them CONCURRENTLY
            # (reference @Async child fan-out semantics) — sequential awaits
            # would stack an A/B split's two branch latencies
            async def _run_group(b: int, idxs: list[int]):
                sub = [msgs[i] for i in idxs]
                if b == ROUTE_ALL:
                    outs = await self._fanout_many(node, sub)
                else:
                    fb = self._fallback_branch(node, b)
                    if fb is not None and self._branch_breaker_open(node, b):
                        outs = await self._degraded_group(node, fb, sub)
                    else:
                        try:
                            outs = await self._get_output_many(
                                node.children[b], sub
                            )
                        except Exception as e:  # noqa: BLE001 - gated below
                            if fb is None or not self._fallback_eligible(e):
                                raise
                            outs = await self._degraded_group(node, fb, sub)
                return idxs, outs

            results: list[SeldonMessage | None] = [None] * len(msgs)
            for idxs, outs in await _gather_settled(
                *(_run_group(b, idxs) for b, idxs in groups.items())
            ):
                for i, o in zip(idxs, outs):
                    results[i] = o
            for shadow_name, task, mirror_idxs in shadow_spawned:
                # primaries exist now: compare each mirror when it finishes
                self._attach_compare(
                    shadow_name, task, [results[j] for j in mirror_idxs]
                )
            out_msgs = results  # type: ignore[assignment]
        else:
            out_msgs = await self._fanout_many(node, msgs)

        if _has_method(node, PredictiveUnitMethod.TRANSFORM_OUTPUT):
            out_msgs = await self._merged_call(
                node, "transform_output", unit.transform_output, out_msgs
            )
        return out_msgs

    async def _settle_quorum(self, node: Node, aws: list):
        """Settle every child walk; with a configured COMBINER quorum, a
        partial fan-out failure degrades to aggregating the survivors
        instead of failing the request. Returns (surviving outputs,
        degraded?). Without a quorum (or below it) this is exactly
        _gather_settled: all siblings settle, then the failure re-raises."""
        results = await asyncio.gather(*aws, return_exceptions=True)
        failures = [r for r in results if isinstance(r, BaseException)]
        ok = [r for r in results if not isinstance(r, BaseException)]
        if not failures:
            return ok, False
        quorum = node.policy.quorum
        if (
            quorum is not None
            and _has_method(node, PredictiveUnitMethod.AGGREGATE)
            and len(ok) >= max(quorum, 1)
        ):
            self._degraded_event(node, "quorum")
            return ok, True
        raise failures[0]

    async def _fanout_many(
        self, node: Node, msgs: list[SeldonMessage]
    ) -> list[SeldonMessage]:
        """All-children fan-out for a batch: each child walks the whole batch,
        then AGGREGATE runs once on the row-aligned merged child outputs."""
        unit = node.unit
        targets = node.children
        degraded = False
        if len(targets) == 1:
            child_outs = [await self._get_output_many(targets[0], msgs)]
        else:
            child_outs, degraded = await self._settle_quorum(
                node, [self._get_output_many(c, msgs) for c in targets]
            )

        if _has_method(node, PredictiveUnitMethod.AGGREGATE):
            merged_children = [self._merge_rows(co) for co in child_outs]
            out = await self._call(node, "aggregate", unit.aggregate, merged_children)
            out = await self._settle_to_host(out)
            if degraded:
                out = out.with_meta(
                    out.meta.merged_with(Meta(tags={DEGRADED_TAG: "quorum"}))
                )
            base = []
            for i, m in enumerate(msgs):
                meta = m.meta
                for co in child_outs:
                    meta = meta.merged_with(co[i].meta)
                base.append(m.with_meta(meta))
            return self._scatter_rows(base, out)
        if len(child_outs) == 1:
            return child_outs[0]
        raise APIException(
            ErrorCode.ENGINE_INVALID_ROUTING,
            f"unit '{node.name}' fanned out to {len(child_outs)} children without AGGREGATE",
        )

    @staticmethod
    def _counts_for_breaker(e: BaseException) -> bool:
        """Which failures indict the ENDPOINT's health: everything except
        our own budget exhaustion, breaker fast-fails, and cancellation —
        those say nothing about whether the backend is up."""
        if isinstance(e, asyncio.CancelledError):
            return False
        if isinstance(e, APIException):
            if e.error in (
                ErrorCode.REQUEST_DEADLINE_EXCEEDED,
                ErrorCode.ENGINE_BREAKER_OPEN,
            ):
                return False
            if e.retryable is False:
                # explicitly deterministic (e.g. remote 4xx on a bad
                # payload): the backend answered correctly — counting it
                # would open the breaker against a healthy endpoint
                return False
        return True

    async def _call(self, node: Node, method: str, fn, *args):
        """One unit-method invocation through the resilience pipeline:

            deadline check -> breaker gate -> timed attempt -> retry loop

        Every attempt is timed individually (the per-unit observability
        contract counts real dispatches, not logical calls) and recorded as
        its OWN trace span — a retried call shows each dispatch, and the
        span is opened BEFORE the dispatch so a remote transport's
        traceparent header names it as the server-side parent. Retries apply
        only to idempotent methods on transport/5xx-class failures and
        never sleep past the request's remaining budget; breaker outcomes
        are recorded per attempt so a flapping endpoint opens its breaker
        even while retries are absorbing the failures. Resilience actions
        (retries, breaker fast-fails, deadline exhaustion) are attached to
        the trace as span events, so a trace shows not just where time went
        but what this layer DID to the request."""
        d = current_deadline()
        if d is not None and d.expired():
            self._events.deadline_exceeded(node.name)
            telemetry.add_event("deadline_exceeded", {"unit": node.name})
            raise deadline_exceeded(f"unit '{node.name}'.{method}")
        breaker = self._breakers.get(node.name)
        took_probe = False
        if breaker is not None and method != "send_feedback":
            if not breaker.allow():
                telemetry.add_event(
                    "breaker_open", {"endpoint": self._breaker_keys[node.name]}
                )
                raise breaker_open_error(self._breaker_keys[node.name], breaker)
            # allow() consumed a probe slot iff the breaker sits half-open
            took_probe = breaker.state == HALF_OPEN
        retry = self._retries.get(node.name)
        attempt = 0
        while True:
            attempt += 1
            t0 = time.perf_counter()
            span_handle = telemetry.begin_spans(
                f"{node.name}.{method}",
                {"unit": node.name, "method": method, "attempt": attempt},
            )
            try:
                result = await fn(*args)
            except BaseException as e:
                telemetry.end_spans(span_handle, error=True)
                self._record_call(node, method, time.perf_counter() - t0)
                if breaker is not None:
                    if self._counts_for_breaker(e):
                        breaker.record_failure()
                    elif took_probe:
                        # no verdict (cancel / deadline): free the probe
                        # slot so the breaker cannot wedge in half-open
                        breaker.release_probe()
                # the backoff actually slept is the SAME jittered value
                # validated against the remaining budget (one RNG draw)
                backoff_s = retry.backoff(attempt) if retry is not None else 0.0
                if retry is not None and retry.should_retry(method, attempt, e, backoff_s):
                    self._events.retry(node.name, attempt)
                    telemetry.add_event(
                        "retry", {"unit": node.name, "attempt": attempt}
                    )
                    await asyncio.sleep(backoff_s)
                    if breaker is not None:
                        if not breaker.allow():
                            # the endpoint tripped open while we backed off
                            telemetry.add_event(
                                "breaker_open",
                                {"endpoint": self._breaker_keys[node.name]},
                            )
                            raise breaker_open_error(
                                self._breaker_keys[node.name], breaker
                            ) from e
                        took_probe = breaker.state == HALF_OPEN
                    continue
                raise
            else:
                telemetry.end_spans(span_handle)
                self._record_call(node, method, time.perf_counter() - t0)
                if breaker is not None:
                    breaker.record_success()
                return result

    def _record_call(self, node: Node, method: str, dt: float) -> None:
        if self._unit_hook is not None:
            self._unit_hook(node.name, method, dt)

    # ------------------------------------------------- graceful degradation
    def _fallback_branch(self, node: Node, chosen: int) -> int | None:
        """The router's configured degradation branch, when it is a real,
        DIFFERENT child than the one routing chose."""
        fb = node.policy.fallback_child
        if fb is None or fb == chosen or not (0 <= fb < len(node.children)):
            return None
        return fb

    def _branch_breaker_open(self, node: Node, branch: int) -> bool:
        """Non-consuming peek at the chosen child's breaker: firmly-open ->
        degrade immediately (don't even dispatch); a reset-elapsed breaker
        reads half-open so probe traffic still reaches the child and can
        recover it."""
        breaker = self._breakers.get(node.children[branch].name)
        return breaker is not None and breaker.is_open()

    @staticmethod
    def _fallback_eligible(e: BaseException) -> bool:
        """Failures a router may degrade around: the chosen child's breaker
        fast-failing, or a transport/5xx-class failure from its subtree.
        Deadline exhaustion is NOT eligible — the budget is gone either
        way, and walking the fallback would just overrun it further."""
        return is_breaker_open_error(e) or is_retryable(e)

    @staticmethod
    def _degrade_meta(msg: SeldonMessage, node_name: str, branch: int, mode: str):
        """Restamp routing with the branch actually served plus the
        degradation marker (feedback must replay down the REAL path)."""
        return msg.with_meta(
            msg.meta.merged_with(
                Meta(routing={node_name: branch}, tags={DEGRADED_TAG: mode})
            )
        )

    def _degraded_event(self, node: Node, mode: str) -> None:
        self._events.degraded(node.name, mode)
        telemetry.add_event("degraded", {"unit": node.name, "mode": mode})

    async def _degraded_group(
        self, node: Node, fb: int, sub: list[SeldonMessage]
    ) -> list[SeldonMessage]:
        """Batched router fallback: walk the whole group down the fallback
        branch, restamping routing + the degraded tag per request."""
        self._degraded_event(node, "router_fallback")
        sub = [self._degrade_meta(m, node.name, fb, "router_fallback") for m in sub]
        return await self._get_output_many(node.children[fb], sub)

    async def _routed_walk(
        self, node: Node, branch: int, msg: SeldonMessage
    ) -> SeldonMessage:
        """Walk the routed child with graceful degradation: when the chosen
        child's breaker is firmly open, serve the configured fallback branch
        without dispatching; when the chosen subtree fails transport-class
        (or fast-fails on a deeper breaker), fail over to the fallback. The
        served branch is restamped into meta.routing so feedback replays
        down the path the request ACTUALLY took."""
        fb = self._fallback_branch(node, branch)
        if fb is not None and self._branch_breaker_open(node, branch):
            self._degraded_event(node, "router_fallback")
            return await self._get_output(
                node.children[fb],
                self._degrade_meta(msg, node.name, fb, "router_fallback"),
            )
        try:
            return await self._get_output(node.children[branch], msg)
        except Exception as e:  # noqa: BLE001 - gated by _fallback_eligible
            if fb is None or not self._fallback_eligible(e):
                raise
            self._degraded_event(node, "router_fallback")
            return await self._get_output(
                node.children[fb],
                self._degrade_meta(msg, node.name, fb, "router_fallback"),
            )

    @staticmethod
    def _shadow_copy(msg: SeldonMessage) -> SeldonMessage:
        """Defensive payload copy for a mirror walk: shadows exist to run
        UNVETTED candidates, and an in-place-mutating candidate must not
        corrupt the payload the primary is about to serve from."""
        if msg.data is not None and msg.data.array is not None:
            return msg.with_array(np.array(np.asarray(msg.array)), msg.names)
        if msg.json_data is not None:
            # json payloads are mutable dicts/lists — deep-copy them too
            import copy

            return msg._copy(
                None, None, None, copy.deepcopy(msg.json_data), msg.meta, msg.status
            )
        return msg  # bytes/str payloads are immutable

    def _spawn_shadow(self, child: Node, payload) -> asyncio.Task:
        """Detached mirror walk of ``child`` (SHADOW fan-out): failures log,
        never propagate — the shadow candidate's behavior must not affect
        the response its primary already owns. Returns the task so the
        caller can attach the agreement comparison once the primary's own
        output exists."""
        if isinstance(payload, list):
            payload = [self._shadow_copy(m) for m in payload]
        else:
            payload = self._shadow_copy(payload)

        async def _run():
            # shadows outlive the primary's response by design — the
            # request's deadline budget must not fail a slow candidate's
            # mirror walk (that would read as disagreement, not latency),
            # and its spans must not land in a trace that already shipped
            DEADLINE.set(None)
            telemetry.clear()
            try:
                if isinstance(payload, list):
                    return await self._get_output_many(child, payload)
                return await self._get_output(child, payload)
            except Exception as e:  # noqa: BLE001 - shadow failures are data, not errors
                log.warning("shadow child '%s' failed: %s", child.name, e)
                return None

        task = asyncio.ensure_future(_run())
        self._shadow_tasks.add(task)
        task.add_done_callback(self._shadow_tasks.discard)
        return task

    @staticmethod
    def _outputs_agree(primary: SeldonMessage | None, shadow: SeldonMessage | None):
        """Did the shadow candidate make the same call as the primary?
        Classifier outputs compare by rowwise argmax (the serving decision);
        other tensors by tolerant allclose; bytes/str/json payloads by
        equality. A failed shadow (None) or a payload-KIND mismatch is a
        disagreement — a candidate that errors or answers in a different
        form where production serves is exactly what shadowing surfaces."""
        if primary is None or shadow is None:
            return False
        if primary.array is not None and shadow.array is not None:
            x, y = np.asarray(primary.array), np.asarray(shadow.array)
            if x.shape != y.shape:
                return False
            if x.ndim >= 2 and x.shape[-1] > 1:
                return bool(np.array_equal(np.argmax(x, -1), np.argmax(y, -1)))
            return bool(np.allclose(x, y, rtol=1e-3, atol=1e-5))
        if primary.array is not None or shadow.array is not None:
            return False  # tensor vs non-tensor: different kinds
        # non-tensor arms: exact equality (the oneof keeps at most one set)
        return bool(
            primary.bin_data == shadow.bin_data
            and primary.str_data == shadow.str_data
            and primary.json_data == shadow.json_data
        )

    def _attach_compare(self, shadow_name: str, task: asyncio.Task, primary_out) -> None:
        """When the shadow finishes, compare its output against the primary's
        (already-served) output and tick the agreement counter. primary_out:
        a SeldonMessage, or (for the batch path) a list aligned with the
        mirror payload."""
        if self._shadow_hook is None:
            return

        def _done(t: asyncio.Task) -> None:
            if t.cancelled():
                return
            out = t.result()
            try:
                if isinstance(primary_out, list):
                    shadows = out if isinstance(out, list) else [None] * len(primary_out)
                    for p, s in zip(primary_out, shadows):
                        self._shadow_hook(shadow_name, self._outputs_agree(p, s))
                else:
                    self._shadow_hook(shadow_name, self._outputs_agree(primary_out, out))
            except Exception as e:  # noqa: BLE001 - metrics must not break serving
                log.warning("shadow comparison for '%s' failed: %s", shadow_name, e)

        task.add_done_callback(_done)

    async def drain_shadows(self) -> None:
        """Await in-flight shadow walks (tests / graceful shutdown).

        The set is drained explicitly: a task can be FINISHED while its
        done-callback (the set discard) is still queued on the loop, and
        awaiting a gather of already-done tasks does not yield — relying on
        the callback alone would busy-spin forever."""
        while self._shadow_tasks:
            pending = list(self._shadow_tasks)
            await asyncio.gather(*pending, return_exceptions=True)
            self._shadow_tasks.difference_update(pending)
            await asyncio.sleep(0)  # let queued done-callbacks run
        # shadows that finished BEFORE their comparison was attached leave
        # the agreement callback queued on the loop even with an empty set —
        # one final yield flushes them so post-drain metrics are complete
        await asyncio.sleep(0)

    async def _get_output(
        self, node: Node, msg: SeldonMessage
    ) -> SeldonMessage:
        unit = node.unit
        # requestPath (reference Meta.requestPath: every node the request
        # visited, mapped to its serving image/implementation)
        msg = msg.with_meta(
            msg.meta.merged_with(Meta(request_path={node.name: unit.image}))
        )

        if _has_method(node, PredictiveUnitMethod.TRANSFORM_INPUT):
            out = await self._call(node, "transform_input", unit.transform_input, msg)
            msg = out.with_meta(msg.meta.merged_with(out.meta))

        if not node.children:
            return msg

        branch = ROUTE_ALL
        routed = False
        if _has_method(node, PredictiveUnitMethod.ROUTE):
            branch = await self._call(node, "route", unit.route, msg)
            routed = True
            # sanityCheckRouting (reference :244-250)
            if branch != ROUTE_ALL and not (0 <= branch < len(node.children)):
                raise APIException(
                    ErrorCode.ENGINE_INVALID_ROUTING,
                    f"unit '{node.name}' routed to {branch} with {len(node.children)} children",
                )
            msg = msg.with_meta(
                msg.meta.merged_with(Meta(routing={node.name: branch}))
            )

        if getattr(unit, "shadow_fanout", False):
            # SHADOW semantics: serve the routed (primary) child; mirror a
            # COPY of the input to every other child fire-and-forget —
            # their latency and failures never touch the response, but
            # their unit TIMERS (unit_call_hook -> prometheus) still tick,
            # which is the point: validate a candidate under production
            # traffic. (Request trace spans cover the primary only — the
            # response has shipped before a shadow finishes.) Deliberately
            # detached (the one exception to settle-before-raise): a slow
            # shadow must not hold the primary's response.
            primary = 0 if branch == ROUTE_ALL else branch
            shadow_spawned = [
                (child.name, self._spawn_shadow(child, msg))
                for i, child in enumerate(node.children)
                if i != primary
            ]
            targets = [node.children[primary]]
        elif branch == ROUTE_ALL:
            targets = node.children
        else:
            targets = [node.children[branch]]

        degraded_quorum = False
        if len(targets) == 1:
            if routed and branch != ROUTE_ALL and not getattr(unit, "shadow_fanout", False):
                child_outputs = [await self._routed_walk(node, branch, msg)]
            else:
                child_outputs = [await self._get_output(targets[0], msg)]
        else:
            child_outputs, degraded_quorum = await self._settle_quorum(
                node, [self._get_output(c, msg) for c in targets]
            )

        if getattr(unit, "shadow_fanout", False):
            # the primary's output exists now: compare each mirror against
            # it when the mirror finishes (agreement counter)
            for shadow_name, task in shadow_spawned:
                self._attach_compare(shadow_name, task, child_outputs[0])

        merged_meta = msg.meta
        for co in child_outputs:
            merged_meta = merged_meta.merged_with(co.meta)

        if _has_method(node, PredictiveUnitMethod.AGGREGATE):
            out = await self._call(node, "aggregate", unit.aggregate, child_outputs)
        elif len(child_outputs) == 1:
            out = child_outputs[0]
        else:
            raise APIException(
                ErrorCode.ENGINE_INVALID_ROUTING,
                f"unit '{node.name}' fanned out to {len(child_outputs)} children without AGGREGATE",
            )
        msg = out.with_meta(merged_meta.merged_with(out.meta))
        if degraded_quorum:
            msg = msg.with_meta(
                msg.meta.merged_with(Meta(tags={DEGRADED_TAG: "quorum"}))
            )

        if _has_method(node, PredictiveUnitMethod.TRANSFORM_OUTPUT):
            out = await self._call(node, "transform_output", unit.transform_output, msg)
            msg = out.with_meta(msg.meta.merged_with(out.meta))
        return msg

    # ------------------------------------------------------------ feedback
    async def send_feedback(self, feedback: Feedback) -> None:
        await self._send_feedback(self.root, feedback)

    async def _send_feedback(self, node: Node, feedback: Feedback) -> None:
        routing_map = {}
        if feedback.response is not None:
            routing_map = dict(feedback.response.meta.routing)
        branch = int(routing_map.get(node.name, ROUTE_ALL))

        if _has_method(node, PredictiveUnitMethod.SEND_FEEDBACK):
            await node.unit.send_feedback(feedback, branch)
            if self._feedback_hook is not None:
                self._feedback_hook(node.name, feedback.reward)

        if not node.children:
            return
        if branch == ROUTE_ALL:
            await _gather_settled(*(self._send_feedback(c, feedback) for c in node.children))
        else:
            if not (0 <= branch < len(node.children)):
                raise APIException(
                    ErrorCode.ENGINE_INVALID_ROUTING,
                    f"feedback routing {branch} invalid for '{node.name}'",
                )
            await self._send_feedback(node.children[branch], feedback)

    # ------------------------------------------------------------- status
    def ready(self) -> bool:
        return all(n.unit.ready() for n in self.root.walk())

    def stateful_units(self) -> dict[str, Unit]:
        """Units with learnable state (for persistence/ checkpointing)."""
        out = {}
        for n in self.root.walk():
            if type(n.unit).send_feedback is not Unit.send_feedback:
                out[n.name] = n.unit
        return out


def build_node(
    spec: PredictiveUnit,
    registry: UnitRegistry,
    context: dict[str, Any],
) -> Node:
    """PredictiveUnitState-equivalent construction
    (reference PredictiveUnitState.java:74-100): resolve each spec unit to a
    runtime Unit via, in order:
      1. explicit override in context['units'] (tests / embedding),
      2. registry implementation (built-ins, JAX_MODEL),
      3. container with model_uri -> zoo model (TPU-resident),
      4. declared endpoint -> RemoteUnit (REST/gRPC escape hatch),
      5. bare identity Unit.
    """
    overrides = context.get("units") or {}
    unit: Unit | None = None
    if spec.name in overrides:
        unit = overrides[spec.name]
        if not isinstance(unit, Unit):
            from seldon_core_tpu.engine.units import PythonClassUnit

            unit = PythonClassUnit(spec, unit)
    if unit is None:
        unit = registry.create(spec, context)
    if unit is None:
        containers = context.get("containers") or {}
        c = containers.get(spec.name)
        if c is not None and getattr(c, "model_uri", ""):
            from seldon_core_tpu.models.zoo import unit_from_container

            unit = unit_from_container(spec, c, context)
    if unit is None and spec.endpoint is not None and spec.endpoint.service_port:
        from seldon_core_tpu.engine.remote import RemoteUnit

        unit = RemoteUnit(spec)
    if unit is None:
        unit = Unit(spec)

    container = (context.get("containers") or {}).get(spec.name)
    if container is not None and getattr(container, "image", ""):
        unit.image = container.image

    children = [build_node(c, registry, context) for c in spec.children]
    return Node(
        spec=spec,
        unit=unit,
        children=children,
        policy=ResilienceSpec.for_unit(spec),
    )


def build_executor(
    predictor: PredictorSpec,
    registry: UnitRegistry | None = None,
    context: dict[str, Any] | None = None,
    feedback_metrics_hook: Callable[[str, float], None] | None = None,
    unit_call_hook: Callable[[str, str, float], None] | None = None,
    shadow_compare_hook: Callable[[str, bool], None] | None = None,
    resilience_events: ResilienceEvents | None = None,
) -> GraphExecutor:
    registry = registry or default_registry()
    context = dict(context or {})
    context.setdefault("containers", {c.name: c for c in predictor.componentSpec.containers})
    context.setdefault("tpu", predictor.tpu)
    root = build_node(predictor.graph, registry, context)
    tpu_cfg = context.get("tpu")
    if tpu_cfg is not None and getattr(tpu_cfg, "fuse_graph", True):
        from seldon_core_tpu.engine.fused import fuse_graph

        root = fuse_graph(root, tpu_cfg, context.get("mesh"))
    return GraphExecutor(
        root,
        feedback_metrics_hook=feedback_metrics_hook,
        unit_call_hook=unit_call_hook,
        shadow_compare_hook=shadow_compare_hook,
        resilience_events=resilience_events,
    )
