"""Graph fusion: compile a pure all-JAX subtree into ONE XLA program.

This is the TPU-native payoff the whole architecture exists for (SURVEY §7
step 3): where the reference executes a COMBINER by fanning per-request RPCs
to N model containers and averaging in Java, a pure subtree here becomes a
single jitted function — N model applies + the combine trace into one XLA
program, so XLA fuses/overlaps them and the host pays one dispatch instead
of N.

Two execution strategies, picked automatically:
- vmapped ensemble: when every child shares the same apply function and
  param structure (e.g. 3x resnet50 with different seeds), params stack on a
  leading ensemble axis and one vmap(apply) computes all members — the
  matmuls batch onto the MXU together;
- traced ensemble: heterogeneous children trace sequentially into the same
  program (still one dispatch, XLA schedules them).

Fusable units are those exposing a pure-fn hook (engine/units.py):
``as_pure_fn`` (combiner aggregate), ``as_pure_input_fn`` /
``as_pure_output_fn`` (transformer math). JaxModelUnit leaves, pure
COMBINER interiors, and pure single-child TRANSFORMER / OUTPUT_TRANSFORMER
interiors all fuse, so a transformer -> models -> combiner DAG compiles to
one dispatch. Routers and stateful/host units never fuse — the executor
remains the correct fallback around the fused islands.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from seldon_core_tpu.engine.executor import Node, _has_method
from seldon_core_tpu.engine.units import Unit
from seldon_core_tpu.graph.spec import (
    PredictiveUnit,
    PredictiveUnitMethod,
    PredictiveUnitType,
)
from seldon_core_tpu.models.base import JaxModelUnit, ModelRuntime

_IDENTITY = "identity"


@dataclass
class _PureSubtree:
    apply_fn: Callable[[Any, jax.Array], jax.Array]
    params: Any
    class_names: tuple[str, ...]
    feature_shape: tuple[int, ...] | None
    n_models: int
    n_nodes: int  # dispatches the fused program saves (models + transforms)


def _pure_transform(node: Node, method: PredictiveUnitMethod):
    """Pure equivalent of the node's input/output transform under walker
    dispatch: _IDENTITY when the walker would not run it (method absent for
    the node type) or the unit inherits the base identity; (fn, params) when
    the unit exposes a pure form; None when the transform is opaque Python
    (blocks fusion)."""
    if not _has_method(node, method):
        return _IDENTITY
    unit = node.unit
    if method is PredictiveUnitMethod.TRANSFORM_INPUT:
        pure = unit.as_pure_input_fn()
        overridden = type(unit).transform_input is not Unit.transform_input
    else:
        pure = unit.as_pure_output_fn()
        overridden = type(unit).transform_output is not Unit.transform_output
    if pure is not None:
        return pure
    return None if overridden else _IDENTITY


def _collect(node: Node) -> _PureSubtree | None:
    """Bottom-up: a JaxModelUnit leaf, or a pure interior node — COMBINER
    (pure aggregate) / single-child TRANSFORMER / OUTPUT_TRANSFORMER — whose
    transforms are pure, over pure children."""
    unit = node.unit
    if not node.children:
        if isinstance(unit, JaxModelUnit):
            rt = unit.runtime
            return _PureSubtree(
                apply_fn=rt.apply_fn,
                params=rt.params,
                class_names=rt.class_names,
                feature_shape=getattr(rt, "feature_shape", None),
                n_models=1,
                n_nodes=1,
            )
        return None

    # routers never fuse: routing is per-request host-side control flow
    if _has_method(node, PredictiveUnitMethod.ROUTE):
        return None

    # a MODEL unit with children is a chain head, not a combiner — its pure
    # fn applies to the INPUT, not to a list of child outputs; fusing it as
    # an interior node would invert the graph
    interior_types = (
        PredictiveUnitType.COMBINER,
        PredictiveUnitType.TRANSFORMER,
        PredictiveUnitType.OUTPUT_TRANSFORMER,
    )
    if node.spec.type not in interior_types:
        return None

    t_in = _pure_transform(node, PredictiveUnitMethod.TRANSFORM_INPUT)
    t_out = _pure_transform(node, PredictiveUnitMethod.TRANSFORM_OUTPUT)
    if t_in is None or t_out is None:
        return None

    if _has_method(node, PredictiveUnitMethod.AGGREGATE):
        pure = unit.as_pure_fn()
        if pure is None:
            return None
        combine_fn, combine_params = pure
    elif len(node.children) == 1:
        combine_fn, combine_params = None, None  # pass-through
    else:  # fan-out without aggregate is an executor error anyway
        return None

    children = [_collect(c) for c in node.children]
    if any(c is None for c in children):
        return None

    same_fn = all(c.apply_fn is children[0].apply_fn for c in children)
    same_tree = all(
        jax.tree.structure(c.params) == jax.tree.structure(children[0].params)
        for c in children
    )
    if same_fn and same_tree and len(children) > 1:
        # homogeneous ensemble: stack params, one vmapped apply
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *(c.params for c in children))
        child_fn = children[0].apply_fn

        def inner(params, x):
            ys = jax.vmap(child_fn, in_axes=(0, None))(params, x)
            return [ys[i] for i in range(ys.shape[0])]

        member_params = stacked
    else:
        child_fns = tuple(c.apply_fn for c in children)

        def inner(params, x, _fns=child_fns):
            return [f(p, x) for f, p in zip(_fns, params)]

        member_params = [c.params for c in children]

    params: dict[str, Any] = {"members": member_params}
    if t_in is not _IDENTITY:
        params["t_in"] = t_in[1]
    if t_out is not _IDENTITY:
        params["t_out"] = t_out[1]

    def fused(
        params,
        x,
        _inner=inner,
        _combine=combine_fn,
        _cp=combine_params,
        _tin=None if t_in is _IDENTITY else t_in[0],
        _tout=None if t_out is _IDENTITY else t_out[0],
    ):
        if _tin is not None:
            x = _tin(params["t_in"], x)
        ys = _inner(params["members"], x)
        y = _combine(_cp, ys) if _combine is not None else ys[0]
        if _tout is not None:
            y = _tout(params["t_out"], y)
        return y

    names = next((c.class_names for c in children if c.class_names), ())
    shape = next((c.feature_shape for c in children if c.feature_shape), None)
    return _PureSubtree(
        apply_fn=fused,
        params=params,
        class_names=names,
        feature_shape=shape,
        n_models=sum(c.n_models for c in children),
        n_nodes=sum(c.n_nodes for c in children) + 1,
    )


class FusedUnit(JaxModelUnit):
    """A whole pure subtree collapsed into one ModelRuntime."""


def fuse_graph(root: Node, tpu_cfg=None, mesh=None) -> Node:
    """Replace fusable subtrees with single FusedUnit leaves. Applied
    top-down: the largest pure island wins. No-op when nothing fuses."""

    sub = _collect(root)
    if sub is not None and sub.n_nodes > 1:
        dtype = jnp.float32
        if tpu_cfg is not None:
            dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}.get(
                getattr(tpu_cfg, "dtype", "float32"), jnp.float32
            )
        runtime = ModelRuntime(
            sub.apply_fn,
            sub.params,
            mesh=mesh,
            buckets=tuple(getattr(tpu_cfg, "batch_buckets", ()) or ())
            if tpu_cfg is not None
            else (),
            max_batch=getattr(tpu_cfg, "max_batch", 64) if tpu_cfg is not None else 64,
            dtype=dtype,
            class_names=sub.class_names,
            donate=False,
        )
        if sub.feature_shape is not None:
            runtime.feature_shape = sub.feature_shape
        spec = PredictiveUnit.model_validate(
            {"name": root.name, "type": PredictiveUnitType.MODEL.value}
        )
        unit = FusedUnit(spec, runtime)
        # requestPath observability: member names survive in the image
        # (per-member trace spans / unit timers do NOT exist for a fused
        # island — that is the documented trade-off of fuse_graph=true)
        members = ",".join(n.name for n in root.walk() if n is not root)
        unit.image = f"fused[{members}]" if len(members) <= 120 else f"fused:{sub.n_models}-models"
        # the island REPLACES the subtree rooted here: the root's resilience
        # knobs (retry/breaker ride the island's single dispatch) survive
        return Node(spec=spec, unit=unit, children=[], policy=root.policy)

    new_children = [fuse_graph(c, tpu_cfg, mesh) for c in root.children]
    if new_children != root.children:
        return Node(
            spec=root.spec, unit=root.unit, children=new_children, policy=root.policy
        )
    return root
