"""Graph fusion: compile a pure all-JAX subtree into ONE XLA program.

This is the TPU-native payoff the whole architecture exists for (SURVEY §7
step 3): where the reference executes a COMBINER by fanning per-request RPCs
to N model containers and averaging in Java, a pure subtree here becomes a
single jitted function — N model applies + the combine trace into one XLA
program, so XLA fuses/overlaps them and the host pays one dispatch instead
of N.

Two execution strategies, picked automatically:
- vmapped ensemble: when every child shares the same apply function and
  param structure (e.g. 3x resnet50 with different seeds), params stack on a
  leading ensemble axis and one vmap(apply) computes all members — the
  matmuls batch onto the MXU together;
- traced ensemble: heterogeneous children trace sequentially into the same
  program (still one dispatch, XLA schedules them).

Fusable units are those exposing ``as_pure_fn()`` (engine/units.py hook):
JaxModelUnit leaves and AverageCombinerUnit interior nodes today. Routers
and stateful/host units never fuse — the executor remains the correct
fallback around the fused islands.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from seldon_core_tpu.engine.executor import Node
from seldon_core_tpu.engine.units import Unit
from seldon_core_tpu.graph.spec import PredictiveUnit, PredictiveUnitType
from seldon_core_tpu.models.base import JaxModelUnit, ModelRuntime


@dataclass
class _PureSubtree:
    apply_fn: Callable[[Any, jax.Array], jax.Array]
    params: Any
    class_names: tuple[str, ...]
    feature_shape: tuple[int, ...] | None
    n_models: int


def _collect(node: Node) -> _PureSubtree | None:
    """Bottom-up: a JaxModelUnit leaf or a pure combiner over pure children."""
    unit = node.unit
    if not node.children:
        if isinstance(unit, JaxModelUnit):
            rt = unit.runtime
            return _PureSubtree(
                apply_fn=rt.apply_fn,
                params=rt.params,
                class_names=rt.class_names,
                feature_shape=getattr(rt, "feature_shape", None),
                n_models=1,
            )
        return None

    # only genuine COMBINER nodes fuse as interior nodes: a MODEL unit also
    # exposes as_pure_fn, but its fn applies to the INPUT, not to a list of
    # child outputs — treating it as a combiner would invert the graph
    if node.spec.type != PredictiveUnitType.COMBINER:
        return None
    pure = unit.as_pure_fn()
    if pure is None:
        return None
    combine_fn, combine_params = pure

    children = [_collect(c) for c in node.children]
    if any(c is None for c in children):
        return None

    same_fn = all(c.apply_fn is children[0].apply_fn for c in children)
    same_tree = all(
        jax.tree.structure(c.params) == jax.tree.structure(children[0].params)
        for c in children
    )
    if same_fn and same_tree and len(children) > 1:
        # homogeneous ensemble: stack params, one vmapped apply
        stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *(c.params for c in children))
        child_fn = children[0].apply_fn

        def fused(params, x, _combine=combine_fn, _cp=combine_params):
            ys = jax.vmap(child_fn, in_axes=(0, None))(params["members"], x)
            return _combine(_cp, [ys[i] for i in range(ys.shape[0])])

        params = {"members": stacked}
    else:
        child_fns = [c.apply_fn for c in children]

        def fused(params, x, _fns=tuple(child_fns), _combine=combine_fn, _cp=combine_params):
            ys = [f(p, x) for f, p in zip(_fns, params["members"])]
            return _combine(_cp, ys)

        params = {"members": [c.params for c in children]}

    names = next((c.class_names for c in children if c.class_names), ())
    shape = next((c.feature_shape for c in children if c.feature_shape), None)
    return _PureSubtree(
        apply_fn=fused,
        params=params,
        class_names=names,
        feature_shape=shape,
        n_models=sum(c.n_models for c in children),
    )


class FusedUnit(JaxModelUnit):
    """A whole pure subtree collapsed into one ModelRuntime."""


def fuse_graph(root: Node, tpu_cfg=None, mesh=None) -> Node:
    """Replace fusable subtrees with single FusedUnit leaves. Applied
    top-down: the largest pure island wins. No-op when nothing fuses."""

    sub = _collect(root)
    if sub is not None and sub.n_models > 1:
        dtype = jnp.float32
        if tpu_cfg is not None:
            dtype = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}.get(
                getattr(tpu_cfg, "dtype", "float32"), jnp.float32
            )
        runtime = ModelRuntime(
            sub.apply_fn,
            sub.params,
            mesh=mesh,
            buckets=tuple(getattr(tpu_cfg, "batch_buckets", ()) or ())
            if tpu_cfg is not None
            else (),
            max_batch=getattr(tpu_cfg, "max_batch", 64) if tpu_cfg is not None else 64,
            dtype=dtype,
            class_names=sub.class_names,
            donate=False,
        )
        if sub.feature_shape is not None:
            runtime.feature_shape = sub.feature_shape
        spec = PredictiveUnit.model_validate(
            {"name": root.name, "type": PredictiveUnitType.MODEL.value}
        )
        unit = FusedUnit(spec, runtime)
        # requestPath observability: member names survive in the image
        # (per-member trace spans / unit timers do NOT exist for a fused
        # island — that is the documented trade-off of fuse_graph=true)
        members = ",".join(n.name for n in root.walk() if n is not root)
        unit.image = f"fused[{members}]" if len(members) <= 120 else f"fused:{sub.n_models}-models"
        return Node(spec=spec, unit=unit, children=[])

    new_children = [fuse_graph(c, tpu_cfg, mesh) for c in root.children]
    if new_children != root.children:
        return Node(spec=root.spec, unit=root.unit, children=new_children)
    return root
