"""Deterministic fault-injection harness for the data plane.

Resilience claims need to be PROVEN under injected faults, not hoped for
(SURVEY §5.3: the reference ships no fault injection at all). This module
wraps any runtime graph unit in a ``ChaosUnit`` that perturbs calls on a
seeded schedule — latency, transport-class errors, hangs ("timeouts"), and
flapping (windows of 100% failure alternating with healthy windows) — so
retry paths, breaker transitions, deadline budgets, and degradation modes
are exercised end-to-end by unit tests (tests/test_resilience.py, marker
``chaos``) and by the soak harness (tools/soak.py --faults).

Everything is driven by one seeded RNG consumed in call order, so a given
(spec, seed) produces the same fault sequence on every run — failures are
reproducible test vectors, not flakes.
"""

from __future__ import annotations

import asyncio
import dataclasses
import random
import threading
from typing import Sequence

from seldon_core_tpu.core.errors import APIException, ErrorCode
from seldon_core_tpu.core.message import Feedback, SeldonMessage
from seldon_core_tpu.engine.units import Unit


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One node's fault profile. Rates are per-call probabilities drawn from
    the seeded RNG; ``flap_period`` > 0 switches to flapping mode where the
    FIRST ``flap_period`` calls of every 2x-period cycle fail at
    ``flap_error_rate`` and the rest at ``error_rate``."""

    error_rate: float = 0.0  # transport-class APIException
    latency_ms: float = 0.0  # added latency per call
    latency_jitter_ms: float = 0.0  # uniform extra latency on top
    timeout_rate: float = 0.0  # calls that hang for hang_s (deadline food)
    hang_s: float = 30.0
    flap_period: int = 0  # calls per unhealthy window; 0 = no flapping
    flap_error_rate: float = 1.0
    seed: int = 0


@dataclasses.dataclass(frozen=True)
class FaultDecision:
    action: str  # "ok" | "error" | "timeout"
    delay_s: float = 0.0


class FaultSchedule:
    """Seeded deterministic per-call decisions, in call order."""

    def __init__(self, spec: FaultSpec):
        self.spec = spec
        self._rng = random.Random(spec.seed)
        self._lock = threading.Lock()
        self.calls = 0
        self.injected = 0

    def _error_rate_now(self) -> float:
        s = self.spec
        if s.flap_period <= 0:
            return s.error_rate
        phase = self.calls % (2 * s.flap_period)
        return s.flap_error_rate if phase < s.flap_period else s.error_rate

    def next(self) -> FaultDecision:
        with self._lock:
            s = self.spec
            rate = self._error_rate_now()
            self.calls += 1
            delay = s.latency_ms / 1000.0
            if s.latency_jitter_ms > 0:
                delay += self._rng.uniform(0, s.latency_jitter_ms / 1000.0)
            # one draw per decision point, always consumed, so the sequence
            # is a pure function of (spec, seed) regardless of outcomes
            err_draw = self._rng.random()
            timeout_draw = self._rng.random()
            if s.timeout_rate > 0 and timeout_draw < s.timeout_rate:
                self.injected += 1
                return FaultDecision("timeout", delay)
            if rate > 0 and err_draw < rate:
                self.injected += 1
                return FaultDecision("error", delay)
            return FaultDecision("ok", delay)


class ChaosUnit(Unit):
    """Wraps a runtime unit and perturbs its calls per a FaultSchedule.

    Installed per-node on a built executor (install_faults) — the wrapped
    unit keeps serving the non-faulted calls, so the graph under test is the
    REAL graph, not a stub. send_feedback passes through unperturbed:
    injecting faults into a non-idempotent method would make the harness
    itself corrupt learner state.
    """

    def __init__(self, inner: Unit, schedule: FaultSchedule, on_fault=None):
        super().__init__(inner.spec)
        self.inner = inner
        self.schedule = schedule
        self.image = inner.image
        # preserve executor-keyed behavior flags of the wrapped unit
        if getattr(inner, "shadow_fanout", False):
            self.shadow_fanout = True
        self._on_fault = on_fault  # (unit_name, kind) -> None

    def ready(self) -> bool:
        return self.inner.ready()

    async def _perturb(self) -> None:
        from seldon_core_tpu import telemetry

        d = self.schedule.next()
        if d.delay_s > 0:
            await asyncio.sleep(d.delay_s)
        if d.action != "ok":
            # injected faults show up in the request's trace as span
            # events, so a chaos run's traces show what was DONE to them
            telemetry.add_event(
                "fault_injected", {"unit": self.name, "kind": d.action}
            )
        if d.action == "timeout":
            if self._on_fault is not None:
                self._on_fault(self.name, "timeout")
            # hang well past any sane deadline; cancellable, so an expired
            # budget reclaims the subtree instead of waiting out the hang
            await asyncio.sleep(self.schedule.spec.hang_s)
            raise APIException(
                ErrorCode.ENGINE_MICROSERVICE_ERROR,
                f"chaos: injected timeout in '{self.name}'",
            )
        if d.action == "error":
            if self._on_fault is not None:
                self._on_fault(self.name, "error")
            raise APIException(
                ErrorCode.ENGINE_MICROSERVICE_ERROR,
                f"chaos: injected fault in '{self.name}'",
            )

    async def transform_input(self, msg: SeldonMessage) -> SeldonMessage:
        await self._perturb()
        return await self.inner.transform_input(msg)

    async def transform_output(self, msg: SeldonMessage) -> SeldonMessage:
        await self._perturb()
        return await self.inner.transform_output(msg)

    async def route(self, msg: SeldonMessage) -> int:
        await self._perturb()
        return await self.inner.route(msg)

    async def aggregate(self, msgs: Sequence[SeldonMessage]) -> SeldonMessage:
        await self._perturb()
        return await self.inner.aggregate(msgs)

    async def send_feedback(self, feedback: Feedback, routing: int) -> None:
        await self.inner.send_feedback(feedback, routing)


@dataclasses.dataclass(frozen=True)
class DecodeFaultSpec:
    """Decode-tier fault profile for ONE scheduler replica.

    Round ordinals are 1-based and count ACTIVE decode rounds from the
    moment ``install_decode_faults`` runs, so a mid-soak installation kills
    the replica's very next round with ``hang_at_round=1`` /
    ``oom_at_round=1`` regardless of how long it has been serving. Probe
    ordinals count ``health_probe`` calls the same way. Every decision is a
    pure function of (spec, call ordinal): reruns replay the identical
    fault sequence, which is what lets the migration oracle compare a
    killed run against an uninterrupted one token for token.
    """

    hang_at_round: int = 0  # decode round that stalls (0 = never)
    hang_s: float = 30.0  # how long the hung round sleeps
    oom_at_round: int = 0  # round whose KV write hits an induced page-OOM
    readback_stall_ms: float = 0.0  # added stall per device readback
    stall_from_round: int = 0  # first round the readback stall applies (0 = never)
    drop_health_from: int = 0  # first health probe to drop (0 = never)
    drop_health_count: int = 0  # probes dropped from there (0 = all of them)
    seed: int = 0


class DecodeFaultState:
    """Deterministic decode-tier fault driver (the continuous-batching twin
    of FaultSchedule). The scheduler consults it at three hook points — top
    of each active round, each device readback, and each health probe — and
    the state counts those calls so decisions depend only on the spec and
    the ordinal, never on wall clock."""

    def __init__(self, spec: DecodeFaultSpec):
        self.spec = spec
        self._lock = threading.Lock()
        self.rounds = 0
        self.probes = 0
        self.injected = 0

    def round_decision(self) -> FaultDecision:
        with self._lock:
            self.rounds += 1
            s = self.spec
            if s.hang_at_round > 0 and self.rounds == s.hang_at_round:
                self.injected += 1
                return FaultDecision("hang", s.hang_s)
            if s.oom_at_round > 0 and self.rounds == s.oom_at_round:
                self.injected += 1
                return FaultDecision("oom")
            return FaultDecision("ok")

    def readback_stall_s(self) -> float:
        with self._lock:
            s = self.spec
            if (
                s.readback_stall_ms > 0
                and s.stall_from_round > 0
                and self.rounds >= s.stall_from_round
            ):
                self.injected += 1
                return s.readback_stall_ms / 1000.0
            return 0.0

    def health_drop(self) -> bool:
        with self._lock:
            self.probes += 1
            s = self.spec
            if s.drop_health_from <= 0 or self.probes < s.drop_health_from:
                return False
            if (
                s.drop_health_count > 0
                and self.probes >= s.drop_health_from + s.drop_health_count
            ):
                return False
            self.injected += 1
            return True


def install_decode_faults(scheduler, spec: DecodeFaultSpec) -> DecodeFaultState:
    """Arm a DecodeScheduler (one fleet replica) with a decode-tier fault
    profile. Mirrors install_faults: the scheduler keeps doing its real
    work, the state object is returned so chaos tests can read
    .rounds/.probes/.injected, and installing over a previous profile
    replaces it (the soak kill flag installs mid-run)."""
    state = DecodeFaultState(spec)
    scheduler._faults = state
    return state


def install_faults(
    executor, faults: dict[str, FaultSpec], on_fault=None
) -> dict[str, FaultSchedule]:
    """Wrap named nodes of a BUILT executor in ChaosUnits. Returns the live
    schedules keyed by node name (tests read .calls/.injected off them).
    Unknown node names are an error — a chaos test silently injecting into
    nothing would 'prove' resilience vacuously. ``on_fault`` defaults to
    the executor's resilience event sink, so injected faults tick
    seldon_tpu_faults_injected_total without every caller re-wiring it."""
    if on_fault is None:
        on_fault = executor._events.fault_injected
    schedules: dict[str, FaultSchedule] = {}
    nodes = {n.name: n for n in executor.root.walk()}
    for name, spec in faults.items():
        node = nodes.get(name)
        if node is None:
            raise ValueError(
                f"install_faults: no node '{name}' in graph (have: {sorted(nodes)})"
            )
        schedule = FaultSchedule(spec)
        node.unit = ChaosUnit(node.unit, schedule, on_fault=on_fault)
        schedules[name] = schedule
    return schedules
