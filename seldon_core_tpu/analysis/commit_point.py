"""CP*: commit-point discipline for per-round scheduler state.

The PR 9 drift class: ``stat_occupancy_sum`` was once updated at two
sites (the spec and plain decode paths) that could silently diverge;
the fix funneled every per-round commit through ONE ``_commit_round``
point. These rules keep that invariant structural:

- CP001: in any class defining ``_commit_round``, an attribute that
  ``_commit_round`` mutates is ROUND-COMMITTED state — mutating it from
  any other method (``_round_reset`` and ``__init__`` excepted) recreates
  the two-site drift hazard.
- CP002: in an ``async`` method, writing the same ``self.*`` attribute on
  both sides of an ``await`` leaves a window where another coroutine
  observes (or interleaves its own write into) a half-updated invariant.
  Writes inside an ``async with self.<lock>`` block are exempt; loop
  bodies are walked linearly (no wrap-around), so a single write site
  inside a loop does not flag.
- CP003: the pipelined scheduler's SHADOW round state (``self._pending*``
  — admissions/input plans decided under an in-flight dispatch, PR 13)
  gets the same single-writer discipline ``_commit_round`` state gets: in
  a class that defines the reconcile funnel (``_apply_pending``) or a
  pipeline builder (``_pipeline_*``), a ``_pending*`` attribute may be
  mutated ONLY by the builders (``_pipeline_*``), the reconcile funnel
  (``_apply_pending``), ``__init__``, and ``_round_reset``. A write from
  anywhere else — including mutating calls like ``.append``/``.clear``,
  which plain store analysis misses — re-opens the speculate-vs-commit
  drift the shadow state exists to prevent.
- CP004: the replica lifecycle funnel (``self._replica_states`` — the
  fleet's up/draining/evicted/down machine, serving/affinity_router.py)
  gets the same discipline: in a class that defines the transition funnel
  (``_set_replica_state``), the state list may be mutated ONLY by
  ``__init__`` and the funnel itself — eligibility flips, lifecycle
  metrics, and flight-recorder fields all hang off the transition, so a
  write from anywhere else ships a half-applied transition.
"""

from __future__ import annotations

import ast
import re

from seldon_core_tpu.analysis.core import ParsedFile, Project
from seldon_core_tpu.analysis.model import Finding

_EXEMPT_METHODS = ("__init__", "_commit_round", "_round_reset")

# CP003: sanctioned writers of self._pending* shadow state — the pipeline
# builders by prefix, the reconcile funnel, and the init/reset funnels
_PENDING_PREFIX = "_pending"
_PENDING_WRITER_PREFIX = "_pipeline_"
_PENDING_WRITERS = ("__init__", "_round_reset", "_apply_pending")
# method calls that mutate their receiver (list/deque/dict/set mutators) —
# a ``self._pending_x.append(...)`` is a shadow-state write even though no
# ast.Assign exists
_MUTATING_CALLS = frozenset(
    (
        "append", "appendleft", "extend", "extendleft", "insert", "remove",
        "pop", "popleft", "popitem", "clear", "add", "discard", "update",
        "setdefault", "sort", "reverse",
    )
)

# CP004: the replica lifecycle list and its single sanctioned funnel
_LIFECYCLE_ATTR = "_replica_states"
_LIFECYCLE_FUNNEL = "_set_replica_state"
_LIFECYCLE_WRITERS = ("__init__", _LIFECYCLE_FUNNEL)


def _self_attr_writes(stmt: ast.stmt) -> list[tuple[str, ast.AST]]:
    """(attr, node) for every ``self.X`` / ``self.X[...]`` store in one
    statement (no recursion into nested statements)."""
    out: list[tuple[str, ast.AST]] = []
    targets: list[ast.expr] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for t in targets:
        for el in ast.walk(t):
            node = el
            if isinstance(node, ast.Starred):
                node = node.value
            if isinstance(node, ast.Subscript):
                node = node.value
            if (
                isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                out.append((node.attr, stmt))
    return out


def _pending_writes(fn: ast.AST) -> list[tuple[str, ast.AST]]:
    """(attr, node) for every mutation of a ``self._pending*`` attribute
    inside ``fn``: plain/aug/ann stores (via _self_attr_writes) plus
    mutating method calls (``self._pending_x.append(...)``)."""
    out: list[tuple[str, ast.AST]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.stmt):
            for attr, site in _self_attr_writes(node):
                if attr.startswith(_PENDING_PREFIX):
                    out.append((attr, site))
        if isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in _MUTATING_CALLS
                and isinstance(f.value, ast.Attribute)
                and isinstance(f.value.value, ast.Name)
                and f.value.value.id == "self"
                and f.value.attr.startswith(_PENDING_PREFIX)
            ):
                out.append((f.value.attr, node))
    return out


class CommitPointPass:
    name = "commit-point"
    rules = {
        "CP001": "round-committed attribute mutated outside _commit_round/_round_reset",
        "CP002": "same self.* attribute written on both sides of an await without a lock",
        "CP003": "shadow/pending round state mutated outside the pipeline builders and _apply_pending",
        "CP004": "replica lifecycle state mutated outside the _set_replica_state funnel",
    }

    def run(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for pf in project.files:
            for node in ast.walk(pf.tree):
                if isinstance(node, ast.ClassDef):
                    self._check_class(pf, node, findings)
        return findings

    # ------------------------------------------------------------ CP001
    def _check_class(
        self, pf: ParsedFile, cls: ast.ClassDef, findings: list[Finding]
    ) -> None:
        methods = [
            n
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        by_name = {m.name: m for m in methods}
        commit = by_name.get("_commit_round")
        if commit is not None:
            protected: set[str] = set()
            for stmt in ast.walk(commit):
                for attr, _ in _self_attr_writes(stmt):
                    protected.add(attr)
            if protected:
                for m in methods:
                    if m.name in _EXEMPT_METHODS:
                        continue
                    for stmt in ast.walk(m):
                        for attr, site in _self_attr_writes(stmt):
                            if attr in protected:
                                findings.append(
                                    Finding(
                                        rule="CP001",
                                        path=pf.path,
                                        line=site.lineno,
                                        col=site.col_offset,
                                        message=(
                                            f"`self.{attr}` is round-committed "
                                            f"state (mutated by `_commit_round`) "
                                            f"but is also mutated in "
                                            f"`{cls.name}.{m.name}` — the "
                                            "two-site drift hazard"
                                        ),
                                        hint=(
                                            "funnel the update through "
                                            "_commit_round (accumulate into a "
                                            "_rb_* field reset by _round_reset)"
                                        ),
                                        symbol=f"{cls.name}.{m.name}",
                                    )
                                )
        self._check_pending(pf, cls, methods, findings)
        self._check_lifecycle(pf, cls, methods, findings)
        for m in methods:
            if isinstance(m, ast.AsyncFunctionDef):
                self._check_async(pf, cls, m, findings)

    # ------------------------------------------------------------ CP003
    def _check_pending(
        self,
        pf: ParsedFile,
        cls: ast.ClassDef,
        methods: list,
        findings: list[Finding],
    ) -> None:
        # the rule engages only on the pipelined-scheduler SHAPE: a class
        # with the reconcile funnel or a pipeline builder. A class that
        # happens to name an attribute `_pending_x` without that state
        # machine is left alone.
        if not any(
            m.name == "_apply_pending"
            or m.name.startswith(_PENDING_WRITER_PREFIX)
            for m in methods
        ):
            return
        for m in methods:
            if (
                m.name in _PENDING_WRITERS
                or m.name.startswith(_PENDING_WRITER_PREFIX)
            ):
                continue
            for attr, site in _pending_writes(m):
                findings.append(
                    Finding(
                        rule="CP003",
                        path=pf.path,
                        line=site.lineno,
                        col=site.col_offset,
                        message=(
                            f"`self.{attr}` is shadow/pending round state "
                            f"but is mutated in `{cls.name}.{m.name}` — "
                            "only the pipeline builders (`_pipeline_*`), "
                            "`_apply_pending`, `__init__`, and "
                            "`_round_reset` may write it (the speculate-"
                            "vs-commit drift hazard)"
                        ),
                        hint=(
                            "build the state in a `_pipeline_*` method and "
                            "consume it through `_apply_pending` (or a "
                            "`_pipeline_take_*` accessor), or rename the "
                            "attribute out of the `_pending` namespace if "
                            "it is not shadow state"
                        ),
                        symbol=f"{cls.name}.{m.name}",
                    )
                )

    # ------------------------------------------------------------ CP004
    def _check_lifecycle(
        self,
        pf: ParsedFile,
        cls: ast.ClassDef,
        methods: list,
        findings: list[Finding],
    ) -> None:
        # engages only on classes defining the transition funnel — a class
        # that happens to name an attribute `_replica_states` without the
        # state machine is left alone (the CP003 shape-gating pattern)
        if not any(m.name == _LIFECYCLE_FUNNEL for m in methods):
            return
        for m in methods:
            if m.name in _LIFECYCLE_WRITERS:
                continue
            sites: list[tuple[str, ast.AST]] = []
            for node in ast.walk(m):
                if isinstance(node, ast.stmt):
                    sites += [
                        (a, s)
                        for a, s in _self_attr_writes(node)
                        if a == _LIFECYCLE_ATTR
                    ]
                if isinstance(node, ast.Call):
                    f = node.func
                    if (
                        isinstance(f, ast.Attribute)
                        and f.attr in _MUTATING_CALLS
                        and isinstance(f.value, ast.Attribute)
                        and isinstance(f.value.value, ast.Name)
                        and f.value.value.id == "self"
                        and f.value.attr == _LIFECYCLE_ATTR
                    ):
                        sites.append((f.value.attr, node))
            for attr, site in sites:
                findings.append(
                    Finding(
                        rule="CP004",
                        path=pf.path,
                        line=site.lineno,
                        col=site.col_offset,
                        message=(
                            f"`self.{attr}` is replica lifecycle state but "
                            f"is mutated in `{cls.name}.{m.name}` — only "
                            f"`{_LIFECYCLE_FUNNEL}` and `__init__` may "
                            "write it (eligibility/metrics/flight fields "
                            "hang off the transition; a direct write ships "
                            "a half-applied one)"
                        ),
                        hint=(
                            f"route the transition through "
                            f"`{_LIFECYCLE_FUNNEL}` (it extends the list "
                            "for new arms itself)"
                        ),
                        symbol=f"{cls.name}.{m.name}",
                    )
                )

    # ------------------------------------------------------------ CP002
    def _check_async(
        self,
        pf: ParsedFile,
        cls: ast.ClassDef,
        fn: ast.AsyncFunctionDef,
        findings: list[Finding],
    ) -> None:
        # attr -> first await-epoch it was written in; flag the first write
        # in a LATER epoch (a write before and after some await)
        first_epoch: dict[str, int] = {}
        flagged: set[str] = set()
        epoch = 0

        def has_await(node: ast.AST) -> bool:
            return any(isinstance(n, ast.Await) for n in ast.walk(node))

        def locked(item: ast.withitem) -> bool:
            # async with self.<something lock-like>: the guarded block's
            # writes are safe — the lock IS the commit funnel. Only
            # name-plausible locks qualify; `async with self.session:`
            # (transports, transactions) provides no mutual exclusion and
            # its body is analyzed like any other statements.
            e = item.context_expr
            if isinstance(e, ast.Call):
                e = e.func
            return (
                isinstance(e, ast.Attribute)
                and isinstance(e.value, ast.Name)
                and e.value.id == "self"
                and re.search(r"lock|mutex|sem|cond", e.attr, re.IGNORECASE)
                is not None
            )

        def note_writes(stmt: ast.stmt) -> None:
            nonlocal epoch
            # the awaited RHS runs BEFORE the store: bump the epoch first
            # so `self.x = await f()` counts as a post-await write
            if has_await(stmt):
                epoch += 1
            if (
                isinstance(stmt, ast.Assign)
                and isinstance(stmt.value, ast.Constant)
                and stmt.value.value is None
            ):
                return  # `self.x = None` is sentinel init, not a torn invariant
            for attr, site in _self_attr_writes(stmt):
                prev = first_epoch.setdefault(attr, epoch)
                if prev != epoch and attr not in flagged:
                    flagged.add(attr)
                    findings.append(
                        Finding(
                            rule="CP002",
                            path=pf.path,
                            line=site.lineno,
                            col=site.col_offset,
                            message=(
                                f"`self.{attr}` is written on both sides of "
                                f"an await in async "
                                f"`{cls.name}.{fn.name}` — another coroutine "
                                "can observe or interleave with the "
                                "half-updated state"
                            ),
                            hint=(
                                "hold an asyncio.Lock across the writes, or "
                                "funnel both into one commit point after "
                                "the await"
                            ),
                            symbol=f"{cls.name}.{fn.name}",
                        )
                    )

        def walk(body: list[ast.stmt]) -> None:
            nonlocal epoch
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(stmt, (ast.AsyncWith, ast.With)):
                    if isinstance(stmt, ast.AsyncWith):
                        epoch += 1  # acquiring awaits
                        if any(locked(i) for i in stmt.items):
                            if any(has_await(s) for s in stmt.body):
                                epoch += 1
                            continue  # guarded writes are safe
                    # non-lock context managers (sessions, transactions)
                    # provide no exclusion — analyze the body normally
                    walk(stmt.body)
                elif isinstance(stmt, ast.If):
                    # mutually exclusive branches do NOT see each other's
                    # awaits: walk each from the same starting epoch and
                    # join (max) afterward, else an await in the if-body
                    # falsely elevates the else-body's writes
                    if has_await(stmt.test):
                        epoch += 1
                    start = epoch
                    walk(stmt.body)
                    after_body = epoch
                    epoch = start
                    walk(stmt.orelse)
                    epoch = max(epoch, after_body)
                elif isinstance(stmt, (ast.For, ast.While, ast.AsyncFor)):
                    if isinstance(stmt, ast.AsyncFor) or has_await(
                        stmt.iter if isinstance(stmt, (ast.For, ast.AsyncFor))
                        else stmt.test
                    ):
                        epoch += 1
                    walk(stmt.body)
                    walk(stmt.orelse)
                elif isinstance(stmt, ast.Try):
                    # an exception can fire BEFORE any of the body's
                    # awaits ran, so handlers walk from the body-START
                    # epoch (error-path recovery writes are not "after
                    # the await" on every execution); join (max) after
                    start = epoch
                    walk(stmt.body)
                    body_end = epoch
                    ends = [body_end]
                    for h in stmt.handlers:
                        epoch = start
                        walk(h.body)
                        ends.append(epoch)
                    epoch = body_end
                    walk(stmt.orelse)
                    ends.append(epoch)
                    epoch = max(ends)
                    walk(stmt.finalbody)
                else:
                    note_writes(stmt)
        walk(fn.body)
