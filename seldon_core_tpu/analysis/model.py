"""Finding/baseline/suppression model shared by every lint pass.

A ``Finding`` is one defect at one source location. Identity for baseline
matching is (rule, path, symbol) — NOT the line number, so a checked-in
baseline survives unrelated edits above the finding. ``symbol`` is the
enclosing ``Class.method`` qualname when the finding sits inside one, else
the offending literal/name itself.
"""

from __future__ import annotations

import json
import re
from dataclasses import asdict, dataclass, field

SEV_ERROR = "error"
SEV_WARNING = "warning"

# trailing-comment suppression: "# lint: ignore" (everything) or
# "# lint: ignore[TS001,CP002]" (listed rules only)
_IGNORE_RE = re.compile(r"#\s*lint:\s*ignore(?:\[([A-Za-z0-9_,\s]+)\])?")


@dataclass
class Finding:
    rule: str  # e.g. "TS001"
    path: str  # repo-relative posix path
    line: int  # 1-based
    col: int  # 0-based (ast convention)
    message: str
    hint: str = ""  # how to fix it, one line
    severity: str = SEV_ERROR
    symbol: str = ""  # enclosing qualname or offending literal

    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.symbol)

    def to_dict(self) -> dict:
        return asdict(self)

    def render(self) -> str:
        out = f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"
        if self.hint:
            out += f" [hint: {self.hint}]"
        return out


def parse_suppressions(source: str) -> dict[int, frozenset[str] | None]:
    """Map 1-based line -> suppressed rule set (None = every rule).

    Scans text lines rather than the token stream: a ``# lint: ignore``
    inside a string literal would be honored too, which is harmless (the
    marker is namespaced enough not to occur by accident) and keeps this
    O(lines) with no tokenizer dependency."""
    out: dict[int, frozenset[str] | None] = {}
    for i, text in enumerate(source.splitlines(), start=1):
        m = _IGNORE_RE.search(text)
        if not m:
            continue
        rules = m.group(1)
        if rules is None:
            out[i] = None
        else:
            out[i] = frozenset(r.strip() for r in rules.split(",") if r.strip())
    return out


def suppressed(finding: Finding, marks: dict[int, frozenset[str] | None]) -> bool:
    mark = marks.get(finding.line)
    if mark is None and finding.line in marks:
        return True  # bare "# lint: ignore"
    return mark is not None and finding.rule in mark


@dataclass
class Baseline:
    """Checked-in deliberate exceptions. Each entry suppresses EVERY
    finding matching its (rule, path, symbol) triple — count-insensitive
    on purpose: the baseline records "this pattern here is accepted", not
    a brittle occurrence tally."""

    entries: list[dict] = field(default_factory=list)

    @staticmethod
    def load(path: str) -> "Baseline":
        with open(path) as f:
            obj = json.load(f)
        entries = obj.get("entries", []) if isinstance(obj, dict) else obj
        for e in entries:
            if not isinstance(e, dict) or not {"rule", "path", "symbol"} <= set(e):
                raise ValueError(
                    f"{path}: baseline entries need rule/path/symbol, got {e!r}"
                )
        return Baseline(entries=entries)

    @staticmethod
    def from_findings(findings: list[Finding]) -> "Baseline":
        seen: set[tuple[str, str, str]] = set()
        entries = []
        for f in findings:
            if f.key() in seen:
                continue
            seen.add(f.key())
            entries.append(
                {
                    "rule": f.rule,
                    "path": f.path,
                    "symbol": f.symbol,
                    "reason": "",
                }
            )
        return Baseline(entries=entries)

    def dump(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"version": 1, "entries": self.entries}, f, indent=2)
            f.write("\n")

    def _keys(self) -> set[tuple[str, str, str]]:
        return {(e["rule"], e["path"], e["symbol"]) for e in self.entries}

    def split(
        self, findings: list[Finding]
    ) -> tuple[list[Finding], list[Finding], list[dict]]:
        """(new, baselined, stale_entries): ``new`` fails the build,
        ``baselined`` is reported informationally, ``stale_entries`` are
        baseline rows that matched nothing (candidates for deletion)."""
        keys = self._keys()
        new = [f for f in findings if f.key() not in keys]
        old = [f for f in findings if f.key() in keys]
        hit = {f.key() for f in old}
        stale = [
            e
            for e in self.entries
            if (e["rule"], e["path"], e["symbol"]) not in hit
        ]
        return new, old, stale
