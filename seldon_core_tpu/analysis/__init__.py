"""Invariant linter: AST static analysis for the repo's own bug classes.

The serving tier rests on invariants that unit tests can only check after
the fact: "zero recompiles" is guarded by warmed-ladder tests, the flight
recorder *discovered* the ``stat_occupancy_sum`` two-site drift rather than
preventing it, and the env/knob/metric registries (utils/env.py,
graph/spec.py + graph/validation.py, metrics/registry.py) drift silently as
modules grow. This package turns those invariants into review-time checks:

- ``trace_safety``  (TS*): host-sync / recompile hazards inside functions
  reachable from a ``jax.jit`` / fused-program definition.
- ``commit_point``  (CP*): per-round scheduler state must funnel through
  ``_commit_round``/``_round_reset``; ``self.*`` state mutated on both
  sides of an ``await`` without a lock is an interleaving hazard.
- ``registry_drift`` (RD*): owned env names read outside utils/env.py,
  ``seldon_tpu_*`` metric names minted outside metrics/registry.py, and
  TpuSpec knobs with no graph/validation.py rule.
- ``phase_registry`` (PH*): every ``_timed_call``/``_phase`` site names a
  registered ``F_*``/``P_*`` flight constant, and every registered
  constant is consumed by at least one instrumentation site.
- ``ladder``        (LC*): every fused program handle / bucket ladder used
  at a dispatch site must be warmed by ``warmup()`` and (for programs)
  reported by ``compile_counts()``.

Pure stdlib (``ast``) — no JAX import, so the CLI and the tier-1 guard test
stay fast. CLI: ``python -m seldon_core_tpu.tools.lint`` (docs/linting.md).

Suppression: a trailing ``# lint: ignore[RULE,...]`` (or bare
``# lint: ignore``) comment silences findings on that line; deliberate
whole-tree exceptions live in the checked-in ``lint-baseline.json``.
"""

from seldon_core_tpu.analysis.core import (
    ALL_PASSES,
    Project,
    lint_paths,
    lint_sources,
    rule_catalogue,
)
from seldon_core_tpu.analysis.model import Baseline, Finding

__all__ = [
    "ALL_PASSES",
    "Baseline",
    "Finding",
    "Project",
    "lint_paths",
    "lint_sources",
    "rule_catalogue",
]
