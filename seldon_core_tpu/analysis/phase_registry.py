"""PH*: the flight recorder's family/phase registries, held at zero drift.

PR 11 added per-round host-phase attribution (``PHASES`` / ``P_*``)
beside PR 9's per-family dispatch split (``FAMILIES`` / ``F_*``); both
registries live in telemetry/flight.py and are consumed by the decode
scheduler's ``_timed_call(F_X, ...)`` / ``with self._phase(P_X):``
sites. Two drift modes matter (the registry-drift family's lesson
applied to the new registry):

- PH001: a ``_timed_call`` / ``_phase`` site whose first argument is not
  a registered ``F_*``/``P_*`` constant. A raw index compiles and runs
  fine — it just silently mis-attributes the round (or walks off the
  fixed array), and nothing downstream can tell.
- PH002: a registered constant that no site outside the registry module
  consumes. An uninstrumented phase/family reads as a permanently-zero
  column in every frame, aggregate, and health read-out — "this phase is
  free" when the truth is "this phase is not measured".
"""

from __future__ import annotations

import ast
import re

from seldon_core_tpu.analysis.core import ParsedFile, Project
from seldon_core_tpu.analysis.model import Finding

# the registry module: the file defining the FAMILIES/PHASES tuples
REGISTRY_SUFFIX = "telemetry/flight.py"
REGISTRY_TUPLES = ("FAMILIES", "PHASES")
_CONST_RE = re.compile(r"^[FP]_[A-Z0-9_]+$")
# call names whose FIRST argument must be a registry constant
TIMER_FUNCS = ("_timed_call", "_phase")


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _is_registry_const(arg: ast.expr) -> bool:
    if isinstance(arg, ast.Name):
        return _CONST_RE.match(arg.id) is not None
    if isinstance(arg, ast.Attribute):
        return _CONST_RE.match(arg.attr) is not None
    return False


class PhaseRegistryPass:
    name = "phase-registry"
    rules = {
        "PH001": "_timed_call/_phase site whose family/phase is not a registered F_*/P_* constant",
        "PH002": "registered F_*/P_* constant no instrumentation site consumes",
    }

    def run(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        registry = next(
            (f for f in project.files if f.path.endswith(REGISTRY_SUFFIX)),
            None,
        )
        for pf in project.files:
            # the analysis package itself spells the patterns out
            if "/analysis/" in f"/{pf.path}":
                continue
            self._check_sites(pf, findings)
        if registry is not None:
            self._check_unused(project, registry, findings)
        return findings

    # ------------------------------------------------------------ PH001
    def _check_sites(self, pf: ParsedFile, findings: list[Finding]) -> None:
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = _call_name(node)
            if fname not in TIMER_FUNCS or not node.args:
                continue
            arg = node.args[0]
            if _is_registry_const(arg):
                continue
            rendered = ast.unparse(arg) if hasattr(ast, "unparse") else "<expr>"
            findings.append(
                Finding(
                    rule="PH001",
                    path=pf.path,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"`{fname}({rendered}, ...)` — the family/phase "
                        "argument must be a registered F_*/P_* constant "
                        "from telemetry/flight.py; a raw index silently "
                        "mis-attributes the round"
                    ),
                    hint=(
                        "import the constant: `from seldon_core_tpu"
                        ".telemetry.flight import F_STEP` (or P_*) and "
                        "pass it by name"
                    ),
                    symbol=pf.qualname(node) or fname,
                )
            )

    # ------------------------------------------------------------ PH002
    def _check_unused(
        self, project: Project, registry: ParsedFile, findings: list[Finding]
    ) -> None:
        # only enforce when the registry file really is the flight module
        # shape (defines one of the registry tuples) — a fixture that
        # happens to end in the suffix without registries is left alone
        tuple_names = {
            t.id
            for stmt in registry.tree.body
            if isinstance(stmt, ast.Assign)
            for t in stmt.targets
            if isinstance(t, ast.Name)
        }
        if not tuple_names.intersection(REGISTRY_TUPLES):
            return
        defined: dict[str, int] = {}
        for stmt in registry.tree.body:
            if not isinstance(stmt, ast.Assign):
                continue
            for target in stmt.targets:
                elts = target.elts if isinstance(target, ast.Tuple) else [target]
                for el in elts:
                    if isinstance(el, ast.Name) and _CONST_RE.match(el.id):
                        defined[el.id] = stmt.lineno
        if not defined:
            return
        used: set[str] = set()
        for pf in project.files:
            if pf is registry or "/analysis/" in f"/{pf.path}":
                continue
            for node in ast.walk(pf.tree):
                if (
                    isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in defined
                ):
                    used.add(node.id)
                elif isinstance(node, ast.Attribute) and node.attr in defined:
                    used.add(node.attr)
        for name in sorted(set(defined) - used):
            findings.append(
                Finding(
                    rule="PH002",
                    path=registry.path,
                    line=defined[name],
                    col=0,
                    message=(
                        f"registered constant `{name}` is never consumed "
                        "by an instrumentation site — its column is "
                        "permanently zero in every frame/aggregate/health "
                        "read-out, which reads as 'free' instead of 'not "
                        "measured'"
                    ),
                    hint=(
                        "instrument the phase/family (a `with self._phase("
                        f"{name}):` block or `_timed_call({name}, ...)` "
                        "site), or remove it from the registry"
                    ),
                    symbol=name,
                )
            )
