"""RD*: the three registries this repo centralizes, held at zero drift.

- RD001: an OWNED env-var name (``ENGINE_*``, ``SELDON_TPU_*``,
  ``PREDICTIVE_UNIT_*``, ``SELDON_DEPLOYMENT_*``, ``LOADTEST_*``,
  ``TEST_CLIENT_*``, ``PERSISTENCE_*``) read from ``os.environ`` /
  ``os.getenv`` as a raw string literal outside utils/env.py. Raw reads
  are how the registry drifted to ~10 call sites historically — a typo'd
  name fails silently to the default. External names (``KUBERNETES_*``,
  ``XLA_FLAGS``, ``JAX_*``) are not ours to register and are ignored.
- RD002: a ``seldon_tpu_*`` metric name minted outside
  metrics/registry.py — dashboards/alerts key on these strings, so every
  spelling must live in the one registry file (docstrings exempt).
- RD003: a ``TpuSpec`` knob (graph/spec.py) that graph/validation.py
  never mentions — config that validation cannot reject drifts into
  "silently ignored". Deliberately unconstrained knobs are acknowledged
  in validation.py's ``UNCONSTRAINED_KNOBS`` tuple, which counts as a
  mention.
"""

from __future__ import annotations

import ast
import re

from seldon_core_tpu.analysis.core import ParsedFile, Project
from seldon_core_tpu.analysis.model import Finding

OWNED_ENV_PREFIXES = (
    "ENGINE_",
    "SELDON_TPU_",
    "PREDICTIVE_UNIT_",
    "SELDON_DEPLOYMENT_",
    "LOADTEST_",
    "TEST_CLIENT_",
    "PERSISTENCE_",
)
METRIC_PREFIX = "seldon_tpu_"
ENV_REGISTRY = "utils/env.py"
METRIC_REGISTRY = "metrics/registry.py"
SPEC_FILE = "graph/spec.py"
VALIDATION_FILE = "graph/validation.py"


def _docstring_nodes(tree: ast.Module) -> set[int]:
    """ids of Constant nodes that are docstrings (exempt from RD002)."""
    out: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(
            node, (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            body = node.body
            if (
                body
                and isinstance(body[0], ast.Expr)
                and isinstance(body[0].value, ast.Constant)
                and isinstance(body[0].value.value, str)
            ):
                out.add(id(body[0].value))
    return out


def _is_environ_read(pf: ParsedFile, call_or_sub: ast.AST) -> ast.expr | None:
    """The key expression when the node reads the process environment:
    os.environ[k] / os.environ.get(k,...) / os.getenv(k,...) /
    environ.get(k) after `from os import environ`."""

    def is_environ(e: ast.expr) -> bool:
        if isinstance(e, ast.Attribute) and e.attr == "environ":
            return isinstance(e.value, ast.Name) and pf.import_mod.get(
                e.value.id
            ) == "os"
        if isinstance(e, ast.Name):
            return pf.import_from.get(e.id) == ("os", "environ")
        return False

    if isinstance(call_or_sub, ast.Subscript) and is_environ(call_or_sub.value):
        return call_or_sub.slice
    if isinstance(call_or_sub, ast.Call):
        f = call_or_sub.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr in ("get", "setdefault", "pop")
            and is_environ(f.value)
            and call_or_sub.args
        ):
            return call_or_sub.args[0]
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "getenv"
            and isinstance(f.value, ast.Name)
            and pf.import_mod.get(f.value.id) == "os"
            and call_or_sub.args
        ):
            return call_or_sub.args[0]
        if (
            isinstance(f, ast.Name)
            and pf.import_from.get(f.id) == ("os", "getenv")
            and call_or_sub.args
        ):
            return call_or_sub.args[0]
    return None


class RegistryDriftPass:
    name = "registry-drift"
    rules = {
        "RD001": "owned env name read raw outside utils/env.py",
        "RD002": "seldon_tpu_* metric name minted outside metrics/registry.py",
        "RD003": "TpuSpec knob with no graph/validation.py rule",
    }

    def run(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for pf in project.files:
            # the analysis package itself spells the rule patterns out
            linter_self = "/analysis/" in f"/{pf.path}"
            if not pf.path.endswith(ENV_REGISTRY) and not linter_self:
                self._check_env(pf, findings)
            if not pf.path.endswith(METRIC_REGISTRY) and not linter_self:
                self._check_metrics(pf, findings)
        self._check_knobs(project, findings)
        return findings

    # ------------------------------------------------------------ RD001
    def _check_env(self, pf: ParsedFile, findings: list[Finding]) -> None:
        for node in ast.walk(pf.tree):
            key = _is_environ_read(pf, node)
            if (
                key is not None
                and isinstance(key, ast.Constant)
                and isinstance(key.value, str)
                and key.value.startswith(OWNED_ENV_PREFIXES)
            ):
                findings.append(
                    Finding(
                        rule="RD001",
                        path=pf.path,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f'raw environment read of "{key.value}" — owned '
                            "env names live as constants in utils/env.py"
                        ),
                        hint=(
                            "import the constant: `from seldon_core_tpu.utils"
                            f".env import {key.value}`"
                        ),
                        symbol=key.value,
                    )
                )

    # ------------------------------------------------------------ RD002
    def _check_metrics(self, pf: ParsedFile, findings: list[Finding]) -> None:
        docstrings = _docstring_nodes(pf.tree)
        for node in ast.walk(pf.tree):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and node.value.startswith(METRIC_PREFIX)
                and id(node) not in docstrings
            ):
                findings.append(
                    Finding(
                        rule="RD002",
                        path=pf.path,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f'metric-namespace literal "{node.value}" outside '
                            "metrics/registry.py — dashboards key on these "
                            "strings; one registry file owns the spelling"
                        ),
                        hint=(
                            "register the series in metrics/registry.py and "
                            "call it through the Metrics facade"
                        ),
                        symbol=node.value,
                    )
                )

    # ------------------------------------------------------------ RD003
    def _check_knobs(self, project: Project, findings: list[Finding]) -> None:
        spec = next(
            (f for f in project.files if f.path.endswith(SPEC_FILE)), None
        )
        validation = next(
            (f for f in project.files if f.path.endswith(VALIDATION_FILE)), None
        )
        if spec is None or validation is None:
            return  # cross-file leg needs both sides in the lint set
        tpu = next(
            (
                n
                for n in ast.walk(spec.tree)
                if isinstance(n, ast.ClassDef) and n.name == "TpuSpec"
            ),
            None,
        )
        if tpu is None:
            return
        # identifiers are matched exactly; string constants are tokenized
        # on word boundaries, so a knob that is a PREFIX of another knob's
        # name inside an error message ("decode_slo" in "decode_slo_ttft_ms
        # must be >= 0") does not count as covered
        mentioned: set[str] = set()
        for node in ast.walk(validation.tree):
            if isinstance(node, ast.Attribute):
                mentioned.add(node.attr)
            elif isinstance(node, ast.Name):
                mentioned.add(node.id)
            elif isinstance(node, ast.Constant) and isinstance(node.value, str):
                mentioned.update(re.findall(r"[A-Za-z_][A-Za-z0-9_]*", node.value))
        for stmt in tpu.body:
            if not (
                isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            ):
                continue
            knob = stmt.target.id
            if knob not in mentioned:
                findings.append(
                    Finding(
                        rule="RD003",
                        path=spec.path,
                        line=stmt.lineno,
                        col=stmt.col_offset,
                        message=(
                            f"TpuSpec knob `{knob}` has no rule in "
                            "graph/validation.py — misconfiguration would be "
                            "silently ignored instead of rejected"
                        ),
                        hint=(
                            "add a validate_deployment check, or list the "
                            "knob in validation.py's UNCONSTRAINED_KNOBS "
                            "acknowledgment"
                        ),
                        symbol=knob,
                    )
                )
