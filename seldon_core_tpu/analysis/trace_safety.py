"""TS*: host-sync and recompile hazards inside traced code.

Scope: functions reachable from a ``jax.jit``/``pjit`` definition (or
matching the repo's ``_fused_*`` naming convention), followed across
modules through ``from m import f`` / ``import m`` call edges. Parameters
are assumed traced unless the jit site marks them static
(``static_argnums``/``static_argnames``) — and staticness propagates
through call edges: a callee parameter fed only static values / literals
at every call site stays static, so per-depth Python loops over a static
``SpecTree`` (models/spec_tree.py) do not false-positive.

Inside traced code a simple forward taint walk tracks which locals carry
traced values (``.shape``/``.ndim``/``.dtype``/``len()`` results are
static by construction) and flags the operations that force a host sync,
break tracing, or bake a recompile per distinct value:

- TS001 host sync: ``np.*`` on a traced value, ``.item()/.tolist()``,
  ``jax.block_until_ready`` / ``jax.device_get`` (always wrong in-trace).
- TS002 Python control flow on a traced value (``if``/``while``/
  ``for``/ternary/``assert`` — needs concrete values, aborts tracing).
- TS003 stringifying a tracer (f-string/print/str) — prints the tracer
  object, not the value; ``jax.debug.print`` is the in-trace tool.
- TS004 ``float()/int()/bool()`` on a traced value — implicit host sync.
- TS005 traced shape fed to a ``jnp`` constructor — a distinct program
  per runtime value, i.e. a hidden recompile per shape.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from seldon_core_tpu.analysis.core import ParsedFile, Project
from seldon_core_tpu.analysis.model import Finding

# attribute reads that yield STATIC (python) values even on a tracer.
# NOT `.at`: `x.at[i].set(v)` returns a traced array — washing taint
# there would blind every TS rule to code built on the update idiom.
_STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "itemsize"})
# builtins whose result is static regardless of argument taint
_STATIC_FUNCS = frozenset({"len", "isinstance", "type", "hasattr", "range"})
# jnp constructors whose first (shape/count) argument must be static
_SHAPE_CTORS = frozenset(
    {"zeros", "ones", "full", "empty", "arange", "eye", "linspace", "tri"}
)
_CAST_FUNCS = frozenset({"float", "int", "bool", "complex"})
_STR_FUNCS = frozenset({"print", "str", "repr", "format"})
_SYNC_METHODS = frozenset({"item", "tolist", "block_until_ready"})


def _module_aliases(pf: ParsedFile, module: str) -> set[str]:
    """Local names bound to ``module`` ('numpy', 'jax.numpy', 'jax')."""
    out = {a for a, m in pf.import_mod.items() if m == module}
    if "." in module:
        parent, _, leaf = module.rpartition(".")
        out |= {
            a for a, (m, n) in pf.import_from.items() if m == parent and n == leaf
        }
    return out


@dataclass
class _Root:
    pf: ParsedFile
    fn: ast.FunctionDef | ast.AsyncFunctionDef
    static: frozenset[str]  # static param names


def _param_names(fn) -> list[str]:
    return [a.arg for a in (fn.args.posonlyargs + fn.args.args)]


def _static_params(fn, call: ast.Call | None) -> frozenset[str]:
    if call is None:
        return frozenset()
    names = _param_names(fn)
    static: set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for v in ast.walk(kw.value):
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    if 0 <= v.value < len(names):
                        static.add(names[v.value])
        elif kw.arg == "static_argnames":
            for v in ast.walk(kw.value):
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    static.add(v.value)
    return frozenset(static)


class TraceSafetyPass:
    name = "trace-safety"
    rules = {
        "TS001": "host sync inside traced code (np.* / .item() / block_until_ready)",
        "TS002": "Python control flow on a traced value",
        "TS003": "stringifying a tracer (f-string / print / str)",
        "TS004": "float()/int()/bool() on a traced value",
        "TS005": "traced shape fed to a jnp constructor (recompile per value)",
    }

    # ------------------------------------------------------------ roots
    def _jit_callee(self, pf: ParsedFile, func: ast.expr) -> bool:
        """Is ``func`` a reference to jax.jit / pjit?"""
        if isinstance(func, ast.Name):
            tgt = pf.import_from.get(func.id)
            return tgt is not None and tgt[0] in ("jax", "jax.experimental.pjit") and (
                tgt[1] in ("jit", "pjit")
            )
        if isinstance(func, ast.Attribute) and func.attr in ("jit", "pjit"):
            base = func.value
            return isinstance(base, ast.Name) and pf.import_mod.get(base.id) in (
                "jax",
                "jax.experimental.pjit",
            )
        return False

    def _collect_roots(self, project: Project) -> list[_Root]:
        roots: list[_Root] = []
        seen: set[int] = set()

        def add(pf, fn, call):
            if id(fn) in seen:
                return
            seen.add(id(fn))
            roots.append(_Root(pf, fn, _static_params(fn, call)))

        for pf in project.files:
            for node in ast.walk(pf.tree):
                # jax.jit(f, ...) with a resolvable first argument
                if (
                    isinstance(node, ast.Call)
                    and self._jit_callee(pf, node.func)
                    and node.args
                    and isinstance(node.args[0], ast.Name)
                ):
                    hit = project.resolve_function(pf, node.args[0].id)
                    if hit is not None:
                        add(hit[0], hit[1], node)
                # @jax.jit / @jit / @partial(jax.jit, ...) decorators
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        if self._jit_callee(pf, dec):
                            add(pf, node, None)
                        elif isinstance(dec, ast.Call):
                            if self._jit_callee(pf, dec.func):
                                add(pf, node, dec)
                            elif (
                                dec.args
                                and self._jit_callee(pf, dec.args[0])
                                # partial(jax.jit, static_argnums=...)
                            ):
                                add(pf, node, dec)
        # naming convention fallback: the _fused_* family is traced even
        # when the jit() wrap is built dynamically. Jit-site roots win so
        # their static_argnums are honored.
        for pf in project.files:
            for fn in pf.functions.values():
                if fn.name.startswith("_fused_") and id(fn) not in seen:
                    add(pf, fn, None)
        return roots

    # -------------------------------------------------------------- run
    def run(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        reported: set[tuple[str, str, int, int]] = set()

        # traced-param masks per function, joined over call sites:
        # param -> True means "some call site feeds this a traced value"
        masks: dict[int, dict[str, bool]] = {}
        nodes: dict[int, tuple[ParsedFile, ast.AST]] = {}
        work: list[int] = []

        def enqueue(pf, fn, traced: dict[str, bool]) -> None:
            key = id(fn)
            cur = masks.get(key)
            if cur is None:
                masks[key] = dict(traced)
                nodes[key] = (pf, fn)
                work.append(key)
                return
            grew = False
            for name, t in traced.items():
                if t and not cur.get(name, False):
                    cur[name] = True
                    grew = True
            if grew and key not in work:
                work.append(key)

        for root in self._collect_roots(project):
            enqueue(
                root.pf,
                root.fn,
                {
                    n: n not in root.static
                    for n in _param_names(root.fn)
                },
            )

        while work:
            key = work.pop()
            pf, fn = nodes[key]
            traced_params = {n for n, t in masks[key].items() if t}
            self._analyze(
                project, pf, fn, traced_params, findings, reported, enqueue
            )
        return findings

    # ---------------------------------------------------- per-function
    def _analyze(
        self, project, pf, fn, traced_params, findings, reported, enqueue
    ) -> None:
        np_alias = _module_aliases(pf, "numpy")
        jnp_alias = _module_aliases(pf, "jax.numpy")
        jax_alias = _module_aliases(pf, "jax")
        tainted: set[str] = set(traced_params)
        qual = pf.qualname(fn)

        def flag(rule: str, node: ast.AST, message: str, hint: str) -> None:
            k = (rule, pf.path, node.lineno, node.col_offset)
            if k in reported:
                return
            reported.add(k)
            findings.append(
                Finding(
                    rule=rule,
                    path=pf.path,
                    line=node.lineno,
                    col=node.col_offset,
                    message=f"{message} (inside traced `{qual}`)",
                    hint=hint,
                    symbol=qual,
                )
            )

        def taint(e: ast.expr) -> bool:
            if isinstance(e, ast.Name):
                return e.id in tainted
            if isinstance(e, ast.Attribute):
                if e.attr in _STATIC_ATTRS:
                    return False
                return taint(e.value)
            if isinstance(e, ast.Subscript):
                return taint(e.value)
            if isinstance(e, ast.Call):
                fname = e.func.id if isinstance(e.func, ast.Name) else None
                if fname in _STATIC_FUNCS or fname in _CAST_FUNCS:
                    # len()/int() results are host ints; the cast itself
                    # is flagged as a sink, not propagated as taint
                    return False
                if (
                    isinstance(e.func, ast.Attribute)
                    and e.func.attr in ("item", "tolist")
                ):
                    return False
                args = list(e.args) + [kw.value for kw in e.keywords]
                return any(taint(a) for a in args) or (
                    isinstance(e.func, ast.Attribute) and taint(e.func.value)
                )
            if isinstance(e, (ast.BinOp,)):
                return taint(e.left) or taint(e.right)
            if isinstance(e, ast.UnaryOp):
                return taint(e.operand)
            if isinstance(e, ast.BoolOp):
                return any(taint(v) for v in e.values)
            if isinstance(e, ast.Compare):
                if all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops):
                    return False  # `x is None` is a static identity check
                return taint(e.left) or any(taint(c) for c in e.comparators)
            if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
                return any(taint(v) for v in e.elts)
            if isinstance(e, ast.Dict):
                return any(taint(v) for v in e.values if v is not None)
            if isinstance(e, ast.IfExp):
                return taint(e.body) or taint(e.orelse) or taint(e.test)
            if isinstance(e, ast.Starred):
                return taint(e.value)
            if isinstance(e, ast.JoinedStr):
                return False
            return False

        def module_of(base: ast.expr) -> str | None:
            if not isinstance(base, ast.Name):
                return None
            if base.id in np_alias:
                return "numpy"
            if base.id in jnp_alias:
                return "jax.numpy"
            if base.id in jax_alias:
                return "jax"
            return None

        def check_call(c: ast.Call) -> None:
            args = list(c.args) + [kw.value for kw in c.keywords]
            any_tainted = any(taint(a) for a in args)
            if isinstance(c.func, ast.Attribute):
                mod = module_of(c.func.value)
                if mod == "numpy" and any_tainted:
                    flag(
                        "TS001",
                        c,
                        f"numpy call `np.{c.func.attr}` on a traced value "
                        "forces a host transfer and breaks tracing",
                        "use the jnp equivalent, or hoist the value out of "
                        "the traced function",
                    )
                elif mod == "jax" and c.func.attr in (
                    "block_until_ready",
                    "device_get",
                ):
                    flag(
                        "TS001",
                        c,
                        f"`jax.{c.func.attr}` inside traced code is a host "
                        "sync at trace time",
                        "sync outside the jitted function (the caller owns "
                        "readback)",
                    )
                elif mod == "jax.numpy" and c.func.attr in _SHAPE_CTORS:
                    if (c.args and taint(c.args[0])) or any(
                        kw.arg == "shape" and taint(kw.value)
                        for kw in c.keywords
                    ):
                        flag(
                            "TS005",
                            c,
                            f"`jnp.{c.func.attr}` with a traced shape "
                            "compiles one program per runtime value",
                            "derive the shape from static `.shape` fields "
                            "or pass it as a static argument",
                        )
                elif c.func.attr in _SYNC_METHODS and taint(c.func.value):
                    flag(
                        "TS001",
                        c,
                        f"`.{c.func.attr}()` on a traced value forces a "
                        "device->host readback",
                        "keep the value on device; read back after the "
                        "jitted call returns",
                    )
            elif isinstance(c.func, ast.Name):
                if c.func.id in _CAST_FUNCS and any_tainted:
                    flag(
                        "TS004",
                        c,
                        f"`{c.func.id}()` on a traced value is an implicit "
                        "host sync",
                        "keep arithmetic in jnp; cast outside the traced "
                        "function",
                    )
                elif c.func.id in _STR_FUNCS and any_tainted:
                    flag(
                        "TS003",
                        c,
                        f"`{c.func.id}()` of a traced value renders the "
                        "tracer object, not the value",
                        "use jax.debug.print for in-trace values",
                    )
                # propagate into resolvable callees with per-arg taint
                hit = project.resolve_function(pf, c.func.id)
                if hit is not None:
                    cpf, cfn = hit
                    names = _param_names(cfn)
                    mask: dict[str, bool] = {}
                    for i, a in enumerate(c.args):
                        if isinstance(a, ast.Starred):
                            break
                        if i < len(names):
                            mask[names[i]] = taint(a)
                    for kw in c.keywords:
                        if kw.arg in names:
                            mask[kw.arg] = taint(kw.value)
                    if any(mask.values()):
                        enqueue(cpf, cfn, mask)
            # module-attribute calls into analyzed modules (import m; m.f())
            if isinstance(c.func, ast.Attribute) and isinstance(
                c.func.value, ast.Name
            ):
                target_mod = pf.import_mod.get(c.func.value.id)
                other = (
                    project.by_module.get(target_mod) if target_mod else None
                )
                if other is not None and c.func.attr in other.functions:
                    cfn = other.functions[c.func.attr]
                    names = _param_names(cfn)
                    mask = {}
                    for i, a in enumerate(c.args):
                        if isinstance(a, ast.Starred):
                            break
                        if i < len(names):
                            mask[names[i]] = taint(a)
                    for kw in c.keywords:
                        if kw.arg in names:
                            mask[kw.arg] = taint(kw.value)
                    if any(mask.values()):
                        enqueue(other, cfn, mask)

        def check_expr(e: ast.expr) -> None:
            for node in ast.walk(e):
                if isinstance(node, ast.Call):
                    check_call(node)
                elif isinstance(node, ast.JoinedStr):
                    if any(
                        taint(v.value)
                        for v in node.values
                        if isinstance(v, ast.FormattedValue)
                    ):
                        flag(
                            "TS003",
                            node,
                            "f-string interpolates a traced value — it "
                            "renders the tracer, not the number",
                            "use jax.debug.print for in-trace values",
                        )
                elif isinstance(node, ast.IfExp) and taint(node.test):
                    flag(
                        "TS002",
                        node,
                        "ternary on a traced condition aborts tracing "
                        "(ConcretizationTypeError)",
                        "use jnp.where / lax.select",
                    )

        def assign_target(t: ast.expr, is_tainted: bool) -> None:
            if isinstance(t, ast.Name):
                if is_tainted:
                    tainted.add(t.id)
                else:
                    tainted.discard(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for el in t.elts:
                    assign_target(el, is_tainted)
            elif isinstance(t, ast.Starred):
                assign_target(t.value, is_tainted)

        def do_body(body: list[ast.stmt]) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue  # nested defs analyzed only if jit-rooted
                if isinstance(stmt, ast.Assign):
                    check_expr(stmt.value)
                    t = taint(stmt.value)
                    for tgt in stmt.targets:
                        assign_target(tgt, t)
                elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                    check_expr(stmt.value)
                    assign_target(stmt.target, taint(stmt.value))
                elif isinstance(stmt, ast.AugAssign):
                    check_expr(stmt.value)
                    if taint(stmt.value):
                        assign_target(stmt.target, True)
                elif isinstance(stmt, (ast.If, ast.While)):
                    check_expr(stmt.test)
                    if taint(stmt.test):
                        kind = "if" if isinstance(stmt, ast.If) else "while"
                        flag(
                            "TS002",
                            stmt,
                            f"`{kind}` on a traced condition aborts tracing "
                            "(ConcretizationTypeError)",
                            "use jnp.where / lax.select / lax.cond on "
                            "traced values",
                        )
                    do_body(stmt.body)
                    do_body(stmt.orelse)
                    if isinstance(stmt, ast.While):
                        do_body(stmt.body)  # second pass: loop-carried taint
                elif isinstance(stmt, ast.For):
                    check_expr(stmt.iter)
                    # iterating a pytree CONTAINER plucked off a traced
                    # structure (`for lp in params["layers"]`) is the
                    # unrolled-layers idiom and static; only a DIRECTLY
                    # traced iterable (a tainted name, `range(traced)`,
                    # `enumerate(traced_name)`) needs concrete values
                    def _direct(it: ast.expr) -> bool:
                        if isinstance(it, ast.Name):
                            return taint(it)
                        if isinstance(it, ast.Call) and isinstance(
                            it.func, ast.Name
                        ):
                            if it.func.id == "range":
                                return any(taint(a) for a in it.args)
                        # enumerate/zip/tuple iters are overwhelmingly
                        # pytree-container walks — not worth the noise
                        return False

                    if _direct(stmt.iter):
                        flag(
                            "TS002",
                            stmt,
                            "`for` over a traced value needs concrete "
                            "lengths at trace time",
                            "loop over static shapes, or use lax.scan / "
                            "lax.fori_loop",
                        )
                    assign_target(stmt.target, taint(stmt.iter))
                    do_body(stmt.body)
                    do_body(stmt.body)  # second pass: loop-carried taint
                    do_body(stmt.orelse)
                elif isinstance(stmt, ast.Assert):
                    check_expr(stmt.test)
                    if taint(stmt.test):
                        flag(
                            "TS002",
                            stmt,
                            "`assert` on a traced value executes at trace "
                            "time, not per call",
                            "use checkify / debug_assert, or assert on "
                            "static shape fields",
                        )
                elif isinstance(stmt, (ast.Return, ast.Expr)):
                    if stmt.value is not None:
                        check_expr(stmt.value)
                elif isinstance(stmt, ast.With):
                    for item in stmt.items:
                        check_expr(item.context_expr)
                    do_body(stmt.body)
                elif isinstance(stmt, ast.Try):
                    do_body(stmt.body)
                    for h in stmt.handlers:
                        do_body(h.body)
                    do_body(stmt.orelse)
                    do_body(stmt.finalbody)
                elif isinstance(stmt, (ast.Raise, ast.Delete)):
                    for node in ast.iter_child_nodes(stmt):
                        if isinstance(node, ast.expr):
                            check_expr(node)

        do_body(fn.body)
