"""LC*: dispatch-ladder coverage — warmed and accounted, or not shipped.

The serving invariant since PR 1 is ZERO live-traffic compiles: every
fused program (``self.*_fn`` jit handles) and every static-shape bucket
ladder (``self.*buckets``) a dispatch site uses must be compiled by
``warmup()`` — one missed bucket is a hidden multi-second XLA compile on
the first live request that needs it (exactly the class of bug PR 5 fixed
for mesh-sharded deployments). ``compile_counts()`` is the observability
half: a program it does not report is invisible to the
``recompiles_since_warmup()`` zero-recompile gate.

- LC001: a ``*_fn`` program handle dispatched outside ``warmup()`` but
  never exercised by it (warmup's own helper methods count — the closure
  over ``self.<method>()`` calls is followed).
- LC002: a dispatched ``*_fn`` handle missing from
  ``compile_counts()``/``compile_count()``.
- LC003: a ``*buckets`` ladder read at a dispatch site but never walked
  by ``warmup()``.

Classes without a ``warmup`` method are out of scope (nothing promises
pre-compilation there).
"""

from __future__ import annotations

import ast

from seldon_core_tpu.analysis.core import ParsedFile, Project
from seldon_core_tpu.analysis.model import Finding


def _is_self_attr(node: ast.AST) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _attrs_used(fn: ast.AST) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(fn):
        attr = _is_self_attr(node)
        if attr is not None:
            out.add(attr)
    return out


def _self_calls(fn: ast.AST) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            attr = _is_self_attr(node.func)
            if attr is not None:
                out.add(attr)
    return out


class LadderCoveragePass:
    name = "ladder"
    rules = {
        "LC001": "fused program handle dispatched but never compiled by warmup()",
        "LC002": "fused program handle missing from compile_counts()",
        "LC003": "bucket ladder used at a dispatch site but not walked by warmup()",
    }

    def run(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for pf in project.files:
            for node in ast.walk(pf.tree):
                if isinstance(node, ast.ClassDef):
                    self._check_class(pf, node, findings)
        return findings

    def _check_class(
        self, pf: ParsedFile, cls: ast.ClassDef, findings: list[Finding]
    ) -> None:
        methods = {
            n.name: n
            for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        warmup = methods.get("warmup")
        if warmup is None:
            return
        counts = methods.get("compile_counts") or methods.get("compile_count")

        # warmup's closure: attrs it (or the self-methods it calls,
        # transitively) touches
        warmed: set[str] = set()
        seen: set[str] = set()
        frontier = ["warmup"]
        while frontier:
            name = frontier.pop()
            if name in seen or name not in methods:
                continue
            seen.add(name)
            warmed |= _attrs_used(methods[name])
            frontier.extend(_self_calls(methods[name]))
        counted = _attrs_used(counts) if counts is not None else None

        # dispatch sites: first use of each handle/ladder outside warmup
        handles: dict[str, ast.AST] = {}
        ladders: dict[str, ast.AST] = {}
        for mname, m in methods.items():
            if mname in seen:
                continue  # warmup closure is the compile site, not a dispatch
            for node in ast.walk(m):
                if isinstance(node, ast.Call):
                    attr = _is_self_attr(node.func)
                    if attr and attr.endswith("_fn"):
                        handles.setdefault(attr, node)
                attr = _is_self_attr(node)
                if (
                    attr
                    and (attr == "buckets" or attr.endswith("_buckets"))
                    and isinstance(node.ctx, ast.Load)
                ):
                    ladders.setdefault(attr, node)

        for attr, site in sorted(handles.items()):
            if attr not in warmed:
                findings.append(
                    Finding(
                        rule="LC001",
                        path=pf.path,
                        line=site.lineno,
                        col=site.col_offset,
                        message=(
                            f"`self.{attr}` is dispatched but `{cls.name}"
                            ".warmup()` never compiles it — the first live "
                            "request pays the XLA compile"
                        ),
                        hint="exercise every bucket of the program in warmup()",
                        symbol=f"{cls.name}.{attr}",
                    )
                )
            if counted is not None and attr not in counted:
                findings.append(
                    Finding(
                        rule="LC002",
                        path=pf.path,
                        line=site.lineno,
                        col=site.col_offset,
                        message=(
                            f"`self.{attr}` is dispatched but not reported "
                            f"by `{cls.name}.compile_counts()` — recompiles "
                            "of it are invisible to the zero-recompile gate"
                        ),
                        hint="add the program's _cache_size() to compile_counts()",
                        symbol=f"{cls.name}.{attr}",
                    )
                )
        for attr, site in sorted(ladders.items()):
            if attr not in warmed:
                findings.append(
                    Finding(
                        rule="LC003",
                        path=pf.path,
                        line=site.lineno,
                        col=site.col_offset,
                        message=(
                            f"ladder `self.{attr}` feeds a dispatch site but "
                            f"`{cls.name}.warmup()` never walks it — "
                            "unwarmed buckets compile on the live path"
                        ),
                        hint="iterate the full ladder in warmup()",
                        symbol=f"{cls.name}.{attr}",
                    )
                )
