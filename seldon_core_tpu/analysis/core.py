"""Visitor core: parsed-project model, pass registry, runner.

Passes are project-scoped, not file-scoped — trace-safety follows calls
across modules (a ``_fused_*`` program in serving/ reaching blocks in
models/) and registry-drift compares graph/spec.py against
graph/validation.py, so every pass receives the whole parsed ``Project``
and returns plain ``Finding`` lists. Suppression comments and the baseline
are applied centrally by the runner, never inside a pass.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field

from seldon_core_tpu.analysis.model import Finding, parse_suppressions, suppressed


@dataclass
class ParsedFile:
    path: str  # repo-relative posix path (finding identity)
    module: str  # dotted module name best-effort ("" when unknown)
    source: str
    tree: ast.Module
    suppressions: dict[int, frozenset[str] | None] = field(default_factory=dict)
    # name -> (module, name) for "from m import n [as alias]";
    # alias -> module for "import m [as alias]"
    import_from: dict[str, tuple[str, str]] = field(default_factory=dict)
    import_mod: dict[str, str] = field(default_factory=dict)
    # simple name -> module-level (or nested) FunctionDef; parents map for
    # qualname reconstruction
    functions: dict[str, ast.FunctionDef | ast.AsyncFunctionDef] = field(
        default_factory=dict
    )
    parents: dict[ast.AST, ast.AST] = field(default_factory=dict)

    def qualname(self, node: ast.AST) -> str:
        """Dotted Class.method / function qualname for the innermost
        def/class enclosing ``node`` (baseline identity)."""
        names: list[str] = []
        cur: ast.AST | None = node
        while cur is not None:
            if isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                names.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(names))


def _index_file(pf: ParsedFile) -> None:
    for parent in ast.walk(pf.tree):
        for child in ast.iter_child_nodes(parent):
            pf.parents[child] = parent
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for alias in node.names:
                pf.import_from[alias.asname or alias.name] = (
                    node.module,
                    alias.name,
                )
        elif isinstance(node, ast.Import):
            for alias in node.names:
                pf.import_mod[alias.asname or alias.name] = alias.name
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # bare-name calls can never land on a class METHOD (those are
            # reached through self./cls.), so keeping methods out of the
            # table stops a method from shadowing a same-named module
            # helper and silently absorbing its call edges. Module-level
            # and nested defs both register; last definition wins, like
            # runtime rebinding would.
            if not isinstance(pf.parents.get(node), ast.ClassDef):
                pf.functions[node.name] = node


@dataclass
class Project:
    files: list[ParsedFile]
    by_module: dict[str, ParsedFile] = field(default_factory=dict)
    by_path: dict[str, ParsedFile] = field(default_factory=dict)

    def __post_init__(self) -> None:
        for pf in self.files:
            if pf.module:
                self.by_module[pf.module] = pf
            self.by_path[pf.path] = pf

    def resolve_function(
        self, pf: ParsedFile, name: str
    ) -> tuple[ParsedFile, ast.FunctionDef | ast.AsyncFunctionDef] | None:
        """Resolve a simple call name inside ``pf`` to a function def in
        the analyzed set: local def first, then ``from m import name``."""
        node = pf.functions.get(name)
        if node is not None:
            return pf, node
        target = pf.import_from.get(name)
        if target is not None:
            mod, orig = target
            other = self.by_module.get(mod)
            if other is not None and orig in other.functions:
                return other, other.functions[orig]
        return None


def _module_name(abs_path: str) -> str:
    """Best-effort dotted module name: walk up while __init__.py exists."""
    parts = [os.path.splitext(os.path.basename(abs_path))[0]]
    d = os.path.dirname(abs_path)
    while os.path.exists(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        nd = os.path.dirname(d)
        if nd == d:
            break
        d = nd
    name = ".".join(reversed(parts))
    return name[: -len(".__init__")] if name.endswith(".__init__") else name


def parse_file(abs_path: str, rel_path: str) -> ParsedFile | None:
    try:
        with open(abs_path, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=rel_path)
    except (OSError, SyntaxError, ValueError):
        return None  # unparsable files are not this linter's business
    pf = ParsedFile(
        path=rel_path.replace(os.sep, "/"),
        module=_module_name(abs_path),
        source=source,
        tree=tree,
        suppressions=parse_suppressions(source),
    )
    _index_file(pf)
    return pf


def parse_source(path: str, source: str, module: str = "") -> ParsedFile:
    """Test/fixture entry: lint in-memory source under a virtual path."""
    pf = ParsedFile(
        path=path,
        module=module or os.path.splitext(os.path.basename(path))[0],
        source=source,
        tree=ast.parse(source, filename=path),
        suppressions=parse_suppressions(source),
    )
    _index_file(pf)
    return pf


def collect_py_files(paths: list[str], root: str) -> list[tuple[str, str]]:
    """(abs, rel) python files under ``paths``, skipping caches/protos."""
    out: list[tuple[str, str]] = []
    for p in paths:
        ap = os.path.abspath(p)
        if os.path.isfile(ap):
            out.append((ap, os.path.relpath(ap, root)))
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = sorted(
                d for d in dirnames if d not in ("__pycache__", ".git")
            )
            for fn in sorted(filenames):
                if not fn.endswith(".py") or fn.endswith("_pb2.py"):
                    continue
                fp = os.path.join(dirpath, fn)
                out.append((fp, os.path.relpath(fp, root)))
    return out


# --------------------------------------------------------------- registry
def _passes():
    # imported lazily so `import seldon_core_tpu.analysis.model` (tests,
    # fixtures) never pays for every pass module
    from seldon_core_tpu.analysis import (  # noqa: PLC0415
        commit_point,
        ladder,
        phase_registry,
        registry_drift,
        trace_safety,
    )

    return [
        trace_safety.TraceSafetyPass(),
        commit_point.CommitPointPass(),
        registry_drift.RegistryDriftPass(),
        phase_registry.PhaseRegistryPass(),
        ladder.LadderCoveragePass(),
    ]


ALL_PASSES = _passes


def rule_catalogue() -> dict[str, dict[str, str]]:
    """pass name -> {rule id -> one-line description} (docs + --rules)."""
    return {p.name: dict(p.rules) for p in _passes()}


def _select(rules: list[str] | None):
    selected = _passes()
    if rules:
        want = {r.strip().lower() for r in rules if r.strip()}
        selected = [
            p
            for p in selected
            if p.name in want
            or any(rid.lower() in want for rid in p.rules)
        ]
        if not selected:
            known = [p.name for p in _passes()]
            raise ValueError(f"no pass matches {sorted(want)}; known: {known}")
    return selected


def run_passes(
    project: Project, rules: list[str] | None = None
) -> list[Finding]:
    """Run (selected) passes and apply inline suppressions. When a rule
    subset is given, findings outside it are dropped even if the owning
    pass also reports other rules."""
    findings: list[Finding] = []
    only: set[str] | None = None
    if rules:
        only = {r.strip().upper() for r in rules if r.strip()}
    for p in _select(rules):
        for f in p.run(project):
            if only and f.rule not in only and p.name.upper() not in only:
                continue
            pf = project.by_path.get(f.path)
            if pf is not None and suppressed(f, pf.suppressions):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_paths(
    paths: list[str], root: str | None = None, rules: list[str] | None = None
) -> list[Finding]:
    root = os.path.abspath(root or os.getcwd())
    files = []
    for ap, rel in collect_py_files(paths, root):
        pf = parse_file(ap, rel)
        if pf is not None:
            files.append(pf)
    return run_passes(Project(files=files), rules=rules)


def lint_sources(
    sources: dict[str, str], rules: list[str] | None = None
) -> list[Finding]:
    """Fixture entry point: {virtual_path: source} -> findings. Module
    names are the file stems, so ``from a import f`` resolves against a
    fixture file named ``a.py``."""
    files = [parse_source(path, text) for path, text in sources.items()]
    return run_passes(Project(files=files), rules=rules)
