"""seldon_core_tpu: a TPU-native model-serving framework.

Capability parity target: seldon-core v0.1.x (see SURVEY.md). The reference
deploys every inference-graph node as its own container and wires them with
per-request HTTP/gRPC (reference: engine/.../PredictiveUnitBean.java). Here the
whole graph lives in ONE process per host: model nodes are jit-compiled JAX
functions resident in TPU HBM, graph fan-out/aggregation compiles into a single
XLA program when pure, and cross-chip communication is XLA collectives over a
`jax.sharding.Mesh` instead of a pod-to-pod RPC mesh.
"""

from seldon_core_tpu.version import __version__

__all__ = ["__version__"]
