"""gRPC service surface, built on grpc generic handlers (no codegen plugin).

Service/method names match the reference contract
(/root/reference/proto/prediction.proto:76-109): Generic, Model, Router,
Transformer, OutputTransformer, Combiner, Seldon. Because reference clients
address methods as /seldon.protos.<Service>/<Method> while our proto package
is seldon.tpu, servers register BOTH package prefixes — the payload bytes are
wire-compatible either way (field numbers match).
"""

from __future__ import annotations

from typing import Callable

import grpc

from seldon_core_tpu.proto import prediction_pb2 as pb

PACKAGES = ("seldon.tpu", "seldon.protos")

# service -> {method: (request_cls, response_cls)}
SERVICES: dict[str, dict[str, tuple]] = {
    "Generic": {
        "TransformInput": (pb.SeldonMessage, pb.SeldonMessage),
        "TransformOutput": (pb.SeldonMessage, pb.SeldonMessage),
        "Route": (pb.SeldonMessage, pb.SeldonMessage),
        "Aggregate": (pb.SeldonMessageList, pb.SeldonMessage),
        "SendFeedback": (pb.Feedback, pb.SeldonMessage),
    },
    "Model": {"Predict": (pb.SeldonMessage, pb.SeldonMessage)},
    "Router": {
        "Route": (pb.SeldonMessage, pb.SeldonMessage),
        "SendFeedback": (pb.Feedback, pb.SeldonMessage),
    },
    "Transformer": {"TransformInput": (pb.SeldonMessage, pb.SeldonMessage)},
    "OutputTransformer": {"TransformOutput": (pb.SeldonMessage, pb.SeldonMessage)},
    "Combiner": {"Aggregate": (pb.SeldonMessageList, pb.SeldonMessage)},
    "Seldon": {
        "Predict": (pb.SeldonMessage, pb.SeldonMessage),
        "SendFeedback": (pb.Feedback, pb.SeldonMessage),
    },
    # TPU-native addition
    "Admin": {"ServerInfo": (pb.ServerInfoRequest, pb.ServerInfo)},
}


def generic_handler(
    service: str, methods: dict[str, Callable], package: str
) -> grpc.GenericRpcHandler:
    """Build a GenericRpcHandler for async unary-unary methods."""
    spec = SERVICES[service]
    rpc_handlers = {}
    for name, fn in methods.items():
        req_cls, resp_cls = spec[name]
        rpc_handlers[name] = grpc.unary_unary_rpc_method_handler(
            fn,
            request_deserializer=req_cls.FromString,
            response_serializer=lambda m: m.SerializeToString(),
        )
    return grpc.method_handlers_generic_handler(f"{package}.{service}", rpc_handlers)


def add_service(server: grpc.aio.Server, service: str, methods: dict[str, Callable]) -> None:
    """Register an implementation under both package prefixes."""
    for package in PACKAGES:
        server.add_generic_rpc_handlers((generic_handler(service, methods, package),))


class ServiceStub:
    """Client stub over a channel for one service (sync or aio channel)."""

    def __init__(self, channel, service: str, package: str = "seldon.tpu"):
        self._methods = {}
        for name, (req_cls, resp_cls) in SERVICES[service].items():
            self._methods[name] = channel.unary_unary(
                f"/{package}.{service}/{name}",
                request_serializer=lambda m: m.SerializeToString(),
                response_deserializer=resp_cls.FromString,
            )

    def __getattr__(self, name: str):
        try:
            return self._methods[name]
        except KeyError as e:
            raise AttributeError(name) from e
