"""Wire-contract protos (see prediction.proto for the compatibility notes)."""

from pathlib import Path

PROTO_DIR = Path(__file__).resolve().parent
