"""Deployment defaulting — pure function, mirroring the pure half of the
reference operator's ``defaulting()``
(cluster-manager/.../k8s/SeldonDeploymentOperatorImpl.java:187-322):

- every unit with a type but no methods gets the type-implied methods;
- MODEL-type units backed by a container get an endpoint wired to sequential
  ports from a base (reference PU base port 9000,
  ClusterManagerProperites.getPuContainerPortBase);
- units with a built-in implementation get no endpoint (in-process);
- TPU additions: a default mesh ({"data": n_local_devices}) and batch buckets
  derived from max_batch.

Kubernetes-side defaulting (probes, lifecycle hooks, engine sidecar env) lives
in operator/resources.py — kept out of here so this stays a pure spec->spec
function testable against JSON fixtures (reference test style:
SeldonDeploymentDefaultingTest.java).
"""

from __future__ import annotations

import dataclasses

from seldon_core_tpu.core.tensor import default_buckets
from seldon_core_tpu.graph.spec import (
    PredictiveUnitMethod,
    bool_param,
    BUILTIN_IMPLEMENTATIONS,
    TYPE_METHODS,
    Endpoint,
    EndpointType,
    PredictiveUnit,
    PredictiveUnitImplementation,
    SeldonDeployment,
    TpuSpec,
)

PU_PORT_BASE = 9000  # reference ClusterManagerProperites.getPuContainerPortBase


def _has_builtin_impl(unit: PredictiveUnit) -> bool:
    return (
        unit.implementation is not None
        and unit.implementation != PredictiveUnitImplementation.UNKNOWN_IMPLEMENTATION
        and unit.implementation in BUILTIN_IMPLEMENTATIONS
    )


def _default_unit(
    unit: PredictiveUnit, container_names: set[str], port_alloc: dict[str, int]
) -> PredictiveUnit:
    update: dict = {}
    wants_finetune = any(
        p.name == "finetune" and bool_param(p.typed_value()) for p in unit.parameters
    )
    if unit.type is not None and not unit.methods:
        methods = list(TYPE_METHODS.get(unit.type, ()))
        # a fine-tuning model consumes labeled feedback: inject the method so
        # the executor's feedback walk reaches it (routers get it from
        # TYPE_METHODS already)
        if wants_finetune and PredictiveUnitMethod.SEND_FEEDBACK not in methods:
            methods.append(PredictiveUnitMethod.SEND_FEEDBACK)
        update["methods"] = methods
    elif wants_finetune and PredictiveUnitMethod.SEND_FEEDBACK not in unit.methods:
        # explicit methods list: still reconcile, or the model never learns
        update["methods"] = list(unit.methods) + [PredictiveUnitMethod.SEND_FEEDBACK]
    needs_endpoint = (
        not _has_builtin_impl(unit)
        and unit.name in container_names
        and (unit.endpoint is None or unit.endpoint.service_port == 0)
    )
    if needs_endpoint:
        port = PU_PORT_BASE + len(port_alloc)
        port_alloc[unit.name] = port
        etype = unit.endpoint.type if unit.endpoint else EndpointType.REST
        update["endpoint"] = Endpoint(service_host="localhost", service_port=port, type=etype)
    children = [_default_unit(c, container_names, port_alloc) for c in unit.children]
    if children != list(unit.children):
        update["children"] = children
    if not update:
        return unit
    return unit.model_copy(update=update)


def default_deployment(dep: SeldonDeployment, n_devices: int | None = None) -> SeldonDeployment:
    """Return a defaulted copy; input is never mutated."""
    if n_devices is None:
        try:
            import jax

            n_devices = jax.local_device_count()
        except Exception:  # noqa: BLE001 - defaulting must work without a backend
            n_devices = 1

    new_predictors = []
    for pred in dep.spec.predictors:
        container_names = {c.name for c in pred.componentSpec.containers}
        port_alloc: dict[str, int] = {}
        graph = _default_unit(pred.graph, container_names, port_alloc)
        tpu = pred.tpu
        tpu_update: dict = {}
        if not tpu.mesh:
            tpu_update["mesh"] = {"data": n_devices}
        if not tpu.batch_buckets:
            tpu_update["batch_buckets"] = list(default_buckets(tpu.max_batch))
        if tpu_update:
            tpu = tpu.model_copy(update=tpu_update)
        new_predictors.append(pred.model_copy(update={"graph": graph, "tpu": tpu}))

    spec = dep.spec.model_copy(update={"predictors": new_predictors})
    return dep.model_copy(update={"spec": spec})
