"""Inference-graph + deployment schema.

Parity target: /root/reference/proto/seldon_deployment.proto:10-124
(SeldonDeployment / DeploymentSpec / PredictorSpec / PredictiveUnit /
Endpoint / Parameter) — same field names and enums so reference CR JSON
(e.g. examples/models/sklearn_iris/sklearn_iris_deployment.json) parses
directly. TPU-first additions are isolated in ``TpuSpec``: mesh shape and
sharding axes for the compiled graph, batch buckets, and dtype — concepts
the reference (one container per node, k8s replicas for scale) has no
analogue for.

Implemented with pydantic for free JSON-schema validation; models are frozen
(specs are immutable config, runtime state lives in engine/).
"""

from __future__ import annotations

import enum
from typing import Any, Optional

from pydantic import BaseModel, ConfigDict, Field


class PredictiveUnitType(str, enum.Enum):
    UNKNOWN_TYPE = "UNKNOWN_TYPE"
    ROUTER = "ROUTER"
    COMBINER = "COMBINER"
    MODEL = "MODEL"
    TRANSFORMER = "TRANSFORMER"
    OUTPUT_TRANSFORMER = "OUTPUT_TRANSFORMER"


class PredictiveUnitImplementation(str, enum.Enum):
    UNKNOWN_IMPLEMENTATION = "UNKNOWN_IMPLEMENTATION"
    SIMPLE_MODEL = "SIMPLE_MODEL"
    SIMPLE_ROUTER = "SIMPLE_ROUTER"
    RANDOM_ABTEST = "RANDOM_ABTEST"
    AVERAGE_COMBINER = "AVERAGE_COMBINER"
    # TPU-native additions beyond the reference's four built-ins:
    EPSILON_GREEDY = "EPSILON_GREEDY"  # bandit router (BASELINE config 5)
    JAX_MODEL = "JAX_MODEL"  # in-process jitted model from the model zoo
    MEAN_TRANSFORMER = "MEAN_TRANSFORMER"  # centering input transformer
    # (reference ships this as a container: examples/transformers/mean_transformer)
    FAULT_INJECTOR = "FAULT_INJECTOR"  # chaos testing (reference has none)
    OUTLIER_DETECTOR = "OUTLIER_DETECTOR"  # z-score request scorer writing
    # meta.tags.outlierScore (reference ships the tier container-only:
    # wrappers/python/outlier_detector_microservice.py:40-50)
    PYTHON_CLASS = "PYTHON_CLASS"  # duck-typed user class loaded in-process
    # from params module/model_dir (single-host platform mode; the reference
    # always puts user classes behind a container endpoint)
    SHADOW = "SHADOW"  # serve child 0, mirror traffic to the other children
    # fire-and-forget (candidate validation under production load; their
    # latency/failures never touch the response, their metrics still tick)
    PREFIX_AFFINITY = "PREFIX_AFFINITY"  # generative replica router: prompts
    # sharing a leading token block consistent-hash to the same (warm)
    # child; keyless prompts ride reward-driven bandit arms fed by the
    # Feedback API; bounded-load shedding on observed child queue depth
    # (serving/affinity_router.py owns the policy engine)


class PredictiveUnitMethod(str, enum.Enum):
    TRANSFORM_INPUT = "TRANSFORM_INPUT"
    TRANSFORM_OUTPUT = "TRANSFORM_OUTPUT"
    ROUTE = "ROUTE"
    AGGREGATE = "AGGREGATE"
    SEND_FEEDBACK = "SEND_FEEDBACK"


class EndpointType(str, enum.Enum):
    REST = "REST"
    GRPC = "GRPC"


class ParameterType(str, enum.Enum):
    INT = "INT"
    FLOAT = "FLOAT"
    DOUBLE = "DOUBLE"
    STRING = "STRING"
    BOOL = "BOOL"


class _Spec(BaseModel):
    model_config = ConfigDict(frozen=True, populate_by_name=True, extra="ignore")


class Endpoint(_Spec):
    service_host: str = ""
    service_port: int = 0
    type: EndpointType = EndpointType.REST


class Parameter(_Spec):
    name: str
    value: str
    type: ParameterType = ParameterType.STRING

    def typed_value(self) -> Any:
        """Typed parse, mirroring reference PredictiveUnitState
        .deserializeParameters (engine) / parse_parameters
        (wrappers/python/microservice.py:119-133)."""
        if self.type == ParameterType.INT:
            return int(self.value)
        if self.type in (ParameterType.FLOAT, ParameterType.DOUBLE):
            return float(self.value)
        if self.type == ParameterType.BOOL:
            return self.value.strip().lower() in ("true", "1", "yes")
        return self.value


def parameters_dict(params: list["Parameter"]) -> dict[str, Any]:
    return {p.name: p.typed_value() for p in params}


def bool_param(value: Any) -> bool:
    """Strict boolean coercion for parameters regardless of declared type:
    the STRING value "false" must not count as enabled."""
    if isinstance(value, bool):
        return value
    return str(value).strip().lower() in ("true", "1", "yes")


class PredictiveUnit(_Spec):
    name: str
    children: list["PredictiveUnit"] = Field(default_factory=list)
    type: Optional[PredictiveUnitType] = None
    implementation: Optional[PredictiveUnitImplementation] = None
    methods: list[PredictiveUnitMethod] = Field(default_factory=list)
    endpoint: Optional[Endpoint] = None
    parameters: list[Parameter] = Field(default_factory=list)

    def walk(self):
        """Pre-order traversal of the unit tree."""
        yield self
        for c in self.children:
            yield from c.walk()


class RetrySpec(_Spec):
    """Per-node retry policy (engine/resilience.RetryState runs it).

    Retries apply only to idempotent methods (never send_feedback) and only
    to transport/5xx-class failures, and never sleep past the request's
    deadline budget."""

    max_attempts: int = 1  # total attempts; 1 = no retry
    backoff_ms: float = 25.0  # first backoff; doubles by backoff_mult
    backoff_mult: float = 2.0
    jitter: float = 0.5  # +/- fraction applied to each backoff
    seed: Optional[int] = None  # deterministic jitter for tests/chaos runs


class BreakerSpec(_Spec):
    """Per-endpoint circuit breaker (engine/resilience.CircuitBreaker).

    Opens on ``failure_threshold`` consecutive failures OR a windowed error
    rate >= ``error_rate``; after ``reset_ms`` admits ``half_open_probes``
    probe calls (success closes, failure re-opens)."""

    failure_threshold: int = 5
    error_rate: float = 0.5
    window: int = 20
    reset_ms: float = 1000.0
    half_open_probes: int = 1


class ResilienceSpec(_Spec):
    """Resilience knobs for ONE graph node, parsed from its CR ``parameters``
    (TpuSpec-style: plain config riding the deployment CR; runtime state
    lives in engine/resilience.py). Parameter names:

    - ``retry_max_attempts`` (INT > 1 enables retry), ``retry_backoff_ms``,
      ``retry_backoff_mult``, ``retry_jitter``, ``retry_seed``
    - ``breaker_failure_threshold`` (INT > 0 enables the breaker),
      ``breaker_error_rate``, ``breaker_window``, ``breaker_reset_ms``,
      ``breaker_half_open_probes``
    - ``fallback_child`` (ROUTER: branch index served when the chosen
      child's breaker is open or its subtree fails transport-class)
    - ``quorum`` (COMBINER: aggregate this many surviving children instead
      of failing the request when a child errors)
    """

    retry: Optional[RetrySpec] = None
    breaker: Optional[BreakerSpec] = None
    fallback_child: Optional[int] = None
    quorum: Optional[int] = None

    @staticmethod
    def from_parameters(params: dict[str, Any]) -> "ResilienceSpec":
        retry = None
        if int(params.get("retry_max_attempts", 1)) > 1:
            retry = RetrySpec(
                max_attempts=int(params["retry_max_attempts"]),
                backoff_ms=float(params.get("retry_backoff_ms", 25.0)),
                backoff_mult=float(params.get("retry_backoff_mult", 2.0)),
                jitter=float(params.get("retry_jitter", 0.5)),
                seed=int(params["retry_seed"]) if "retry_seed" in params else None,
            )
        breaker = None
        if int(params.get("breaker_failure_threshold", 0)) > 0:
            breaker = BreakerSpec(
                failure_threshold=int(params["breaker_failure_threshold"]),
                error_rate=float(params.get("breaker_error_rate", 0.5)),
                window=int(params.get("breaker_window", 20)),
                reset_ms=float(params.get("breaker_reset_ms", 1000.0)),
                half_open_probes=int(params.get("breaker_half_open_probes", 1)),
            )
        return ResilienceSpec(
            retry=retry,
            breaker=breaker,
            fallback_child=int(params["fallback_child"])
            if "fallback_child" in params
            else None,
            quorum=int(params["quorum"]) if "quorum" in params else None,
        )

    @staticmethod
    def for_unit(unit: "PredictiveUnit") -> "ResilienceSpec":
        return ResilienceSpec.from_parameters(parameters_dict(unit.parameters))


class TpuSpec(_Spec):
    """TPU-native execution config for a predictor (no reference analogue).

    The reference scales with k8s replicas; here a predictor is compiled onto
    a device mesh. ``mesh`` maps logical axis name -> size (e.g. {"data": 8}
    for pure batch sharding on v5e-8, {"data": 2, "model": 4} for TP)."""

    mesh: dict[str, int] = Field(default_factory=dict)
    batch_buckets: list[int] = Field(default_factory=list)  # [] -> derived from max_batch
    max_batch: int = 64
    batch_timeout_ms: float = 3.0
    # per-request deadline BUDGET stamped at the serving entrypoint: every
    # node call gets the remaining budget, remote calls use it as their
    # timeout, exhaustion cancels the in-flight subtree and returns 504.
    # 0 = no deadline (per-call defaults only). Requests may tighten (never
    # widen) it with a meta.tags["deadline_ms"] override.
    deadline_ms: float = 0.0
    # how long a request may sit in the batch queue before REQUEST_TIMEOUT:
    # deep DAGs (several device dispatches per walk) or high-RTT links need
    # more than the 2 s default
    queue_timeout_ms: float = 2000.0
    # False -> per-request isolation: a ROUTER decides per request exactly
    # like the reference engine, at the cost of per-request graph calls
    batch_across_requests: bool = True
    # compile pure all-JAX subtrees (e.g. combiner ensembles) into one XLA
    # program (engine/fused.py). Trade-off: a fused island reports ONE
    # requestPath entry / trace span / unit timer (named fused[members])
    # instead of per-member entries — set False to keep per-node execution
    # and per-member observability
    fuse_graph: bool = True
    dtype: str = "float32"  # computation dtype: float32 | bfloat16
    # weight-only int8 quantization ("int8" | ""): halves weight HBM traffic
    # and residency; dequant fuses into the matmul inside jit (models/quant.py)
    weight_quant: str = ""
    # donation only pays when output aliases input shape (e.g. transformers);
    # classifier heads change shape, so default off
    donate_input: bool = False
    # Host-compute offload policy for MODEL nodes: "auto" (default) times
    # each model's forward at warmup and, on the host CPU backend, moves
    # slow forwards (>= ~3 ms) off the event loop onto a worker thread so
    # one wide tenant cannot stall every other tenant's ingress (XLA
    # releases the GIL during execution, so the overlap is real);
    # "always"/"never" force the decision
    offload_compute: str = "auto"
    # Continuous-batching decode scheduler for GENERATIVE predictors
    # (serving/decode_scheduler.py). decode_slots > 0 opts a single-node
    # decoder deployment into iteration-level scheduling over a slot KV
    # cache: requests admit into free slots between steps and retire on EOS
    # / their own max_new_tokens instead of riding one whole-batch scan.
    # 0 (default) keeps the fused lax.scan path.
    decode_slots: int = 0
    # EOS token id that retires a sequence early (-1: no EOS, every
    # sequence runs its max_new_tokens)
    decode_eos_id: int = -1
    # deployment-default sampling; per-request overrides ride meta.tags
    # (temperature / top_k / max_new_tokens). temperature <= 0 = greedy
    # (the fused-oracle-equivalent default), top_k <= 0 = full vocabulary.
    decode_temperature: float = 0.0
    decode_top_k: int = 0
    decode_seed: int = 0
    # Draft-model speculative decoding for the decode scheduler: a zoo URI
    # (e.g. "zoo://draft?layers=1") naming a small decoder that shares the
    # target's vocabulary (vocab/max_len are injected from the target when
    # the URI doesn't pin them), and the number of tokens it proposes per
    # target dispatch. BOTH must be set to opt in; greedy output stays
    # bit-identical to the non-speculative scheduler, temperature > 0 uses
    # the residual-resampling acceptance rule so the output distribution
    # is unchanged. Requests may tighten (never widen) k with a
    # meta.tags["spec_k"] override; spec_k=0 there opts a request out.
    decode_draft_model: str = ""
    decode_spec_k: int = 0
    # Tree speculation (models/spec_tree.py): per-depth top-b branching,
    # e.g. "4,2,1" — the draft proposes 4 candidates at depth 1, 2 per
    # surviving branch at depth 2, 1 at depth 3, and the whole flattened
    # tree is scored in the ONE widened verify dispatch (Medusa/EAGLE/
    # SpecInfer-style), so accepted-tokens-per-dispatch rises at the same
    # 2-dispatch round cost. Needs decode_draft_model; subsumes
    # decode_spec_k (the tree's depth plays its role — a chain IS the
    # degenerate "1,1,...,1" tree). The flattened tree is capped at
    # spec_tree.MAX_TREE_NODES nodes (verify-width headroom). Requests
    # may tighten per-depth widths (never widen) via
    # meta.tags["spec_tree"]; greedy output stays bit-identical to the
    # plain scheduler, temperature > 0 uses per-depth recursive rejection
    # resampling so the output distribution is unchanged. Composes with
    # paged/int8 KV, the prefix cache, and decode_mesh_axes (the tree
    # axis is replicated; heads stay sharded).
    decode_spec_tree: str = ""
    # Accept-rate-adaptive speculation: > 0 enables a rolling (EWMA)
    # accept-rate estimate that scales the EFFECTIVE speculation depth
    # between plain decode (estimate below the floor — a cold or
    # adversarial workload stops paying draft + widened-verify cost,
    # with a periodic depth-1 probe so the estimate can recover) and the
    # configured spec_k / tree-depth ceiling. Adaptation changes only
    # per-slot limit DATA, never program shapes — zero recompiles by
    # construction. 0 (default) pins the configured shape.
    decode_spec_accept_floor: float = 0.0
    # Prefix-cache KV reuse for the decode scheduler: > 0 allocates a
    # device-resident prefix pool of that many rows beside the slot cache,
    # indexed host-side by prompt token prefixes (radix trie, longest-
    # common-prefix match). On admit the matched prefix K/V is copied into
    # the slot with one fused gather and only the uncovered suffix is
    # prefilled — shared system prompts stop being recomputed per request.
    # Populated from retiring slots (full prompt) and meta.tags
    # ["cache_prefix"] hints; ref-counted, LRU-evicted. Greedy output stays
    # bit-identical to a cold prefill.
    decode_prefix_slots: int = 0
    # tokens of prompt prefix each pool row can hold (0 -> the deployment's
    # seq bucket; clamped to it — only prompt positions are ever cached)
    decode_prefix_ctx: int = 0
    # Sarathi-style chunked prefill: cap the prompt tokens a slot prefills
    # per scheduler round (0 = whole suffix in one dispatch). Chunks run on
    # a power-of-two bucket ladder interleaved with decode steps, so long
    # prompt waves no longer stall running slots' inter-token latency.
    # Requests may tighten (never widen) it via meta.tags["prefill_chunk"].
    decode_prefill_chunk: int = 0
    # Paged KV memory (serving/kv_pool.py): the decode scheduler's K/V
    # lives in a device page pool shared by live slots and the prefix
    # cache, gathered through per-slot block tables.
    # decode_kv_page_size: tokens per page (0 = auto, 16). With an
    # explicit page size, decode_prefill_chunk must be page-aligned (a
    # multiple of it) so chunk rounds land on page boundaries.
    decode_kv_page_size: int = 0
    # decode_kv_pages: total page budget (0 = auto: flat-equivalent —
    # every slot can hold its full context with zero sharing). An explicit
    # budget is where paging pays: shared system-prompt pages are counted
    # once pool-wide, so more slots fit the same HBM; admission throttles
    # on a reservation invariant instead of deadlocking, and a budget too
    # small for even one slot's residency is rejected up front.
    decode_kv_pages: int = 0
    # decode_kv_dtype: "int8" stores the pool quantized (per-page-row
    # scale/zero-point, dequant fused into the attention gather) for
    # roughly half the KV bytes per token; greedy output is then
    # tolerance-close, not bit-identical, to the fp pool. "" keeps the
    # computation dtype.
    decode_kv_dtype: str = ""
    # Tiered prefix-page hierarchy (serving/kv_host_tier.py): > 0 gives
    # the prefix cache a host-RAM demotion tier of this byte budget.
    # Prefix entries the device pool evicts under pressure demote to host
    # RAM (bytes exactly as stored on device — an int8 pool's quantized
    # planes verbatim); a device miss at admission promotes the entry
    # back into pinned free pages instead of recomputing, riding the
    # pipelined rounds' overlap window. Host-only state: zero recompiles,
    # greedy output stays bit-identical to a cold prefill. Requests may
    # opt out (never widen) via meta.tags["kv_tier"] = "off" | "host".
    # Needs decode_prefix_slots > 0. 0 (default) keeps evictions final.
    decode_kv_host_bytes: int = 0
    # Store URL (persistence/state.make_state_store: file:// or redis://)
    # the host tier's own LRU spills its coldest entries to — the third
    # tier, shared across replica restarts. Store outages degrade to
    # skip-store, never abort. "" (default) = no store tier (host-LRU
    # evictions are final). Needs decode_kv_host_bytes > 0.
    decode_kv_store_tier: str = ""
    # Tensor-parallel decode over a named device mesh (parallel/tp.py):
    # e.g. {"tp": 4} shards decoder params, the paged KV page pool, and
    # the draft's flat cache on the attention HEAD axis (FFN on its
    # hidden axis) across 4 devices, with the per-layer all-reduces
    # fused into the step/chunk/verify programs. Exactly ONE axis;
    # n_heads and ffn (target AND draft) must be divisible by its size,
    # which must not exceed the attached devices. Needs decode_slots > 0;
    # greedy output stays token-identical to the single-device scheduler
    # at any width. {} (default) keeps single-device dispatch.
    decode_mesh_axes: dict[str, int] = Field(default_factory=dict)
    # Multi-replica decode scale-out (serving/affinity_router.py): run N
    # full decode-scheduler replicas — each with its own params copy, page
    # pool, and prefix index, mapped round-robin onto the attached devices
    # — behind a prefix-affinity router. Prompts sharing a leading block
    # land on the same warm replica (prefix hit-rate holds at the
    # single-replica level while throughput multiplies); prompts with no
    # affinity signal ride reward-driven bandit arms fed by the Feedback
    # API. 1 (default) keeps the single scheduler. Needs decode_slots > 0;
    # not composable with decode_mesh_axes yet (they partition the same
    # device budget).
    decode_replicas: int = 1
    # Routing policy across the replicas: "" / "affinity" (default —
    # prefix-affinity + bounded-load shed + bandit fallback),
    # "round_robin" (the control policy: documents the prefix hit-rate
    # collapse), "bandit" (pure reward-driven arms, no affinity).
    decode_router_policy: str = ""
    # Queue-depth autoscale: > decode_replicas lets the router grow the
    # fleet up to this cap when the mean un-admitted queue depth (the
    # /decode/health ``queue_depth`` signal) sustains at or above
    # decode_autoscale_queue_depth. A scale-up replica boots WARM:
    # the hottest replica's refcount-ranked prefix pages are spilled
    # through persistence/state.py and pre-seeded into the new pool, so
    # its first shared-prompt request rides the warm TTFT path. 0
    # disables autoscale.
    decode_autoscale_replicas: int = 0
    decode_autoscale_queue_depth: int = 0
    # Fleet health/eviction (serving/affinity_router.py): poll each
    # replica's /decode/health probe every decode_health_poll_ms; a
    # replica missing decode_health_miss_threshold consecutive probes
    # (exception, dropped response, or active slots with a stagnant tick
    # counter — a hung dispatch loop answers host-side probes) trips its
    # per-replica breaker: it leaves rendezvous ranking, its in-flight
    # generations migrate to surviving replicas (teacher-forced replay
    # from the last committed token — bit-identical resume), and it is
    # readmitted through the breaker's half-open probe once it answers
    # again. 0 (default) disables polling; request-path crash eviction
    # still works without it.
    decode_health_poll_ms: float = 0.0
    decode_health_miss_threshold: int = 3
    # Graceful drain budget (drain_replica/scale_down): how long a
    # draining replica may finish in-flight work before the remainder is
    # migrated and its device released.
    decode_drain_timeout_ms: float = 5000.0
    # Decode-loop SLO targets (serving/decode_scheduler.py + telemetry/
    # flight.py): per-request TTFT / inter-token-latency budgets in ms the
    # goodput/attainment telemetry is judged against. 0 (default) = not
    # configured — no per-token comparisons run. Breaches feed the
    # seldon_tpu_decode_slo_attainment_total counter (with a flight-ring
    # dump exemplar) and flip the request's meta.tags.slo verdict; they do
    # NOT fail the request (deadline_ms is the enforcement knob — these
    # are the observation ones).
    decode_slo_ttft_ms: float = 0.0
    decode_slo_itl_ms: float = 0.0
    # True: binData that parses as npy decodes to the tensor arm at ingress
    # (the binary tensor fast path), including base64 binData inside the
    # JSON envelope. False: binData is NEVER sniffed — opaque passthrough
    # everywhere (the reference's unconditional oneof semantics), for graphs
    # whose PYTHON_CLASS units speak a bytes contract that could collide
    # with the npy magic.
    decode_npy_bindata: bool = True


class ContainerSpec(_Spec):
    """Minimal PodTemplateSpec-container equivalent: what the operator needs to
    wire a MODEL unit to its runtime (reference uses full k8s v1.Container;
    we keep image/name/env + a model_uri for weight loading)."""

    name: str
    image: str = ""
    env: dict[str, str] = Field(default_factory=dict)
    model_uri: str = ""  # checkpoint path for JAX_MODEL units


class ComponentSpec(_Spec):
    containers: list[ContainerSpec] = Field(default_factory=list)


class PredictorSpec(_Spec):
    name: str
    graph: PredictiveUnit
    componentSpec: ComponentSpec = Field(default_factory=ComponentSpec)
    replicas: int = 1
    annotations: dict[str, str] = Field(default_factory=dict)
    tpu: TpuSpec = Field(default_factory=TpuSpec)


class DeploymentSpec(_Spec):
    name: str = ""
    predictors: list[PredictorSpec] = Field(default_factory=list)
    oauth_key: str = ""
    oauth_secret: str = ""
    annotations: dict[str, str] = Field(default_factory=dict)


class PredictorStatus(_Spec):
    name: str
    status: str = ""
    description: str = ""
    replicas: int = 0
    replicasAvailable: int = 0


class DeploymentStatus(_Spec):
    state: str = ""
    description: str = ""
    predictorStatus: list[PredictorStatus] = Field(default_factory=list)


class ObjectMeta(_Spec):
    name: str = ""
    namespace: str = "default"
    labels: dict[str, str] = Field(default_factory=dict)
    annotations: dict[str, str] = Field(default_factory=dict)
    resourceVersion: str = ""


class SeldonDeployment(_Spec):
    """The CRD-equivalent resource (reference seldon_deployment.proto:10-16;
    CRD group machinelearning.seldon.io/v1alpha1, kind SeldonDeployment)."""

    apiVersion: str = "machinelearning.seldon.io/v1alpha1"
    kind: str = "SeldonDeployment"
    metadata: ObjectMeta = Field(default_factory=ObjectMeta)
    spec: DeploymentSpec = Field(default_factory=DeploymentSpec)
    status: Optional[DeploymentStatus] = None

    @staticmethod
    def from_dict(obj: dict) -> "SeldonDeployment":
        return SeldonDeployment.model_validate(obj)

    def to_dict(self) -> dict:
        return self.model_dump(mode="json", exclude_none=True)


# Methods implied by each unit type — reference PredictorConfigBean
# type->methods map (engine/.../predictors/PredictorConfigBean.java:44-72).
TYPE_METHODS: dict[PredictiveUnitType, tuple[PredictiveUnitMethod, ...]] = {
    PredictiveUnitType.MODEL: (PredictiveUnitMethod.TRANSFORM_INPUT,),
    PredictiveUnitType.TRANSFORMER: (PredictiveUnitMethod.TRANSFORM_INPUT,),
    PredictiveUnitType.OUTPUT_TRANSFORMER: (PredictiveUnitMethod.TRANSFORM_OUTPUT,),
    PredictiveUnitType.ROUTER: (
        PredictiveUnitMethod.ROUTE,
        PredictiveUnitMethod.SEND_FEEDBACK,
    ),
    PredictiveUnitType.COMBINER: (PredictiveUnitMethod.AGGREGATE,),
}

# Implementations hard-wired in-engine (no microservice/container needed) —
# reference PredictorConfigBean nodeImplementationMap:77-83 plus our additions.
BUILTIN_IMPLEMENTATIONS = frozenset(
    {
        PredictiveUnitImplementation.SIMPLE_MODEL,
        PredictiveUnitImplementation.SIMPLE_ROUTER,
        PredictiveUnitImplementation.RANDOM_ABTEST,
        PredictiveUnitImplementation.AVERAGE_COMBINER,
        PredictiveUnitImplementation.EPSILON_GREEDY,
        PredictiveUnitImplementation.JAX_MODEL,
        PredictiveUnitImplementation.MEAN_TRANSFORMER,
        PredictiveUnitImplementation.FAULT_INJECTOR,
        PredictiveUnitImplementation.OUTLIER_DETECTOR,
        PredictiveUnitImplementation.PYTHON_CLASS,
        PredictiveUnitImplementation.SHADOW,
        PredictiveUnitImplementation.PREFIX_AFFINITY,
    }
)
