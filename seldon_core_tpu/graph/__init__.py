from seldon_core_tpu.graph.spec import (
    DeploymentSpec,
    Endpoint,
    EndpointType,
    Parameter,
    ParameterType,
    PredictiveUnit,
    PredictiveUnitImplementation,
    PredictiveUnitMethod,
    PredictiveUnitType,
    PredictorSpec,
    SeldonDeployment,
)
from seldon_core_tpu.graph.defaulting import default_deployment
from seldon_core_tpu.graph.validation import ValidationError, validate_deployment

__all__ = [
    "DeploymentSpec",
    "Endpoint",
    "EndpointType",
    "Parameter",
    "ParameterType",
    "PredictiveUnit",
    "PredictiveUnitImplementation",
    "PredictiveUnitMethod",
    "PredictiveUnitType",
    "PredictorSpec",
    "SeldonDeployment",
    "ValidationError",
    "default_deployment",
    "validate_deployment",
]
