"""Deployment validation — pure function.

Parity: reference operator ``validate()``
(cluster-manager/.../k8s/SeldonDeploymentOperatorImpl.java:325-364):
- every non-builtin unit must name an existing container (:325-347);
- every unit must have a type or explicit methods (:356-364);
plus structural rules the reference enforces implicitly at runtime:
- predictor names unique; unit names unique within a graph;
- ROUTER/COMBINER must have children, COMBINER >= 1 child;
- oauth_key/secret both-or-neither;
- TPU additions: mesh sizes positive, batch buckets sorted ascending,
  dtype in {float32, bfloat16}.
Raises ValidationError listing every problem (not just the first) — fixture
-JSON test style per SeldonDeploymentValidationTest.java.
"""

from __future__ import annotations

from seldon_core_tpu.graph.spec import (
    BUILTIN_IMPLEMENTATIONS,
    PredictiveUnit,
    PredictiveUnitImplementation,
    PredictiveUnitType,
    SeldonDeployment,
)


class ValidationError(ValueError):
    def __init__(self, problems: list[str]):
        self.problems = problems
        super().__init__("; ".join(problems))


# TpuSpec knobs that deliberately carry NO validation rule, acknowledged
# here so the registry-drift lint (RD003, seldon_core_tpu/analysis) can
# hold "every knob has a rule or a recorded waiver" at zero:
# - batch_across_requests / fuse_graph / donate_input / decode_npy_bindata:
#   plain booleans, pydantic already rejects non-bool (npy_bindata
#   additionally has its cross-predictor agreement rule below);
# - decode_temperature: <= 0 means greedy by contract, any float is legal;
# - decode_top_k: <= 0 means full vocabulary by contract;
# - decode_seed: any int seeds the per-deployment RNG stream.
UNCONSTRAINED_KNOBS = (
    "batch_across_requests",
    "fuse_graph",
    "donate_input",
    "decode_temperature",
    "decode_top_k",
    "decode_seed",
)


def _validate_unit(
    unit: PredictiveUnit, container_names: set[str], seen: set[str], problems: list[str]
) -> None:
    if unit.name in seen:
        problems.append(f"duplicate unit name '{unit.name}' in graph")
    seen.add(unit.name)

    has_builtin = (
        unit.implementation is not None
        and unit.implementation != PredictiveUnitImplementation.UNKNOWN_IMPLEMENTATION
        and unit.implementation in BUILTIN_IMPLEMENTATIONS
    )
    has_endpoint = unit.endpoint is not None and unit.endpoint.service_port != 0
    if not has_builtin and not has_endpoint and unit.name not in container_names:
        problems.append(
            f"unit '{unit.name}' has no implementation and no matching container"
        )
    if unit.type is None and not unit.methods and unit.implementation is None:
        problems.append(f"unit '{unit.name}' must have a type, methods, or implementation")

    if unit.type == PredictiveUnitType.COMBINER and not unit.children:
        problems.append(f"COMBINER '{unit.name}' must have children")
    if unit.type == PredictiveUnitType.ROUTER and not unit.children:
        problems.append(f"ROUTER '{unit.name}' must have children")

    for c in unit.children:
        _validate_unit(c, container_names, seen, problems)


def validate_deployment(dep: SeldonDeployment) -> None:
    problems: list[str] = []
    if not dep.spec.predictors:
        problems.append("deployment must have at least one predictor")
    names = [p.name for p in dep.spec.predictors]
    if len(set(names)) != len(names):
        problems.append("predictor names must be unique")
    if bool(dep.spec.oauth_key) != bool(dep.spec.oauth_secret):
        problems.append("oauth_key and oauth_secret must be set together")

    for pred in dep.spec.predictors:
        container_names = {c.name for c in pred.componentSpec.containers}
        _validate_unit(pred.graph, container_names, set(), problems)
        if pred.replicas < 0:
            problems.append(f"predictor '{pred.name}' replicas must be >= 0")
        for axis, size in pred.tpu.mesh.items():
            if size <= 0:
                problems.append(f"predictor '{pred.name}' mesh axis '{axis}' must be > 0")
        bb = pred.tpu.batch_buckets
        if bb and bb != sorted(bb):
            problems.append(f"predictor '{pred.name}' batch_buckets must be ascending")
        if pred.tpu.dtype not in ("float32", "bfloat16", "float16"):
            problems.append(f"predictor '{pred.name}' dtype '{pred.tpu.dtype}' unsupported")
        if pred.tpu.max_batch < 1:
            problems.append(f"predictor '{pred.name}' max_batch must be >= 1")
        for knob in ("batch_timeout_ms", "deadline_ms", "queue_timeout_ms"):
            if getattr(pred.tpu, knob) < 0:
                problems.append(f"predictor '{pred.name}' {knob} must be >= 0")
        if pred.tpu.weight_quant not in ("", "int8"):
            problems.append(
                f"predictor '{pred.name}' weight_quant "
                f"'{pred.tpu.weight_quant}' unsupported (want '' or 'int8')"
            )
        if pred.tpu.offload_compute not in ("auto", "always", "never"):
            problems.append(
                f"predictor '{pred.name}' offload_compute "
                f"'{pred.tpu.offload_compute}' must be auto|always|never"
            )
        if pred.tpu.decode_eos_id < -1:
            # -1 is the documented "no EOS" sentinel; anything below it is
            # a typo that would silently disable early retirement
            problems.append(
                f"predictor '{pred.name}' decode_eos_id must be >= -1"
            )
        for knob in (
            "decode_prefix_slots",
            "decode_prefix_ctx",
            "decode_prefill_chunk",
            "decode_kv_page_size",
            "decode_kv_pages",
            "decode_slo_ttft_ms",
            "decode_slo_itl_ms",
        ):
            if getattr(pred.tpu, knob) < 0:
                problems.append(f"predictor '{pred.name}' {knob} must be >= 0")
        if (
            pred.tpu.decode_slo_ttft_ms > 0 or pred.tpu.decode_slo_itl_ms > 0
        ) and pred.tpu.decode_slots <= 0:
            problems.append(
                f"predictor '{pred.name}' decode_slo_ttft_ms/decode_slo_itl_ms "
                "need decode_slots > 0 (the SLO attainment telemetry lives in "
                "the decode scheduler)"
            )
        if pred.tpu.decode_kv_dtype not in ("", "int8"):
            problems.append(
                f"predictor '{pred.name}' decode_kv_dtype "
                f"'{pred.tpu.decode_kv_dtype}' unsupported (want '' or 'int8')"
            )
        if (
            pred.tpu.decode_kv_page_size > 0
            or pred.tpu.decode_kv_pages > 0
            or pred.tpu.decode_kv_dtype
        ) and pred.tpu.decode_slots <= 0:
            # the paged-KV knobs configure the continuous-batching
            # scheduler's pool; without it they would be silently ignored
            problems.append(
                f"predictor '{pred.name}' decode_kv_page_size/decode_kv_pages/"
                "decode_kv_dtype need decode_slots > 0 (the continuous-"
                "batching scheduler)"
            )
        if (
            pred.tpu.decode_kv_page_size > 0
            and pred.tpu.decode_prefill_chunk > 0
            and pred.tpu.decode_prefill_chunk % pred.tpu.decode_kv_page_size != 0
        ):
            # page-aligned chunk rounds: every chunk boundary lands on a
            # page boundary, so chunked prefill never copy-on-writes its
            # own half-written page mid-prompt
            problems.append(
                f"predictor '{pred.name}' decode_prefill_chunk "
                f"({pred.tpu.decode_prefill_chunk}) must be a multiple of "
                f"decode_kv_page_size ({pred.tpu.decode_kv_page_size})"
            )
        if (
            pred.tpu.decode_kv_pages > 0
            and pred.tpu.decode_kv_pages < pred.tpu.decode_slots + 1
        ):
            # static half of the minimal-residency check (the scheduler
            # re-checks against the actual context geometry at build):
            # fewer pages than slots (+ the junk sink) can never reach the
            # configured concurrency — admission would starve, not deadlock,
            # but the config is unservable as asked
            problems.append(
                f"predictor '{pred.name}' decode_kv_pages "
                f"({pred.tpu.decode_kv_pages}) is below decode_slots + 1 "
                f"({pred.tpu.decode_slots + 1}) — the page budget cannot "
                "host the configured concurrency"
            )
        if pred.tpu.decode_mesh_axes:
            # tensor-parallel decode (parallel/tp.py): structural rules
            # here; the head/FFN divisibility rules need the model's
            # geometry and are enforced at scheduler build (hard error on
            # direct construction, warn-and-disable through serving)
            if len(pred.tpu.decode_mesh_axes) != 1:
                problems.append(
                    f"predictor '{pred.name}' decode_mesh_axes must name "
                    f"exactly one tensor-parallel axis, got "
                    f"{dict(pred.tpu.decode_mesh_axes)}"
                )
            for axis, size in pred.tpu.decode_mesh_axes.items():
                if size < 1:
                    problems.append(
                        f"predictor '{pred.name}' decode_mesh_axes axis "
                        f"'{axis}' must be >= 1"
                    )
            if pred.tpu.decode_slots <= 0:
                problems.append(
                    f"predictor '{pred.name}' decode_mesh_axes needs "
                    "decode_slots > 0 (the continuous-batching scheduler "
                    "owns the sharded decode programs)"
                )
            # NO device-budget check here: validation may run on a
            # control-plane host (operator/reconciler) whose device count
            # says nothing about the data plane's — same reason tpu.mesh
            # only checks sizes > 0. The data plane enforces the budget at
            # scheduler build (decode_mesh_problems) with warn-disable.
        if pred.tpu.decode_spec_k < 0:
            problems.append(f"predictor '{pred.name}' decode_spec_k must be >= 0")
        if pred.tpu.decode_spec_k > 0 or pred.tpu.decode_spec_tree:
            # speculation knobs configure the continuous-batching
            # scheduler and need a draft to propose with — without either
            # they were previously only caught (or silently ignored) at
            # trace time
            if pred.tpu.decode_slots <= 0:
                problems.append(
                    f"predictor '{pred.name}' decode_spec_k/decode_spec_tree "
                    "need decode_slots > 0 (the continuous-batching scheduler)"
                )
            if not pred.tpu.decode_draft_model:
                problems.append(
                    f"predictor '{pred.name}' decode_spec_k/decode_spec_tree "
                    "need decode_draft_model (the draft that proposes)"
                )
        if pred.tpu.decode_spec_tree:
            # the tree shape must parse AND fit the widened-verify /
            # draft-cache headroom: the verify dispatch materializes
            # [n_slots, 1 + n_tree, vocab] logits, so the flattened node
            # count is capped (MAX_TREE_NODES) — an oversized tree (or a
            # typo'd branching like "44" for "4,4") is a config error,
            # caught here instead of at trace time. No decode_mesh_axes
            # divisibility constraint exists for the tree: its axis is
            # REPLICATED over the mesh (parallel/tp.tree_node_sharding) —
            # the head/FFN divisibility rules, unchanged by the tree, are
            # the only mesh constraints and are enforced at scheduler
            # build where the model geometry is known.
            from seldon_core_tpu.models.spec_tree import MAX_TREE_NODES, SpecTree

            try:
                tree = SpecTree.from_text(pred.tpu.decode_spec_tree)
            except ValueError as e:
                problems.append(f"predictor '{pred.name}' decode_spec_tree: {e}")
            else:
                if tree.n_tree > MAX_TREE_NODES:
                    problems.append(
                        f"predictor '{pred.name}' decode_spec_tree "
                        f"'{pred.tpu.decode_spec_tree}' flattens to "
                        f"{tree.n_tree} nodes — the widened verify dispatch "
                        f"caps at {MAX_TREE_NODES}"
                    )
        elif pred.tpu.decode_spec_k > 0:
            # the chain rides the same widened dispatch (a k-chain IS a
            # branching-1 tree of k nodes) — same headroom cap; an
            # oversized meta.tags.spec_k can only TIGHTEN below this
            from seldon_core_tpu.models.spec_tree import MAX_TREE_NODES

            if pred.tpu.decode_spec_k > MAX_TREE_NODES:
                problems.append(
                    f"predictor '{pred.name}' decode_spec_k "
                    f"({pred.tpu.decode_spec_k}) exceeds the widened-verify "
                    f"headroom ({MAX_TREE_NODES} proposed tokens per dispatch)"
                )
        if not (0.0 <= pred.tpu.decode_spec_accept_floor < 1.0):
            problems.append(
                f"predictor '{pred.name}' decode_spec_accept_floor "
                f"({pred.tpu.decode_spec_accept_floor}) must be in [0, 1)"
            )
        if pred.tpu.decode_spec_accept_floor > 0 and not (
            pred.tpu.decode_spec_k > 0 or pred.tpu.decode_spec_tree
        ):
            problems.append(
                f"predictor '{pred.name}' decode_spec_accept_floor needs "
                "decode_spec_k > 0 or decode_spec_tree (nothing to adapt)"
            )
        # multi-replica decode scale-out (serving/affinity_router.py)
        if pred.tpu.decode_replicas < 1:
            problems.append(
                f"predictor '{pred.name}' decode_replicas must be >= 1"
            )
        if pred.tpu.decode_autoscale_replicas < 0:
            problems.append(
                f"predictor '{pred.name}' decode_autoscale_replicas must be >= 0"
            )
        if pred.tpu.decode_autoscale_queue_depth < 0:
            problems.append(
                f"predictor '{pred.name}' decode_autoscale_queue_depth must be >= 0"
            )
        fleet_max = max(pred.tpu.decode_replicas, pred.tpu.decode_autoscale_replicas)
        if fleet_max > 1:
            if pred.tpu.decode_slots <= 0:
                problems.append(
                    f"predictor '{pred.name}' decode_replicas/"
                    "decode_autoscale_replicas need decode_slots > 0 (the "
                    "replicated tier multiplies the continuous-batching "
                    "scheduler)"
                )
            if pred.tpu.decode_mesh_axes:
                problems.append(
                    f"predictor '{pred.name}' decode_replicas/"
                    "decode_autoscale_replicas cannot combine with "
                    "decode_mesh_axes yet (replica scale-out and tensor "
                    "parallelism partition the same device budget)"
                )
        if (
            0 < pred.tpu.decode_autoscale_replicas <= pred.tpu.decode_replicas
        ):
            # == is rejected too: a cap equal to the configured fleet
            # leaves the autoscaler nothing to add — the config would be
            # silently inert, the exact trap this block exists to close
            problems.append(
                f"predictor '{pred.name}' decode_autoscale_replicas "
                f"({pred.tpu.decode_autoscale_replicas}) must exceed "
                f"decode_replicas ({pred.tpu.decode_replicas}) — the "
                "autoscale cap needs headroom to scale into (and cannot "
                "shrink the configured fleet)"
            )
        if (
            pred.tpu.decode_autoscale_replicas > pred.tpu.decode_replicas
            and pred.tpu.decode_autoscale_queue_depth <= 0
        ):
            problems.append(
                f"predictor '{pred.name}' decode_autoscale_replicas needs "
                "decode_autoscale_queue_depth > 0 (the scale-up signal)"
            )
        if (
            pred.tpu.decode_autoscale_queue_depth > 0
            and pred.tpu.decode_autoscale_replicas <= 0
        ):
            problems.append(
                f"predictor '{pred.name}' decode_autoscale_queue_depth needs "
                "decode_autoscale_replicas > 0 (nothing to scale)"
            )
        if pred.tpu.decode_router_policy not in ("", "affinity", "round_robin", "bandit"):
            problems.append(
                f"predictor '{pred.name}' decode_router_policy "
                f"'{pred.tpu.decode_router_policy}' must be "
                "affinity|round_robin|bandit (or empty for the affinity "
                "default)"
            )
        if pred.tpu.decode_router_policy and fleet_max <= 1:
            problems.append(
                f"predictor '{pred.name}' decode_router_policy needs "
                "decode_replicas > 1 or decode_autoscale_replicas > 1 "
                "(one replica leaves nothing to route)"
            )
        if pred.tpu.decode_health_poll_ms < 0:
            problems.append(
                f"predictor '{pred.name}' decode_health_poll_ms must be >= 0"
            )
        if pred.tpu.decode_health_miss_threshold < 1:
            problems.append(
                f"predictor '{pred.name}' decode_health_miss_threshold must "
                "be >= 1 (zero would evict on the first poll)"
            )
        if pred.tpu.decode_drain_timeout_ms < 0:
            problems.append(
                f"predictor '{pred.name}' decode_drain_timeout_ms must be >= 0"
            )
        if pred.tpu.decode_health_poll_ms > 0 and fleet_max <= 1:
            problems.append(
                f"predictor '{pred.name}' decode_health_poll_ms needs "
                "decode_replicas > 1 or decode_autoscale_replicas > 1 (a "
                "single replica has no surviving arm to evict onto)"
            )
        if pred.tpu.decode_kv_host_bytes < 0:
            problems.append(
                f"predictor '{pred.name}' decode_kv_host_bytes must be >= 0"
            )
        if pred.tpu.decode_kv_host_bytes > 0 and pred.tpu.decode_prefix_slots <= 0:
            # the host tier demotes/promotes PREFIX entries — without the
            # prefix cache there is nothing to tier
            problems.append(
                f"predictor '{pred.name}' decode_kv_host_bytes needs "
                "decode_prefix_slots > 0 (the host tier holds demoted "
                "prefix-cache entries)"
            )
        if pred.tpu.decode_kv_store_tier and pred.tpu.decode_kv_host_bytes <= 0:
            problems.append(
                f"predictor '{pred.name}' decode_kv_store_tier needs "
                "decode_kv_host_bytes > 0 (the store is fed by the host "
                "tier's LRU)"
            )
        if pred.tpu.decode_prefix_ctx > 0 and pred.tpu.decode_prefix_slots == 0:
            problems.append(
                f"predictor '{pred.name}' decode_prefix_ctx needs "
                "decode_prefix_slots > 0"
            )
        if (
            pred.tpu.decode_prefix_slots > 0 or pred.tpu.decode_prefill_chunk > 0
        ) and pred.tpu.decode_slots <= 0:
            # without the scheduler these knobs would be silently ignored
            # (scheduler_for_executor returns None before reading them)
            problems.append(
                f"predictor '{pred.name}' decode_prefix_slots/decode_prefill_chunk "
                "need decode_slots > 0 (the continuous-batching scheduler)"
            )

    # wire semantics are DEPLOYMENT-level: the gateway classifies a body
    # before it knows which predictor will serve it, so predictors must
    # agree on whether binData is sniffed for npy
    toggles = {p.tpu.decode_npy_bindata for p in dep.spec.predictors}
    if len(toggles) > 1:
        problems.append(
            "all predictors must agree on tpu.decode_npy_bindata "
            "(wire-level sniffing is per-deployment, not per-predictor)"
        )

    if problems:
        raise ValidationError(problems)
