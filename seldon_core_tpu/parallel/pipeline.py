"""Pipeline parallelism over a mesh axis via shard_map + ppermute.

Greenfield vs the reference (its only scaling axis is k8s replicas): a
GPipe-style microbatch pipeline where each device along the "pipe" mesh axis
owns one stage's parameters and activations flow stage-to-stage over ICI
with ``lax.ppermute``. The schedule is the classic (M + S - 1)-tick loop: at
tick t, stage 0 feeds microbatch t while stage s works on microbatch t - s;
bubbles are the usual (S-1)/(M+S-1) fraction.

Backward comes for free: JAX differentiates through the scan + ppermute
(the transpose of a permute is the inverse permute), so jax.grad of a loss
over pipeline outputs yields the reverse-schedule backward pipeline without
hand-writing it — train steps in training/steps.py compose directly.

Stage parameters are a pytree whose leaves are stacked on axis 0 with length
|pipe| and sharded P("pipe", ...) — device s holds slice s (its stage).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from seldon_core_tpu.parallel.compat import pvary

StageFn = Callable[[Any, jax.Array], jax.Array]


def _pipeline_local(
    stage_params: Any,
    x_micro: jax.Array,
    stage_fn: StageFn,
    axis_name: str,
):
    """Per-device body. stage_params: this stage's params (leading stacked
    axis of size 1, squeezed). x_micro: [M, mb, ...] full microbatch stack
    (replicated; only stage 0 reads it). Returns [M, mb, ...] outputs valid
    on the LAST stage (zeros elsewhere)."""
    n_stages = lax.psum(1, axis_name)
    stage_id = lax.axis_index(axis_name)
    params = jax.tree.map(lambda a: a[0], stage_params)
    n_micro = x_micro.shape[0]
    ticks = n_micro + n_stages - 1

    mb_shape = x_micro.shape[1:]
    perm = [(i, i + 1) for i in range(n_stages - 1)]  # stage s -> s+1

    def tick(carry, t):
        recv, outs = carry
        feed_idx = jnp.clip(t, 0, n_micro - 1)
        inp = jnp.where(stage_id == 0, x_micro[feed_idx], recv)
        out = stage_fn(params, inp)
        # ship my output to the next stage (last stage's send is dropped)
        recv_next = lax.ppermute(out, axis_name, perm)
        # last stage stores microbatch t-(S-1) once the pipe is full
        store_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        is_valid = (t >= n_stages - 1) & (stage_id == n_stages - 1)
        outs = jnp.where(
            is_valid,
            outs.at[store_idx].set(out),
            outs,
        )
        return (recv_next, outs), None

    init_recv = pvary(jnp.zeros(mb_shape, x_micro.dtype), (axis_name,))
    init_outs = pvary(jnp.zeros_like(x_micro), (axis_name,))
    (_, outs), _ = lax.scan(tick, (init_recv, init_outs), jnp.arange(ticks))
    # broadcast the last stage's buffer to every device so the caller gets a
    # replicated result (psum of zeros elsewhere)
    outs = jnp.where(stage_id == n_stages - 1, outs, jnp.zeros_like(outs))
    return lax.psum(outs, axis_name)


def pipeline_apply(
    stage_fn: StageFn,
    stage_params: Any,
    x_micro: jax.Array,
    mesh: Mesh,
    *,
    pipe_axis: str = "pipe",
) -> jax.Array:
    """Run x_micro [M, mb, ...] through S pipeline stages.

    stage_params: pytree with leaves stacked [S, ...]; stage_fn(params, x)
    must map [mb, ...] -> [mb, ...] (uniform stage signature). Returns
    [M, mb, ...] outputs, replicated over the pipe axis.
    """
    n_stages = mesh.shape[pipe_axis]
    for leaf in jax.tree.leaves(stage_params):
        if leaf.shape[0] != n_stages:
            # a mismatch would silently run only each device's first local
            # stage slice (tree.map a[0]) and return wrong outputs
            raise ValueError(
                f"stage_params stacked axis is {leaf.shape[0]} but mesh "
                f"'{pipe_axis}' axis has {n_stages} devices — they must match"
            )
    param_specs = jax.tree.map(lambda _: P(pipe_axis), stage_params)
    from seldon_core_tpu.parallel.compat import shard_map

    fn = shard_map(
        partial(_pipeline_local, stage_fn=stage_fn, axis_name=pipe_axis),
        mesh=mesh,
        in_specs=(param_specs, P()),
        out_specs=P(),
    )
    return fn(stage_params, x_micro)
