"""jax version compatibility shims for manual-axes (shard_map) code."""

from __future__ import annotations

import jax
from jax import lax


def pvary(x, axes):
    """Mark x as varying over manual mesh axes. jax >= 0.9 renamed
    lax.pvary to lax.pcast(..., to='varying'); jax <= 0.5 has neither and
    does not type scan carries by mesh-axis variance, so identity is
    correct there."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axes)
    return x


def shard_map(*args, **kwargs):
    """jax >= 0.7 exports shard_map at top level; older versions keep it in
    jax.experimental.shard_map."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(*args, **kwargs)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(*args, **kwargs)
