"""jax version compatibility shims for manual-axes (shard_map) code."""

from __future__ import annotations

from jax import lax


def pvary(x, axes):
    """Mark x as varying over manual mesh axes. jax >= 0.9 renamed
    lax.pvary to lax.pcast(..., to='varying')."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    return lax.pvary(x, axes)
