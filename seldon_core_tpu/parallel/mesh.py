"""Device-mesh construction and axis conventions.

This replaces the reference's only scaling mechanism — k8s ``replicas`` of
whole predictor pods behind a Service (proto/seldon_deployment.proto:48) —
with SPMD over a ``jax.sharding.Mesh``:

    axis "data"   — batch sharding (the serving workhorse; ICI all-gather
                    only at the output edge)
    axis "model"  — tensor parallelism for models too big for one chip's HBM
    axis "seq"    — sequence/context parallelism (ring attention) for
                    long-sequence models (ops/ring_attention.py)
    axis "expert" — expert parallelism (MoE models)

Multi-host: `initialize_distributed()` wires jax.distributed across hosts of
a slice (ICI within, DCN across slices) — the TPU-native analogue of the
reference's pod-to-pod RPC mesh (SURVEY §5.8).
"""

from __future__ import annotations

import os
from typing import Mapping, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"


def mesh_from_spec(axes: Mapping[str, int] | None, devices=None) -> Mesh | None:
    """Build a Mesh from {axis_name: size}. Sizes must multiply to the device
    count used; a single-device request returns None (no sharding needed —
    plain jit is faster than a 1-device mesh)."""
    if not axes:
        return None
    devices = list(devices) if devices is not None else list(jax.devices())
    sizes = [int(s) for s in axes.values()]
    total = int(np.prod(sizes))
    if total == 1:
        return None
    if total > len(devices):
        # graceful degradation: shrink the data axis to what exists (serving
        # must come up on a smaller slice; reference analogue: fewer replicas)
        axes = dict(axes)
        shrink = total // len(devices)
        if DATA_AXIS in axes and axes[DATA_AXIS] % shrink == 0:
            axes[DATA_AXIS] //= shrink
            sizes = [int(s) for s in axes.values()]
            total = int(np.prod(sizes))
        if total > len(devices):
            raise ValueError(
                f"mesh {dict(axes)} needs {total} devices, have {len(devices)}"
            )
    mesh_devices = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(mesh_devices, tuple(axes.keys()))


def data_sharding(mesh: Mesh | None, axis: str = DATA_AXIS) -> NamedSharding | None:
    if mesh is None:
        return None
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh | None) -> NamedSharding | None:
    if mesh is None:
        return None
    return NamedSharding(mesh, P())


def initialize_distributed(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Multi-host init (jax.distributed). No-ops on single-host. Args default
    from the standard env vars so a k8s operator can inject them the same way
    the reference injects ENGINE_* vars."""
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if not coordinator:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes
        if num_processes is not None
        else int(os.environ.get("JAX_NUM_PROCESSES", "1")),
        process_id=process_id
        if process_id is not None
        else int(os.environ.get("JAX_PROCESS_ID", "0")),
    )
