from seldon_core_tpu.parallel.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    data_sharding,
    initialize_distributed,
    mesh_from_spec,
    replicated,
)
from seldon_core_tpu.parallel.tp import (
    decode_mesh_problems,
    decode_tp_mesh,
    decoder_param_pspecs,
    decoder_param_shardings,
    kv_sharding,
    tp_width,
)

__all__ = [
    "DATA_AXIS",
    "EXPERT_AXIS",
    "MODEL_AXIS",
    "SEQ_AXIS",
    "data_sharding",
    "decode_mesh_problems",
    "decode_tp_mesh",
    "decoder_param_pspecs",
    "decoder_param_shardings",
    "initialize_distributed",
    "kv_sharding",
    "mesh_from_spec",
    "replicated",
    "tp_width",
]
