from seldon_core_tpu.parallel.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    data_sharding,
    initialize_distributed,
    mesh_from_spec,
    replicated,
)

__all__ = [
    "DATA_AXIS",
    "EXPERT_AXIS",
    "MODEL_AXIS",
    "SEQ_AXIS",
    "data_sharding",
    "initialize_distributed",
    "mesh_from_spec",
    "replicated",
]
