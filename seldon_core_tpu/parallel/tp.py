"""Tensor-parallel partitioning for the generative decode tier.

The decode scheduler (serving/decode_scheduler.py) runs every fused
program — prefill chunk ladder, decode step, verify, draft, paged
copy/CoW — as ONE jit dispatch. This module supplies the shardings that
turn those dispatches into SPMD programs over a named device mesh
(``tpu.decode_mesh_axes``, e.g. ``{"tp": 4}``), following the
low-latency decode partitioning of Pope et al., *Efficiently Scaling
Transformer Inference* (2022):

- **attention sharded on the head axis**: the paged KV pool
  ``[L, n_pages, h, page_size, hd]``, the draft's flat slot cache
  ``[L, n_slots, h, ctx, hd]``, and every per-head attention tensor
  carry ``h`` split over the mesh axis — each device runs its heads'
  scores/softmax/context entirely locally (per-head attention has no
  cross-head reduction);
- **FFN sharded on the hidden axis**: ``mlp_in`` column-parallel
  (output ``ffn`` axis), ``mlp_out`` row-parallel (input ``ffn`` axis);
- **row-parallel output projections**: ``attn_out``'s input axis is
  sharded head-aligned (the merged ``h*hd`` activation axis is sharded
  by its head factor), so each residual branch ends in ONE fused
  all-reduce — two per layer (attention + FFN), the canonical
  Megatron/Pope pattern, inserted by GSPMD inside the already-fused
  step program (no extra dispatches);
- **everything else replicated**: layer norms, embeddings, the
  weight-tied lm head, and the packed ``qkv`` projection. ``qkv.w``
  stays replicated because its ``[hidden, 3*hidden]`` layout interleaves
  q/k/v at boundaries a contiguous shard cannot respect (slicing a
  sharded axis mid-shard would cost a reshard per layer); its redundant
  FLOPs are 3h^2 of the ~12h^2 per-token weight FLOPs, while the
  sharded tensors carry the attention + FFN majority AND the KV bytes —
  the HBM axis that actually caps decode concurrency.

int8 paged KV: the per-page-row (scale, zero-point) planes
``[L, n_pages, page_size]`` have no head axis and stay replicated —
quantization reduces over ``(h, hd)`` of REPLICATED fresh K/V rows, so
every device derives identical scales and the dequant fused into each
device's head-shard gather reads its local copy.

Host-side structures — block tables, the ``PageAllocator``, the radix
``PrefixIndex`` — are device-count-agnostic: a block table maps logical
to physical PAGES, and a page is itself head-sharded, so admission,
copy-on-write, and reclaim logic never see the mesh.

Greedy output stays token-identical to the single-device scheduler at
any width (asserted by tests/test_tp_decode.py and the ``gen.tp_*``
bench sub-leg): the partitioning only reorders floating-point
reductions inside the row-parallel matmuls, which the argmax margins of
the decode contract absorb.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from seldon_core_tpu.parallel.mesh import mesh_from_spec


def tp_width(mesh_axes) -> int:
    """The tensor-parallel width a ``decode_mesh_axes`` mapping asks for
    (1 when unset/empty — single-device)."""
    if not mesh_axes:
        return 1
    w = 1
    for size in mesh_axes.values():
        w *= int(size)
    return w


def decode_mesh_problems(mesh_axes, params=None, draft_params=None) -> list[str]:
    """Everything wrong with a ``decode_mesh_axes`` request, as a list of
    problems (empty = servable). Pure host checks: axis shape, device
    budget, and — when the decoder params are at hand — the divisibility
    rules head/FFN sharding needs. ``decode_tp_mesh`` raises these;
    ``scheduler_for_executor`` warn-and-disables on them (the spec-mode
    precedent for unservable opt-in configs)."""
    problems: list[str] = []
    if not mesh_axes:
        return problems
    if len(mesh_axes) != 1:
        problems.append(
            f"decode_mesh_axes supports exactly ONE tensor-parallel axis, "
            f"got {dict(mesh_axes)!r}"
        )
    for name, size in mesh_axes.items():
        if int(size) < 1:
            problems.append(f"decode_mesh_axes axis '{name}' must be >= 1, got {size}")
    tp = tp_width(mesh_axes)
    n_dev = len(jax.devices())
    if tp > n_dev:
        problems.append(
            f"decode_mesh_axes={dict(mesh_axes)} needs {tp} devices, have {n_dev}"
        )
    for what, p in (("decoder", params), ("draft", draft_params)):
        if p is None or tp <= 1:
            continue
        from seldon_core_tpu.models.decoder import decoder_dims

        dims = decoder_dims(p)
        ffn = p["layers"][0]["mlp_in"]["w"].shape[1]
        if dims["heads"] % tp:
            problems.append(
                f"{what} n_heads={dims['heads']} not divisible by tp width {tp} "
                "(attention is sharded on the head axis)"
            )
        if ffn % tp:
            problems.append(
                f"{what} ffn={ffn} not divisible by tp width {tp} "
                "(the FFN is sharded on its hidden axis)"
            )
    return problems


def decode_tp_mesh(mesh_axes, params=None, draft_params=None):
    """Build the decode mesh: ``(mesh, axis_name, tp_width)``.

    Returns ``(None, None, 1)`` for an unset/width-1 request (plain jit
    beats a 1-device mesh). Raises ValueError listing every problem —
    the scheduler's contract when handed mesh axes directly; the serving
    builder pre-checks with ``decode_mesh_problems`` and warn-disables
    instead, so a deployment degrades to single-device rather than
    failing to boot."""
    problems = decode_mesh_problems(mesh_axes, params, draft_params)
    if problems:
        raise ValueError("; ".join(problems))
    if tp_width(mesh_axes) <= 1:
        return None, None, 1
    mesh = mesh_from_spec(dict(mesh_axes))
    if mesh is None:
        return None, None, 1
    axis = mesh.axis_names[0]
    return mesh, axis, mesh.shape[axis]


def decoder_param_pspecs(params: dict, axis: str):
    """PartitionSpec pytree for the models/decoder.py param layout (see
    the module docstring for the partitioning rationale)."""

    def _ln(p):
        return {k: P() for k in p}

    def _layer(lp):
        return {
            "ln1": _ln(lp["ln1"]),
            # packed q/k/v boundaries don't align with contiguous shards
            "qkv": {"w": P(), "b": P()},
            # row-parallel: input axis sharded head-aligned, bias applied
            # to the all-reduced (replicated) output
            "attn_out": {"w": P(axis, None), "b": P()},
            "ln2": _ln(lp["ln2"]),
            # column-parallel: output ffn axis sharded, bias rides the shard
            "mlp_in": {"w": P(None, axis), "b": P(axis)},
            "mlp_out": {"w": P(axis, None), "b": P()},
        }

    out = {
        "tok_emb": P(),
        "pos_emb": P(),
        "layers": [_layer(lp) for lp in params["layers"]],
        "ln_f": _ln(params["ln_f"]),
    }
    if "fc" in params:
        # feature-draft head (models/decoder.init_feature_draft): the
        # [2*hidden -> hidden] feature+embedding fuse replicates — its
        # input is the replicated feat buffer + embedding, and its output
        # feeds the head's qkv which is replicated too
        out["fc"] = {"w": P(), "b": P()}
    return out


def decoder_param_shardings(params: dict, mesh: Mesh, axis: str):
    """NamedSharding pytree matching ``params``' structure."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        decoder_param_pspecs(params, axis),
        is_leaf=lambda x: isinstance(x, P),
    )


def kv_sharding(mesh: Mesh, axis: str, arr) -> NamedSharding:
    """Sharding for one KV-cache buffer: the 5-D layouts — page pool
    ``[L, n_pages, h, page_size, hd]`` and flat slot cache
    ``[L, n_slots, h, ctx, hd]`` — both carry heads at axis 2 and shard
    there; everything else (int8 scale/zero-point planes, which have no
    head axis) replicates."""
    if getattr(arr, "ndim", 0) == 5:
        return NamedSharding(mesh, P(None, None, axis, None, None))
    return NamedSharding(mesh, P())


def tree_node_sharding(mesh: Mesh, axis: str) -> NamedSharding:
    """Sharding for the tree-speculation round's IN-REGISTER node K/V
    ``[L, n_slots, h, n_tree, hd]`` (models/decoder.draft_propose_tree /
    paged_tree_verify outputs, alive only between the round's two
    dispatches): heads shard at axis 2 exactly like the persistent KV
    buffers; the TREE axis is replicated. Widening the verify to a token
    tree therefore adds NO collective — per-head scores/softmax over the
    tree's queries stay device-local and each residual branch still ends
    in the one fused all-reduce, so the tree composes with any mesh width
    the head/FFN divisibility rules admit (no tree-width divisibility
    constraint exists, by construction)."""
    return NamedSharding(mesh, P(None, None, axis, None, None))
