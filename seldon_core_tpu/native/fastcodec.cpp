// fastcodec: native wire-codec hot path for the serving runtime.
//
// Role in the framework: the reference's "native tier" is its Java engine —
// every request body is JSON-parsed and re-serialized on the hot path
// (engine/.../InternalPredictionService.java form-encoded json= hops). Our
// engine keeps the graph in-process, so the remaining CPU cost of a REST
// prediction is exactly (a) parsing the request's number matrix and
// (b) serializing the response's number matrix. Both are implemented here in
// C++ and bound via ctypes (native/__init__.py), with a pure-Python fallback
// when no compiler is available.
//
// Contract (all functions return 0 on success, negative error codes below):
//   ndarray_find   locate the value span of the first "ndarray" key
//   ndarray_probe  shape-check a rectangular 2D numeric JSON array
//   ndarray_parse  parse it into a caller-allocated float32 buffer
//   ndarray_encode serialize a float32 matrix to JSON into a caller buffer
//   pad_rows_f32   copy rows into a zero-padded bucket buffer
//
// Build: g++ -O3 -shared -fPIC (native/__init__.py compiles lazily and
// caches the .so next to this file).

#include <cstdio>
#include <cstdlib>
#include <cstring>

extern "C" {

enum {
  OK = 0,
  ERR_NOT_FOUND = -1,   // no "ndarray" key
  ERR_SYNTAX = -2,      // malformed JSON in the array span
  ERR_NOT_RECT = -3,    // ragged rows
  ERR_NOT_NUMERIC = -4, // strings/objects inside the array
  ERR_TOO_DEEP = -5,    // not a 1D/2D array
  ERR_BOUNDS = -6,      // caller buffer too small
};

static const char *skip_ws(const char *p, const char *end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
    ++p;
  return p;
}

// Find the first occurrence of the JSON key "ndarray" (outside of string
// values we can cheaply ignore: we scan for the quoted key then a colon) and
// return the [start, end) byte span of its value.
int ndarray_find(const char *buf, long len, long *start, long *end) {
  static const char key[] = "\"ndarray\"";
  const char *bufend = buf + len;
  const char *p = buf;
  bool in_str = false;
  while (p < bufend) {
    if (in_str) {
      if (*p == '\\' && p + 1 < bufend)
        ++p;
      else if (*p == '"')
        in_str = false;
      ++p;
      continue;
    }
    if (*p == '"') {
      if ((long)(bufend - p) >= (long)sizeof(key) - 1 &&
          memcmp(p, key, sizeof(key) - 1) == 0) {
        const char *q = skip_ws(p + sizeof(key) - 1, bufend);
        if (q < bufend && *q == ':') {
          q = skip_ws(q + 1, bufend);
          if (q >= bufend || *q != '[')
            return ERR_SYNTAX;
          // scan to the matching bracket (strings inside are rejected later
          // by probe, but skip them correctly here)
          long depth = 0;
          bool s = false;
          const char *r = q;
          while (r < bufend) {
            char c = *r;
            if (s) {
              if (c == '\\' && r + 1 < bufend)
                ++r;
              else if (c == '"')
                s = false;
            } else if (c == '"') {
              s = true;
            } else if (c == '[') {
              ++depth;
            } else if (c == ']') {
              if (--depth == 0) {
                *start = (long)(q - buf);
                *end = (long)(r - buf) + 1;
                return OK;
              }
            }
            ++r;
          }
          return ERR_SYNTAX;
        }
      }
      in_str = true;
      ++p;
      continue;
    }
    ++p;
  }
  return ERR_NOT_FOUND;
}

// Parse one number with strtod; returns nullptr on failure.
static const char *parse_num(const char *p, const char *end, double *out) {
  char *q;
  *out = strtod(p, &q);
  if (q == p || q > end)
    return nullptr;
  return q;
}

// Structural scan of one number: strict JSON number grammar
// ('-'? digits ('.' digits)? ([eE][+-]? digits)?) so the fast path accepts
// exactly what the Python oracle accepts — no strtod needed here, the parse
// pass re-reads the value. Returns nullptr on grammar violation.
static const char *scan_num(const char *p, const char *end) {
  const char *q = p;
  if (q < end && *q == '-')
    ++q;
  const char *int_start = q;
  while (q < end && *q >= '0' && *q <= '9')
    ++q;
  if (q == int_start)
    return nullptr; // no integer part ('.5', '+1', '-' alone all invalid)
  if (q < end && *q == '.') {
    ++q;
    const char *frac_start = q;
    while (q < end && *q >= '0' && *q <= '9')
      ++q;
    if (q == frac_start)
      return nullptr; // trailing dot ('5.')
  }
  if (q < end && (*q == 'e' || *q == 'E')) {
    ++q;
    if (q < end && (*q == '+' || *q == '-'))
      ++q;
    const char *exp_start = q;
    while (q < end && *q >= '0' && *q <= '9')
      ++q;
    if (q == exp_start)
      return nullptr;
  }
  return q;
}

// Probe a 1D or 2D numeric array: shape check + syntax check in one pass.
// 1D arrays report rows=1, cols=n, is2d=0.
int ndarray_probe(const char *buf, long len, long *rows, long *cols,
                  int *is2d) {
  const char *end = buf + len;
  const char *p = skip_ws(buf, end);
  if (p >= end || *p != '[')
    return ERR_SYNTAX;
  p = skip_ws(p + 1, end);
  if (p < end && *p == ']') { // empty array
    *rows = 0;
    *cols = 0;
    *is2d = 0;
    return OK;
  }
  if (p < end && *p == '[') {
    // 2D
    long r = 0, c_first = -1;
    while (true) {
      if (p >= end || *p != '[')
        return ERR_SYNTAX;
      p = skip_ws(p + 1, end);
      long c = 0;
      if (p < end && *p != ']') {
        while (true) {
          const char *q = scan_num(p, end);
          if (!q)
            return ERR_NOT_NUMERIC;
          ++c;
          p = skip_ws(q, end);
          if (p < end && *p == ',') {
            p = skip_ws(p + 1, end);
            continue;
          }
          break;
        }
      }
      if (p >= end || *p != ']')
        return ERR_SYNTAX;
      ++r;
      if (c_first < 0)
        c_first = c;
      else if (c != c_first)
        return ERR_NOT_RECT;
      p = skip_ws(p + 1, end);
      if (p < end && *p == ',') {
        p = skip_ws(p + 1, end);
        if (p < end && *p == '[')
          continue;
        return ERR_TOO_DEEP; // mixed 2D and scalar elements
      }
      break;
    }
    if (p >= end || *p != ']')
      return ERR_SYNTAX;
    *rows = r;
    *cols = c_first < 0 ? 0 : c_first;
    *is2d = 1;
    return OK;
  }
  // 1D
  long c = 0;
  while (true) {
    const char *q = scan_num(p, end);
    if (!q)
      return ERR_NOT_NUMERIC;
    ++c;
    p = skip_ws(q, end);
    if (p < end && *p == ',') {
      p = skip_ws(p + 1, end);
      continue;
    }
    break;
  }
  if (p >= end || *p != ']')
    return ERR_SYNTAX;
  *rows = 1;
  *cols = c;
  *is2d = 0;
  return OK;
}

// Fill a pre-allocated float32 buffer of rows*cols (caller ran probe).
int ndarray_parse(const char *buf, long len, float *out, long rows,
                  long cols) {
  const char *end = buf + len;
  const char *p = buf;
  long need = rows * cols, got = 0;
  while (p < end && got < need) {
    char ch = *p;
    if ((ch >= '0' && ch <= '9') || ch == '-') {
      // re-validate the token grammar (probe ran scan_num over the same
      // text, but defense-in-depth keeps the two passes agreeing), then
      // convert with strtod and require a structural terminator so
      // '1-2' / '1.2.3' can never silently parse as one number
      const char *tok_end = scan_num(p, end);
      if (!tok_end)
        return ERR_NOT_NUMERIC;
      double v;
      const char *q = parse_num(p, end, &v);
      if (!q || q != tok_end)
        return ERR_NOT_NUMERIC;
      if (q < end) {
        char t = *q;
        if (!(t == ',' || t == ']' || t == ' ' || t == '\t' || t == '\n' ||
              t == '\r'))
          return ERR_NOT_NUMERIC;
      }
      out[got++] = (float)v;
      p = q;
    } else {
      ++p;
    }
  }
  return got == need ? OK : ERR_SYNTAX;
}

// Serialize a float32 matrix as a 2D JSON array into dst (cap bytes incl.
// NUL). Returns bytes written (excl. NUL) or a negative error.
long ndarray_encode(const float *src, long rows, long cols, char *dst,
                    long cap) {
  long w = 0;
#define PUT(c)                                                                 \
  do {                                                                         \
    if (w + 1 >= cap)                                                          \
      return ERR_BOUNDS;                                                       \
    dst[w++] = (c);                                                            \
  } while (0)
  PUT('[');
  for (long r = 0; r < rows; ++r) {
    if (r)
      PUT(',');
    PUT('[');
    for (long c = 0; c < cols; ++c) {
      if (c)
        PUT(',');
      if (w + 32 >= cap)
        return ERR_BOUNDS;
      // %.9g round-trips float32 exactly
      int n = snprintf(dst + w, (size_t)(cap - w), "%.9g",
                       (double)src[r * cols + c]);
      if (n < 0)
        return ERR_SYNTAX;
      w += n;
    }
    PUT(']');
  }
  PUT(']');
  dst[w] = '\0';
  return w;
#undef PUT
}

// Copy n rows of feat floats into a bucket x feat buffer, zeroing the tail.
int pad_rows_f32(const float *src, long n, long feat, long bucket,
                 float *dst) {
  if (n > bucket)
    return ERR_BOUNDS;
  memcpy(dst, src, (size_t)(n * feat) * sizeof(float));
  memset(dst + n * feat, 0, (size_t)((bucket - n) * feat) * sizeof(float));
  return OK;
}

} // extern "C"

// ---------------------------------------------------------------------------
// HTTP/1.1 request-head parser — the fast ingress's hot-path front half
// (serving/fast_http.py). One pass over the buffer extracts everything the
// data plane needs: method/path spans, Content-Length, the raw Content-Type
// and Authorization values, and connection flags. Python keeps the full
// header-dict parse as the fallback/semantic reference.

extern "C" {

enum {
  HTTP_INCOMPLETE = 0,   // no \r\n\r\n yet — read more
  HTTP_MALFORMED = -1,
};

enum {
  HDRF_HAS_CTYPE = 1,
  HDRF_CONN_CLOSE = 2,
  HDRF_HAS_TE = 4,
  HDRF_HAS_CLEN = 8,
};

// RFC 7230 3.2.6 token charset for header field-names. Names containing
// anything else (form-feed, vertical tab, NBSP, NUL...) are rejected
// outright — lenient proxies normalize some of these, re-opening the
// hidden-Transfer-Encoding smuggling family if we merely mis-file them.
static int is_tchar(unsigned char c) {
  if ((c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') || (c >= '0' && c <= '9'))
    return 1;
  switch (c) {
    case '!': case '#': case '$': case '%': case '&': case '\'': case '*':
    case '+': case '-': case '.': case '^': case '_': case '`': case '|':
    case '~':
      return 1;
  }
  return 0;
}

static int ieq_n(const unsigned char *a, long n, const char *lit) {
  for (long i = 0; i < n; i++) {
    if (lit[i] == '\0') return 0;  // name longer than lit (embedded NUL safe)
    unsigned char c = a[i];
    if (c >= 'A' && c <= 'Z') c += 32;
    if (c != (unsigned char)lit[i]) return 0;
  }
  return lit[n] == '\0';
}

// Returns the body-start offset (> 0), HTTP_INCOMPLETE, or HTTP_MALFORMED.
// method/path are returned as (offset, length) into buf; header values are
// copied verbatim (caller buffers; value truncated to cap, reported length
// is the TRUNCATED length — caps are sized far above legal values).
long http_parse_head(const unsigned char *buf, long n,
                     long *method_len,
                     long *path_off, long *path_len,
                     long long *content_length, long *flags,
                     unsigned char *ctype_buf, long ctype_cap, long *ctype_len,
                     unsigned char *auth_buf, long auth_cap, long *auth_len) {
  *flags = 0;
  *content_length = -1;
  *ctype_len = -1;
  *auth_len = -1;
  // find end of head
  long head_end = -1;
  for (long i = 0; i + 3 < n; i++) {
    if (buf[i] == '\r' && buf[i + 1] == '\n' && buf[i + 2] == '\r' &&
        buf[i + 3] == '\n') {
      head_end = i;
      break;
    }
  }
  if (head_end < 0) return HTTP_INCOMPLETE;

  // strict line discipline over the whole head: every '\n' must be part of
  // a CRLF and every '\r' must start one. A bare LF accepted as a line
  // break by a tolerant front proxy (RFC 7230 3.5) would hide a
  // Transfer-Encoding header inside what we'd treat as a header VALUE —
  // the TE.CL smuggling family again, via framing disagreement
  for (long i = 0; i < head_end; i++) {
    if (buf[i] == '\n' && (i == 0 || buf[i - 1] != '\r')) return HTTP_MALFORMED;
    if (buf[i] == '\r' && buf[i + 1] != '\n') return HTTP_MALFORMED;
  }

  // request line: METHOD SP PATH SP VERSION
  long p = 0;
  while (p < head_end && buf[p] != ' ') p++;
  if (p == 0 || p >= head_end) return HTTP_MALFORMED;
  *method_len = p;
  long ps = p + 1;
  long pe = ps;
  // bound the path scan at the request line's own end: without this, a
  // request line missing the HTTP version would swallow header bytes
  while (pe < head_end && buf[pe] != ' ' && buf[pe] != '\r') pe++;
  if (pe == ps || pe >= head_end || buf[pe] != ' ') return HTTP_MALFORMED;
  *path_off = ps;
  *path_len = pe - ps;
  // skip to end of request line
  long line = pe;
  while (line + 1 < head_end && !(buf[line] == '\r' && buf[line + 1] == '\n'))
    line++;
  long pos = line + 2;  // first header line (or == head_end + something)

  while (pos < head_end) {
    long eol = pos;
    while (eol + 1 <= head_end && !(buf[eol] == '\r' && buf[eol + 1] == '\n'))
      eol++;
    // leading whitespace = obs-fold line continuation (RFC 7230 3.2.4):
    // reject rather than guess — a proxy that trims it would file
    // " Transfer-Encoding: chunked" under TE while we'd skip it
    if (buf[pos] == ' ' || buf[pos] == '\t') return HTTP_MALFORMED;
    // header: NAME ':' OWS VALUE
    long colon = pos;
    while (colon < eol && buf[colon] != ':') colon++;
    if (colon < eol) {
      long name_len = colon - pos;
      // RFC 7230 3.2.4/3.2.6: the field-name must be pure token chars —
      // rejects "Transfer-Encoding : chunked" (space before colon) and
      // form-feed/NBSP variants alike; empty names are malformed too
      if (name_len == 0) return HTTP_MALFORMED;
      for (long i = pos; i < colon; i++)
        if (!is_tchar(buf[i])) return HTTP_MALFORMED;
      long vs = colon + 1;
      while (vs < eol && (buf[vs] == ' ' || buf[vs] == '\t')) vs++;
      long ve = eol;
      while (ve > vs && (buf[ve - 1] == ' ' || buf[ve - 1] == '\t')) ve--;
      const unsigned char *name = buf + pos;
      if (ieq_n(name, name_len, "content-length")) {
        long long v = 0;
        int any = 0;
        for (long i = vs; i < ve; i++) {
          if (buf[i] < '0' || buf[i] > '9') return HTTP_MALFORMED;
          if (v > (1LL << 53)) return HTTP_MALFORMED;  // overflow guard:
          // a 20-digit length would wrap signed 64-bit (UB) and smuggle
          // body bytes into the next pipelined request
          v = v * 10 + (buf[i] - '0');
          any = 1;
        }
        if (!any) return HTTP_MALFORMED;
        // RFC 7230 3.3.2: multiple differing Content-Length values MUST be
        // rejected (CL.CL desync); equal duplicates are tolerated
        if ((*flags & HDRF_HAS_CLEN) && *content_length != v)
          return HTTP_MALFORMED;
        *content_length = v;
        *flags |= HDRF_HAS_CLEN;
      } else if (ieq_n(name, name_len, "content-type")) {
        *flags |= HDRF_HAS_CTYPE;
        long len = ve - vs;
        if (len > ctype_cap) len = ctype_cap;
        memcpy(ctype_buf, buf + vs, (size_t)len);
        *ctype_len = len;
      } else if (ieq_n(name, name_len, "authorization")) {
        long len = ve - vs;
        if (len > auth_cap) len = auth_cap;
        memcpy(auth_buf, buf + vs, (size_t)len);
        *auth_len = len;
      } else if (ieq_n(name, name_len, "connection")) {
        if (ve - vs == 5 && ieq_n(buf + vs, 5, "close")) *flags |= HDRF_CONN_CLOSE;
      } else if (ieq_n(name, name_len, "transfer-encoding")) {
        // ANY Transfer-Encoding (chunked, "gzip, chunked", unknown codings)
        // is outside this server's contract; flag on presence so the caller
        // rejects instead of framing by Content-Length (TE.CL smuggling)
        *flags |= HDRF_HAS_TE;
      }
    }
    pos = eol + 2;
  }
  return head_end + 4;
}

} // extern "C"
