"""Native wire codec: lazy g++ build + ctypes binding, Python fallback.

The C++ side (fastcodec.cpp) parses/serializes the ndarray number matrix —
the dominant CPU cost of a REST prediction once the graph runs in-process.
This module compiles it on first use (cached .so next to the source,
rebuilt when the .cpp is newer) and exposes:

    find_ndarray_span(raw: bytes) -> (start, end) | None
    parse_ndarray(raw: bytes) -> np.ndarray (float32, 1D or 2D) | None
    encode_ndarray(arr) -> bytes | None
    pad_rows(arr, bucket) -> np.ndarray

Every entry returns None (or falls back to numpy) when the library is
unavailable or the payload isn't a rectangular numeric array — callers keep
the pure-Python path as the semantic source of truth.
"""

from __future__ import annotations

import contextlib
import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

log = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "fastcodec.cpp")
_SO = os.path.join(_HERE, "_fastcodec.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _build() -> str | None:
    try:
        if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
            return _SO
        # pid-unique temp name: concurrent processes (platform + microservice
        # on one host) may both build; a shared .tmp path would interleave
        # writes and os.replace could install a corrupt .so
        tmp = f"{_SO}.tmp.{os.getpid()}"
        try:
            res = subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC],
                capture_output=True,
                timeout=120,
            )
            if res.returncode != 0:
                log.warning("fastcodec build failed: %s", res.stderr.decode()[:500])
                return None
            os.replace(tmp, _SO)
        finally:
            # failed/timed-out builds must not strand pid-unique temp files
            # in the package dir (they are never overwritten by later pids)
            with contextlib.suppress(OSError):
                os.unlink(tmp)
        return _SO
    except Exception as e:  # noqa: BLE001 - no compiler / RO filesystem
        log.warning("fastcodec build unavailable: %s", e)
        return None


def get_lib():
    """The loaded library or None. Thread-safe, builds at most once."""
    global _lib, _build_failed
    if _lib is not None or _build_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        path = _build()
        if path is None:
            _build_failed = True
            return None
        lib = ctypes.CDLL(path)
        lib.ndarray_find.restype = ctypes.c_int
        lib.ndarray_find.argtypes = [
            ctypes.c_char_p,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long),
        ]
        lib.ndarray_probe.restype = ctypes.c_int
        lib.ndarray_probe.argtypes = [
            ctypes.c_char_p,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.ndarray_parse.restype = ctypes.c_int
        lib.ndarray_parse.argtypes = [
            ctypes.c_char_p,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_long,
            ctypes.c_long,
        ]
        lib.ndarray_encode.restype = ctypes.c_long
        lib.ndarray_encode.argtypes = [
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_long,
            ctypes.c_long,
            ctypes.c_char_p,
            ctypes.c_long,
        ]
        lib.pad_rows_f32.restype = ctypes.c_int
        lib.pad_rows_f32.argtypes = [
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_long,
            ctypes.c_long,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_float),
        ]
        lib.http_parse_head.restype = ctypes.c_long
        lib.http_parse_head.argtypes = [
            ctypes.c_char_p,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_long),  # method_len
            ctypes.POINTER(ctypes.c_long),  # path_off
            ctypes.POINTER(ctypes.c_long),  # path_len
            ctypes.POINTER(ctypes.c_longlong),  # content_length
            ctypes.POINTER(ctypes.c_long),  # flags
            ctypes.c_char_p,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_long),  # ctype
            ctypes.c_char_p,
            ctypes.c_long,
            ctypes.POINTER(ctypes.c_long),  # auth
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


def find_ndarray_span(raw: bytes) -> tuple[int, int] | None:
    lib = get_lib()
    if lib is None:
        return None
    start, end = ctypes.c_long(), ctypes.c_long()
    rc = lib.ndarray_find(raw, len(raw), ctypes.byref(start), ctypes.byref(end))
    if rc != 0:
        return None
    return start.value, end.value


def parse_ndarray(raw: bytes) -> np.ndarray | None:
    """Parse a JSON 1D/2D numeric array (bytes) to float32. None on any
    deviation (ragged, strings, nesting >2) — caller falls back to json."""
    lib = get_lib()
    if lib is None:
        return None
    rows, cols = ctypes.c_long(), ctypes.c_long()
    is2d = ctypes.c_int()
    rc = lib.ndarray_probe(
        raw, len(raw), ctypes.byref(rows), ctypes.byref(cols), ctypes.byref(is2d)
    )
    if rc != 0:
        return None
    r, c = rows.value, cols.value
    out = np.empty(r * c, dtype=np.float32)
    if r * c:
        rc = lib.ndarray_parse(
            raw, len(raw), out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), r, c
        )
        if rc != 0:
            return None
    return out.reshape(r, c) if is2d.value else out.reshape(c)


def encode_ndarray(arr: np.ndarray) -> bytes | None:
    """float32 2D matrix -> JSON bytes ('[[...],[...]]'). None if lib absent
    or array not 2D float-convertible."""
    lib = get_lib()
    if lib is None or arr.ndim != 2:
        return None
    a = np.ascontiguousarray(arr, dtype=np.float32)
    cap = a.size * 32 + a.shape[0] * 2 + 16
    buf = ctypes.create_string_buffer(cap)
    n = lib.ndarray_encode(
        a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        a.shape[0],
        a.shape[1],
        buf,
        cap,
    )
    if n < 0:
        return None
    return buf.raw[:n]


def pad_rows(arr: np.ndarray, bucket: int) -> np.ndarray:
    """Zero-pad the batch axis to ``bucket`` (C memcpy when available)."""
    a = np.ascontiguousarray(arr, dtype=np.float32)
    n, feat = a.shape[0], int(np.prod(a.shape[1:], initial=1))
    lib = get_lib()
    if lib is None:
        out = np.zeros((bucket, *a.shape[1:]), dtype=np.float32)
        out[:n] = a
        return out
    out = np.empty((bucket, *a.shape[1:]), dtype=np.float32)
    rc = lib.pad_rows_f32(
        a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        n,
        feat,
        bucket,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    if rc != 0:
        raise ValueError(f"pad_rows: batch {n} exceeds bucket {bucket}")
    return out


# HTTP head-parse flag bits (mirror fastcodec.cpp)
HDRF_HAS_CTYPE = 1
HDRF_CONN_CLOSE = 2
HDRF_HAS_TE = 4  # Transfer-Encoding header present (any value)
HDRF_HAS_CLEN = 8


class ParsedHead:
    """One parsed HTTP/1.1 request head (C fast path)."""

    __slots__ = ("body_start", "method", "path", "content_length", "flags",
                 "content_type", "authorization")

    def __init__(self, body_start, method, path, content_length, flags,
                 content_type, authorization):
        self.body_start = body_start
        self.method = method
        self.path = path
        self.content_length = content_length  # -1 when header absent
        self.flags = flags
        self.content_type = content_type  # raw value or None
        self.authorization = authorization  # raw value or None


# single source of truth for the head-parse out-buffer capacities: the
# scratch allocation, the caps passed to C, and the truncation checks must
# move together (a cap raised past the allocation would make the C memcpy a
# heap overflow)
_CTYPE_CAP = 512
_AUTH_CAP = 4096

_parse_tls = threading.local()


def _parse_scratch():
    """Per-thread reusable ctypes out-params for parse_http_head: the hot
    path calls it once per request, and allocating two string buffers plus
    eight ctypes scalars each time measured ~25 us/request of pure wrapper
    overhead on the serving profile."""
    s = getattr(_parse_tls, "scratch", None)
    if s is None:
        s = (
            ctypes.c_long(),  # method_len
            ctypes.c_long(),  # path_off
            ctypes.c_long(),  # path_len
            ctypes.c_longlong(),  # clen
            ctypes.c_long(),  # flags
            ctypes.create_string_buffer(_CTYPE_CAP),
            ctypes.c_long(),  # ctype_len
            ctypes.create_string_buffer(_AUTH_CAP),
            ctypes.c_long(),  # auth_len
        )
        _parse_tls.scratch = s
    return s


def parse_http_head(buf) -> "ParsedHead | int | None":
    """Parse an HTTP/1.1 request head in one C pass.

    Returns a ParsedHead, 0 when the head is incomplete (read more), -1
    when malformed, or None when the native library is unavailable (caller
    uses its Python parse)."""
    lib = get_lib()
    if lib is None:
        return None
    raw = bytes(buf)
    (
        method_len,
        path_off,
        path_len,
        clen,
        flags,
        ctype_buf,
        ctype_len,
        auth_buf,
        auth_len,
    ) = _parse_scratch()
    rc = lib.http_parse_head(
        raw, len(raw),
        ctypes.byref(method_len),
        ctypes.byref(path_off), ctypes.byref(path_len),
        ctypes.byref(clen), ctypes.byref(flags),
        ctype_buf, _CTYPE_CAP, ctypes.byref(ctype_len),
        auth_buf, _AUTH_CAP, ctypes.byref(auth_len),
    )
    if rc <= 0:
        # incomplete/malformed heads can still have memcpy'd an
        # Authorization value before the parse stopped (e.g. auth header
        # followed by a bad Content-Length) — the reused per-thread scratch
        # must not retain it on ANY exit path, same invariant as below
        ctypes.memset(auth_buf, 0, _AUTH_CAP)
        return 0 if rc == 0 else -1
    if ctype_len.value >= _CTYPE_CAP or auth_len.value >= _AUTH_CAP:
        # possible truncation (oversized JWTs etc.): a clipped credential
        # would 401 on this path but pass the Python parse — hand the
        # request to the uncapped Python parser instead
        ctypes.memset(auth_buf, 0, _AUTH_CAP)
        return None
    head = ParsedHead(
        body_start=int(rc),
        method=raw[: method_len.value].decode("latin-1"),
        path=raw[path_off.value : path_off.value + path_len.value].decode("latin-1"),
        content_length=int(clen.value),
        flags=int(flags.value),
        content_type=(
            ctype_buf.raw[: ctype_len.value].decode("latin-1")
            if ctype_len.value >= 0
            else None
        ),
        authorization=(
            auth_buf.raw[: auth_len.value].decode("latin-1")
            if auth_len.value >= 0
            else None
        ),
    )
    if auth_len.value > 0:
        # the reused scratch must not retain the client's credential past
        # the request (a core dump would otherwise hold the latest JWT per
        # thread at a stable address)
        ctypes.memset(auth_buf, 0, auth_len.value)
    return head
