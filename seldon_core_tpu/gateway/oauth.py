"""OAuth2 provider for the ingress gateway.

Parity: reference api-frontend Spring OAuth2 stack (C15) —
AuthorizationServerConfiguration.java (RedisTokenStore, client_credentials +
password grants), InMemoryClientDetailsService.java:34-44 (12 h token
lifetime, one client per deployment keyed by oauth_key). Token persistence is
pluggable: in-memory for single-process, file-backed so gateway restarts keep
sessions (the reference uses Redis for exactly that), redis if available.
"""

from __future__ import annotations

import json
import os
import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

TOKEN_TTL_S = 12 * 3600  # reference: 12h (InMemoryClientDetailsService.java:41-43)


@dataclass
class TokenRecord:
    client_id: str
    expires_at: float


class InMemoryTokenStore:
    """Token -> principal map with expiry (RedisTokenStore stand-in)."""

    def __init__(self):
        self._tokens: dict[str, TokenRecord] = {}
        self._lock = threading.Lock()

    def put(self, token: str, record: TokenRecord) -> None:
        with self._lock:
            self._tokens[token] = record

    def get(self, token: str) -> Optional[TokenRecord]:
        with self._lock:
            rec = self._tokens.get(token)
            if rec is None:
                return None
            if rec.expires_at < time.time():
                del self._tokens[token]
                return None
            return rec

    def revoke_client(self, client_id: str) -> None:
        with self._lock:
            self._tokens = {
                t: r for t, r in self._tokens.items() if r.client_id != client_id
            }


class FileTokenStore(InMemoryTokenStore):
    """Durable token store: gateway restarts don't invalidate sessions — the
    property the reference gets from Redis (AuthorizationServerConfiguration
    .java:64-67). Append-only JSONL (token grants + revoke tombstones) so a
    token issuance is O(1) I/O, not a whole-file rewrite; the log is
    compacted to live tokens on load."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        if os.path.exists(path):
            try:
                with open(path) as f:
                    for line in f:
                        rec = json.loads(line)
                        if "revoke_client" in rec:
                            cid = rec["revoke_client"]
                            self._tokens = {
                                t: r
                                for t, r in self._tokens.items()
                                if r.client_id != cid
                            }
                        elif rec.get("expires_at", 0) > time.time():
                            self._tokens[rec["token"]] = TokenRecord(
                                rec["client_id"], rec["expires_at"]
                            )
            except Exception:  # noqa: BLE001 - corrupt store: start clean
                self._tokens = {}
            self._compact()

    def _compact(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            for t, r in self._tokens.items():
                f.write(
                    json.dumps(
                        {"token": t, "client_id": r.client_id, "expires_at": r.expires_at}
                    )
                    + "\n"
                )
        os.replace(tmp, self.path)

    def _append(self, rec: dict) -> None:
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")

    def put(self, token: str, record: TokenRecord) -> None:
        with self._lock:
            self._tokens[token] = record
            self._append(
                {
                    "token": token,
                    "client_id": record.client_id,
                    "expires_at": record.expires_at,
                }
            )

    def revoke_client(self, client_id: str) -> None:
        with self._lock:
            self._tokens = {
                t: r for t, r in self._tokens.items() if r.client_id != client_id
            }
            self._append({"revoke_client": client_id})


def make_token_store(url: str | None = None):
    """'' | None -> in-memory; file://<path> -> durable file;
    redis://host[:port] -> redis when the client lib is importable."""
    if not url:
        return InMemoryTokenStore()
    if url.startswith("file://"):
        return FileTokenStore(url[len("file://") :])
    if url.startswith("redis://"):
        try:
            return RedisTokenStore(url)
        except ImportError:
            return InMemoryTokenStore()
    raise ValueError(f"unknown token store url: {url}")


class RedisTokenStore(InMemoryTokenStore):
    """Redis-backed store, key per token with native TTL expiry."""

    def __init__(self, url: str):
        import redis  # gated: not in the base image

        super().__init__()
        self._r = redis.Redis.from_url(url)

    def put(self, token: str, record: TokenRecord) -> None:
        ttl = max(1, int(record.expires_at - time.time()))
        self._r.setex(f"oauth:{token}", ttl, record.client_id)

    def get(self, token: str) -> Optional[TokenRecord]:
        cid = self._r.get(f"oauth:{token}")
        if cid is None:
            return None
        return TokenRecord(cid.decode(), time.time() + 1)

    def revoke_client(self, client_id: str) -> None:
        for key in self._r.scan_iter("oauth:*"):
            if self._r.get(key) == client_id.encode():
                self._r.delete(key)


@dataclass
class ClientDetails:
    client_id: str
    client_secret: str
    scopes: tuple[str, ...] = ("read", "write")


class OAuthProvider:
    """client_credentials (and password-grant, accepted but identical) token
    issuance + validation. One registered client per deployment, exactly the
    reference's DeploymentStore.deploymentAdded -> addClient flow."""

    def __init__(self, token_store=None):
        self.tokens = token_store or InMemoryTokenStore()
        self._clients: dict[str, ClientDetails] = {}
        self._lock = threading.Lock()

    # ---- client registry (driven by the deployment store)
    def add_client(self, client_id: str, client_secret: str) -> None:
        with self._lock:
            self._clients[client_id] = ClientDetails(client_id, client_secret)

    def remove_client(self, client_id: str) -> None:
        with self._lock:
            self._clients.pop(client_id, None)
        self.tokens.revoke_client(client_id)

    def has_client(self, client_id: str) -> bool:
        with self._lock:
            return client_id in self._clients

    # ---- grants
    def issue_token(self, client_id: str, client_secret: str) -> dict:
        """Returns the standard token response or raises PermissionError."""
        with self._lock:
            details = self._clients.get(client_id)
        if details is None or not secrets.compare_digest(
            details.client_secret, client_secret
        ):
            raise PermissionError("invalid client credentials")
        token = secrets.token_urlsafe(32)
        self.tokens.put(token, TokenRecord(client_id, time.time() + TOKEN_TTL_S))
        return {
            "access_token": token,
            "token_type": "bearer",
            "expires_in": TOKEN_TTL_S,
            "scope": "read write",
        }

    def principal(self, token: str) -> Optional[str]:
        rec = self.tokens.get(token)
        return rec.client_id if rec else None
