"""Request/response audit stream.

Parity: reference api-frontend Kafka producer (C17,
KafkaRequestResponseProducer.java) — publishes the (request, response) pair
to a topic named after the OAuth client id, fire-and-forget, and the gateway
must keep serving when the broker is down (:49-57 catches producer errors).

Sinks are pluggable: in-memory ring (tests), JSONL file per client (the
single-host log equivalent of a topic), Kafka when a client lib exists.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any

from seldon_core_tpu.core.codec_json import message_to_dict
from seldon_core_tpu.core.message import SeldonMessage


class AuditSink:
    def send(self, client_id: str, request: SeldonMessage, response: SeldonMessage) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class NullAuditSink(AuditSink):
    def send(self, client_id, request, response) -> None:
        pass


class MemoryAuditSink(AuditSink):
    """Bounded ring per client (test double for the Kafka consumer check
    kafka/tests/src/read_predictions.py)."""

    def __init__(self, maxlen: int = 1000):
        self.topics: dict[str, collections.deque] = {}
        self._lock = threading.Lock()
        self.maxlen = maxlen

    def send(self, client_id, request, response) -> None:
        with self._lock:
            topic = self.topics.setdefault(client_id, collections.deque(maxlen=self.maxlen))
            topic.append(
                {
                    "ts": time.time(),
                    "request": message_to_dict(request),
                    "response": message_to_dict(response),
                }
            )


class JsonlAuditSink(AuditSink):
    """One append-only JSONL file per client id under ``directory`` — the
    single-host stand-in for one Kafka topic per client."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()

    def send(self, client_id, request, response) -> None:
        record = {
            "ts": time.time(),
            "request": message_to_dict(request),
            "response": message_to_dict(response),
        }
        safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in client_id) or "anon"
        path = os.path.join(self.directory, f"{safe}.jsonl")
        line = json.dumps(record) + "\n"
        try:
            with self._lock, open(path, "a") as f:
                f.write(line)
        except OSError:
            # audit must never take down serving (reference
            # KafkaRequestResponseProducer.java:68-71 swallows the same way)
            pass


class KafkaAuditSink(AuditSink):
    """Kafka producer when a client library is importable; errors are
    swallowed like the reference's (KafkaRequestResponseProducer.java:68-71 —
    audit must never take down serving)."""

    def __init__(self, bootstrap: str):
        from kafka import KafkaProducer  # gated: not in the base image

        self._producer = KafkaProducer(
            bootstrap_servers=bootstrap,
            value_serializer=lambda v: json.dumps(v).encode(),
        )

    def send(self, client_id, request, response) -> None:
        try:
            self._producer.send(
                client_id,
                {
                    "ts": time.time(),
                    "request": message_to_dict(request),
                    "response": message_to_dict(response),
                },
            )
        except Exception:  # noqa: BLE001
            pass


def make_audit_sink(url: str | None) -> AuditSink:
    """'' | None -> null; mem:// -> memory; file://<dir> -> jsonl;
    kafka://host:port -> kafka (falls back to null if lib missing)."""
    if not url:
        return NullAuditSink()
    if url.startswith("mem://"):
        return MemoryAuditSink()
    if url.startswith("file://"):
        return JsonlAuditSink(url[len("file://") :])
    if url.startswith("kafka://"):
        try:
            return KafkaAuditSink(url[len("kafka://") :])
        except ImportError:
            return NullAuditSink()
    raise ValueError(f"unknown audit sink url: {url}")
