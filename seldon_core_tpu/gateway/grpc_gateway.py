"""gRPC ingress gateway with oauth_token metadata auth.

Parity (C16): reference api-frontend SeldonGrpcServer.java +
HeaderServerInterceptor.java:42-75 — reads metadata key ``oauth_token``,
validates it against the token store, resolves the principal's deployment,
and forwards Seldon.Predict / Seldon.SendFeedback. The reference keeps a
per-deployment ManagedChannel cache (:114-132, 197-203); the in-process
backend makes that a dict lookup, and the channel-cache behavior survives in
RemoteBackend's pooled session.

Two server modes (VERDICT r4 Next #2 — the gRPC ingress ran at 28% of the
REST fast ingress; the full floor analysis with every number below lives in
docs/reference/external-api.md §"gRPC ingress floor"):

- ``aio`` (default): pure grpc.aio — everything on the event loop.
  Measured on the 1-core bench host: a zero-logic echo tops out at
  ~3.4k RPC/s (~19 asyncio callback dispatches per unary call under
  cProfile) — already BELOW the ~5.1k req/s the complete REST fast-ingress
  path sustains on the same core. The gateway logic itself adds only
  ~92 us CPU per RPC (auth 9 + proto decode 57 + encode 25).
- ``sync``: the C-core ``grpc.server`` with a small thread pool; HTTP/2
  framing, flow control, and proto parse run in C threads, and each RPC
  bridges ONCE into the asyncio loop (run_coroutine_threadsafe) where
  auth -> codec -> backend -> audit stay loop-confined exactly as in the
  REST path. Echo measures ~5.1k RPC/s (+48%) — but on a single shared
  core the thread<->loop bridge hop erases the win for the loop-confined
  batcher (full path measured 3.5k vs aio's 5.8k preds/s), so aio stays
  the default there. On multi-core hosts the C threads run beside the
  loop and ``mode='sync'`` is the right pick.
"""

from __future__ import annotations

import asyncio

import grpc

from seldon_core_tpu.core.codec_proto import (
    feedback_from_proto,
    message_from_proto,
    message_to_proto,
)
from seldon_core_tpu.core.errors import APIException
from seldon_core_tpu.core.message import SeldonMessage
from seldon_core_tpu.proto.services import add_service

OAUTH_METADATA_KEY = "oauth_token"  # HeaderServerInterceptor.java:42-44


def _gateway_methods(gw):
    """The loop-confined request coroutines shared by both server modes."""

    def _auth(metadata) -> tuple[str, object]:
        token = ""
        for key, value in metadata or ():
            if key == OAUTH_METADATA_KEY:
                token = value
                break
        principal = gw.oauth.principal(token) if token else None
        if not principal:
            from seldon_core_tpu.core.errors import ErrorCode

            raise APIException(ErrorCode.APIFE_GRPC_NO_PRINCIPAL_FOUND, "oauth_token")
        return principal, gw._deployment(principal)

    async def predict(request, metadata):
        try:
            principal, dep = _auth(metadata)
            msg = message_from_proto(request)
            # W3C trace context rides gRPC metadata exactly like the REST
            # header — forwarded so the engine continues the caller's trace
            tp = next(
                (v for k, v in metadata or () if k == "traceparent"), None
            )
            out = await gw.backend.predict(dep, msg, traceparent=tp)
            gw.audit.send(principal, msg, out)
            return message_to_proto(out)
        except APIException as e:
            msg = SeldonMessage.failure(e.error.code, e.error.message, e.info)
            return message_to_proto(msg)

    async def send_feedback(request, metadata):
        try:
            principal, dep = _auth(metadata)
            out = await gw.backend.feedback(dep, feedback_from_proto(request))
            return message_to_proto(out)
        except APIException as e:
            msg = SeldonMessage.failure(e.error.code, e.error.message, e.info)
            return message_to_proto(msg)

    return predict, send_feedback


async def start_gateway_grpc(
    gw, host: str = "0.0.0.0", port: int = 5000, mode: str = "aio"
):
    """Start the gRPC ingress. ``mode='aio'`` (default) = pure grpc.aio,
    fastest when the backend shares the core with the loop; ``mode='sync'``
    = C-core server + one loop bridge per RPC, the pick for multi-core
    hosts (see module docstring for the measured tradeoff). Both return an
    object with an async ``stop(grace)``."""
    if mode == "aio":
        return await _start_aio(gw, host, port)
    if mode != "sync":
        raise ValueError(f"grpc gateway mode must be 'sync' or 'aio', got {mode!r}")
    return await _start_sync(gw, host, port)


async def _start_aio(gw, host: str, port: int) -> grpc.aio.Server:
    server = grpc.aio.server(
        options=[
            ("grpc.max_receive_message_length", 64 * 1024 * 1024),
            ("grpc.max_send_message_length", 64 * 1024 * 1024),
        ]
    )
    predict, send_feedback = _gateway_methods(gw)

    async def predict_rpc(request, context):
        return await predict(request, context.invocation_metadata())

    async def feedback_rpc(request, context):
        return await send_feedback(request, context.invocation_metadata())

    add_service(
        server, "Seldon", {"Predict": predict_rpc, "SendFeedback": feedback_rpc}
    )
    server.add_insecure_port(f"{host}:{port}")
    await server.start()
    return server


class _SyncBridgeServer:
    """C-core grpc.server whose handlers bridge into the asyncio loop.

    The worker thread does only: deserialized-request in (C parse already
    done), ONE run_coroutine_threadsafe into the loop that owns the
    batcher/backend, blocking result wait, serialized response out (C).
    App logic stays loop-confined — the same single-writer discipline the
    REST ingress relies on, so no gateway/backend state needs locks."""

    def __init__(self, server: grpc.Server, loop: asyncio.AbstractEventLoop):
        self._server = server
        self._loop = loop

    async def stop(self, grace):
        # grpc.Server.stop is thread-safe and non-blocking; wait off-loop
        event = self._server.stop(grace)
        await asyncio.get_running_loop().run_in_executor(None, event.wait)


async def _start_sync(gw, host: str, port: int) -> _SyncBridgeServer:
    from concurrent import futures as _futures

    loop = asyncio.get_running_loop()
    predict, send_feedback = _gateway_methods(gw)

    def bridge(coro_fn):
        def handler(request, context):
            fut = asyncio.run_coroutine_threadsafe(
                coro_fn(request, context.invocation_metadata()), loop
            )
            return fut.result()

        return handler

    server = grpc.server(
        # few threads: handlers only park on the loop bridge; C-core does
        # the HTTP/2 + parse work on its own event engine threads
        _futures.ThreadPoolExecutor(max_workers=4),
        options=[
            ("grpc.max_receive_message_length", 64 * 1024 * 1024),
            ("grpc.max_send_message_length", 64 * 1024 * 1024),
        ],
    )
    add_service(
        server,
        "Seldon",
        {"Predict": bridge(predict), "SendFeedback": bridge(send_feedback)},
    )
    server.add_insecure_port(f"{host}:{port}")
    server.start()
    return _SyncBridgeServer(server, loop)
