"""gRPC ingress gateway with oauth_token metadata auth.

Parity (C16): reference api-frontend SeldonGrpcServer.java +
HeaderServerInterceptor.java:42-75 — reads metadata key ``oauth_token``,
validates it against the token store, resolves the principal's deployment,
and forwards Seldon.Predict / Seldon.SendFeedback. The reference keeps a
per-deployment ManagedChannel cache (:114-132, 197-203); the in-process
backend makes that a dict lookup, and the channel-cache behavior survives in
RemoteBackend's pooled session.
"""

from __future__ import annotations

import grpc

from seldon_core_tpu.core.codec_proto import (
    feedback_from_proto,
    message_from_proto,
    message_to_proto,
)
from seldon_core_tpu.core.errors import APIException
from seldon_core_tpu.core.message import SeldonMessage
from seldon_core_tpu.proto.services import add_service

OAUTH_METADATA_KEY = "oauth_token"  # HeaderServerInterceptor.java:42-44


async def start_gateway_grpc(gw, host: str = "0.0.0.0", port: int = 5000) -> grpc.aio.Server:
    server = grpc.aio.server(
        options=[
            ("grpc.max_receive_message_length", 64 * 1024 * 1024),
            ("grpc.max_send_message_length", 64 * 1024 * 1024),
        ]
    )

    def _auth(context) -> tuple[str, object]:
        meta = dict(context.invocation_metadata() or ())
        token = meta.get(OAUTH_METADATA_KEY, "")
        principal = gw.oauth.principal(token) if token else None
        if not principal:
            from seldon_core_tpu.core.errors import ErrorCode

            raise APIException(ErrorCode.APIFE_GRPC_NO_PRINCIPAL_FOUND, "oauth_token")
        return principal, gw._deployment(principal)

    async def predict(request, context):
        try:
            principal, dep = _auth(context)
            msg = message_from_proto(request)
            out = await gw.backend.predict(dep, msg)
            gw.audit.send(principal, msg, out)
            return message_to_proto(out)
        except APIException as e:
            msg = SeldonMessage.failure(e.error.code, e.error.message, e.info)
            return message_to_proto(msg)

    async def send_feedback(request, context):
        try:
            principal, dep = _auth(context)
            out = await gw.backend.feedback(dep, feedback_from_proto(request))
            return message_to_proto(out)
        except APIException as e:
            msg = SeldonMessage.failure(e.error.code, e.error.message, e.info)
            return message_to_proto(msg)

    add_service(server, "Seldon", {"Predict": predict, "SendFeedback": send_feedback})
    server.add_insecure_port(f"{host}:{port}")
    await server.start()
    return server
