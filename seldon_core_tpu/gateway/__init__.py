from seldon_core_tpu.gateway.app import (
    Backend,
    Gateway,
    InProcessBackend,
    RemoteBackend,
    build_gateway_app,
)
from seldon_core_tpu.gateway.audit import (
    AuditSink,
    JsonlAuditSink,
    KafkaAuditSink,
    MemoryAuditSink,
    NullAuditSink,
    make_audit_sink,
)
from seldon_core_tpu.gateway.oauth import (
    FileTokenStore,
    InMemoryTokenStore,
    OAuthProvider,
    make_token_store,
)
from seldon_core_tpu.gateway.store import DeploymentStore

__all__ = [
    "AuditSink",
    "Backend",
    "DeploymentStore",
    "FileTokenStore",
    "Gateway",
    "InMemoryTokenStore",
    "InProcessBackend",
    "JsonlAuditSink",
    "KafkaAuditSink",
    "MemoryAuditSink",
    "NullAuditSink",
    "OAuthProvider",
    "RemoteBackend",
    "build_gateway_app",
    "make_audit_sink",
    "make_token_store",
]
