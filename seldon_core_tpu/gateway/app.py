"""Ingress gateway: OAuth2 + external prediction API (reference api-frontend).

Parity (C13): REST POST /api/v0.1/predictions and /api/v0.1/feedback with
Bearer auth, POST /oauth/token (client_credentials), principal ->
DeploymentSpec lookup (APIFE_NO_RUNNING_DEPLOYMENT when absent —
PredictionService.java:42-46), request/response audit after every prediction
(RestClientController.java:164), ingress metrics (:188-189).

Backends: the reference always crosses the network to the engine Service.
Here the default is IN-PROCESS — the engine (graph executor + TPU runtimes)
lives in the same process, so gateway->engine is a function call; the
RemoteBackend (pooled HTTP, reference timeouts 200/500/2000 ms, retry) covers
split deployments where the predictor runs on a different TPU host.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os

from aiohttp import web

from seldon_core_tpu.core.codec_json import message_from_dict, message_to_dict
from seldon_core_tpu.core.errors import APIException, ErrorCode
from seldon_core_tpu.core.message import Feedback, SeldonMessage
from seldon_core_tpu.gateway.audit import AuditSink, NullAuditSink
from seldon_core_tpu.gateway.oauth import OAuthProvider
from seldon_core_tpu.gateway.store import DeploymentStore
from seldon_core_tpu.utils.env import TEST_CLIENT_KEY, TEST_CLIENT_SECRET


class Backend:
    # wire_npy: the gateway saw an EXPLICIT application/x-npy declaration —
    # backends must honor it (decode to the tensor arm / forward the raw
    # binary) even for deployments that opted out of binData sniffing
    # traceparent: the client's W3C trace context header, forwarded so the
    # engine continues the caller's trace (in-process: straight into the
    # service; remote: re-sent as an HTTP header)
    async def predict(
        self,
        deployment,
        msg: SeldonMessage,
        wire_npy: bool = False,
        traceparent: str | None = None,
    ) -> SeldonMessage:
        raise NotImplementedError

    async def feedback(self, deployment, fb: Feedback) -> SeldonMessage:
        raise NotImplementedError


class InProcessBackend(Backend):
    """deployment name -> PredictionService living in this process (the
    TPU-native collapse of the reference's gateway->engine network hop)."""

    def __init__(self):
        self.services: dict[str, object] = {}

    def register(self, name: str, service) -> None:
        self.services[name] = service

    def unregister(self, name: str) -> None:
        self.services.pop(name, None)

    def _service(self, deployment):
        svc = self.services.get(deployment.name)
        if svc is None:
            raise APIException(ErrorCode.APIFE_NO_RUNNING_DEPLOYMENT, deployment.name)
        return svc

    async def predict(
        self,
        deployment,
        msg: SeldonMessage,
        wire_npy: bool = False,
        traceparent: str | None = None,
    ) -> SeldonMessage:
        return await self._service(deployment).predict(
            msg, wire_npy=wire_npy, traceparent=traceparent
        )

    async def feedback(self, deployment, fb: Feedback) -> SeldonMessage:
        return await self._service(deployment).send_feedback(fb)


class RemoteBackend(Backend):
    """Pooled HTTP to a per-deployment engine host. Reference parity:
    api-frontend InternalPredictionService.java — 150-connection pool
    (:60-61), timeouts conn 500 ms / total 2000 ms (:52-54), one retry on
    idempotent failure (HttpRetryHandler.java)."""

    def __init__(self, resolve=None):
        # resolve(deployment) -> base url; default: k8s-style service DNS
        self._resolve = resolve or (lambda d: f"http://{d.name}:8000")
        self._session = None

    async def _get_session(self):
        import aiohttp

        if self._session is None:
            self._session = aiohttp.ClientSession(
                connector=aiohttp.TCPConnector(limit=150, limit_per_host=150),
                timeout=aiohttp.ClientTimeout(total=2.0, connect=0.5),
            )
        return self._session

    async def _roundtrip(
        self,
        deployment,
        path: str,
        *,
        json_payload: dict | None = None,
        data: bytes | None = None,
        headers: dict | None = None,
    ) -> tuple[bytes, str, dict]:
        """POST with one retry; returns (body, content_type, headers).
        5xx retries; 4xx re-raises the engine's status-JSON error code when
        the body has that shape (errors.py), else wraps in APIFE_*."""
        session = await self._get_session()
        url = self._resolve(deployment) + path
        last_exc: Exception | None = None
        for _ in range(2):  # original + 1 retry
            try:
                kwargs = (
                    {"data": data, "headers": headers}
                    if data is not None
                    else {"json": json_payload, "headers": headers}
                )
                async with session.post(url, **kwargs) as resp:
                    body = await resp.read()
                    if resp.status >= 500:
                        last_exc = APIException(
                            ErrorCode.APIFE_MICROSERVICE_ERROR,
                            body[:200].decode(errors="replace"),
                        )
                        continue
                    if resp.status >= 400:
                        # engine status-JSON error body (errors.py shape):
                        # re-raise with the engine's code, don't parse it
                        # as a SeldonMessage
                        try:
                            parsed = json.loads(body)
                        except (ValueError, UnicodeDecodeError):
                            parsed = None
                        if isinstance(parsed, dict) and parsed.get("status") == "FAILURE":
                            code = parsed.get("code")
                            err = next(
                                (e for e in ErrorCode if e.code == code),
                                ErrorCode.APIFE_MICROSERVICE_ERROR,
                            )
                            raise APIException(err, str(parsed.get("info", "")))
                        raise APIException(
                            ErrorCode.APIFE_MICROSERVICE_ERROR,
                            body[:200].decode(errors="replace"),
                        )
                    return body, resp.content_type or "", dict(resp.headers)
            except APIException:
                raise  # engine-reported errors are not retryable
            except Exception as e:  # noqa: BLE001
                last_exc = e
        if isinstance(last_exc, APIException):
            raise last_exc
        raise APIException(ErrorCode.APIFE_MICROSERVICE_ERROR, str(last_exc))

    async def _post(
        self, deployment, path: str, payload: dict, headers: dict | None = None
    ) -> dict:
        body, _, _ = await self._roundtrip(
            deployment, path, json_payload=payload, headers=headers
        )
        return json.loads(body)

    async def predict(
        self,
        deployment,
        msg: SeldonMessage,
        wire_npy: bool = False,
        traceparent: str | None = None,
    ) -> SeldonMessage:
        tp_headers = {"traceparent": traceparent} if traceparent else None
        if wire_npy and msg.bin_data is not None:
            # keep the BINARY fast path across the network hop: raw npy with
            # the x-npy declaration (compact, no base64/JSON inflation; the
            # engine decodes by declaration even when sniffing is opted out)
            body, ctype, headers = await self._roundtrip(
                deployment,
                "/api/v0.1/predictions",
                data=msg.bin_data,
                headers={"Content-Type": "application/x-npy", **(tp_headers or {})},
            )
            if ctype == "application/x-npy":
                from seldon_core_tpu.core.codec_json import meta_from_dict

                meta = meta_from_dict(json.loads(headers.get("Seldon-Meta", "{}")))
                return SeldonMessage(bin_data=body, meta=meta)
            # bytes-out graph: the engine fell back to the JSON envelope
            return message_from_dict(json.loads(body))
        out = await self._post(
            deployment,
            "/api/v0.1/predictions",
            message_to_dict(msg),
            headers=tp_headers,
        )
        return message_from_dict(out)

    async def feedback(self, deployment, fb: Feedback) -> SeldonMessage:
        from seldon_core_tpu.core.codec_json import feedback_to_dict

        out = await self._post(deployment, "/api/v0.1/feedback", feedback_to_dict(fb))
        return message_from_dict(out)

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None


class Gateway:
    def __init__(
        self,
        store: DeploymentStore | None = None,
        oauth: OAuthProvider | None = None,
        backend: Backend | None = None,
        audit: AuditSink | None = None,
        metrics=None,
    ):
        self.oauth = oauth or OAuthProvider()
        self.store = store or DeploymentStore(oauth=self.oauth)
        if self.store.oauth is None:
            self.store.oauth = self.oauth
        self.backend = backend or InProcessBackend()
        self.audit = audit or NullAuditSink()
        self.metrics = metrics
        # reference backdoor: TEST_CLIENT_KEY env registers a test client
        # (AuthorizationServerConfiguration.java:78-96)
        test_key = os.environ.get(TEST_CLIENT_KEY, "")
        if test_key:
            self.oauth.add_client(test_key, os.environ.get(TEST_CLIENT_SECRET, "secret"))

    # ----- auth helpers
    def principal_from_auth(self, auth: str) -> str:
        if auth.lower().startswith("bearer "):
            token = auth[7:].strip()
            principal = self.oauth.principal(token)
            if principal:
                return principal
        raise APIException(ErrorCode.APIFE_GRPC_NO_PRINCIPAL_FOUND, "invalid or missing token")

    def _principal(self, request: web.Request) -> str:
        return self.principal_from_auth(request.headers.get("Authorization", ""))

    def _deployment(self, principal: str):
        dep = self.store.by_principal(principal)
        if dep is None:
            # TEST_CLIENT_KEY principal maps to the sole deployment if any
            if principal == os.environ.get(TEST_CLIENT_KEY, "") and self.store.names():
                return self.store.by_name(self.store.names()[0])
            raise APIException(ErrorCode.APIFE_NO_RUNNING_DEPLOYMENT, principal)
        return dep


from seldon_core_tpu.serving.http_util import from_wire_response, to_wire_request

_log = logging.getLogger(__name__)




def build_gateway_app(gw: Gateway) -> web.Application:
    app = web.Application(client_max_size=64 * 1024 * 1024)
    app["gateway"] = gw

    # handlers delegate to the transport-neutral wire core (serving/wire.py)
    # shared with the fast ingress, so the two transports cannot drift
    async def token(request: web.Request) -> web.Response:
        from seldon_core_tpu.serving import wire

        req = await to_wire_request(request)
        return from_wire_response(await wire.gateway_token(gw, req))

    async def predictions(request: web.Request) -> web.Response:
        from seldon_core_tpu.serving import wire

        req = await to_wire_request(request)
        return from_wire_response(await wire.gateway_predictions(gw, req))

    async def feedback(request: web.Request) -> web.Response:
        from seldon_core_tpu.serving import wire

        req = await to_wire_request(request)
        return from_wire_response(await wire.gateway_feedback(gw, req))

    async def ready(request: web.Request) -> web.Response:
        return web.Response(text="ready")

    async def ping(request: web.Request) -> web.Response:
        return web.Response(text="pong")

    async def prometheus(request: web.Request) -> web.Response:
        from seldon_core_tpu.serving.http_util import prometheus_response

        return prometheus_response(request, gw.metrics)

    async def grpc_web_predict(request: web.Request) -> web.Response:
        from seldon_core_tpu.serving import wire

        req = await to_wire_request(request)
        return from_wire_response(await wire.gateway_grpc_web_predict(gw, req))

    async def grpc_web_feedback(request: web.Request) -> web.Response:
        from seldon_core_tpu.serving import wire

        req = await to_wire_request(request)
        return from_wire_response(await wire.gateway_grpc_web_feedback(gw, req))

    async def grpc_web_preflight(request: web.Request) -> web.Response:
        from seldon_core_tpu.serving import wire

        return web.Response(status=204, headers=dict(wire.GRPC_WEB_CORS_HEADERS))

    app.router.add_post("/oauth/token", token)
    app.router.add_post("/api/v0.1/predictions", predictions)
    app.router.add_post("/api/v0.1/feedback", feedback)
    app.router.add_get("/ready", ready)
    app.router.add_get("/ping", ping)
    app.router.add_get("/metrics", prometheus)
    app.router.add_get("/prometheus", prometheus)
    # gRPC-Web unary — same wire-core handlers AND the same route table
    # constant (wire.GRPC_WEB_ROUTES) as the fast ingress: one source, no
    # drift channel between the transports
    from seldon_core_tpu.serving.wire import GRPC_WEB_ROUTES

    for path, method in GRPC_WEB_ROUTES:
        app.router.add_options(path, grpc_web_preflight)
        app.router.add_post(
            path, grpc_web_predict if method == "Predict" else grpc_web_feedback
        )
    return app
