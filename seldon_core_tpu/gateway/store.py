"""Deployment registry for the gateway.

Parity: reference api-frontend DeploymentStore.java (oauth_key ->
DeploymentSpec ConcurrentHashMap :37, deploymentAdded registers the OAuth
client :63-71) + DeploymentsHandler/Listener fan-out (C14). The reference
fills this from a 5-second CRD watch; here the operator (or local API) calls
add/remove directly — same listener contract, no polling.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from seldon_core_tpu.graph.spec import DeploymentSpec

Listener = Callable[[str, Optional[DeploymentSpec]], None]  # (event, spec)


class DeploymentStore:
    def __init__(self, oauth=None):
        self._by_key: dict[str, DeploymentSpec] = {}
        self._by_name: dict[str, DeploymentSpec] = {}
        self._lock = threading.Lock()
        self._listeners: list[Listener] = []
        self.oauth = oauth

    def add_listener(self, fn: Listener) -> None:
        self._listeners.append(fn)

    def _notify(self, event: str, spec: DeploymentSpec | None) -> None:
        for fn in self._listeners:
            fn(event, spec)

    def deployment_added(self, spec: DeploymentSpec) -> None:
        stale_key = ""
        with self._lock:
            old = self._by_name.get(spec.name)
            if old is not None and old.oauth_key and old.oauth_key != spec.oauth_key:
                # credential rotation: the retired key must stop routing AND
                # stop minting tokens
                self._by_key.pop(old.oauth_key, None)
                stale_key = old.oauth_key
            if spec.oauth_key:
                self._by_key[spec.oauth_key] = spec
            self._by_name[spec.name] = spec
        if self.oauth is not None and stale_key:
            self.oauth.remove_client(stale_key)
        # register the deployment's OAuth client, exactly
        # DeploymentStore.java:63-71
        if self.oauth is not None and spec.oauth_key:
            self.oauth.add_client(spec.oauth_key, spec.oauth_secret)
        self._notify("added", spec)

    def deployment_updated(self, spec: DeploymentSpec) -> None:
        self.deployment_added(spec)

    def deployment_removed(self, spec_or_name) -> None:
        name = getattr(spec_or_name, "name", spec_or_name)
        with self._lock:
            spec = self._by_name.pop(name, None)
            if spec is not None and spec.oauth_key:
                self._by_key.pop(spec.oauth_key, None)
        if spec is not None and self.oauth is not None and spec.oauth_key:
            self.oauth.remove_client(spec.oauth_key)
        self._notify("removed", spec)

    def by_principal(self, principal: str) -> DeploymentSpec | None:
        """OAuth client-id == deployment oauth_key (the reference's routing
        key: PredictionService.java:42-46)."""
        with self._lock:
            return self._by_key.get(principal)

    def by_name(self, name: str) -> DeploymentSpec | None:
        with self._lock:
            return self._by_name.get(name)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._by_name)
