"""All-in-one platform process: control plane + gateway + engines.

The reference splits this across three Java services and k8s (cluster-manager
operator, api-frontend gateway, one engine pod per predictor). On a TPU host
the economical shape is ONE process: deployments are applied through the
control API (or a watched directory of CR files), reconciled into in-process
executors with weights in HBM, and served through the OAuth2 gateway — no
per-request network hop anywhere in the graph.

CLI:
    python -m seldon_core_tpu.platform --port 8080 --grpc-port 5000 \
        [--watch-dir deployments/] [--apply dep.json ...] \
        [--audit-sink file://audit/] [--token-store file://tokens.jsonl]
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal

from aiohttp import web

from seldon_core_tpu.gateway import (
    DeploymentStore,
    Gateway,
    InProcessBackend,
    OAuthProvider,
    build_gateway_app,
    make_audit_sink,
    make_token_store,
)
from seldon_core_tpu.metrics import get_metrics
from seldon_core_tpu.operator import (
    DeploymentManager,
    add_operator_routes,
    watch_directory,
)

log = logging.getLogger(__name__)


class Platform:
    def __init__(
        self,
        *,
        token_store_url: str = "",
        audit_sink_url: str = "",
        metrics_enabled: bool = True,
        state_store_url: str = "",
        hbm_budget_bytes: int | None = None,
        allow_python_class: bool | None = None,
    ):
        self.metrics = get_metrics(metrics_enabled)
        self.oauth = OAuthProvider(token_store=make_token_store(token_store_url))
        self.store = DeploymentStore(oauth=self.oauth)
        self.backend = InProcessBackend()
        self.gateway = Gateway(
            store=self.store,
            oauth=self.oauth,
            backend=self.backend,
            audit=make_audit_sink(audit_sink_url),
            metrics=self.metrics,
        )
        self.manager = DeploymentManager(
            store=self.store,
            backend=self.backend,
            metrics=self.metrics,
            state_store_url=state_store_url,
            hbm_budget_bytes=hbm_budget_bytes,
            allow_python_class=allow_python_class,
        )
        self._fast_server = None

    def build_app(self) -> web.Application:
        app = build_gateway_app(self.gateway)
        add_operator_routes(app, self.manager)

        async def _gc_policy(request: web.Request) -> web.Response:
            # operator-invoked re-freeze for tenants applied at runtime
            # (gc_policy.py): call during a quiet window — freeze pins any
            # in-flight request state permanently
            from seldon_core_tpu.serving.gc_policy import apply_serving_gc_policy

            return web.json_response({"frozen": apply_serving_gc_policy()})

        app.router.add_post("/v1/gc-policy", _gc_policy)
        return app

    async def serve(
        self,
        host: str = "0.0.0.0",
        port: int = 8080,
        grpc_port: int | None = 5000,
        watch_dir: str | None = None,
        watch_interval_s: float = 5.0,
        watch_k8s: bool = False,
        k8s_namespace: str = "default",
        fast_ingress: bool = False,
        admin_port: int = 8082,
        grpc_mode: str = "aio",
    ):
        self._fast_server = None
        if fast_ingress:
            # data plane on the purpose-built ingress (serving/fast_http.py,
            # ~half the per-request overhead); the FULL aiohttp app — incl.
            # the control-plane API — moves to the admin port, the
            # reference engine's admin-port-8082 topology (TomcatConfig
            # additionalPorts; operator wires admin=8082)
            from seldon_core_tpu.serving.fast_http import (
                gateway_routes,
                start_fast_server,
            )

            self._fast_server = await start_fast_server(
                gateway_routes(self.gateway), host, port
            )
        app_port = admin_port if fast_ingress else port
        runner = web.AppRunner(self.build_app())
        await runner.setup()
        await web.TCPSite(runner, host, app_port).start()
        if fast_ingress:
            log.info(
                "platform fast ingress on %s:%s, admin REST on %s:%s",
                host, port, host, app_port,
            )
        else:
            log.info("platform REST on %s:%s", host, port)

        grpc_server = None
        if grpc_port:
            from seldon_core_tpu.gateway.grpc_gateway import start_gateway_grpc

            grpc_server = await start_gateway_grpc(
                self.gateway, host=host, port=grpc_port, mode=grpc_mode
            )
            log.info("platform gRPC on %s:%s (%s)", host, grpc_port, grpc_mode)

        # event-loop health probe: one tenant's host-side compute stalling
        # the shared loop is visible as seldon_tpu_event_loop_lag_ms before
        # it becomes cross-tenant p99 (alert rule in deploy/monitoring)
        from seldon_core_tpu.metrics.registry import run_loop_lag_probe

        self._lag_probe = asyncio.create_task(run_loop_lag_probe(self.metrics))
        # gen-2 GC pauses are the measured multi-tenant tail-lag source —
        # freeze boot/warmup survivors out of the scan set (gc_policy.py)
        from seldon_core_tpu.serving.gc_policy import apply_serving_gc_policy

        apply_serving_gc_policy()

        watch_task = None
        if watch_dir:
            watch_task = asyncio.create_task(
                watch_directory(self.manager, watch_dir, watch_interval_s)
            )
        elif watch_k8s:
            from seldon_core_tpu.operator.k8s_watcher import KubernetesWatcher

            # construct BEFORE create_task: a missing kubernetes client must
            # fail the boot loudly, not kill a background task silently
            watcher = KubernetesWatcher(self.manager, namespace=k8s_namespace)
            watch_task = asyncio.create_task(watcher.run(interval_s=watch_interval_s))
        return runner, grpc_server, watch_task


async def _amain(args) -> None:
    platform = Platform(
        token_store_url=args.token_store,
        audit_sink_url=args.audit_sink,
        state_store_url=args.state_store,
        hbm_budget_bytes=int(args.hbm_budget_gb * (1 << 30))
        if args.hbm_budget_gb
        else None,
        # None -> DeploymentManager falls back to SELDON_TPU_ALLOW_PYTHON_CLASS
        allow_python_class=True if args.allow_python_class else None,
    )
    for path in args.apply or []:
        import json as _json

        with open(path) as f:
            result = platform.manager.apply(_json.load(f))
        log.info("apply %s: %s %s", path, result.action, result.message)

    runner, grpc_server, watch_task = await platform.serve(
        host=args.host,
        port=args.port,
        grpc_port=args.grpc_port,
        watch_dir=args.watch_dir,
        watch_k8s=args.watch_k8s,
        k8s_namespace=args.k8s_namespace,
        fast_ingress=args.fast_ingress,
        admin_port=args.admin_port,
        grpc_mode=args.grpc_mode,
    )

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()

    lag_probe = getattr(platform, "_lag_probe", None)
    if lag_probe is not None:
        lag_probe.cancel()
    if watch_task is not None:
        watch_task.cancel()
    if grpc_server is not None:
        await grpc_server.stop(5)
    if platform._fast_server is not None:
        platform._fast_server.close()
        await platform._fast_server.wait_closed()
    await runner.cleanup()


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument("--grpc-port", type=int, default=5000)
    watch_group = parser.add_mutually_exclusive_group()
    watch_group.add_argument("--watch-dir", default=None)
    watch_group.add_argument(
        "--watch-k8s",
        action="store_true",
        help="watch SeldonDeployment CRs on the Kubernetes API server "
        "(needs the 'kubernetes' package); mutually exclusive with --watch-dir",
    )
    parser.add_argument("--k8s-namespace", default="default")
    parser.add_argument("--apply", nargs="*", help="CR JSON files to apply at boot")
    parser.add_argument("--token-store", default="", help="'' | file://p | redis://h")
    parser.add_argument("--audit-sink", default="", help="'' | mem:// | file://d | kafka://h")
    parser.add_argument("--state-store", default="", help="'' | file://d | redis://h (router state)")
    parser.add_argument(
        "--hbm-budget-gb",
        type=float,
        default=0.0,
        help="reject deployments whose params would exceed this HBM budget (0 = unlimited)",
    )
    parser.add_argument("--no-grpc", action="store_true")
    parser.add_argument(
        "--grpc-mode",
        choices=("aio", "sync"),
        default="aio",
        help="gRPC ingress implementation: 'aio' (pure grpc.aio — fastest "
        "when the backend shares the core with the event loop) or 'sync' "
        "(C-core server + one loop bridge per RPC — the pick for "
        "multi-core hosts; see docs/reference/external-api.md section 5)",
    )
    parser.add_argument(
        "--fast-ingress",
        action="store_true",
        help="serve the data plane on the purpose-built HTTP ingress "
        "(serving/fast_http.py, lower per-request overhead) and move the "
        "full REST app incl. the control-plane API to --admin-port",
    )
    parser.add_argument(
        "--admin-port",
        type=int,
        default=8082,  # the reference engine's admin port
        help="control-plane/admin REST port when --fast-ingress is on",
    )
    parser.add_argument(
        "--allow-python-class",
        action="store_true",
        help="let CRs mount local user classes in-process (PYTHON_CLASS "
        "implementation) — CR authors gain code execution in this process, "
        "so only enable when every CR source is trusted",
    )
    args = parser.parse_args()
    if args.no_grpc:
        args.grpc_port = None
    logging.basicConfig(level=logging.INFO)
    asyncio.run(_amain(args))


if __name__ == "__main__":
    main()
