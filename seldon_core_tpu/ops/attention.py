"""Blockwise (flash-style) attention in pure JAX.

No reference analogue (the reference serves tabular/image models over RPC —
SURVEY §5.7 'long-context: absent'); this is the greenfield long-context
tier: an online-softmax attention whose KV axis is processed in blocks with
running (max, denominator, numerator) statistics, so memory is O(block)
instead of O(seq^2), and whose math is the per-step building block of ring
attention (ops/ring_attention.py) where the "blocks" arrive over ICI.

All shapes [batch, heads, seq, head_dim]; lax.scan keeps the loop inside one
XLA program (no Python-unrolled graph bloat at long seq).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _block_stats(q, k, v, mask=None):
    """One KV block: returns (m, l, o) running stats for online softmax.
    m: rowwise max [.., sq], l: rowwise denom [.., sq], o: numerator
    [.., sq, d]."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.asarray(
        q.shape[-1] ** 0.5, q.dtype
    )
    if mask is not None:
        s = jnp.where(mask, s, jnp.asarray(NEG_INF, s.dtype))
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return m, l, o


def combine_stats(m1, l1, o1, m2, l2, o2):
    """Merge two online-softmax partials (associative — the reduction law
    that makes blockwise and ring attention exact, not approximate)."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    l = a1 * l1 + a2 * l2
    o = a1[..., None] * o1 + a2[..., None] * o2
    return m, l, o


# seq length at which the dense O(s^2) score matrix gives way to the
# blockwise kernel — the ONE policy constant shared by the single-device
# default (models/bert.py) and the seq-parallel local bodies (ops/ulysses.py)
FLASH_MIN_SEQ = 1024

# seq length from which the TPU backend routes to the hand-tiled Pallas
# kernel (ops/pallas_flash) instead of this pure-JAX blockwise path.
# Measured on the v5e harness (bf16, 12 heads, d=64, RTT-differenced):
# parity at 2k/4k, 2.2x at 8k, 2.4x at 16k — blockwise's per-step
# [.., sq, block] score tensors go HBM-bound while the kernel keeps the
# working set in VMEM. 4096 is the conservative crossover (>= parity).
PALLAS_MIN_SEQ = 4096


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    block_size: int = 512,
    causal: bool = False,
    vary_axes: tuple = (),
) -> jax.Array:
    """Exact attention with KV processed in blocks of ``block_size``.

    q,k,v: [batch, heads, seq, head_dim] -> [batch, heads, seq, head_dim].

    ``vary_axes``: when called INSIDE shard_map, the scan carry is
    initialized from axis-invariant constants and must be marked varying
    over the manual mesh axes or the carry-in/carry-out types mismatch —
    pass the enclosing mesh axis names (same fix ring_attention applies).
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block = min(block_size, sk)
    if sk % block != 0:
        # pad KV to a block multiple; padded keys are masked out
        pad = block - sk % block
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    n_blocks = k.shape[2] // block

    q_pos = jnp.arange(sq)

    def body(carry, blk):
        m_acc, l_acc, o_acc = carry
        kb = lax.dynamic_slice_in_dim(k, blk * block, block, axis=2)
        vb = lax.dynamic_slice_in_dim(v, blk * block, block, axis=2)
        k_pos = blk * block + jnp.arange(block)
        valid = k_pos < sk
        mask = valid[None, None, None, :]
        if causal:
            mask = mask & (k_pos[None, None, None, :] <= q_pos[None, None, :, None])
        m, l, o = _block_stats(q, kb, vb, mask)
        return combine_stats(m_acc, l_acc, o_acc, m, l, o), None

    init = (
        jnp.full((b, h, sq), NEG_INF, q.dtype),
        jnp.zeros((b, h, sq), q.dtype),
        jnp.zeros((b, h, sq, d), q.dtype),
    )
    if vary_axes:
        from seldon_core_tpu.parallel.compat import pvary

        init = tuple(pvary(x, vary_axes) for x in init)
    (m, l, o), _ = lax.scan(body, init, jnp.arange(n_blocks))
    return o / l[..., None]


def causal_attention_auto(q, k, v) -> jax.Array:
    """Backend-adaptive CAUSAL attention — the one policy shared by every
    causal consumer (decoder prefill today): dense below FLASH_MIN_SEQ,
    blockwise above it, the Pallas causal kernel on the TPU backend from
    PALLAS_MIN_SEQ when the KV axis tiles. Mirrors models/bert.py's
    non-causal `_default_attention` thresholds so the two policies cannot
    drift apart in spirit."""
    s = q.shape[2]
    if s >= FLASH_MIN_SEQ:
        if s >= PALLAS_MIN_SEQ and jax.default_backend() == "tpu" and k.shape[2] % 128 == 0:
            from seldon_core_tpu.ops.pallas_flash import (
                flash_attention,
                pallas_available,
            )

            if pallas_available():
                return flash_attention(q, k, v, causal=True)
        return blockwise_attention(q, k, v, block_size=512, causal=True)
    return naive_attention(q, k, v, causal=True)


def naive_attention(q, k, v, *, causal: bool = False) -> jax.Array:
    """Reference O(seq^2) attention for testing."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.asarray(q.shape[-1] ** 0.5, q.dtype)
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        mask = jnp.arange(sk)[None, :] <= jnp.arange(sq)[:, None]
        s = jnp.where(mask[None, None], s, jnp.asarray(NEG_INF, s.dtype))
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)
