"""Ulysses-style all-to-all sequence parallelism: exact attention over a
sequence-sharded mesh axis, the alternative strategy to ring attention.

Where ring attention (ops/ring_attention.py) keeps the sequence sharded and
ROTATES K/V shards around the ring — |ring| ppermute steps, compute
overlapping communication — the all-to-all strategy RE-SHARDS for the
attention op itself (DeepSpeed-Ulysses pattern, arXiv:2309.14509):

    [b, h, s/N, d]  --all_to_all-->  [b, h/N, s, d]
        (sequence-sharded)            (head-sharded, FULL sequence local)

Each device then runs plain attention for its head subset over the whole
sequence, and a second all-to-all restores sequence sharding for the
(sequence-local) MLP and layernorms. Two collectives per attention instead
of |ring| permutes: on TPU both lower to ICI all-to-alls, and the better
choice is workload-dependent — ring wins when compute per step hides the
permute latency (very long sequences); all-to-all wins at moderate lengths
where the ring's |N|-step latency chain dominates. Both are exact, so the
framework exposes the choice as a deployment knob
(``parameters: [{"name": "seq_parallel", "value": "ulysses"}]`` on a BERT
unit) rather than hard-coding either.

Constraint: attention heads must divide by the seq-axis size (heads are the
resharding currency); ring attention has the complementary constraint on
sequence length only.
"""

from __future__ import annotations

from functools import partial

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from seldon_core_tpu.ops.attention import (
    FLASH_MIN_SEQ,
    blockwise_attention,
    naive_attention,
)

from seldon_core_tpu.parallel.compat import shard_map as _shard_map


def _local_attention(q, k, v, causal: bool, vary_axes: tuple):
    # same dense/blockwise policy boundary as the single-device default
    # (models/bert.py _default_attention): dense below FLASH_MIN_SEQ
    if q.shape[2] < FLASH_MIN_SEQ:
        return naive_attention(q, k, v, causal=causal)
    # vary_axes: the blockwise scan carry must be varying over the manual
    # mesh axes or shard_map rejects the scan (carry type mismatch)
    return blockwise_attention(q, k, v, causal=causal, vary_axes=vary_axes)


def _ulysses_local(q, k, v, *, axis_name: str, causal: bool, vary_axes: tuple):
    """Per-device body (runs under shard_map). q,k,v: sequence-sharded
    local blocks [b, h, s_local, d]."""
    # scatter heads / gather sequence: [b, h, s/N, d] -> [b, h/N, s, d]
    qh = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2, tiled=True)
    kh = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2, tiled=True)
    vh = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2, tiled=True)
    o = _local_attention(qh, kh, vh, causal, vary_axes)
    # gather heads / scatter sequence back: [b, h/N, s, d] -> [b, h, s/N, d]
    return lax.all_to_all(o, axis_name, split_axis=2, concat_axis=1, tiled=True)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    seq_axis: str = "seq",
    data_axis: str = "data",
    causal: bool = False,
) -> jax.Array:
    """q,k,v: [batch, heads, seq, head_dim] GLOBAL arrays; returns attention
    output with the same (sequence-sharded) layout as ring_attention, so the
    two strategies are drop-in interchangeable. heads AND seq must divide
    evenly by the mesh's seq-axis size."""
    heads, seq = q.shape[1], q.shape[2]
    n = mesh.shape[seq_axis]
    if heads % n != 0:
        raise ValueError(
            f"ulysses: {heads} heads not divisible by seq-axis size {n} "
            "(heads are the all-to-all resharding currency — use ring "
            "attention for head counts below the mesh axis)"
        )
    if seq % n != 0:
        raise ValueError(f"ulysses: seq {seq} not divisible by seq-axis size {n}")
    batch_entry = data_axis if data_axis in mesh.shape else None
    spec = P(batch_entry, None, seq_axis, None)
    fn = _shard_map(
        partial(
            _ulysses_local,
            axis_name=seq_axis,
            causal=causal,
            vary_axes=tuple(mesh.axis_names),
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
