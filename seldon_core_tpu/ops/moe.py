"""Mixture-of-Experts FFN with expert parallelism over the mesh "expert" axis.

Greenfield vs the reference (SURVEY §2: no expert parallelism exists there).
Top-1 gated MoE in the dense-dispatch formulation: every expert computes
every token and a one-hot gate selects, which XLA partitions cleanly — with
``w1``/``w2`` sharded P("expert", ...) each device computes only its local
experts' [E/|expert|, ...] slice of the ``ebsf`` intermediate and the final
gate-weighted reduction over the expert axis becomes one psum over ICI.
Dense dispatch trades FLOPs (xE) for zero routing collectives — the right
call for moderate expert counts in serving; capacity-based sparse dispatch
(all_to_all) is the known upgrade path for large E.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def init_moe(
    seed: int,
    d_model: int,
    d_ff: int,
    n_experts: int,
) -> dict:
    rng = np.random.default_rng(seed)
    s1 = (2.0 / d_model) ** 0.5
    s2 = (2.0 / d_ff) ** 0.5
    return {
        "gate": (rng.standard_normal((d_model, n_experts)) * 0.02).astype(np.float32),
        "w1": (rng.standard_normal((n_experts, d_model, d_ff)) * s1).astype(np.float32),
        "b1": np.zeros((n_experts, d_ff), np.float32),
        "w2": (rng.standard_normal((n_experts, d_ff, d_model)) * s2).astype(np.float32),
        "b2": np.zeros((n_experts, d_model), np.float32),
    }


def moe_pspecs(expert_axis: str = "expert") -> dict:
    """Expert-parallel shardings: experts split over the mesh expert axis,
    gate replicated."""
    return {
        "gate": P(),
        "w1": P(expert_axis, None, None),
        "b1": P(expert_axis, None),
        "w2": P(expert_axis, None, None),
        "b2": P(expert_axis, None),
    }


def moe_ffn(params: dict, x: jax.Array) -> jax.Array:
    """x: [batch, seq, d_model] -> [batch, seq, d_model], top-1 routing.

    Pure function of (params, x): under jit with expert-sharded params XLA
    derives the per-device expert slab compute + final psum from the
    shardings alone — no hand-written collectives.
    """
    logits = x @ params["gate"].astype(x.dtype)  # [b, s, E]
    probs = jax.nn.softmax(logits, axis=-1)
    top = jnp.argmax(probs, axis=-1)  # [b, s]
    onehot = jax.nn.one_hot(top, logits.shape[-1], dtype=x.dtype)  # [b, s, E]
    gate_weight = jnp.sum(probs * onehot, axis=-1, keepdims=True)  # [b, s, 1]

    # dense dispatch: every expert computes every token, sharded over E
    h = jnp.einsum("bsd,edf->ebsf", x, params["w1"].astype(x.dtype))
    h = jax.nn.relu(h + params["b1"].astype(x.dtype)[:, None, None, :])
    y = jnp.einsum("ebsf,efd->ebsd", h, params["w2"].astype(x.dtype))
    y = y + params["b2"].astype(x.dtype)[:, None, None, :]
    # gate-select: reduction over the (sharded) expert axis -> psum
    out = jnp.einsum("ebsd,bse->bsd", y, onehot)
    return out * gate_weight


def moe_load_balance_loss(params: dict, x: jax.Array) -> jax.Array:
    """Auxiliary load-balancing loss (Switch-style: E * sum(frac_e * prob_e));
    added to the task loss when fine-tuning MoE models."""
    logits = x @ params["gate"].astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    top = jnp.argmax(probs, axis=-1)
    n_experts = logits.shape[-1]
    frac = jnp.mean(jax.nn.one_hot(top, n_experts, dtype=jnp.float32), axis=(0, 1))
    mean_prob = jnp.mean(probs, axis=(0, 1))
    return n_experts * jnp.sum(frac * mean_prob)
