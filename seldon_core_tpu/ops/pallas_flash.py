"""Pallas flash-attention forward kernel for TPU.

The hot op of the BERT/long-context serving path, hand-tiled for the MXU.
Grid (batch*heads, Q blocks, KV blocks) with the KV axis innermost: each
(bh, q) pair streams KV blocks through VMEM while online-softmax statistics
(running max, denominator, f32 accumulator) live in VMEM scratch carried
across the KV grid steps — TPU grids execute sequentially, which is what
makes the carry sound. KV never resides fully in VMEM, so sequence length is
bounded by HBM, not the 16 MB VMEM (the previous full-KV design OOMed at
seq 16k).

Dots run in the input dtype (bf16 on the serving path) with f32
accumulation — the MXU's native mode and ~2x the f32 rate; softmax stats
stay f32 for exactness. Stats are stored lane-replicated ([block_q, 128])
and re-collapsed with a max over lanes, the standard Mosaic-friendly layout.

On non-TPU backends (tests run on the 8-device CPU mesh) the same kernel
runs in interpreter mode, so numerics are covered everywhere while the
compiled path exercises Mosaic only on real hardware.

Measured on the v5e harness (bench.py pallas_long_seq, bf16, 12 heads,
d=64, RTT-differenced): crossover vs the pure-JAX blockwise path is
~seq 4k (parity there, within run noise); at 8k the kernel wins ~2x and at
16k ~2.4x — blockwise's per-step score tensors go HBM-bound while the
kernel keeps its working set in VMEM. Block defaults from a 9-point sweep
at seq 8k: block_q 512 / block_k 2048 (5.34 ms vs 6.25 at the previous
1024 KV block). models/bert.py routes long sequences here on the TPU
backend (PALLAS_MIN_SEQ policy).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend only exists on TPU-enabled builds
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # noqa: BLE001
    _HAS_PLTPU = False

NEG_INF = -1e30
_LANES = 128  # stats are stored lane-replicated at this width
# default KV block (flash_attention block_k): 9-point sweep at seq 8k on
# the v5e harness picked 2048; the bert routing policy reuses it as the
# single-block-fit bound for non-128-multiple sequences
DEFAULT_BLOCK_K = 2048


def pallas_available() -> bool:
    """Whether this jax build can run the kernel at all (compiled OR
    interpret — both need the pltpu memory-space types for scratch). The
    routing policy in models/bert.py checks this before selecting the
    kernel so a pltpu-less build serves blockwise instead of raising."""
    return _HAS_PLTPU


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
    *, n_kv: int, scale: float, block_q: int, block_k: int, causal: bool,
):
    """One (bh, q-block, kv-block) program; scratch carries across kv.

    Causal mode: KV blocks strictly above the diagonal are skipped whole
    (pl.when on the block predicate — no dots issued), the straddling
    block masks entrywise. Init/finalize stay unconditional so the scratch
    lifecycle is identical in both modes."""
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _update():
        q = q_ref[0]  # [block_q, d] input dtype
        k = k_ref[0]  # [block_k, d]
        v = v_ref[0]
        # scale in f32 then return to the input dtype: bf16 dot at MXU
        # rate, f32 accumulation via preferred_element_type
        qs = (q.astype(jnp.float32) * scale).astype(q.dtype)
        s = jnp.dot(qs, k.T, preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            # entrywise mask for the diagonal-straddling block (cheap
            # enough to apply on every executed block; fully-below-diagonal
            # blocks mask nothing)
            rows = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(cols <= rows, s, NEG_INF)

        # lane-replicated stats -> collapse with a max (all lanes equal)
        m_prev = jnp.max(m_ref[...], axis=-1, keepdims=True)  # [bq, 1]
        l_prev = jnp.max(l_ref[...], axis=-1, keepdims=True)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)  # [bq, bk] f32
        alpha = jnp.exp(m_prev - m_new)  # [bq, 1]
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)
        acc_ref[...] = alpha * acc_ref[...] + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )

    if causal:
        # skip blocks with no col <= row entry: min col > max row
        block_live = j * block_k <= i * block_q + block_q - 1
        pl.when(block_live)(_update)
    else:
        _update()

    @pl.when(j == n_kv - 1)
    def _finalize():
        # causal rows with zero mass cannot occur (row r always sees col
        # <= r); padded q rows are sliced off by the wrapper, and their
        # l stays 0 only when EVERY kv block was skipped — guard the
        # divide so those garbage rows stay finite instead of inf/nan
        l_fin = jnp.max(l_ref[...], axis=-1, keepdims=True)
        l_safe = jnp.where(l_fin == 0.0, 1.0, l_fin)
        o_ref[0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def _kv_block(sk: int, requested: int) -> int:
    """Largest power-of-two block <= requested that divides sk (any
    128-multiple sk admits 128)."""
    b = min(requested, sk)
    while b > 128 and sk % b:
        b //= 2
    if sk % b:
        raise ValueError(
            f"kv seq {sk} must be a multiple of 128 (pad inputs before "
            "calling, or use blockwise_attention)"
        )
    return b


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    block_q: int = 512,
    block_k: int = DEFAULT_BLOCK_K,
    causal: bool = False,
    interpret: bool | None = None,
) -> jax.Array:
    """q,k,v: [batch, heads, seq, head_dim] -> same shape.

    ``causal=True`` applies the autoregressive mask with whole KV blocks
    above the diagonal skipped (no dots issued) — decoder-style scoring;
    seq-parallel causal long-context goes through ring_attention. Chip
    measurements at seq 8192: 2.5x over the pure-JAX causal blockwise
    path; ~1.1x under the non-causal kernel (the skip saves MXU work but
    the block pipeline still prefetches skipped KV blocks — a triangular
    grid would reclaim that DMA, a known upgrade)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if interpret is None:
        interpret = jax.default_backend() != "tpu" or not _HAS_PLTPU

    # pad head_dim to the 128 lane width: zero-padded K dims add 0 to every
    # dot product and padded V dims are sliced off, so numerics are
    # unchanged (scale uses the original d). Measured: Mosaic at d=64
    # un-padded is ~2x SLOWER than padded-128 (lane under-utilization), so
    # the pad applies on the compiled path; interpret mode skips it.
    orig_d = d
    if not interpret and d % _LANES:
        pad_d = _LANES - d % _LANES
        q = jnp.pad(q, ((0, 0), (0, 0), (0, 0), (0, pad_d)))
        k = jnp.pad(k, ((0, 0), (0, 0), (0, 0), (0, pad_d)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, 0), (0, pad_d)))
        d = q.shape[-1]

    block_q = min(block_q, sq)
    block_k = _kv_block(sk, block_k)
    # padded Q rows are harmless (sliced off after)
    pad_q = (-sq) % block_q
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))

    qf = q.reshape(b * h, q.shape[2], d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    n_q = qf.shape[1] // block_q
    n_kv = sk // block_k

    if not _HAS_PLTPU:
        raise RuntimeError(
            "pallas TPU support unavailable in this jax build — use "
            "ops.attention.blockwise_attention (the serving policy in "
            "models/bert.py only routes here when the kernel is viable)"
        )
    kernel = functools.partial(
        _flash_kernel,
        n_kv=n_kv,
        scale=1.0 / (orig_d**0.5),
        block_q=block_q,
        block_k=block_k,
        causal=causal,
    )
    # scratch carries the online-softmax state across the (sequential) kv
    # grid dimension; interpret mode emulates VMEM scratch faithfully
    scratch_shapes = [
        pltpu.VMEM((block_q, _LANES), jnp.float32),  # m (lane-replicated)
        pltpu.VMEM((block_q, _LANES), jnp.float32),  # l (lane-replicated)
        pltpu.VMEM((block_q, d), jnp.float32),  # acc
    ]
    out = pl.pallas_call(
        kernel,
        grid=(b * h, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda g, i, j: (g, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda g, i, j: (g, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda g, i, j: (g, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda g, i, j: (g, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        scratch_shapes=scratch_shapes,
        interpret=interpret,
    )(qf, kf, vf)
    out = out.reshape(b, h, -1, d)
    if pad_q:
        out = out[:, :, :sq, :]
    if d != orig_d:
        out = out[..., :orig_d]
    return out
