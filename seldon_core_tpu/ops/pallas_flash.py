"""Pallas flash-attention forward kernel for TPU.

The hot op of the BERT/long-context serving path, hand-tiled for the MXU:
grid over (batch*heads, Q blocks); the kernel streams KV blocks through VMEM
with a fori_loop carrying online-softmax stats in f32 scratch. On non-TPU
backends (tests run on the 8-device CPU mesh) the same kernel runs in
interpreter mode, so numerics are covered everywhere while the compiled path
exercises Mosaic only on real hardware.

Block sizes respect the f32 (8,128) / bf16 (16,128) tiling minima; head_dim
is padded to the 128 lane width by the wrapper when needed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend only exists on TPU-enabled builds
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # noqa: BLE001
    _HAS_PLTPU = False

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, sk: int, scale: float):
    """One (batch*head, q-block) program: stream KV in blocks of block_k."""
    q = q_ref[0].astype(jnp.float32) * scale  # [block_q, d]
    block_q, d = q.shape
    n_kv = sk // block_k

    def body(i, carry):
        m_acc, l_acc, o_acc = carry
        k = k_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(i * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # MXU
        m_new = jnp.maximum(m_acc, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_acc - m_new)
        l_new = alpha * l_acc + jnp.sum(p, axis=-1)
        o_new = alpha[:, None] * o_acc + jnp.dot(
            p, v, preferred_element_type=jnp.float32
        )
        return m_new, l_new, o_new

    init = (
        jnp.full((block_q,), NEG_INF, jnp.float32),
        jnp.zeros((block_q,), jnp.float32),
        jnp.zeros((block_q, d), jnp.float32),
    )
    m, l, o = jax.lax.fori_loop(0, n_kv, body, init)
    o_ref[0] = (o / l[:, None]).astype(o_ref.dtype)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """q,k,v: [batch, heads, seq, head_dim] -> same shape. Non-causal (the
    serving encoder path); causal long-context goes through ring_attention.
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if interpret is None:
        interpret = jax.default_backend() != "tpu" or not _HAS_PLTPU

    # pad head_dim to the 128 lane width for the compiled path: zero-padded
    # K dims add 0 to every dot product and padded V dims are sliced off, so
    # numerics are unchanged (scale uses the original d)
    orig_d = d
    if not interpret and d % 128:
        pad_d = 128 - d % 128
        q = jnp.pad(q, ((0, 0), (0, 0), (0, 0), (0, pad_d)))
        k = jnp.pad(k, ((0, 0), (0, 0), (0, 0), (0, pad_d)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, 0), (0, pad_d)))
        d = q.shape[-1]

    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    # padded Q rows are harmless (sliced off after); padded K would need
    # in-kernel masking, so the KV axis must already be a block multiple —
    # the serving batcher buckets seq to these sizes anyway
    pad_q = (-sq) % block_q
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
    if sk % block_k:
        raise ValueError(
            f"kv seq {sk} must be a multiple of block_k {block_k} "
            "(pad inputs before calling)"
        )

    qf = q.reshape(b * h, q.shape[2], d)
    kf = k.reshape(b * h, sk, d)
    vf = v.reshape(b * h, sk, d)
    n_q = qf.shape[1] // block_q

    kernel = functools.partial(
        _flash_kernel, block_k=block_k, sk=sk, scale=1.0 / (orig_d**0.5)
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, n_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda g, i: (g, i, 0)),
            pl.BlockSpec((1, sk, d), lambda g, i: (g, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda g, i: (g, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda g, i: (g, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    out = out.reshape(b, h, -1, d)
    if pad_q:
        out = out[:, :, :sq, :]
    if d != orig_d:
        out = out[..., :orig_d]
    return out
