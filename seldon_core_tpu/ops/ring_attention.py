"""Ring attention: exact attention over a sequence-sharded mesh axis.

Long-context sequence parallelism (greenfield vs the reference — SURVEY
§5.7): the sequence axis is sharded over the mesh "seq" axis, each device
holding one Q/K/V shard. K/V shards rotate around the ring with
``lax.ppermute`` (XLA lowers it to ICI neighbor transfers) while each device
accumulates online-softmax partials of its local Q against every visiting
K/V shard — after |seq| steps every Q block has attended to the full
sequence exactly, with peak memory O(seq/|ring|) per device and communication
overlapped with the per-step attention compute by XLA's async collectives.

Causal masking works on global positions: each device knows its shard offset
from lax.axis_index.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from seldon_core_tpu.parallel.compat import pvary, shard_map as _shard_map

from seldon_core_tpu.ops.attention import NEG_INF, _block_stats, combine_stats


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool, seq_per_dev: int, vary_axes: tuple):
    """Per-device body (runs under shard_map). q,k,v: local shards
    [b, h, s_local, d]."""
    b, h, s, d = q.shape
    ring_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    q_pos = my_idx * seq_per_dev + jnp.arange(s)  # global positions of local Q

    def step(carry, i):
        m_acc, l_acc, o_acc, k_cur, v_cur = carry
        # the K/V block currently held arrived from device (my_idx + i) % ring
        src = (my_idx + i) % ring_size
        k_pos = src * seq_per_dev + jnp.arange(s)
        mask = None
        if causal:
            mask = k_pos[None, None, None, :] <= q_pos[None, None, :, None]
        m, l, o = _block_stats(q, k_cur, v_cur, mask)
        m_acc, l_acc, o_acc = combine_stats(m_acc, l_acc, o_acc, m, l, o)
        # rotate K/V around the ring (device p receives from p+1: after step
        # i every device holds the shard of (my_idx + i + 1) % ring)
        perm = [(j, (j - 1) % ring_size) for j in range(ring_size)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (m_acc, l_acc, o_acc, k_nxt, v_nxt), None

    # constants created inside shard_map are axis-invariant; the carry must
    # be marked varying over EVERY manual axis the inputs vary over (on a
    # mixed data+seq mesh that includes the batch axis) to match the loop
    # outputs
    init = (
        pvary(jnp.full((b, h, s), NEG_INF, q.dtype), vary_axes),
        pvary(jnp.zeros((b, h, s), q.dtype), vary_axes),
        pvary(jnp.zeros((b, h, s, d), q.dtype), vary_axes),
        k,
        v,
    )
    (m, l, o, _, _), _ = lax.scan(step, init, jnp.arange(ring_size))
    return o / l[..., None]


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    seq_axis: str = "seq",
    data_axis: str = "data",
    causal: bool = False,
) -> jax.Array:
    """q,k,v: [batch, heads, seq, head_dim] GLOBAL arrays (or already
    sharded); returns attention output sharded the same way. seq must divide
    evenly by the mesh's seq-axis size. On a mixed data+seq mesh the batch
    dim shards over ``data_axis`` too — otherwise every device in the data
    group would recompute attention for the full batch."""
    seq = q.shape[2]
    ring = mesh.shape[seq_axis]
    if seq % ring != 0:
        raise ValueError(f"seq {seq} not divisible by ring size {ring}")
    seq_per_dev = seq // ring
    batch_entry = data_axis if data_axis in mesh.shape else None
    spec = P(batch_entry, None, seq_axis, None)

    fn = _shard_map(
        partial(
            _ring_attention_local,
            axis_name=seq_axis,
            causal=causal,
            seq_per_dev=seq_per_dev,
            vary_axes=tuple(mesh.axis_names),
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
