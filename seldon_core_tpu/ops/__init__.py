from seldon_core_tpu.ops.attention import blockwise_attention, naive_attention
from seldon_core_tpu.ops.pallas_flash import flash_attention
from seldon_core_tpu.ops.ring_attention import ring_attention

__all__ = [
    "blockwise_attention",
    "flash_attention",
    "naive_attention",
    "ring_attention",
]
