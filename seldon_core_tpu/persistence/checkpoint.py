"""Model checkpoint save/restore (file:// model_uri scheme).

Parity role: the reference bakes model weights into docker images (e.g.
examples/models/sklearn_iris/IrisClassifier.sav loaded by IrisClassifier.py)
— "checkpointing" there is docker push. Here weights are first-class: a
checkpoint directory holds the params pytree plus enough metadata to rebuild
the apply function and its TP PartitionSpecs from the zoo registry, so
restore lands the weights straight onto the device mesh.

Format: <dir>/metadata.json {model, kwargs, param_tree} +
<dir>/params.msgpack (flax.serialization bytes — framework-stable, no pickle).
"""

from __future__ import annotations

import json
import os
from typing import Any

import numpy as np

import jax


def _to_state_dict(params: Any):
    from flax import serialization

    return serialization.to_bytes(jax.tree.map(np.asarray, params))


def save_model(path: str, model: str, params: Any, kwargs: dict | None = None) -> None:
    """Persist params + the zoo builder identity that owns the apply fn."""
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "metadata.json"), "w") as f:
        json.dump({"model": model, "kwargs": kwargs or {}}, f)
    with open(os.path.join(path, "params.msgpack"), "wb") as f:
        f.write(_to_state_dict(params))


def restore_model(path: str):
    """Rebuild the ModelSpec: zoo builder gives apply_fn/pspecs/shapes, the
    checkpoint bytes replace the fresh-init params."""
    from flax import serialization

    from seldon_core_tpu.models import zoo

    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    name, kwargs = meta["model"], meta.get("kwargs", {})
    ms = zoo.get_model(name, **kwargs)  # lazy-registers heavy models itself
    with open(os.path.join(path, "params.msgpack"), "rb") as f:
        restored = serialization.from_bytes(jax.tree.map(np.asarray, ms.params), f.read())
    return zoo.ModelSpec(
        ms.apply_fn,
        restored,
        ms.feature_shape,
        ms.class_names,
        param_pspecs=ms.param_pspecs,
        apply_factory=ms.apply_factory,  # mesh-aware serving survives restore
    )
