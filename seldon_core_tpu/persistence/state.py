"""Stateful-unit persistence: periodic snapshot + restore-on-boot.

Parity (C19): reference wrappers/python/persistence.py — a thread cPickles
the live user object to Redis key
``persistence_{SELDON_DEPLOYMENT_ID}_{PREDICTIVE_UNIT_ID}`` every 60 s
(:26-48) and restores it at boot (:17-24), keeping learned router/bandit
state across restarts. Same contract here with pluggable stores (file dir
for single-host, redis when importable) and the same key naming. The
persisted payload is the unit's __getstate__ (e.g. EpsilonGreedyRouter's arm
counts/values — host-side state, never jitted).
"""

from __future__ import annotations


import hashlib
import logging
import os
import pickle
import time
from typing import Any, Iterable

log = logging.getLogger(__name__)

DEFAULT_PERIOD_S = 60.0  # reference persistence.py default


def state_key(deployment_id: str, unit_id: str) -> str:
    return f"persistence_{deployment_id}_{unit_id}"  # reference key format


class FileStateStore:
    """One pickle file per key under a directory."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, key: str) -> str:
        safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in key)
        # sanitizing is lossy ("a/b" and "a_b" collide) — a short digest of
        # the RAW key keeps distinct keys in distinct files
        digest = hashlib.blake2b(key.encode(), digest_size=4).hexdigest()
        return os.path.join(self.directory, f"{safe}.{digest}.pkl")

    def save(self, key: str, payload: bytes) -> None:
        tmp = self._path(key) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(payload)
        os.replace(tmp, self._path(key))

    def load(self, key: str) -> bytes | None:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None


class RedisStateStore:
    """Redis-backed store with a bounded socket budget: a hung Redis must
    degrade to skip-store (save dropped, load misses — both logged), never
    block the serving loop mid-spill/preseed."""

    def __init__(self, url: str):
        import redis  # gated: not in the base image

        from seldon_core_tpu.utils.env import redis_timeout_s

        timeout = redis_timeout_s()
        self._errors = (redis.exceptions.ConnectionError, redis.exceptions.TimeoutError)
        self._r = redis.Redis.from_url(
            url,
            socket_timeout=timeout,
            socket_connect_timeout=timeout,
        )

    def save(self, key: str, payload: bytes) -> None:
        try:
            self._r.set(key, payload)
        except self._errors as e:
            log.warning("redis save skipped (store unreachable): %s", e)

    def load(self, key: str) -> bytes | None:
        try:
            return self._r.get(key)
        except self._errors as e:
            log.warning("redis load skipped (store unreachable): %s", e)
            return None


def make_state_store(url: str):
    if not url:
        return None
    if url.startswith("file://"):
        return FileStateStore(url[len("file://") :])
    if url.startswith("redis://"):
        try:
            return RedisStateStore(url)
        except ImportError:
            log.warning("redis not importable; state persistence disabled")
            return None
    raise ValueError(f"unknown state store url: {url}")


class StatePersister:
    """Snapshots stateful units on a period; restores them at attach time.

    A unit is stateful iff it defines __getstate__/__setstate__ (the builtin
    EpsilonGreedyRouter does; pure units and TPU model runtimes do not —
    model *weights* are checkpoints, not state, exactly as in the reference
    where weights live in the image and only learned router state persists).
    """

    def __init__(self, store, deployment_id: str, period_s: float = DEFAULT_PERIOD_S):
        self.store = store
        self.deployment_id = deployment_id
        self.period_s = period_s
        self._units: dict[str, Any] = {}
        self._thread: "threading.Thread | None" = None
        self._stop = None  # threading.Event once started

    @staticmethod
    def is_stateful(unit: Any) -> bool:
        # object defines a default __getstate__ (3.11+); a unit is stateful
        # only if its own class hierarchy defines BOTH dunder explicitly
        mro = [c for c in type(unit).__mro__ if c is not object]
        return any("__getstate__" in c.__dict__ for c in mro) and any(
            "__setstate__" in c.__dict__ for c in mro
        )

    def attach(self, units: Iterable[Any], prefix: str = "") -> int:
        """Register stateful units and restore any saved state. Returns the
        number restored. ``prefix`` namespaces the unit id (predictor name)
        so same-named units in different predictors don't share a slot."""
        restored = 0
        for unit in units:
            if not self.is_stateful(unit):
                continue
            name = getattr(unit, "name", None) or type(unit).__name__
            if prefix:
                name = f"{prefix}.{name}"
            self._units[name] = unit
            payload = self.store.load(state_key(self.deployment_id, name))
            if payload is not None:
                try:
                    unit.__setstate__(pickle.loads(payload))
                    restored += 1
                except Exception as e:  # noqa: BLE001 - stale/corrupt state
                    log.warning("could not restore state for %s: %s", name, e)
        return restored

    def persist_now(self) -> int:
        saved = 0
        for name, unit in self._units.items():
            try:
                payload = pickle.dumps(unit.__getstate__())
                self.store.save(state_key(self.deployment_id, name), payload)
                saved += 1
            except Exception as e:  # noqa: BLE001
                log.warning("could not persist state for %s: %s", name, e)
        return saved

    def start(self) -> None:
        """Begin periodic snapshots on a daemon thread — like the reference's
        PersistenceThread (persistence.py:43-48); a thread (not an asyncio
        task) so it works no matter which thread reconciles the deployment."""
        import threading

        if not self._units or self._thread is not None:
            return
        self._stop = threading.Event()

        def loop():
            while not self._stop.wait(self.period_s):
                self.persist_now()

        self._thread = threading.Thread(
            target=loop, name=f"persist-{self.deployment_id}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=2)
            self._thread = None
        self.persist_now()  # final flush, like the reference's atexit intent
