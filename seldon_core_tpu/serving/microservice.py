"""Microservice CLI: serve one duck-typed user model class standalone.

Parity (C18): reference wrappers/python/microservice.py —
    python microservice.py <UserClass> <REST|GRPC> --service-type MODEL \
        [--persistence]
- imports module <UserClass> and instantiates class <UserClass> from it,
  passing typed constructor args parsed from the PREDICTIVE_UNIT_PARAMETERS
  env JSON (microservice.py:119-148);
- serves the unit-type API (MODEL/ROUTER/TRANSFORMER/OUTPUT_TRANSFORMER/
  COMBINER) over REST on PREDICTIVE_UNIT_SERVICE_PORT (default 5000 like
  the reference's default) and/or gRPC;
- --persistence snapshots the live user object periodically and restores it
  at boot (C19; reference --persistence flag, microservice.py:141,150-152).

This makes the framework a drop-in replacement for a reference model
container: the engine (ours or the reference's) can call this process over
REST/gRPC with the same wire format.
"""

from __future__ import annotations

import argparse
import asyncio
import importlib
import json
import logging
import os
import sys

from seldon_core_tpu.graph.spec import (
    PredictiveUnit,
    PredictiveUnitType,
    PredictorSpec,
)

log = logging.getLogger(__name__)

SERVICE_TYPES = {
    "MODEL": PredictiveUnitType.MODEL,
    "ROUTER": PredictiveUnitType.ROUTER,
    "TRANSFORMER": PredictiveUnitType.TRANSFORMER,
    "OUTPUT_TRANSFORMER": PredictiveUnitType.OUTPUT_TRANSFORMER,
    "COMBINER": PredictiveUnitType.COMBINER,
    # the reference's fourth wrapper flavor (microservice.py:140,162): serves
    # /transform-input, calls user score(), tags meta.tags.outlierScore
    "OUTLIER_DETECTOR": PredictiveUnitType.TRANSFORMER,
}


def parse_parameters(raw: str | None) -> dict:
    """PREDICTIVE_UNIT_PARAMETERS: [{"name":..,"value":..,"type":..}] with
    typed coercion (reference microservice.py:119-133)."""
    if not raw:
        return {}
    out = {}
    for p in json.loads(raw):
        value, ptype = p.get("value"), p.get("type", "STRING")
        if ptype == "INT":
            value = int(value)
        elif ptype in ("FLOAT", "DOUBLE"):
            value = float(value)
        elif ptype == "BOOL":
            value = str(value).lower() in ("1", "true", "yes")
        out[p["name"]] = value
    return out


def _import_user_module(name: str, model_dir: str):
    """Load ``<model_dir>/<name>.py`` under a key unique to that path.

    A long-lived multi-CR platform process cannot use the bare module name:
    ``importlib.import_module`` caches by name, so two CRs whose modules are
    both called ``Model`` (different dirs) would silently share the first
    dir's code, and a re-applied CR would never pick up an edited file.
    Loading by file location under a per-path key gives each dir its own
    module and re-executes the file on every build. model_dir still joins
    sys.path (deduped) so the user module can import its siblings.
    """
    import hashlib
    import importlib.util

    path = os.path.abspath(os.path.join(model_dir, name + ".py"))
    if not os.path.exists(path):  # fall back to the plain import contract
        if model_dir not in sys.path:
            sys.path.insert(0, model_dir)
        return importlib.import_module(name)
    if model_dir not in sys.path:
        sys.path.insert(0, model_dir)
    key = f"_seldon_user_{hashlib.sha1(path.encode()).hexdigest()[:12]}_{name}"
    spec = importlib.util.spec_from_file_location(key, path)
    module = importlib.util.module_from_spec(spec)
    sys.modules[key] = module
    spec.loader.exec_module(module)
    return module


def load_user_object(name: str, model_dir: str | None = None, parameters: dict | None = None):
    """Import module ``name``, instantiate class ``name`` with the typed
    parameters as kwargs — the reference contract (interface_name == module
    name == class name, microservice.py:136-140)."""
    if model_dir:
        module = _import_user_module(name, model_dir)
    else:
        module = importlib.import_module(name)
    cls = getattr(module, name)
    return cls(**(parameters or {}))


def build_single_unit_predictor(name: str, service_type: str) -> PredictorSpec:
    # children stay empty even for routers/combiners: a standalone
    # microservice exposes the unit's own API; the graph around it lives in
    # whichever engine calls this process
    unit_type = SERVICE_TYPES[service_type]
    return PredictorSpec(
        name=name,
        graph=PredictiveUnit.model_validate(
            {"name": name, "type": unit_type.value, "children": []}
        ),
    )


async def serve_microservice(
    user_object,
    name: str,
    service_type: str = "MODEL",
    *,
    host: str = "0.0.0.0",
    http_port: int | None = None,
    grpc_port: int | None = None,
    enable_rest: bool = True,
    persistence_url: str = "",
    persistence_period_s: float = 60.0,
):
    """Boot REST (+ optional gRPC) for one user object. Returns (runner,
    grpc_server, persister)."""
    from aiohttp import web

    from seldon_core_tpu.engine import build_executor
    from seldon_core_tpu.engine.units import PythonClassUnit
    from seldon_core_tpu.metrics import get_metrics
    from seldon_core_tpu.serving.rest import build_app
    from seldon_core_tpu.serving.service import PredictionService

    predictor = build_single_unit_predictor(name, service_type)
    # unit_object may wrap user_object; persistence below must keep snapshotting
    # the RAW user object (its learned state), never the wrapper
    unit_object = user_object
    if service_type == "OUTLIER_DETECTOR":
        from seldon_core_tpu.engine.units import OutlierDetectorUnit

        unit_object = OutlierDetectorUnit(predictor.graph, user_object)
    executor = build_executor(
        predictor, context={"units": {name: unit_object}}
    )
    service = PredictionService(executor, deployment_name=name, metrics=get_metrics(True))

    persister = None
    if persistence_url:
        from seldon_core_tpu.persistence.state import StatePersister, make_state_store

        store = make_state_store(persistence_url)
        if store is not None:
            deployment_id = os.environ.get("SELDON_DEPLOYMENT_ID", name)
            unit_id = os.environ.get("PREDICTIVE_UNIT_ID", name)

            class _UserStateAdapter:
                """User objects persist whole (reference pickles the object);
                adapt to the persister's getstate/setstate contract."""

                def __init__(self):
                    self.name = unit_id

                def __getstate__(self):
                    return user_object.__dict__

                def __setstate__(self, state):
                    user_object.__dict__.update(state)

            persister = StatePersister(store, deployment_id, period_s=persistence_period_s)
            restored = persister.attach([_UserStateAdapter()])
            if restored:
                log.info("restored persisted state for %s", unit_id)
            persister.start()

    runner = None
    if enable_rest:
        runner = web.AppRunner(build_app(service))
        await runner.setup()
        port = http_port or int(
            os.environ.get("PREDICTIVE_UNIT_SERVICE_PORT", "5000")
        )
        site = web.TCPSite(runner, host, port)
        await site.start()
        log.info("microservice %s (%s) REST on %s:%s", name, service_type, host, port)

    grpc_server = None
    if grpc_port:
        from seldon_core_tpu.serving.grpc_server import start_grpc_server

        grpc_server = await start_grpc_server(service, host=host, port=grpc_port)
        log.info("microservice gRPC on %s:%s", host, grpc_port)
    return runner, grpc_server, persister


async def _amain(args) -> None:
    import signal

    parameters = parse_parameters(os.environ.get("PREDICTIVE_UNIT_PARAMETERS"))
    user_object = load_user_object(args.interface_name, args.model_dir, parameters)
    persistence_url = ""
    if args.persistence:
        persistence_url = os.environ.get(
            "PERSISTENCE_STORE", "file://./.seldon_state"
        )
    runner, grpc_server, persister = await serve_microservice(
        user_object,
        args.interface_name,
        args.service_type,
        http_port=args.port,
        grpc_port=args.grpc_port if args.api in ("GRPC", "BOTH") else None,
        enable_rest=args.api in ("REST", "BOTH"),
        persistence_url=persistence_url,
    )
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    if persister is not None:
        persister.stop()
    if grpc_server is not None:
        await grpc_server.stop(5)
    if runner is not None:
        await runner.cleanup()


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("interface_name", help="module and class name of the user model")
    p.add_argument("api", nargs="?", default="REST", choices=["REST", "GRPC", "BOTH"])
    p.add_argument("--service-type", default="MODEL", choices=sorted(SERVICE_TYPES))
    p.add_argument("--model-dir", default=".")
    p.add_argument("--port", type=int, default=None)
    p.add_argument("--grpc-port", type=int, default=5001)
    p.add_argument("--persistence", action="store_true")
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    asyncio.run(_amain(args))


if __name__ == "__main__":
    main()
