"""Microservice CLI: serve one duck-typed user model class standalone.

Parity (C18): reference wrappers/python/microservice.py —
    python microservice.py <UserClass> <REST|GRPC> --service-type MODEL \
        [--persistence]
- imports module <UserClass> and instantiates class <UserClass> from it,
  passing typed constructor args parsed from the PREDICTIVE_UNIT_PARAMETERS
  env JSON (microservice.py:119-148);
- serves the unit-type API (MODEL/ROUTER/TRANSFORMER/OUTPUT_TRANSFORMER/
  COMBINER) over REST on PREDICTIVE_UNIT_SERVICE_PORT (default 5000 like
  the reference's default) and/or gRPC;
- --persistence snapshots the live user object periodically and restores it
  at boot (C19; reference --persistence flag, microservice.py:141,150-152).

This makes the framework a drop-in replacement for a reference model
container: the engine (ours or the reference's) can call this process over
REST/gRPC with the same wire format.
"""

from __future__ import annotations

import argparse
import asyncio
import importlib
import json
import logging
import os
import sys

from seldon_core_tpu.graph.spec import (
    PredictiveUnit,
    PredictiveUnitType,
    PredictorSpec,
)
from seldon_core_tpu.utils.env import (
    PERSISTENCE_STORE,
    PREDICTIVE_UNIT_ID,
    PREDICTIVE_UNIT_PARAMETERS,
    PREDICTIVE_UNIT_SERVICE_PORT,
    SELDON_DEPLOYMENT_ID,
)

log = logging.getLogger(__name__)

SERVICE_TYPES = {
    "MODEL": PredictiveUnitType.MODEL,
    "ROUTER": PredictiveUnitType.ROUTER,
    "TRANSFORMER": PredictiveUnitType.TRANSFORMER,
    "OUTPUT_TRANSFORMER": PredictiveUnitType.OUTPUT_TRANSFORMER,
    "COMBINER": PredictiveUnitType.COMBINER,
    # the reference's fourth wrapper flavor (microservice.py:140,162): serves
    # /transform-input, calls user score(), tags meta.tags.outlierScore
    "OUTLIER_DETECTOR": PredictiveUnitType.TRANSFORMER,
}


def parse_parameters(raw: str | None) -> dict:
    """PREDICTIVE_UNIT_PARAMETERS: [{"name":..,"value":..,"type":..}] with
    typed coercion (reference microservice.py:119-133)."""
    if not raw:
        return {}
    out = {}
    for p in json.loads(raw):
        value, ptype = p.get("value"), p.get("type", "STRING")
        if ptype == "INT":
            value = int(value)
        elif ptype in ("FLOAT", "DOUBLE"):
            value = float(value)
        elif ptype == "BOOL":
            value = str(value).lower() in ("1", "true", "yes")
        out[p["name"]] = value
    return out


import contextlib

_USER_PREFIX = "_seldon_user_"


class _ModelDirFinder:
    """Process-global meta-path finder for per-dir module keys.

    Re-keyed dir-local modules live in sys.modules as
    ``_seldon_user_<dirkey>_<name>``; this finder makes those names
    IMPORTABLE, not just cached — which is what pickle needs: user state
    holding a sibling-class instance pickles the class as
    ``(module, qualname)``, and unpickling __import__s that module. The
    dir_key is a content address (sha1 of abs_dir), so a fresh process
    that re-applies the same CR re-registers the same key and restores
    state persisted by the previous process (C19 restore-on-boot)."""

    registry: dict[str, str] = {}  # dir_key -> abs_dir

    def find_spec(self, fullname, path=None, target=None):
        if not fullname.startswith(_USER_PREFIX):
            return None
        rest = fullname[len(_USER_PREFIX) :]
        dir_key, sep, mod = rest.partition("_")
        abs_dir = self.registry.get(dir_key)
        if not abs_dir or not sep or not mod:
            return None
        import importlib.util

        parts = mod.split(".")
        flat = os.path.join(abs_dir, *parts) + ".py"
        if os.path.exists(flat):
            return importlib.util.spec_from_file_location(fullname, flat)
        pkg_init = os.path.join(abs_dir, *parts, "__init__.py")
        if os.path.exists(pkg_init):
            return importlib.util.spec_from_file_location(
                fullname,
                pkg_init,
                submodule_search_locations=[os.path.join(abs_dir, *parts)],
            )
        return None


_finder = _ModelDirFinder()
_active_dirs: set[str] = set()  # dirs with an open _dir_import_context


def _dir_key_for(abs_dir: str) -> str:
    import hashlib

    return hashlib.sha1(abs_dir.encode()).hexdigest()[:12]


def _rekey_module(mod_name: str, module, dir_key: str) -> None:
    """Move a dir-local module from its bare sys.modules name to the
    per-dir key, updating the module's own identity (__name__/__spec__)
    and the __module__ of its defs — including classes NESTED inside other
    classes (pickle references them by module + qualname too) — so pickle
    emits the importable per-dir name instead of the popped bare one."""
    import inspect

    new_name = f"{_USER_PREFIX}{dir_key}_{mod_name}"

    def _rewrite(obj, seen: set) -> None:
        if id(obj) in seen:
            return
        seen.add(id(obj))
        if getattr(obj, "__module__", None) != mod_name:
            return  # foreign object — nothing of ours can be nested in it
        try:
            obj.__module__ = new_name
        except (AttributeError, TypeError):
            return
        if inspect.isclass(obj):
            for member in list(vars(obj).values()):
                if inspect.isclass(member) or inspect.isfunction(member):
                    _rewrite(member, seen)

    seen: set = set()
    for obj in list(vars(module).values()):
        _rewrite(obj, seen)
    try:
        module.__name__ = new_name
        if getattr(module, "__spec__", None) is not None:
            module.__spec__.name = new_name
    except (AttributeError, TypeError):
        pass
    sys.modules[new_name] = sys.modules.pop(mod_name)


@contextlib.contextmanager
def _dir_import_context(abs_dir: str, dir_key: str):
    """Scoped sibling isolation for one model dir.

    Inside the context, abs_dir is on sys.path so the entry module (and its
    __init__) can import dir-local code. On exit the dir leaves sys.path
    and every module that was loaded FROM it — flat sibling .py, sibling
    package, or a package-form entry module itself — is re-keyed from its
    bare sys.modules name to a per-dir name: loaded objects keep their
    direct references, the per-dir names stay importable through
    _ModelDirFinder (pickle/persistence), and the next CR's same-named
    module resolves fresh from ITS dir instead of silently sharing this
    one's code. Reentrant for the same dir (inner context is a no-op).
    Residual limitation: a dir-local module imported lazily at request
    time (inside predict()) by its BARE name raises ImportError instead
    of reusing another dir's module — do runtime imports at the entry
    module's top level or in __init__.
    """
    if _finder not in sys.meta_path:
        sys.meta_path.append(_finder)
    _ModelDirFinder.registry[dir_key] = abs_dir
    if abs_dir in _active_dirs:
        # nested context for the same dir: the outermost owns the re-key
        yield
        return
    _active_dirs.add(abs_dir)
    path_added = abs_dir not in sys.path
    if path_added:
        sys.path.insert(0, abs_dir)
    before = set(sys.modules)
    try:
        yield
    finally:
        _active_dirs.discard(abs_dir)
        if path_added and abs_dir in sys.path:
            sys.path.remove(abs_dir)
        for mod_name in set(sys.modules) - before:
            if mod_name.startswith(_USER_PREFIX):
                continue  # already per-dir keyed (entry module)
            m = sys.modules.get(mod_name)
            if m is not None and _module_from_dir(m, abs_dir):
                _rekey_module(mod_name, m, dir_key)


def _module_from_dir(mod, abs_dir: str) -> bool:
    mod_file = getattr(mod, "__file__", None) or ""
    if mod_file and os.path.abspath(mod_file).startswith(abs_dir + os.sep):
        return True
    # namespace/regular packages: __path__ entries instead of __file__.
    # Some modules carry exotic __path__ objects (torch.classes) — treat
    # anything not iterable into strings as not-from-dir.
    try:
        entries = [os.fspath(p) for p in getattr(mod, "__path__", ()) or ()]
    except TypeError:
        return False
    return any(os.path.abspath(p).startswith(abs_dir + os.sep) for p in entries)


def _import_user_module(name: str, model_dir: str):
    """Load ``<model_dir>/<name>.py`` under a key unique to that path.

    A long-lived multi-CR platform process cannot use the bare module name:
    ``importlib.import_module`` caches by name, so two CRs whose modules are
    both called ``Model`` (different dirs) would silently share the first
    dir's code, and a re-applied CR would never pick up an edited file.
    Loading by file location under a per-path key gives each dir its own
    module and re-executes the file on every build; _dir_import_context
    gives its siblings the same isolation.
    """
    import importlib.util

    abs_dir = os.path.abspath(model_dir)
    dir_key = _dir_key_for(abs_dir)
    path = os.path.join(abs_dir, name + ".py")
    with _dir_import_context(abs_dir, dir_key):
        if os.path.exists(path):
            key = f"{_USER_PREFIX}{dir_key}_{name}"
            spec = importlib.util.spec_from_file_location(key, path)
            module = importlib.util.module_from_spec(spec)
            sys.modules[key] = module
            spec.loader.exec_module(module)
            return module
        # package-form entry (<name>/__init__.py) or an installed module:
        # import by bare name; if it came from this dir the context's
        # re-key moves it out of the bare-name cache like any sibling, so
        # another dir's same-named entry resolves fresh (installed modules
        # stay cached — they're dir-independent)
        return importlib.import_module(name)


def load_user_object(name: str, model_dir: str | None = None, parameters: dict | None = None):
    """Import module ``name``, instantiate class ``name`` with the typed
    parameters as kwargs — the reference contract (interface_name == module
    name == class name, microservice.py:136-140). Instantiation runs INSIDE
    the dir-import context (which is reentrant, so the nested
    _import_user_module context is a no-op): user __init__s lazily import
    dir-local helpers (e.g. a train-on-first-boot module), and those get
    the same per-dir isolation as top-level imports."""
    if model_dir:
        abs_dir = os.path.abspath(model_dir)
        with _dir_import_context(abs_dir, _dir_key_for(abs_dir)):
            module = _import_user_module(name, model_dir)
            cls = getattr(module, name)
            return cls(**(parameters or {}))
    module = importlib.import_module(name)
    cls = getattr(module, name)
    return cls(**(parameters or {}))


def build_single_unit_predictor(name: str, service_type: str) -> PredictorSpec:
    # children stay empty even for routers/combiners: a standalone
    # microservice exposes the unit's own API; the graph around it lives in
    # whichever engine calls this process
    unit_type = SERVICE_TYPES[service_type]
    return PredictorSpec(
        name=name,
        graph=PredictiveUnit.model_validate(
            {"name": name, "type": unit_type.value, "children": []}
        ),
    )


async def serve_microservice(
    user_object,
    name: str,
    service_type: str = "MODEL",
    *,
    host: str = "0.0.0.0",
    http_port: int | None = None,
    grpc_port: int | None = None,
    enable_rest: bool = True,
    persistence_url: str = "",
    persistence_period_s: float = 60.0,
    decode_npy: bool = True,
):
    """Boot REST (+ optional gRPC) for one user object. Returns (runner,
    grpc_server, persister)."""
    from aiohttp import web

    from seldon_core_tpu.engine import build_executor
    from seldon_core_tpu.engine.units import PythonClassUnit
    from seldon_core_tpu.metrics import get_metrics
    from seldon_core_tpu.serving.rest import build_app
    from seldon_core_tpu.serving.service import PredictionService

    predictor = build_single_unit_predictor(name, service_type)
    # unit_object may wrap user_object; persistence below must keep snapshotting
    # the RAW user object (its learned state), never the wrapper
    unit_object = user_object
    if service_type == "OUTLIER_DETECTOR":
        from seldon_core_tpu.engine.units import OutlierDetectorUnit

        unit_object = OutlierDetectorUnit(predictor.graph, user_object)
    executor = build_executor(
        predictor, context={"units": {name: unit_object}}
    )
    service = PredictionService(
        executor,
        deployment_name=name,
        metrics=get_metrics(True),
        decode_npy=decode_npy,
    )

    persister = None
    if persistence_url:
        from seldon_core_tpu.persistence.state import StatePersister, make_state_store

        store = make_state_store(persistence_url)
        if store is not None:
            deployment_id = os.environ.get(SELDON_DEPLOYMENT_ID, name)
            unit_id = os.environ.get(PREDICTIVE_UNIT_ID, name)

            class _UserStateAdapter:
                """User objects persist whole (reference pickles the object);
                adapt to the persister's getstate/setstate contract."""

                def __init__(self):
                    self.name = unit_id

                def __getstate__(self):
                    return user_object.__dict__

                def __setstate__(self, state):
                    user_object.__dict__.update(state)

            persister = StatePersister(store, deployment_id, period_s=persistence_period_s)
            restored = persister.attach([_UserStateAdapter()])
            if restored:
                log.info("restored persisted state for %s", unit_id)
            persister.start()

    runner = None
    if enable_rest:
        runner = web.AppRunner(build_app(service))
        await runner.setup()
        port = http_port or int(
            os.environ.get(PREDICTIVE_UNIT_SERVICE_PORT, "5000")
        )
        site = web.TCPSite(runner, host, port)
        await site.start()
        log.info("microservice %s (%s) REST on %s:%s", name, service_type, host, port)

    grpc_server = None
    if grpc_port:
        from seldon_core_tpu.serving.grpc_server import start_grpc_server

        grpc_server = await start_grpc_server(service, host=host, port=grpc_port)
        log.info("microservice gRPC on %s:%s", host, grpc_port)
    return runner, grpc_server, persister


async def _amain(args) -> None:
    import signal

    parameters = parse_parameters(os.environ.get(PREDICTIVE_UNIT_PARAMETERS))
    user_object = load_user_object(args.interface_name, args.model_dir, parameters)
    persistence_url = ""
    if args.persistence:
        persistence_url = os.environ.get(
            PERSISTENCE_STORE, "file://./.seldon_state"
        )
    runner, grpc_server, persister = await serve_microservice(
        user_object,
        args.interface_name,
        args.service_type,
        http_port=args.port,
        grpc_port=args.grpc_port if args.api in ("GRPC", "BOTH") else None,
        enable_rest=args.api in ("REST", "BOTH"),
        persistence_url=persistence_url,
        decode_npy=not args.no_decode_npy,
    )
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    if persister is not None:
        persister.stop()
    if grpc_server is not None:
        await grpc_server.stop(5)
    if runner is not None:
        await runner.cleanup()


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("interface_name", help="module and class name of the user model")
    p.add_argument("api", nargs="?", default="REST", choices=["REST", "GRPC", "BOTH"])
    p.add_argument("--service-type", default="MODEL", choices=sorted(SERVICE_TYPES))
    p.add_argument("--model-dir", default=".")
    p.add_argument("--port", type=int, default=None)
    p.add_argument("--grpc-port", type=int, default=5001)
    p.add_argument("--persistence", action="store_true")
    p.add_argument(
        "--no-decode-npy",
        action="store_true",
        help="never sniff binData for npy — opaque passthrough for bytes-"
        "contract models whose payloads could collide with the npy magic",
    )
    args = p.parse_args()
    logging.basicConfig(level=logging.INFO)
    asyncio.run(_amain(args))


if __name__ == "__main__":
    main()
