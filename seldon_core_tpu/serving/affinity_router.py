"""Multi-replica decode scale-out: prefix-affinity routing + reward-driven
load balancing.

The single decode scheduler is saturated on every measured axis (pipelined
rounds hide the host bubble, the feature draft amortizes each dispatch), so
the next throughput multiple is horizontal: N scheduler replicas, each with
its own page pool, prefix index, and dispatch stream — mapped round-robin
onto the attached devices — behind a ROUTER that keeps warm routing warm.
This is the source system's defining capability (ROUTER graph nodes + bandit
routers fed by the Feedback reward API, PAPER.md L3/L5) pointed at the
generative tier:

- **Prefix-affinity routing**: a prefix-pool hit cuts TTFT 78.0 -> 28.2 ms
  (PR 5), but the hit only exists on the replica that CAPTURED the prefix.
  The router normalizes the prompt to its leading block (the same
  normalization ``PrefixIndex`` admission applies — shared helpers below)
  and rendezvous-hashes it, so every request sharing a system prompt lands
  on the same warm replica while distinct prefix groups spread across the
  fleet. Naive round-robin splits each group R ways and multiplies the cold
  misses by the replica count — the bench's control leg documents exactly
  that collapse.
- **Bounded-load shedding**: affinity must not melt the hot replica. When
  the rendezvous winner's queue depth exceeds ``load_factor`` x the fleet
  mean (+1 slack), the pick degrades to power-of-two-choices between the
  top TWO rendezvous ranks by live queue depth (``/decode/health`` exposes
  ``queue_depth`` per replica for the out-of-process twin) — the classic
  consistent-hashing-with-bounded-loads escape valve, and the shed target
  is still deterministic per key (rank 2), so a spilled group stays warm on
  ONE overflow replica instead of spraying.
- **Reward-driven fallback**: requests with no affinity signal (prompts
  shorter than one block) ride per-replica bandit arms — epsilon-greedy or
  Thompson — rewarded through the existing Feedback API by the
  TTFT/ITL/SLO-attainment verdicts PR 9 already stamps into
  ``meta.tags.slo`` (the serving layer closes the loop automatically; no
  client change).
- **Warm scale-up**: a new replica is cold by construction. Scale-up spills
  the hottest replica's refcount-ranked prefix-pool pages (int8 pools spill
  the stored bytes + scale planes verbatim — no dequant round-trip) through
  ``persistence/state.py`` and pre-seeds them into the new replica's pool,
  so its FIRST shared-prompt request already rides the warm TTFT path.
- **Fault tolerance**: a health poller probes every replica each interval
  (``health_probe`` in-process; GET /decode/health out-of-process), feeds
  the polled ``queue_depth`` into the balancer, and counts consecutive
  misses into a per-replica circuit breaker (engine/resilience.py
  semantics). A breaker that opens EVICTS the replica from rendezvous
  ranking and MIGRATES its in-flight generations: each tracked request
  resubmits on a surviving replica with the tokens it already streamed as
  a teacher-forced replay, so the client's stream resumes at the next
  token — bit-identical to an uninterrupted greedy run, no duplicates, no
  gaps. A half-open probe readmits the replica once it answers again.
  ``drain_replica``/``scale_down`` is the graceful inverse of scale-up:
  stop admission, let in-flight work finish (migrating stragglers), spill
  the refcount-ranked prefix pages to the store AND push them to their new
  rendezvous homes, then release the device. Every replica state write
  goes through the ``_set_replica_state`` funnel (lint-enforced single
  writer, the PR 10/13 pattern).

Everything here is host-side policy — no device programs, no new compile
ladders. The replicas' fused program sets are untouched; the tier's greedy
output is bit-identical to a single scheduler for every routing policy.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import pickle
import random
import threading
import time

import numpy as np

from seldon_core_tpu.core.errors import APIException, ErrorCode
from seldon_core_tpu.core.message import Meta, SeldonMessage
from seldon_core_tpu.engine.resilience import (
    CLOSED,
    OPEN,
    CircuitBreaker,
    current_deadline,
)
from seldon_core_tpu.graph.spec import BreakerSpec
from seldon_core_tpu.metrics import NullMetrics

log = logging.getLogger(__name__)

# default affinity block: one KV page of tokens (the pool's auto page size)
# — sharers whose common prefix spans at least one page are the ones whose
# reuse actually displaces prefill work
DEFAULT_AFFINITY_BLOCK = 16

ROUTER_POLICIES = ("affinity", "round_robin", "bandit")
FALLBACK_POLICIES = ("epsilon_greedy", "thompson")

# replica lifecycle states (the drain/eviction funnel — every write goes
# through ReplicatedDecodeScheduler._set_replica_state, single-writer by
# lint CP004):
#   up --(breaker opens)--> evicted --(half-open probe ok)--> up
#   up --(drain_replica)--> draining --(spill + close)--> down   [terminal]
REPLICA_UP = "up"
REPLICA_DRAINING = "draining"
REPLICA_EVICTED = "evicted"
REPLICA_DOWN = "down"

_REPLICA_STATE_VALUES = {
    REPLICA_UP: 0,
    REPLICA_DRAINING: 1,
    REPLICA_EVICTED: 2,
    REPLICA_DOWN: 3,
}


def replica_state_value(state: str) -> int:
    """Numeric encoding for the seldon_tpu_replica_state gauge (the
    breaker_state_value pattern)."""
    return _REPLICA_STATE_VALUES.get(state, -1)

# bounded migration retries per request: a request may survive multiple
# replica deaths, but a poisoned prompt that kills EVERY replica it lands
# on must eventually fail instead of cycling the fleet forever
MAX_MIGRATIONS = 3


# --------------------------------------------------------------------------
# prompt -> prefix-key normalization (shared by scheduler admission and the
# router; previously inlined in DecodeScheduler._admit_decide/_maybe_capture
# and only exercised through scheduler e2e paths)
def usable_prefix_len(length: int, seq_len: int) -> int:
    """The longest REUSABLE span of a prompt prefix on a ``seq_len`` prompt
    bucket: clamped to ``seq_len - 1`` because the last prompt position must
    always be computed fresh — its logits are the first generated token's
    distribution (the LCP boundary rule admission applies to every
    radix-trie match). Degenerate inputs (empty prompts, seq_len <= 1)
    normalize to 0: nothing reusable."""
    return max(0, min(int(length), int(seq_len) - 1))


def capture_prefix_len(length: int, prefix_ctx: int, seq_len: int) -> int:
    """The span a retiring/hinted slot may CAPTURE into the prefix index:
    the requested length clamped to the deployment's prefix window
    (``decode_prefix_ctx``) and the prompt bucket — only prompt positions
    are ever cached. 0 means nothing capturable."""
    return max(0, min(int(length), int(prefix_ctx), int(seq_len)))


def prefix_route_key(prompt, *, block: int = DEFAULT_AFFINITY_BLOCK, seq_len: int = 0):
    """The prompt's affinity key: its leading ``block`` tokens, as a tuple.

    Uses the SAME normalization the radix index applies on admission: when
    ``seq_len`` is given, only the usable span (``usable_prefix_len``) may
    contribute — a prompt whose usable span is shorter than one block has no
    affinity signal and returns ``()`` (the router falls back to its bandit
    arms). One block is deliberately the whole key: two groups that agree on
    their first block but diverge later also share radix-trie ancestry, so
    co-locating them is exactly what keeps the shared span warm."""
    n = len(prompt)
    usable = usable_prefix_len(n, seq_len) if seq_len > 0 else n
    if block <= 0 or usable < block:
        return ()
    return tuple(int(t) for t in prompt[:block])


def _key_rank(key: tuple, arm: int) -> int:
    """Rendezvous (highest-random-weight) score of ``arm`` for ``key`` —
    deterministic across processes/restarts (hashlib, not hash())."""
    h = hashlib.blake2b(digest_size=8)
    h.update(repr(key).encode())
    h.update(arm.to_bytes(4, "little", signed=False))
    return int.from_bytes(h.digest(), "little")


class AffinityBalancer:
    """Host-side routing policy over N replica arms.

    - ``pick(key, depths)``: rendezvous-hash ``key`` over the live arms with
      bounded-load shedding on queue depth; keyless requests ride the
      reward-driven fallback arms (epsilon-greedy or Thompson).
    - ``reward(arm, r)``: reward ingestion (r in [0, 1]) — what the
      Feedback API and the serving layer's automatic SLO sink call.

    Arm state is plain host data and picklable (persistence/state.py
    checkpoints it exactly like the EpsilonGreedyRouter's counts)."""

    def __init__(
        self,
        n_arms: int,
        *,
        policy: str = "affinity",
        fallback: str = "epsilon_greedy",
        epsilon: float = 0.1,
        load_factor: float = 1.25,
        depth_ttl_s: float | None = None,
        seed=None,
    ):
        if n_arms < 1:
            raise ValueError(f"balancer needs >= 1 arm, got {n_arms}")
        if policy not in ROUTER_POLICIES:
            raise ValueError(
                f"router policy {policy!r} unsupported (want one of "
                f"{ROUTER_POLICIES})"
            )
        if fallback not in FALLBACK_POLICIES:
            raise ValueError(
                f"fallback policy {fallback!r} unsupported (want one of "
                f"{FALLBACK_POLICIES})"
            )
        self.policy = policy
        self.fallback = fallback
        self.epsilon = float(epsilon)
        self.load_factor = float(load_factor)
        self._rng = random.Random(int(seed)) if seed is not None else random.Random()
        self.counts = [0] * n_arms
        self.rewards = [0.0] * n_arms
        # Thompson state: Beta posterior per arm (successes/failures in
        # fractional units — an SLO verdict is 0/1, a shaped reward may
        # land between)
        self.alpha = [1.0] * n_arms
        self.beta = [1.0] * n_arms
        # externally-observed queue depths (the /decode/health poll path);
        # in-process callers pass live depths to pick() instead. Each
        # observation carries a timestamp: a reading older than DEPTH_TTL_S
        # reads as 0 — a crashed poller's last spike must not shed a
        # group off its warm replica forever
        self.depths = [0] * n_arms
        self._depth_ts = [0.0] * n_arms
        # fleet ELIGIBILITY: evicted/draining/down arms stay in the arrays
        # (rendezvous ranks are positional — removing an arm would reshuffle
        # every key's home) but are skipped by every pick path
        self._eligible = [True] * n_arms
        # staleness TTL for polled depths: the router ties it to its poll
        # interval so a dead poller's last spike decays within a few missed
        # polls instead of pinning a shed for the class default
        self.depth_ttl_s = (
            float(depth_ttl_s) if depth_ttl_s is not None else self.DEPTH_TTL_S
        )
        self._rr = 0
        self._lock = threading.Lock()
        self.stat_routes = {"affinity": 0, "shed": 0, "fallback": 0, "round_robin": 0}

    @property
    def n_arms(self) -> int:
        return len(self.counts)

    def add_arm(self) -> int:
        """Grow the fleet by one arm (scale-up). Rendezvous hashing moves
        only ~1/N of the keyspace onto the new arm — existing prefix groups
        overwhelmingly keep their warm home."""
        with self._lock:
            self.counts.append(0)
            self.rewards.append(0.0)
            self.alpha.append(1.0)
            self.beta.append(1.0)
            self.depths.append(0)
            self._depth_ts.append(0.0)
            self._eligible.append(True)
            return len(self.counts) - 1

    def set_eligible(self, arm: int, ok: bool) -> None:
        """Mark one arm routable/unroutable (the replica state funnel's
        view into the balancer: only UP replicas are eligible)."""
        with self._lock:
            if 0 <= arm < len(self._eligible):
                self._eligible[arm] = bool(ok)

    def eligible_arms(self) -> list[int]:
        with self._lock:
            return [i for i, ok in enumerate(self._eligible) if ok]

    # observed depths older than this read as 0 in pick() — bounds the
    # damage of a stale spike when the health poller stops
    DEPTH_TTL_S = 30.0

    def observe_depth(self, arm: int, depth: int) -> None:
        """Ingest a polled queue depth (the /decode/health ``queue_depth``
        field) for out-of-process replicas."""
        with self._lock:
            if 0 <= arm < len(self.depths):
                self.depths[arm] = max(0, int(depth))
                self._depth_ts[arm] = time.monotonic()

    def _observed_depths(self) -> list[int]:
        """The polled depths with the staleness TTL applied (lock held)."""
        now = time.monotonic()
        return [
            d if now - t <= self.depth_ttl_s else 0
            for d, t in zip(self.depths, self._depth_ts)
        ]

    # ---------------------------------------------------------------- picks
    def pick(self, key, depths=None) -> tuple[int, str]:
        """Route one request: returns ``(arm, reason)`` with reason one of
        affinity | shed | fallback | round_robin."""
        with self._lock:
            n = len(self.counts)
            d = [
                int(x)
                for x in (depths if depths is not None else self._observed_depths())
            ]
            d += [0] * (n - len(d))
            # every pick path ranges over the ELIGIBLE arms only — an
            # evicted/draining replica is invisible to routing. A fully
            # ineligible fleet routes anyway (the submit path's migration
            # retry will surface the failure; refusing to pick would turn
            # a degraded fleet into a hard outage at the router)
            live = [i for i in range(n) if self._eligible[i]]
            if not live:
                live = list(range(n))
            if self.policy == "round_robin":
                arm = live[self._rr % len(live)]
                self._rr += 1
                self.stat_routes["round_robin"] += 1
                return arm, "round_robin"
            if self.policy == "affinity" and key:
                ranked = sorted(
                    live, key=lambda a: _key_rank(tuple(key), a), reverse=True
                )
                primary = ranked[0]
                # bounded load: the hot replica may run ahead of the fleet
                # mean by load_factor (+1 slack so tiny fleets don't shed
                # on depth 1-vs-0); past that, power-of-two-choices between
                # the top two rendezvous ranks keeps the spill warm on ONE
                # deterministic overflow replica
                bound = self.load_factor * (
                    sum(d[i] for i in live) / len(live)
                ) + 1.0
                if len(ranked) > 1 and d[primary] > bound:
                    second = ranked[1]
                    if d[second] < d[primary]:
                        # a shed is only a shed when the key MOVES — an
                        # even-deeper rank 2 keeps the request home, and
                        # counting that as displaced would overstate shed
                        # traffic in the routes metric
                        self.stat_routes["shed"] += 1
                        return second, "shed"
                self.stat_routes["affinity"] += 1
                return primary, "affinity"
            # keyless (or policy=bandit): the reward-driven fallback arms
            self.stat_routes["fallback"] += 1
            return self._fallback_pick(d, live), "fallback"

    def _fallback_pick(self, depths, live) -> int:
        if self.fallback == "thompson":
            draws = {
                i: self._rng.betavariate(self.alpha[i], self.beta[i]) for i in live
            }
            return int(max(live, key=draws.__getitem__))
        if self._rng.random() < self.epsilon:
            return live[self._rng.randrange(len(live))]
        means = {
            i: self.rewards[i] / self.counts[i] if self.counts[i] else float("inf")
            for i in live
        }
        best = max(means.values())
        # estimate ties break by LIVE load, then index: before any reward
        # lands every arm ties at +inf, and without this the exploit
        # branch would herd ~1-epsilon of keyless traffic onto arm 0
        # while the rest of the fleet idles
        tied = [i for i in live if means[i] == best]
        return int(min(tied, key=lambda i: (depths[i], i)))

    # -------------------------------------------------------------- rewards
    def reward(self, arm: int, r: float) -> None:
        """Reward ingestion for one served request (clamped to [0, 1]) —
        moves BOTH estimators so a live policy flip needs no re-learning."""
        if not (0 <= int(arm) < len(self.counts)):
            return
        r = min(1.0, max(0.0, float(r)))
        with self._lock:
            self.counts[arm] += 1
            self.rewards[arm] += r
            self.alpha[arm] += r
            self.beta[arm] += 1.0 - r

    def arm_estimate(self, arm: int) -> float:
        c = self.counts[arm]
        return self.rewards[arm] / c if c else 0.0

    # persistence hooks (persistence/state.py contract)
    def __getstate__(self):
        d = self.__dict__.copy()
        d.pop("_lock", None)
        return d

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()
        # checkpoints from before the fault-tolerance fields existed
        self.__dict__.setdefault("_eligible", [True] * len(self.counts))
        self.__dict__.setdefault("depth_ttl_s", self.DEPTH_TTL_S)


# --------------------------------------------------------------------------
# prefix-page spill / preseed (warm scale-up through persistence/state.py)
SPILL_UNIT = "prefix_pages"  # state_key unit id the spill payload rides


def spill_key(deployment_id: str) -> str:
    from seldon_core_tpu.persistence.state import state_key

    return state_key(deployment_id or "decode", SPILL_UNIT)


def spill_to_store(sched, store, deployment_id: str, top_n: int = 0) -> int:
    """Export ``sched``'s hottest prefix entries into a persistence store
    (FileStateStore/RedisStateStore). Returns entries spilled."""
    payload = sched.export_prefix_state(top_n=top_n)
    if payload is None or not payload["entries"]:
        return 0
    store.save(spill_key(deployment_id), pickle.dumps(payload))
    return len(payload["entries"])


def preseed_from_store(sched, store, deployment_id: str) -> int:
    """Pre-seed ``sched``'s page pool from a spilled payload; returns the
    entries seeded (0 when the store holds nothing or nothing fits)."""
    raw = store.load(spill_key(deployment_id))
    if raw is None:
        return 0
    try:
        payload = pickle.loads(raw)
    except Exception:  # noqa: BLE001 - stale/corrupt spill must not fail boot
        log.warning("corrupt prefix spill for %r ignored", deployment_id)
        return 0
    return sched.preseed_prefix_state(payload)


def preseed_enabled() -> bool:
    """ENGINE_DECODE_REPLICA_PRESEED kill switch (default on): "off"
    disables warm pre-seeding at scale-up/boot — cold boots only."""
    import os

    from seldon_core_tpu.utils.env import ENGINE_DECODE_REPLICA_PRESEED

    return os.environ.get(ENGINE_DECODE_REPLICA_PRESEED, "on").lower() not in (
        "off",
        "0",
        "false",
    )


# --------------------------------------------------------------------------
class _TrackedRequest:
    """Router-side record of one in-flight generation: the emitted tokens
    (recorded through the on_token shim — only FRESH tokens arrive there,
    replayed ones are suppressed scheduler-side), the serving arm, and the
    replica-submit task the migration path cancels. This is what makes a
    generation recoverable: on eviction the record resubmits elsewhere
    with ``tokens`` as the teacher-forced replay."""

    __slots__ = ("prompt", "tokens", "caller_on_token", "arm", "task", "migrating")

    def __init__(self, prompt, caller_on_token):
        self.prompt = prompt
        self.tokens: list[int] = []
        self.caller_on_token = caller_on_token
        self.arm = -1
        self.task: asyncio.Task | None = None
        self.migrating = False

    def on_token(self, tok: int, idx: int) -> None:
        # global stream index contract: the scheduler emits idx ==
        # len(seq.tokens) - 1 with replayed positions pre-appended, so a
        # resumed request's first fresh token arrives at exactly
        # len(self.tokens) — append keeps the record aligned with the
        # client-visible stream across any number of migrations
        self.tokens.append(int(tok))
        if self.caller_on_token is not None:
            self.caller_on_token(tok, idx)


class ReplicatedDecodeScheduler:
    """N decode-scheduler replicas behind the affinity balancer, presenting
    the single scheduler's serving surface (``submit`` /
    ``execute_message`` / ``warmup`` / ``close`` / stats) so the batcher,
    the streaming ingress, and the bench drive it unchanged.

    Each replica owns its full device state (params copy, page pool, prefix
    index, draft cache) on its own device — ``factory(i)`` places replica i
    on ``jax.devices()[i % n_devices]`` — so N replicas are N independent
    dispatch streams: the in-process twin of N decode pods, and the real
    thing on a multi-chip host. Greedy output is bit-identical to a single
    scheduler under EVERY routing policy (each replica is the proven
    scheduler; routing only decides which warm pool serves a request).

    Autoscale: when ``autoscale_replicas`` caps a larger fleet, a sustained
    mean queue depth >= ``autoscale_queue_depth`` (the same signal
    ``/decode/health`` exports) boots one more replica in the background —
    pre-seeded from the hottest replica's spilled prefix pages so it serves
    shared prompts warm from its first request."""

    # a scale-up needs BOTH: this many hot observations AND the queue
    # held hot for this long — the observation count alone would let one
    # millisecond-scale burst (several submits arriving together) boot an
    # expensive replica that the burst never needed
    AUTOSCALE_STREAK = 3
    AUTOSCALE_HOLD_S = 0.5

    def __init__(
        self,
        factory,
        n_replicas: int,
        *,
        policy: str = "",
        fallback: str = "epsilon_greedy",
        epsilon: float = 0.1,
        load_factor: float = 1.25,
        affinity_block: int = DEFAULT_AFFINITY_BLOCK,
        autoscale_replicas: int = 0,
        autoscale_queue_depth: int = 0,
        spill_store=None,
        spill_store_factory=None,
        health_poll_ms: float = 0.0,
        health_miss_threshold: int = 3,
        drain_timeout_ms: float = 5000.0,
        metrics: NullMetrics | None = None,
        deployment_name: str = "",
        seed: int = 0,
    ):
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        self.factory = factory
        self.policy = policy or "affinity"
        self.replicas = [self._attach(factory(i)) for i in range(n_replicas)]
        self.affinity_block = int(affinity_block) or DEFAULT_AFFINITY_BLOCK
        self.autoscale_replicas = int(autoscale_replicas)
        self.autoscale_queue_depth = int(autoscale_queue_depth)
        self.spill_store = spill_store
        # resolved on the FIRST spill, not at build: a file store's ctor
        # mkdirs its directory, and most fleets never scale up
        self._spill_store_factory = spill_store_factory
        self._metrics = metrics or NullMetrics()
        self._deployment = deployment_name
        # health poll / eviction / drain knobs (tpu.decode_health_poll_ms,
        # decode_health_miss_threshold, decode_drain_timeout_ms)
        self.health_poll_s = max(0.0, float(health_poll_ms)) / 1e3
        self.health_miss_threshold = max(1, int(health_miss_threshold))
        self.drain_timeout_s = max(0.0, float(drain_timeout_ms)) / 1e3
        self.balancer = AffinityBalancer(
            n_replicas,
            policy=self.policy,
            fallback=fallback,
            epsilon=epsilon,
            load_factor=load_factor,
            # tie the stale-depth TTL to the poll cadence when polling is
            # on: a dead poller's spike decays after ~3 missed polls
            # instead of the 30s class default
            depth_ttl_s=(3.0 * self.health_poll_s if self.health_poll_s > 0 else None),
            seed=seed,
        )
        self._hot_streak = 0
        self._hot_since: float | None = None
        self._scaling = False
        self._scale_task: asyncio.Task | None = None
        self._closed = False
        # replica lifecycle state, indexed like self.replicas. ALL writes
        # go through _set_replica_state (lint CP004 single-writer) — the
        # funnel owns the balancer eligibility flip, the lifecycle
        # counters/metrics, and the flight-recorder health fields, so no
        # transition can half-apply.
        self._replica_states = [REPLICA_UP] * n_replicas
        # per-replica health breakers (engine/resilience.py): threshold
        # consecutive probe misses open the breaker (-> eviction); after
        # reset it half-opens and ONE successful probe readmits. reset is
        # one poll interval so the first post-eviction poll already probes.
        self._breakers = [self._new_breaker(i) for i in range(n_replicas)]
        self._misses = [0] * n_replicas
        self._last_ticks = [-1] * n_replicas
        self._inflight: list[set[_TrackedRequest]] = [set() for _ in range(n_replicas)]
        self._poll_task: asyncio.Task | None = None
        self.stat_scale_ups = 0
        self.stat_preseeded_entries = 0
        self.stat_evictions = 0
        self.stat_recoveries = 0
        self.stat_drains = 0
        self.stat_migrations = 0
        self.stat_boot_failures = 0
        self.stat_spill_failures = 0
        self.stat_health_misses = 0
        # sibling prefix pulls (tiered-KV fleet economy): in-flight
        # transfer per (target arm, route key) so a thundering herd of
        # same-prefix requests issues ONE pull; herd members await it
        self._pulls: dict[tuple, asyncio.Task] = {}
        self.stat_sibling_pulls = 0
        self.stat_sibling_pull_failures = 0
        self._metrics.router_replicas(self._deployment, len(self.replicas))

    def _new_breaker(self, arm: int) -> CircuitBreaker:
        spec = BreakerSpec(
            failure_threshold=self.health_miss_threshold,
            error_rate=1.0,
            window=self.health_miss_threshold,
            reset_ms=max(self.health_poll_s * 1e3, 1.0),
            half_open_probes=1,
        )
        return CircuitBreaker(
            spec, on_transition=lambda state, a=arm: self._on_breaker(a, state)
        )

    def _attach(self, replica):
        """Fleet wiring for one replica: dispatches hop OFF the event loop
        onto a dedicated single-thread executor (one dispatch stream per
        replica — N replicas' device work genuinely overlaps; XLA releases
        the GIL during execution) even on the CPU backend, where a lone
        scheduler would dispatch inline."""
        from concurrent.futures import ThreadPoolExecutor

        replica._offload_dispatch = True
        replica._dispatch_pool = ThreadPoolExecutor(
            max_workers=1,
            thread_name_prefix=f"decode-r{getattr(replica, 'replica_id', 0)}",
        )
        return replica

    # ------------------------------------------------------------ delegates
    @property
    def live_replicas(self):
        """(arm, replica) pairs that still exist — drained replicas leave a
        None TOMBSTONE in self.replicas (removing the entry would renumber
        every surviving arm and reshuffle rendezvous homes)."""
        return [(i, r) for i, r in enumerate(self.replicas) if r is not None]

    @property
    def _r0(self):
        for r in self.replicas:
            if r is not None:
                return r
        raise RuntimeError("decode fleet has no live replicas")

    @property
    def seq_len(self) -> int:
        return self._r0.seq_len

    @property
    def max_new_tokens(self) -> int:
        return self._r0.max_new_tokens

    @property
    def eos_id(self) -> int:
        return self._r0.eos_id

    @property
    def slo_ttft_s(self) -> float:
        return self._r0.slo_ttft_s

    @property
    def slo_itl_s(self) -> float:
        return self._r0.slo_itl_s

    @property
    def active(self) -> int:
        return sum(r.active for _, r in self.live_replicas)

    @property
    def queue_depth(self) -> int:
        return sum(r.queue_depth for _, r in self.live_replicas)

    @property
    def prefix_enabled(self) -> bool:
        return self._r0.prefix_enabled

    # aggregated attribution (bench/soak read these off the single
    # scheduler today; the replicated tier sums)
    @property
    def stat_prefix_hits(self) -> int:
        return sum(r.stat_prefix_hits for _, r in self.live_replicas)

    @property
    def stat_prefix_misses(self) -> int:
        return sum(r.stat_prefix_misses for _, r in self.live_replicas)

    @property
    def stat_prefix_tokens_saved(self) -> int:
        return sum(r.stat_prefix_tokens_saved for _, r in self.live_replicas)

    @property
    def stat_tokens(self) -> int:
        return sum(r.stat_tokens for _, r in self.live_replicas)

    @property
    def stat_chunk_dispatches(self) -> int:
        return sum(r.stat_chunk_dispatches for _, r in self.live_replicas)

    def __getattr__(self, name: str):
        # any scheduler attribution counter not explicitly aggregated
        # above sums across the fleet (soak/bench read stat_* freely)
        if name.startswith("stat_"):
            return sum(
                getattr(r, name) for r in self.__dict__["replicas"] if r is not None
            )
        raise AttributeError(name)

    def request_params_from_meta(self, meta: Meta) -> dict:
        return self._r0.request_params_from_meta(meta)

    def warmup(self) -> None:
        for _, r in self.live_replicas:
            r.warmup()
        # the fused program set is module-level, so sibling replicas share
        # each function's underlying jit cache: replica N's warmup entries
        # (distinct device placements = distinct signatures) would read as
        # phantom "recompiles" against replica 0's earlier baseline.
        # Re-snapshot every replica once the WHOLE fleet is warm.
        for _, r in self.live_replicas:
            r._warmup_compile_counts = r.compile_counts()

    def compile_counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for i, r in self.live_replicas:
            for k, v in r.compile_counts().items():
                out[f"r{i}.{k}"] = v
        return out

    def recompiles_since_warmup(self) -> int:
        return sum(r.recompiles_since_warmup() for _, r in self.live_replicas)

    async def close(self) -> None:
        self._closed = True
        poll = self._poll_task
        if poll is not None:
            poll.cancel()
            try:
                await poll
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._poll_task = None
        task = self._scale_task
        if task is not None:
            # let an in-flight scale-up settle: cancelling mid-warmup
            # would leak a half-built replica's device state
            try:
                await task
            except Exception:  # noqa: BLE001 - logged by the task itself
                pass
        # evicted replicas ABORT (a hung loop never drains close()'s way);
        # healthy ones drain normally
        await asyncio.gather(
            *(
                (r.abort() if self._replica_states[i] == REPLICA_EVICTED else r.close())
                for i, r in self.live_replicas
            )
        )
        for _, r in self.live_replicas:
            pool = getattr(r, "_dispatch_pool", None)
            if pool is not None:
                pool.shutdown(wait=False)

    # -------------------------------------------------------------- routing
    def _live_depths(self) -> list[int]:
        # queue depth + active slots: a replica with free slots beats one
        # that is merely not-queueing (both O(1) reads). Tombstones read 0
        # — they are ineligible, so the value never routes anything; it
        # only keeps the list positionally aligned with the arms.
        return [
            0 if r is None else r.queue_depth + r.active for r in self.replicas
        ]

    def route(self, prompt) -> tuple[int, str]:
        """Pick the serving replica for one prompt (token ids)."""
        key = prefix_route_key(
            prompt, block=self.affinity_block, seq_len=self.seq_len
        )
        arm, reason = self.balancer.pick(key, self._live_depths())
        self._metrics.router_route(self._deployment, self.policy, reason)
        return arm, reason

    def _reward_sink(self, arm: int, inner):
        """Per-request reward closure for the STREAMING path (no buffered
        response tags to ride the Feedback API): the scheduler's SLO
        verdict rewards the serving arm directly. Buffered requests carry
        ``meta.tags.replica`` instead and reward through
        ``ingest_feedback`` — one reward per request either way."""

        def sink(ok: bool) -> None:
            self._reward_arm(arm, 1.0 if ok else 0.0)
            if inner is not None:
                inner(ok)

        return sink

    def _reward_arm(self, arm: int, r: float) -> None:
        self.balancer.reward(arm, r)
        self._metrics.router_arm(
            self._deployment,
            arm,
            self.balancer.arm_estimate(arm),
        )

    async def submit(self, prompt, *, _slo_sink=None, **kw):
        """Route one sequence to its replica and submit (the streaming
        ingress path — per-row SLO verdicts reward the serving arm
        directly, since a streamed response never rides the Feedback
        API). The request is TRACKED: if its replica is evicted or its
        loop crashes mid-generation, it resubmits on a surviving replica
        with the already-streamed tokens as a teacher-forced replay —
        the caller (and its SSE stream) never sees the failure."""
        self._ensure_poller()
        self._autoscale_tick()
        out, _arm = await self._submit_routed(prompt, kw, _slo_sink, reward=True)
        return out

    async def _submit_routed(
        self, prompt, kw: dict, slo_sink, *, reward: bool
    ) -> tuple[np.ndarray, int]:
        """The tracked submit/migrate loop every request rides. Returns
        (result, serving_arm). ``reward`` wires the streaming path's
        direct SLO->arm reward sink; the buffered path rewards through
        meta.tags.replica + ingest_feedback instead (one reward per
        request either way)."""
        rec = _TrackedRequest(prompt, kw.pop("on_token", None))
        migrations = 0
        while True:
            arm, _reason = self.route(prompt)
            sink = slo_sink
            if reward and (self.slo_ttft_s > 0 or self.slo_itl_s > 0):
                sink = self._reward_sink(arm, slo_sink)
            replica = self.replicas[arm]
            if replica is None:
                # the balancer routed into a tombstone (whole fleet
                # ineligible) — nothing can serve this
                raise APIException(
                    ErrorCode.ENGINE_MICROSERVICE_ERROR,
                    "decode fleet has no serving replicas",
                )
            if not rec.tokens:
                # tiered-KV sibling pull: before this request prefills
                # cold, ask the key's rendezvous home for the prefix
                # entry (any of ITS tiers) — bounded, deduped, and
                # degrade-on-failure inside; resumed legs skip it (their
                # replay teacher-forces the whole context anyway)
                await self._maybe_sibling_pull(prompt, arm)
            kw2 = dict(kw)
            if rec.tokens:
                # resumed leg: teacher-force the already-streamed tokens
                # and ride PLAIN rounds — replayed positions must write
                # the replayed tokens' K/V, and only the plain step's
                # cache write is conditioned on the effective input (a
                # speculative round writes its PROPOSED tokens). Greedy
                # spec output is bit-identical to plain, so the opt-out
                # changes nothing downstream.
                kw2["_replay_tokens"] = list(rec.tokens)
                kw2["spec_k"] = 0
                kw2["spec_tree"] = "0"
            rec.arm = arm
            rec.migrating = False
            self._inflight[arm].add(rec)
            rec.task = asyncio.ensure_future(
                replica.submit(prompt, on_token=rec.on_token, _slo_sink=sink, **kw2)
            )
            try:
                out = await rec.task
                return out, arm
            except asyncio.CancelledError:
                if rec.migrating and not self._closed:
                    # eviction/drain cancelled the replica-side future:
                    # re-route (the dead arm is already ineligible) and
                    # resume from the last streamed token
                    migrations += 1
                    if migrations > MAX_MIGRATIONS:
                        raise APIException(
                            ErrorCode.ENGINE_MICROSERVICE_ERROR,
                            f"generation migrated {migrations - 1} times "
                            "without completing — giving up",
                        )
                    continue
                # genuine caller cancellation (client vanished): make sure
                # the replica-side future is cancelled too, then propagate
                rec.task.cancel()
                raise
            except APIException as e:
                if (
                    e.error is ErrorCode.ENGINE_MICROSERVICE_ERROR
                    and not self._closed
                    and migrations < MAX_MIGRATIONS
                    and self._note_replica_failure(arm, str(e))
                ):
                    # the replica LOOP died under this request (induced
                    # OOM, wedged dispatch): evict it and migrate
                    migrations += 1
                    continue
                raise
            finally:
                self._inflight[arm].discard(rec)

    async def _maybe_sibling_pull(self, prompt, arm: int) -> None:
        """Fleet-wide prefix economy: when ``arm`` holds the prompt's
        leading block in NONE of its local tiers (device index, host
        pool, store index), ask the key's rendezvous home — the replica
        affinity routing WOULD have sent this prefix to, so the likeliest
        holder — for the entry before recomputing it cold. Per-(arm, key)
        in-flight dedup: a thundering herd of same-prefix requests issues
        one transfer and everyone awaits it. Every failure path degrades
        to cold prefill; rides the ENGINE_DECODE_REPLICA_PRESEED kill
        switch (it IS a preseed, request-time instead of boot-time)."""
        if not preseed_enabled() or len(self.live_replicas) <= 1:
            return
        target = self.replicas[arm]
        if target is None or not getattr(target, "prefix_enabled", False):
            return
        probe = getattr(target, "prefix_probe_depth", None)
        if probe is None:
            return
        key = prefix_route_key(
            prompt, block=self.affinity_block, seq_len=self.seq_len
        )
        if not key:
            return  # keyless prompt (shorter than the affinity block)
        try:
            if probe(prompt) >= self.affinity_block:
                return  # some local tier is already warm for the block
        except Exception:  # noqa: BLE001 - a probe bug must not block serving
            return
        kt = (arm,) + tuple(key)
        task = self._pulls.get(kt)
        if task is None:
            survivors = [
                i
                for i, _ in self.live_replicas
                if i != arm and self._replica_states[i] == REPLICA_UP
            ]
            if not survivors:
                return
            home = max(survivors, key=lambda a: _key_rank(tuple(key), a))
            task = asyncio.ensure_future(
                self._pull_entry(home, arm, np.asarray(prompt, np.int32))
            )
            self._pulls[kt] = task
            task.add_done_callback(lambda _t, kt=kt: self._pulls.pop(kt, None))
        await task

    async def _pull_entry(self, home_arm: int, target_arm: int, prompt) -> None:
        """One sibling transfer: export the deepest covering entry from
        the home's tiers (single-entry ``export_prefix_state`` payload)
        and preseed it into the target's pool. Never raises — a failed
        pull costs exactly what not pulling costs (a cold prefill)."""
        try:
            home = self.replicas[home_arm]
            target = self.replicas[target_arm]
            if home is None or target is None:
                return
            payload = home.export_prefix_entry(prompt)
            if not payload:
                self._metrics.decode_kv_sibling_pull(self._deployment, "miss")
                return
            if target.preseed_prefix_state(payload):
                self.stat_sibling_pulls += 1
                self._metrics.decode_kv_sibling_pull(self._deployment, "hit")
            else:
                # geometry mismatch / pool pressure / covered in the race
                # window — the preseed declined, which is fine
                self._metrics.decode_kv_sibling_pull(self._deployment, "miss")
        except Exception:  # noqa: BLE001 - pull failure degrades to cold prefill
            self.stat_sibling_pull_failures += 1
            self._metrics.decode_kv_sibling_pull(self._deployment, "error")
            log.warning(
                "sibling prefix pull %s -> %s failed — cold prefill instead",
                home_arm, target_arm, exc_info=True,
            )

    async def execute_message(self, msg: SeldonMessage) -> SeldonMessage:
        """Buffered serving entry: every row routes independently (rows of
        one request sharing a prefix land on the same warm replica; mixed
        rows spread), each rides the TRACKED submit path (so buffered
        requests survive replica death exactly like streams), and the
        merged response mirrors the single scheduler's contract — plus
        ``meta.tags.replica`` (per-row serving replica) so the Feedback
        API can route rewards back to the arms."""
        arr = msg.array
        if arr is None:
            raise APIException(
                ErrorCode.ENGINE_INVALID_JSON,
                "generative predictor needs tensor token ids",
            )
        self._ensure_poller()
        self._autoscale_tick()
        rows = np.atleast_2d(np.asarray(arr)).astype(np.int32)
        overrides = self.request_params_from_meta(msg.meta)
        r0 = self._r0
        track_slo = bool(self.slo_ttft_s or self.slo_itl_s) or (
            current_deadline() is not None
        )
        slo_flags: list[bool] = [True] * len(rows)
        picks: list[int] = [0] * len(rows)

        async def one(i: int) -> np.ndarray:
            sink = (
                (lambda ok, i=i: slo_flags.__setitem__(i, ok))
                if track_slo
                else None
            )
            out, arm = await self._submit_routed(
                rows[i], dict(overrides), sink, reward=False
            )
            picks[i] = arm
            return out

        # settle EVERY row before failing the request (the single
        # scheduler's gather contract)
        outs = await asyncio.gather(
            *(one(i) for i in range(len(rows))), return_exceptions=True
        )
        for o in outs:
            if isinstance(o, BaseException):
                raise o
        max_new = overrides.get("max_new_tokens", r0.max_new_tokens)
        max_new = max(1, min(int(max_new), r0.max_new_tokens))
        width = rows.shape[1] + max_new
        pad_id = self.eos_id if self.eos_id >= 0 else 0
        full = np.full((len(outs), width), pad_id, np.int32)
        gen_lens: list[int] = []
        for i, o in enumerate(outs):
            full[i, : len(o)] = o
            gen_lens.append(int(len(o) - rows.shape[1]))
        tags = {**msg.meta.tags, "replica": picks, "gen_lens": gen_lens}
        if track_slo:
            tags["slo"] = ["met" if ok else "breached" for ok in slo_flags]
        meta = Meta(
            puid=msg.meta.puid,
            tags=tags,
            routing=dict(msg.meta.routing),
            request_path=dict(msg.meta.request_path),
        )
        return msg.with_array_meta(full, meta)

    # ----------------------------------------------------------- feedback
    def ingest_feedback(self, feedback, *, use_slo: bool = False) -> int:
        """Feedback-API reward ingestion: the response's per-row
        ``meta.tags.replica`` names the serving arms; ``feedback.reward``
        moves their estimates. ``use_slo=True`` (the serving layer's
        AUTOMATIC sink only) rewards each row from the response's own SLO
        verdict instead — a client's explicit reward is always honored
        verbatim, including an explicit 0.0 down-vote. Returns arms
        updated; rows naming an unknown replica (forged tags, or a
        response predating a fleet resize) are skipped, never an
        error."""
        resp = feedback.response
        if resp is None:
            return 0
        arms = resp.meta.tags.get("replica")
        if not isinstance(arms, (list, tuple)) or not arms:
            return 0
        slo = resp.meta.tags.get("slo")
        updated = 0
        for i, arm in enumerate(arms):
            try:
                arm = int(arm)
            except (TypeError, ValueError):
                continue
            if not (0 <= arm < len(self.replicas)):
                continue
            r = float(feedback.reward)
            if use_slo and isinstance(slo, (list, tuple)) and i < len(slo):
                r = 1.0 if slo[i] == "met" else 0.0
            self._reward_arm(arm, r)
            updated += 1
        return updated

    # ----------------------------------------------- health poll / eviction
    def replica_states(self) -> list[str]:
        """Lifecycle state per arm (positional, tombstones included)."""
        return list(self._replica_states)

    def _set_replica_state(self, arm: int, state: str, reason: str = "") -> None:
        """THE replica lifecycle transition funnel — the only writer of
        ``_replica_states`` (lint CP004, the _commit_round/_pending*
        pattern). Owns everything a transition implies: the balancer
        eligibility flip, the lifecycle counters + prometheus metrics, and
        the replica's flight-recorder health fields, so no consumer can
        observe a half-applied transition."""
        while len(self._replica_states) <= arm:
            self._replica_states.append(REPLICA_UP)
        prev = self._replica_states[arm]
        if prev == state:
            return
        self._replica_states[arm] = state
        self.balancer.set_eligible(arm, state == REPLICA_UP)
        r = self.replicas[arm] if arm < len(self.replicas) else None
        if r is not None:
            r.flight.replica_state = state
        self._metrics.replica_state(self._deployment, arm, state)
        if state == REPLICA_EVICTED:
            self.stat_evictions += 1
            self._metrics.replica_eviction(self._deployment)
        elif state == REPLICA_UP and prev == REPLICA_EVICTED:
            self.stat_recoveries += 1
            self._metrics.replica_recovery(self._deployment)
        elif state == REPLICA_DOWN:
            self.stat_drains += 1
            self._metrics.replica_drain(self._deployment)
        log.info(
            "decode replica %s: %s -> %s%s",
            arm, prev, state, f" ({reason})" if reason else "",
        )

    def _ensure_poller(self) -> None:
        """Start the health poll task lazily (it needs a running loop —
        the router is built before serving starts). Idempotent, called
        from the request paths."""
        if self.health_poll_s <= 0 or self._closed:
            return
        t = self._poll_task
        if t is not None and not t.done():
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return
        self._poll_task = loop.create_task(self._health_poll_loop())

    async def _health_poll_loop(self) -> None:
        while not self._closed:
            await asyncio.sleep(self.health_poll_s)
            try:
                self.poll_fleet_once()
            except Exception:  # noqa: BLE001 - the poller must outlive one bad poll
                log.exception("decode health poll failed")

    def poll_fleet_once(self) -> None:
        """One poll sweep over the fleet (public so soak/tests can drive
        the state machine synchronously): probe every live replica, feed
        its queue depth to the balancer, count consecutive misses into its
        breaker. A probe that ANSWERS but shows active slots with no tick
        progress since the last poll is a miss too — a hung dispatch
        answers host-side probes while serving nothing. Evicted replicas
        get the breaker's half-open probe and readmit on success."""
        for arm, r in self.live_replicas:
            state = self._replica_states[arm]
            if state in (REPLICA_DOWN, REPLICA_DRAINING):
                continue
            br = self._breakers[arm]
            if state == REPLICA_EVICTED:
                if not br.allow():
                    # still inside the open window — no probe this poll
                    continue
                if self._probe_ok(arm, r):
                    br.record_success()  # -> closed -> readmit (funnel)
                    self._misses[arm] = 0
                else:
                    br.record_failure()  # half-open fail -> re-open
                continue
            if self._probe_ok(arm, r):
                self._misses[arm] = 0
                br.record_success()
            else:
                self._misses[arm] += 1
                self.stat_health_misses += 1
                br.record_failure()  # threshold misses -> open -> evict
            r.flight.consecutive_misses = self._misses[arm]

    def _probe_ok(self, arm: int, r) -> bool:
        try:
            h = r.health_probe()
        except Exception:  # noqa: BLE001 - any probe failure is a miss
            self._last_ticks[arm] = -1
            return False
        ticks = int(h.get("ticks", 0))
        stuck = int(h.get("active", 0)) > 0 and ticks == self._last_ticks[arm]
        self._last_ticks[arm] = ticks
        if stuck:
            return False
        self.balancer.observe_depth(arm, int(h.get("queue_depth", 0)))
        return True

    def _on_breaker(self, arm: int, state: str) -> None:
        """Breaker transition hook: every transition ticks the existing
        breaker metrics (one endpoint per replica, so dashboards see the
        open/half-open/closed funnel per arm), and open/closed drive the
        replica lifecycle."""
        self._metrics.breaker(self._deployment, f"decode-replica-{arm}", state)
        if state == OPEN and self._replica_states[arm] == REPLICA_UP:
            self._set_replica_state(arm, REPLICA_EVICTED, "health breaker open")
            self._migrate_inflight(arm, "eviction")
        elif state == CLOSED and self._replica_states[arm] == REPLICA_EVICTED:
            self._set_replica_state(arm, REPLICA_UP, "half-open probe recovered")

    def _note_replica_failure(self, arm: int, reason: str) -> bool:
        """A request-path replica failure (loop crash fails every slot
        future with ENGINE_MICROSERVICE_ERROR): force the breaker open so
        eviction AND readmission ride the same funnel the poller uses.
        Returns True when the replica is out of rotation (the caller may
        migrate); False when it was already evicted/draining or there is
        nowhere left to migrate to."""
        if self._replica_states[arm] == REPLICA_UP:
            br = self._breakers[arm]
            while br.state != OPEN:
                br.record_failure()
            log.warning("decode replica %s failed in-request: %s", arm, reason)
        others = [
            i
            for i, _ in self.live_replicas
            if i != arm and self._replica_states[i] == REPLICA_UP
        ]
        return bool(others)

    def _migrate_inflight(self, arm: int, reason: str) -> int:
        """Kick every tracked request off ``arm``: flag it migrating and
        cancel its replica-side future (the scheduler retires cancelled
        slots and frees their pages on its next round — or at abort() for
        a hung loop). The tracked submit loop catches the cancellation,
        re-routes, and resumes from the last streamed token."""
        recs = [rec for rec in self._inflight[arm] if rec.task is not None]
        for rec in recs:
            rec.migrating = True
            if not rec.task.done():
                rec.task.cancel()
        if recs:
            self.stat_migrations += len(recs)
            self._metrics.replica_migration(self._deployment, len(recs))
            log.info(
                "decode replica %s: migrating %d in-flight generation(s) (%s)",
                arm, len(recs), reason,
            )
        return len(recs)

    # ------------------------------------------------------ drain/scale-down
    async def drain_replica(self, arm: int, *, timeout_s: float | None = None) -> dict:
        """Graceful scale-DOWN of one replica — the inverse of warm
        scale-up. Stops admission (draining arms are ineligible), waits up
        to the drain timeout for in-flight work to finish, migrates any
        stragglers, spills the refcount-ranked prefix pages to the store
        AND pushes each entry to its new rendezvous home among the
        survivors, then closes the replica and tombstones its slot.
        Terminal: a drained arm never serves again (scale-up appends a
        fresh arm instead — rendezvous positions are forever)."""
        if not (0 <= arm < len(self.replicas)) or self.replicas[arm] is None:
            raise ValueError(f"replica {arm} does not exist")
        if self._replica_states[arm] != REPLICA_UP:
            raise ValueError(
                f"replica {arm} is not serving (state: {self._replica_states[arm]})"
            )
        survivors = [
            i
            for i, _ in self.live_replicas
            if i != arm and self._replica_states[i] == REPLICA_UP
        ]
        if not survivors:
            raise ValueError("cannot drain the last serving replica")
        r = self.replicas[arm]
        self._set_replica_state(arm, REPLICA_DRAINING, "drain requested")
        budget = self.drain_timeout_s if timeout_s is None else max(0.0, timeout_s)
        deadline = time.monotonic() + budget
        while (
            (r.active or r.queue_depth or self._inflight[arm])
            and time.monotonic() < deadline
        ):
            await asyncio.sleep(0.01)
        migrated = 0
        if self._inflight[arm]:
            migrated = self._migrate_inflight(arm, "drain timeout")
            # the migrating requests leave this arm's tracking set as soon
            # as their cancellations land — bounded wait, then proceed to
            # close (their replica-side futures are already cancelled)
            waited = 0.0
            while self._inflight[arm] and waited < 1.0:
                await asyncio.sleep(0.005)
                waited += 0.005
        spilled = await self._spill_replica_state(arm, r)
        await r.close()
        pool = getattr(r, "_dispatch_pool", None)
        if pool is not None:
            pool.shutdown(wait=False)
        self.replicas[arm] = None
        self._set_replica_state(arm, REPLICA_DOWN, "drained")
        self._metrics.router_replicas(self._deployment, len(self.live_replicas))
        log.info(
            "decode replica %s drained: %d generation(s) migrated, "
            "%d prefix entr(ies) pushed to siblings",
            arm, migrated, spilled,
        )
        return {"replica": arm, "migrated": migrated, "spilled_entries": spilled}

    async def scale_down(self) -> dict:
        """Drain the COLDEST serving replica: fewest prefix hits, then
        lightest live load, then the highest arm id (prefer releasing the
        newest device)."""
        candidates = [
            i for i, _ in self.live_replicas if self._replica_states[i] == REPLICA_UP
        ]
        if len(candidates) <= 1:
            raise ValueError("cannot scale down a single-replica fleet")
        arm = min(
            candidates,
            key=lambda i: (
                self.replicas[i].stat_prefix_hits,
                self.replicas[i].queue_depth + self.replicas[i].active,
                -i,
            ),
        )
        return await self.drain_replica(arm)

    async def _spill_replica_state(self, arm: int, r) -> int:
        """Drain-side prefix handoff: export the draining replica's
        refcount-ranked pages (quiescence-retry like the scale-up spill),
        round-trip through the persistence store (so an operator restart
        boots from it), and PUSH each entry to the surviving arm that
        rendezvous-owns its key — the sibling serves the group warm on its
        next request instead of waiting for a pull."""
        if not (preseed_enabled() and self.prefix_enabled):
            return 0
        payload = None
        for _ in range(500):
            try:
                payload = r.export_prefix_state()
                break
            except RuntimeError:
                await asyncio.sleep(0.005)
        if not payload or not payload["entries"]:
            return 0
        if self.spill_store is None and self._spill_store_factory is not None:
            try:
                self.spill_store = self._spill_store_factory()
            except Exception:  # noqa: BLE001 - a broken store must not fail the drain
                log.exception("replica spill store unusable — sibling push only")
            self._spill_store_factory = None
        if self.spill_store is not None:
            try:
                self.spill_store.save(
                    spill_key(self._deployment), pickle.dumps(payload)
                )
            except Exception:  # noqa: BLE001 - degraded, not fatal — and COUNTED
                self.stat_spill_failures += 1
                self._metrics.replica_spill_failure(self._deployment)
                log.exception(
                    "drain spill store save failed — sibling push continues"
                )
        survivors = [
            i
            for i, _ in self.live_replicas
            if i != arm and self._replica_states[i] == REPLICA_UP
        ]
        if not survivors:
            return 0
        targets: dict[int, list] = {}
        seq_len = self.seq_len
        for e in payload["entries"]:
            key = prefix_route_key(
                e["tokens"], block=self.affinity_block, seq_len=seq_len
            )
            if key:
                home = max(survivors, key=lambda a: _key_rank(tuple(key), a))
            else:
                # keyless span (shorter than one block): park it on the
                # least-loaded survivor
                home = min(
                    survivors, key=lambda a: self.replicas[a].queue_depth
                )
            targets.setdefault(home, []).append(e)
        seeded = 0
        for home, entries in targets.items():
            sub = {
                "page_size": payload["page_size"],
                "kv_dtype": payload["kv_dtype"],
                "entries": entries,
            }
            try:
                seeded += self.replicas[home].preseed_prefix_state(sub)
            except Exception:  # noqa: BLE001 - a full sibling pool degrades, not fails
                self.stat_spill_failures += 1
                self._metrics.replica_spill_failure(self._deployment)
                log.exception("drain preseed into replica %s failed", home)
        self.stat_preseeded_entries += seeded
        return seeded

    # ---------------------------------------------------------- autoscale
    def _autoscale_tick(self) -> None:
        """Queue-depth autoscale check (O(replicas), runs per request):
        a sustained mean queue depth >= the threshold boots one replica in
        the background, warm-seeded from the hottest replica's spill."""
        # per-request (not per-row) queue-depth gauge refresh — route()'s
        # per-row hot path reads depths but must not pay O(replicas)
        # metric label resolutions per row
        for i, d in enumerate(self._live_depths()):
            self._metrics.router_queue_depth(self._deployment, i, d)
        live = self.live_replicas
        if (
            not live
            or self.autoscale_replicas <= len(live)
            or self.autoscale_queue_depth <= 0
            or self._scaling
        ):
            return
        mean_depth = sum(r.queue_depth for _, r in live) / len(live)
        now = time.monotonic()
        if mean_depth >= self.autoscale_queue_depth:
            self._hot_streak += 1
            if self._hot_since is None:
                self._hot_since = now
        else:
            self._hot_streak = 0
            self._hot_since = None
            return
        if (
            self._hot_streak >= self.AUTOSCALE_STREAK
            and now - self._hot_since >= self.AUTOSCALE_HOLD_S
        ):
            self._scaling = True
            self._hot_streak = 0
            self._hot_since = None
            self._scale_task = asyncio.ensure_future(self._scale_up())

    def _hottest_replica(self):
        """The serving replica whose prefix index served the most hits —
        the one whose working set a new replica wants."""
        up = [
            r
            for i, r in self.live_replicas
            if self._replica_states[i] == REPLICA_UP
        ] or [r for _, r in self.live_replicas]
        return max(up, key=lambda r: r.stat_prefix_hits)

    async def _export_spill(self) -> dict | None:
        """Export the hottest replica's prefix pages ON the event loop —
        the allocator/index cannot mutate mid-read there (no awaits inside
        the export), so entry->pages->bytes stays consistent. The pool's
        device buffers may still be mid-donation to an in-flight dispatch
        (reads raise "Array has been deleted"); retry until the export
        lands between rounds."""
        src = self._hottest_replica()
        for _ in range(500):
            try:
                return src.export_prefix_state()
            except RuntimeError:
                await asyncio.sleep(0.005)
        log.warning("prefix spill never found a quiescent round — cold boot")
        return None

    def _build_warm_replica(self, replica_id: int, payload):
        """Blocking build: construct + preseed + warmup (runs on a worker
        thread — XLA compiles must not stall the serving loop; the spill
        payload is host data exported on the loop beforehand)."""
        new = self._attach(self.factory(replica_id))
        if payload is not None:
            self.stat_preseeded_entries += new.preseed_prefix_state(payload)
        new.warmup()
        # shared-jit-cache note (see warmup): the new replica's compiles
        # would read as phantom recompiles on the serving replicas —
        # re-baseline them at the scale-up boundary
        for _, r in self.live_replicas:
            r._warmup_compile_counts = r.compile_counts()
        return new

    async def _scale_up(self) -> None:
        t0 = time.perf_counter()
        try:
            rid = len(self.replicas)
            payload = None
            if preseed_enabled() and self.prefix_enabled:
                payload = await self._export_spill()
                if self.spill_store is None and self._spill_store_factory is not None:
                    try:
                        self.spill_store = self._spill_store_factory()
                    except Exception:  # noqa: BLE001 - a broken store must not fail the scale-up
                        log.exception("replica spill store unusable — in-process spill only")
                    self._spill_store_factory = None
                if self.spill_store is not None and payload and payload["entries"]:
                    # round-trip THROUGH the persistence store so an
                    # operator restart (or an out-of-process replica)
                    # boots from the same payload this scale-up used — but
                    # a store outage (disk full, redis down) must not
                    # abort the scale-up: the in-memory payload in hand
                    # still warm-boots the replica
                    try:
                        self.spill_store.save(
                            spill_key(self._deployment), pickle.dumps(payload)
                        )
                        raw = self.spill_store.load(spill_key(self._deployment))
                        if raw is not None:
                            payload = pickle.loads(raw)
                    except Exception:  # noqa: BLE001 - degraded, not fatal — and COUNTED
                        self.stat_spill_failures += 1
                        self._metrics.replica_spill_failure(self._deployment)
                        log.exception(
                            "replica spill store round-trip failed — "
                            "scale-up continues with the in-process payload"
                        )
            loop = asyncio.get_running_loop()
            new = await loop.run_in_executor(
                None, self._build_warm_replica, rid, payload
            )
            self.replicas.append(new)
            self.balancer.add_arm()
            # grow the per-arm health tracking in lockstep with the fleet
            # (the funnel extends _replica_states itself — CP004 keeps it
            # the single writer of that list)
            self._breakers.append(self._new_breaker(rid))
            self._misses.append(0)
            self._last_ticks.append(-1)
            self._inflight.append(set())
            self._set_replica_state(rid, REPLICA_UP, "scale-up boot")
            self.stat_scale_ups += 1
            self._metrics.router_replicas(self._deployment, len(self.live_replicas))
            log.info(
                "decode autoscale: replica %s up in %.1fs (queue depth %s, "
                "preseeded entries so far: %s)",
                rid,
                time.perf_counter() - t0,
                self.autoscale_queue_depth,
                self.stat_preseeded_entries,
            )
        except Exception:  # noqa: BLE001 - a failed scale-up must not kill serving — but COUNTED
            self.stat_boot_failures += 1
            self._metrics.replica_boot_failure(self._deployment)
            log.exception("decode autoscale: replica boot failed")
        finally:
            self._scaling = False
            self._scale_task = None

    # ------------------------------------------------------------- audits
    def allocator_audits(self) -> None:
        """Per-replica pool-consistency audits (soak/test gate)."""
        for _, r in self.live_replicas:
            r.pool.alloc.check()
