"""Multi-replica decode scale-out: prefix-affinity routing + reward-driven
load balancing.

The single decode scheduler is saturated on every measured axis (pipelined
rounds hide the host bubble, the feature draft amortizes each dispatch), so
the next throughput multiple is horizontal: N scheduler replicas, each with
its own page pool, prefix index, and dispatch stream — mapped round-robin
onto the attached devices — behind a ROUTER that keeps warm routing warm.
This is the source system's defining capability (ROUTER graph nodes + bandit
routers fed by the Feedback reward API, PAPER.md L3/L5) pointed at the
generative tier:

- **Prefix-affinity routing**: a prefix-pool hit cuts TTFT 78.0 -> 28.2 ms
  (PR 5), but the hit only exists on the replica that CAPTURED the prefix.
  The router normalizes the prompt to its leading block (the same
  normalization ``PrefixIndex`` admission applies — shared helpers below)
  and rendezvous-hashes it, so every request sharing a system prompt lands
  on the same warm replica while distinct prefix groups spread across the
  fleet. Naive round-robin splits each group R ways and multiplies the cold
  misses by the replica count — the bench's control leg documents exactly
  that collapse.
- **Bounded-load shedding**: affinity must not melt the hot replica. When
  the rendezvous winner's queue depth exceeds ``load_factor`` x the fleet
  mean (+1 slack), the pick degrades to power-of-two-choices between the
  top TWO rendezvous ranks by live queue depth (``/decode/health`` exposes
  ``queue_depth`` per replica for the out-of-process twin) — the classic
  consistent-hashing-with-bounded-loads escape valve, and the shed target
  is still deterministic per key (rank 2), so a spilled group stays warm on
  ONE overflow replica instead of spraying.
- **Reward-driven fallback**: requests with no affinity signal (prompts
  shorter than one block) ride per-replica bandit arms — epsilon-greedy or
  Thompson — rewarded through the existing Feedback API by the
  TTFT/ITL/SLO-attainment verdicts PR 9 already stamps into
  ``meta.tags.slo`` (the serving layer closes the loop automatically; no
  client change).
- **Warm scale-up**: a new replica is cold by construction. Scale-up spills
  the hottest replica's refcount-ranked prefix-pool pages (int8 pools spill
  the stored bytes + scale planes verbatim — no dequant round-trip) through
  ``persistence/state.py`` and pre-seeds them into the new replica's pool,
  so its FIRST shared-prompt request already rides the warm TTFT path.

Everything here is host-side policy — no device programs, no new compile
ladders. The replicas' fused program sets are untouched; the tier's greedy
output is bit-identical to a single scheduler for every routing policy.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import pickle
import random
import threading
import time

import numpy as np

from seldon_core_tpu.core.errors import APIException, ErrorCode
from seldon_core_tpu.core.message import Meta, SeldonMessage
from seldon_core_tpu.metrics import NullMetrics

log = logging.getLogger(__name__)

# default affinity block: one KV page of tokens (the pool's auto page size)
# — sharers whose common prefix spans at least one page are the ones whose
# reuse actually displaces prefill work
DEFAULT_AFFINITY_BLOCK = 16

ROUTER_POLICIES = ("affinity", "round_robin", "bandit")
FALLBACK_POLICIES = ("epsilon_greedy", "thompson")


# --------------------------------------------------------------------------
# prompt -> prefix-key normalization (shared by scheduler admission and the
# router; previously inlined in DecodeScheduler._admit_decide/_maybe_capture
# and only exercised through scheduler e2e paths)
def usable_prefix_len(length: int, seq_len: int) -> int:
    """The longest REUSABLE span of a prompt prefix on a ``seq_len`` prompt
    bucket: clamped to ``seq_len - 1`` because the last prompt position must
    always be computed fresh — its logits are the first generated token's
    distribution (the LCP boundary rule admission applies to every
    radix-trie match). Degenerate inputs (empty prompts, seq_len <= 1)
    normalize to 0: nothing reusable."""
    return max(0, min(int(length), int(seq_len) - 1))


def capture_prefix_len(length: int, prefix_ctx: int, seq_len: int) -> int:
    """The span a retiring/hinted slot may CAPTURE into the prefix index:
    the requested length clamped to the deployment's prefix window
    (``decode_prefix_ctx``) and the prompt bucket — only prompt positions
    are ever cached. 0 means nothing capturable."""
    return max(0, min(int(length), int(prefix_ctx), int(seq_len)))


def prefix_route_key(prompt, *, block: int = DEFAULT_AFFINITY_BLOCK, seq_len: int = 0):
    """The prompt's affinity key: its leading ``block`` tokens, as a tuple.

    Uses the SAME normalization the radix index applies on admission: when
    ``seq_len`` is given, only the usable span (``usable_prefix_len``) may
    contribute — a prompt whose usable span is shorter than one block has no
    affinity signal and returns ``()`` (the router falls back to its bandit
    arms). One block is deliberately the whole key: two groups that agree on
    their first block but diverge later also share radix-trie ancestry, so
    co-locating them is exactly what keeps the shared span warm."""
    n = len(prompt)
    usable = usable_prefix_len(n, seq_len) if seq_len > 0 else n
    if block <= 0 or usable < block:
        return ()
    return tuple(int(t) for t in prompt[:block])


def _key_rank(key: tuple, arm: int) -> int:
    """Rendezvous (highest-random-weight) score of ``arm`` for ``key`` —
    deterministic across processes/restarts (hashlib, not hash())."""
    h = hashlib.blake2b(digest_size=8)
    h.update(repr(key).encode())
    h.update(arm.to_bytes(4, "little", signed=False))
    return int.from_bytes(h.digest(), "little")


class AffinityBalancer:
    """Host-side routing policy over N replica arms.

    - ``pick(key, depths)``: rendezvous-hash ``key`` over the live arms with
      bounded-load shedding on queue depth; keyless requests ride the
      reward-driven fallback arms (epsilon-greedy or Thompson).
    - ``reward(arm, r)``: reward ingestion (r in [0, 1]) — what the
      Feedback API and the serving layer's automatic SLO sink call.

    Arm state is plain host data and picklable (persistence/state.py
    checkpoints it exactly like the EpsilonGreedyRouter's counts)."""

    def __init__(
        self,
        n_arms: int,
        *,
        policy: str = "affinity",
        fallback: str = "epsilon_greedy",
        epsilon: float = 0.1,
        load_factor: float = 1.25,
        seed=None,
    ):
        if n_arms < 1:
            raise ValueError(f"balancer needs >= 1 arm, got {n_arms}")
        if policy not in ROUTER_POLICIES:
            raise ValueError(
                f"router policy {policy!r} unsupported (want one of "
                f"{ROUTER_POLICIES})"
            )
        if fallback not in FALLBACK_POLICIES:
            raise ValueError(
                f"fallback policy {fallback!r} unsupported (want one of "
                f"{FALLBACK_POLICIES})"
            )
        self.policy = policy
        self.fallback = fallback
        self.epsilon = float(epsilon)
        self.load_factor = float(load_factor)
        self._rng = random.Random(int(seed)) if seed is not None else random.Random()
        self.counts = [0] * n_arms
        self.rewards = [0.0] * n_arms
        # Thompson state: Beta posterior per arm (successes/failures in
        # fractional units — an SLO verdict is 0/1, a shaped reward may
        # land between)
        self.alpha = [1.0] * n_arms
        self.beta = [1.0] * n_arms
        # externally-observed queue depths (the /decode/health poll path);
        # in-process callers pass live depths to pick() instead. Each
        # observation carries a timestamp: a reading older than DEPTH_TTL_S
        # reads as 0 — a crashed poller's last spike must not shed a
        # group off its warm replica forever
        self.depths = [0] * n_arms
        self._depth_ts = [0.0] * n_arms
        self._rr = 0
        self._lock = threading.Lock()
        self.stat_routes = {"affinity": 0, "shed": 0, "fallback": 0, "round_robin": 0}

    @property
    def n_arms(self) -> int:
        return len(self.counts)

    def add_arm(self) -> int:
        """Grow the fleet by one arm (scale-up). Rendezvous hashing moves
        only ~1/N of the keyspace onto the new arm — existing prefix groups
        overwhelmingly keep their warm home."""
        with self._lock:
            self.counts.append(0)
            self.rewards.append(0.0)
            self.alpha.append(1.0)
            self.beta.append(1.0)
            self.depths.append(0)
            self._depth_ts.append(0.0)
            return len(self.counts) - 1

    # observed depths older than this read as 0 in pick() — bounds the
    # damage of a stale spike when the health poller stops
    DEPTH_TTL_S = 30.0

    def observe_depth(self, arm: int, depth: int) -> None:
        """Ingest a polled queue depth (the /decode/health ``queue_depth``
        field) for out-of-process replicas."""
        with self._lock:
            if 0 <= arm < len(self.depths):
                self.depths[arm] = max(0, int(depth))
                self._depth_ts[arm] = time.monotonic()

    def _observed_depths(self) -> list[int]:
        """The polled depths with the staleness TTL applied (lock held)."""
        now = time.monotonic()
        return [
            d if now - t <= self.DEPTH_TTL_S else 0
            for d, t in zip(self.depths, self._depth_ts)
        ]

    # ---------------------------------------------------------------- picks
    def pick(self, key, depths=None) -> tuple[int, str]:
        """Route one request: returns ``(arm, reason)`` with reason one of
        affinity | shed | fallback | round_robin."""
        with self._lock:
            n = len(self.counts)
            d = [
                int(x)
                for x in (depths if depths is not None else self._observed_depths())
            ]
            d += [0] * (n - len(d))
            if self.policy == "round_robin":
                arm = self._rr % n
                self._rr += 1
                self.stat_routes["round_robin"] += 1
                return arm, "round_robin"
            if self.policy == "affinity" and key:
                ranked = sorted(range(n), key=lambda a: _key_rank(tuple(key), a), reverse=True)
                primary = ranked[0]
                # bounded load: the hot replica may run ahead of the fleet
                # mean by load_factor (+1 slack so tiny fleets don't shed
                # on depth 1-vs-0); past that, power-of-two-choices between
                # the top two rendezvous ranks keeps the spill warm on ONE
                # deterministic overflow replica
                bound = self.load_factor * (sum(d) / n) + 1.0
                if n > 1 and d[primary] > bound:
                    second = ranked[1]
                    if d[second] < d[primary]:
                        # a shed is only a shed when the key MOVES — an
                        # even-deeper rank 2 keeps the request home, and
                        # counting that as displaced would overstate shed
                        # traffic in the routes metric
                        self.stat_routes["shed"] += 1
                        return second, "shed"
                self.stat_routes["affinity"] += 1
                return primary, "affinity"
            # keyless (or policy=bandit): the reward-driven fallback arms
            self.stat_routes["fallback"] += 1
            return self._fallback_pick(d), "fallback"

    def _fallback_pick(self, depths) -> int:
        n = len(self.counts)
        if self.fallback == "thompson":
            draws = [
                self._rng.betavariate(self.alpha[i], self.beta[i]) for i in range(n)
            ]
            return int(max(range(n), key=draws.__getitem__))
        if self._rng.random() < self.epsilon:
            return self._rng.randrange(n)
        means = [
            self.rewards[i] / self.counts[i] if self.counts[i] else float("inf")
            for i in range(n)
        ]
        best = max(means)
        # estimate ties break by LIVE load, then index: before any reward
        # lands every arm ties at +inf, and without this the exploit
        # branch would herd ~1-epsilon of keyless traffic onto arm 0
        # while the rest of the fleet idles
        tied = [i for i in range(n) if means[i] == best]
        return int(min(tied, key=lambda i: (depths[i], i)))

    # -------------------------------------------------------------- rewards
    def reward(self, arm: int, r: float) -> None:
        """Reward ingestion for one served request (clamped to [0, 1]) —
        moves BOTH estimators so a live policy flip needs no re-learning."""
        if not (0 <= int(arm) < len(self.counts)):
            return
        r = min(1.0, max(0.0, float(r)))
        with self._lock:
            self.counts[arm] += 1
            self.rewards[arm] += r
            self.alpha[arm] += r
            self.beta[arm] += 1.0 - r

    def arm_estimate(self, arm: int) -> float:
        c = self.counts[arm]
        return self.rewards[arm] / c if c else 0.0

    # persistence hooks (persistence/state.py contract)
    def __getstate__(self):
        d = self.__dict__.copy()
        d.pop("_lock", None)
        return d

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._lock = threading.Lock()


# --------------------------------------------------------------------------
# prefix-page spill / preseed (warm scale-up through persistence/state.py)
SPILL_UNIT = "prefix_pages"  # state_key unit id the spill payload rides


def spill_key(deployment_id: str) -> str:
    from seldon_core_tpu.persistence.state import state_key

    return state_key(deployment_id or "decode", SPILL_UNIT)


def spill_to_store(sched, store, deployment_id: str, top_n: int = 0) -> int:
    """Export ``sched``'s hottest prefix entries into a persistence store
    (FileStateStore/RedisStateStore). Returns entries spilled."""
    payload = sched.export_prefix_state(top_n=top_n)
    if payload is None or not payload["entries"]:
        return 0
    store.save(spill_key(deployment_id), pickle.dumps(payload))
    return len(payload["entries"])


def preseed_from_store(sched, store, deployment_id: str) -> int:
    """Pre-seed ``sched``'s page pool from a spilled payload; returns the
    entries seeded (0 when the store holds nothing or nothing fits)."""
    raw = store.load(spill_key(deployment_id))
    if raw is None:
        return 0
    try:
        payload = pickle.loads(raw)
    except Exception:  # noqa: BLE001 - stale/corrupt spill must not fail boot
        log.warning("corrupt prefix spill for %r ignored", deployment_id)
        return 0
    return sched.preseed_prefix_state(payload)


def preseed_enabled() -> bool:
    """ENGINE_DECODE_REPLICA_PRESEED kill switch (default on): "off"
    disables warm pre-seeding at scale-up/boot — cold boots only."""
    import os

    from seldon_core_tpu.utils.env import ENGINE_DECODE_REPLICA_PRESEED

    return os.environ.get(ENGINE_DECODE_REPLICA_PRESEED, "on").lower() not in (
        "off",
        "0",
        "false",
    )


# --------------------------------------------------------------------------
class ReplicatedDecodeScheduler:
    """N decode-scheduler replicas behind the affinity balancer, presenting
    the single scheduler's serving surface (``submit`` /
    ``execute_message`` / ``warmup`` / ``close`` / stats) so the batcher,
    the streaming ingress, and the bench drive it unchanged.

    Each replica owns its full device state (params copy, page pool, prefix
    index, draft cache) on its own device — ``factory(i)`` places replica i
    on ``jax.devices()[i % n_devices]`` — so N replicas are N independent
    dispatch streams: the in-process twin of N decode pods, and the real
    thing on a multi-chip host. Greedy output is bit-identical to a single
    scheduler under EVERY routing policy (each replica is the proven
    scheduler; routing only decides which warm pool serves a request).

    Autoscale: when ``autoscale_replicas`` caps a larger fleet, a sustained
    mean queue depth >= ``autoscale_queue_depth`` (the same signal
    ``/decode/health`` exports) boots one more replica in the background —
    pre-seeded from the hottest replica's spilled prefix pages so it serves
    shared prompts warm from its first request."""

    # a scale-up needs BOTH: this many hot observations AND the queue
    # held hot for this long — the observation count alone would let one
    # millisecond-scale burst (several submits arriving together) boot an
    # expensive replica that the burst never needed
    AUTOSCALE_STREAK = 3
    AUTOSCALE_HOLD_S = 0.5

    def __init__(
        self,
        factory,
        n_replicas: int,
        *,
        policy: str = "",
        fallback: str = "epsilon_greedy",
        epsilon: float = 0.1,
        load_factor: float = 1.25,
        affinity_block: int = DEFAULT_AFFINITY_BLOCK,
        autoscale_replicas: int = 0,
        autoscale_queue_depth: int = 0,
        spill_store=None,
        spill_store_factory=None,
        metrics: NullMetrics | None = None,
        deployment_name: str = "",
        seed: int = 0,
    ):
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        self.factory = factory
        self.policy = policy or "affinity"
        self.replicas = [self._attach(factory(i)) for i in range(n_replicas)]
        self.affinity_block = int(affinity_block) or DEFAULT_AFFINITY_BLOCK
        self.autoscale_replicas = int(autoscale_replicas)
        self.autoscale_queue_depth = int(autoscale_queue_depth)
        self.spill_store = spill_store
        # resolved on the FIRST spill, not at build: a file store's ctor
        # mkdirs its directory, and most fleets never scale up
        self._spill_store_factory = spill_store_factory
        self._metrics = metrics or NullMetrics()
        self._deployment = deployment_name
        self.balancer = AffinityBalancer(
            n_replicas,
            policy=self.policy,
            fallback=fallback,
            epsilon=epsilon,
            load_factor=load_factor,
            seed=seed,
        )
        self._hot_streak = 0
        self._hot_since: float | None = None
        self._scaling = False
        self._scale_task: asyncio.Task | None = None
        self.stat_scale_ups = 0
        self.stat_preseeded_entries = 0
        self._metrics.router_replicas(self._deployment, len(self.replicas))

    def _attach(self, replica):
        """Fleet wiring for one replica: dispatches hop OFF the event loop
        onto a dedicated single-thread executor (one dispatch stream per
        replica — N replicas' device work genuinely overlaps; XLA releases
        the GIL during execution) even on the CPU backend, where a lone
        scheduler would dispatch inline."""
        from concurrent.futures import ThreadPoolExecutor

        replica._offload_dispatch = True
        replica._dispatch_pool = ThreadPoolExecutor(
            max_workers=1,
            thread_name_prefix=f"decode-r{getattr(replica, 'replica_id', 0)}",
        )
        return replica

    # ------------------------------------------------------------ delegates
    @property
    def _r0(self):
        return self.replicas[0]

    @property
    def seq_len(self) -> int:
        return self._r0.seq_len

    @property
    def max_new_tokens(self) -> int:
        return self._r0.max_new_tokens

    @property
    def eos_id(self) -> int:
        return self._r0.eos_id

    @property
    def slo_ttft_s(self) -> float:
        return self._r0.slo_ttft_s

    @property
    def slo_itl_s(self) -> float:
        return self._r0.slo_itl_s

    @property
    def active(self) -> int:
        return sum(r.active for r in self.replicas)

    @property
    def queue_depth(self) -> int:
        return sum(r.queue_depth for r in self.replicas)

    @property
    def prefix_enabled(self) -> bool:
        return self._r0.prefix_enabled

    # aggregated attribution (bench/soak read these off the single
    # scheduler today; the replicated tier sums)
    @property
    def stat_prefix_hits(self) -> int:
        return sum(r.stat_prefix_hits for r in self.replicas)

    @property
    def stat_prefix_misses(self) -> int:
        return sum(r.stat_prefix_misses for r in self.replicas)

    @property
    def stat_prefix_tokens_saved(self) -> int:
        return sum(r.stat_prefix_tokens_saved for r in self.replicas)

    @property
    def stat_tokens(self) -> int:
        return sum(r.stat_tokens for r in self.replicas)

    @property
    def stat_chunk_dispatches(self) -> int:
        return sum(r.stat_chunk_dispatches for r in self.replicas)

    def __getattr__(self, name: str):
        # any scheduler attribution counter not explicitly aggregated
        # above sums across the fleet (soak/bench read stat_* freely)
        if name.startswith("stat_"):
            return sum(getattr(r, name) for r in self.replicas)
        raise AttributeError(name)

    def request_params_from_meta(self, meta: Meta) -> dict:
        return self._r0.request_params_from_meta(meta)

    def warmup(self) -> None:
        for r in self.replicas:
            r.warmup()
        # the fused program set is module-level, so sibling replicas share
        # each function's underlying jit cache: replica N's warmup entries
        # (distinct device placements = distinct signatures) would read as
        # phantom "recompiles" against replica 0's earlier baseline.
        # Re-snapshot every replica once the WHOLE fleet is warm.
        for r in self.replicas:
            r._warmup_compile_counts = r.compile_counts()

    def compile_counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for i, r in enumerate(self.replicas):
            for k, v in r.compile_counts().items():
                out[f"r{i}.{k}"] = v
        return out

    def recompiles_since_warmup(self) -> int:
        return sum(r.recompiles_since_warmup() for r in self.replicas)

    async def close(self) -> None:
        task = self._scale_task
        if task is not None:
            # let an in-flight scale-up settle: cancelling mid-warmup
            # would leak a half-built replica's device state
            try:
                await task
            except Exception:  # noqa: BLE001 - logged by the task itself
                pass
        await asyncio.gather(*(r.close() for r in self.replicas))
        for r in self.replicas:
            pool = getattr(r, "_dispatch_pool", None)
            if pool is not None:
                pool.shutdown(wait=False)

    # -------------------------------------------------------------- routing
    def _live_depths(self) -> list[int]:
        # queue depth + active slots: a replica with free slots beats one
        # that is merely not-queueing (both O(1) reads)
        return [r.queue_depth + r.active for r in self.replicas]

    def route(self, prompt) -> tuple[int, str]:
        """Pick the serving replica for one prompt (token ids)."""
        key = prefix_route_key(
            prompt, block=self.affinity_block, seq_len=self.seq_len
        )
        arm, reason = self.balancer.pick(key, self._live_depths())
        self._metrics.router_route(self._deployment, self.policy, reason)
        return arm, reason

    def _reward_sink(self, arm: int, inner):
        """Per-request reward closure for the STREAMING path (no buffered
        response tags to ride the Feedback API): the scheduler's SLO
        verdict rewards the serving arm directly. Buffered requests carry
        ``meta.tags.replica`` instead and reward through
        ``ingest_feedback`` — one reward per request either way."""

        def sink(ok: bool) -> None:
            self._reward_arm(arm, 1.0 if ok else 0.0)
            if inner is not None:
                inner(ok)

        return sink

    def _reward_arm(self, arm: int, r: float) -> None:
        self.balancer.reward(arm, r)
        self._metrics.router_arm(
            self._deployment,
            arm,
            self.balancer.arm_estimate(arm),
        )

    async def submit(self, prompt, *, _slo_sink=None, **kw):
        """Route one sequence to its replica and submit (the streaming
        ingress path — per-row SLO verdicts reward the serving arm
        directly, since a streamed response never rides the Feedback
        API)."""
        self._autoscale_tick()
        arm, _reason = self.route(prompt)
        sink = _slo_sink
        if self.slo_ttft_s > 0 or self.slo_itl_s > 0:
            sink = self._reward_sink(arm, _slo_sink)
        return await self.replicas[arm].submit(prompt, _slo_sink=sink, **kw)

    async def execute_message(self, msg: SeldonMessage) -> SeldonMessage:
        """Buffered serving entry: every row routes independently (rows of
        one request sharing a prefix land on the same warm replica; mixed
        rows spread), each rides its replica's own execute_message, and
        the merged response mirrors the single scheduler's contract —
        plus ``meta.tags.replica`` (per-row serving replica) so the
        Feedback API can route rewards back to the arms."""
        arr = msg.array
        if arr is None:
            raise APIException(
                ErrorCode.ENGINE_INVALID_JSON,
                "generative predictor needs tensor token ids",
            )
        self._autoscale_tick()
        rows = np.atleast_2d(np.asarray(arr)).astype(np.int32)
        picks = []
        for row in rows:
            arm, _reason = self.route(row)
            picks.append(arm)

        async def one(i: int) -> SeldonMessage:
            sub = SeldonMessage.from_array(rows[i : i + 1], meta=msg.meta)
            return await self.replicas[picks[i]].execute_message(sub)

        outs = await asyncio.gather(
            *(one(i) for i in range(len(rows))), return_exceptions=True
        )
        for o in outs:
            if isinstance(o, BaseException):
                raise o
        full = np.concatenate([np.atleast_2d(np.asarray(o.array)) for o in outs])
        tags = {**msg.meta.tags, "replica": picks}
        gen_lens: list[int] = []
        slo: list[str] = []
        for o in outs:
            gen_lens.extend(o.meta.tags.get("gen_lens") or [])
            slo.extend(o.meta.tags.get("slo") or [])
        tags["gen_lens"] = gen_lens
        if slo:
            tags["slo"] = slo
        meta = Meta(
            puid=msg.meta.puid,
            tags=tags,
            routing=dict(msg.meta.routing),
            request_path=dict(msg.meta.request_path),
        )
        return msg.with_array_meta(full, meta)

    # ----------------------------------------------------------- feedback
    def ingest_feedback(self, feedback, *, use_slo: bool = False) -> int:
        """Feedback-API reward ingestion: the response's per-row
        ``meta.tags.replica`` names the serving arms; ``feedback.reward``
        moves their estimates. ``use_slo=True`` (the serving layer's
        AUTOMATIC sink only) rewards each row from the response's own SLO
        verdict instead — a client's explicit reward is always honored
        verbatim, including an explicit 0.0 down-vote. Returns arms
        updated; rows naming an unknown replica (forged tags, or a
        response predating a fleet resize) are skipped, never an
        error."""
        resp = feedback.response
        if resp is None:
            return 0
        arms = resp.meta.tags.get("replica")
        if not isinstance(arms, (list, tuple)) or not arms:
            return 0
        slo = resp.meta.tags.get("slo")
        updated = 0
        for i, arm in enumerate(arms):
            try:
                arm = int(arm)
            except (TypeError, ValueError):
                continue
            if not (0 <= arm < len(self.replicas)):
                continue
            r = float(feedback.reward)
            if use_slo and isinstance(slo, (list, tuple)) and i < len(slo):
                r = 1.0 if slo[i] == "met" else 0.0
            self._reward_arm(arm, r)
            updated += 1
        return updated

    # ---------------------------------------------------------- autoscale
    def _autoscale_tick(self) -> None:
        """Queue-depth autoscale check (O(replicas), runs per request):
        a sustained mean queue depth >= the threshold boots one replica in
        the background, warm-seeded from the hottest replica's spill."""
        # per-request (not per-row) queue-depth gauge refresh — route()'s
        # per-row hot path reads depths but must not pay O(replicas)
        # metric label resolutions per row
        for i, d in enumerate(self._live_depths()):
            self._metrics.router_queue_depth(self._deployment, i, d)
        if (
            self.autoscale_replicas <= len(self.replicas)
            or self.autoscale_queue_depth <= 0
            or self._scaling
        ):
            return
        mean_depth = sum(r.queue_depth for r in self.replicas) / len(self.replicas)
        now = time.monotonic()
        if mean_depth >= self.autoscale_queue_depth:
            self._hot_streak += 1
            if self._hot_since is None:
                self._hot_since = now
        else:
            self._hot_streak = 0
            self._hot_since = None
            return
        if (
            self._hot_streak >= self.AUTOSCALE_STREAK
            and now - self._hot_since >= self.AUTOSCALE_HOLD_S
        ):
            self._scaling = True
            self._hot_streak = 0
            self._hot_since = None
            self._scale_task = asyncio.ensure_future(self._scale_up())

    def _hottest_replica(self):
        """The replica whose prefix index served the most hits — the one
        whose working set a new replica wants."""
        return max(self.replicas, key=lambda r: r.stat_prefix_hits)

    async def _export_spill(self) -> dict | None:
        """Export the hottest replica's prefix pages ON the event loop —
        the allocator/index cannot mutate mid-read there (no awaits inside
        the export), so entry->pages->bytes stays consistent. The pool's
        device buffers may still be mid-donation to an in-flight dispatch
        (reads raise "Array has been deleted"); retry until the export
        lands between rounds."""
        src = self._hottest_replica()
        for _ in range(500):
            try:
                return src.export_prefix_state()
            except RuntimeError:
                await asyncio.sleep(0.005)
        log.warning("prefix spill never found a quiescent round — cold boot")
        return None

    def _build_warm_replica(self, replica_id: int, payload):
        """Blocking build: construct + preseed + warmup (runs on a worker
        thread — XLA compiles must not stall the serving loop; the spill
        payload is host data exported on the loop beforehand)."""
        new = self._attach(self.factory(replica_id))
        if payload is not None:
            self.stat_preseeded_entries += new.preseed_prefix_state(payload)
        new.warmup()
        # shared-jit-cache note (see warmup): the new replica's compiles
        # would read as phantom recompiles on the serving replicas —
        # re-baseline them at the scale-up boundary
        for r in self.replicas:
            r._warmup_compile_counts = r.compile_counts()
        return new

    async def _scale_up(self) -> None:
        t0 = time.perf_counter()
        try:
            rid = len(self.replicas)
            payload = None
            if preseed_enabled() and self.prefix_enabled:
                payload = await self._export_spill()
                if self.spill_store is None and self._spill_store_factory is not None:
                    try:
                        self.spill_store = self._spill_store_factory()
                    except Exception:  # noqa: BLE001 - a broken store must not fail the scale-up
                        log.exception("replica spill store unusable — in-process spill only")
                    self._spill_store_factory = None
                if self.spill_store is not None and payload and payload["entries"]:
                    # round-trip THROUGH the persistence store so an
                    # operator restart (or an out-of-process replica)
                    # boots from the same payload this scale-up used — but
                    # a store outage (disk full, redis down) must not
                    # abort the scale-up: the in-memory payload in hand
                    # still warm-boots the replica
                    try:
                        self.spill_store.save(
                            spill_key(self._deployment), pickle.dumps(payload)
                        )
                        raw = self.spill_store.load(spill_key(self._deployment))
                        if raw is not None:
                            payload = pickle.loads(raw)
                    except Exception:  # noqa: BLE001 - degraded, not fatal
                        log.exception(
                            "replica spill store round-trip failed — "
                            "scale-up continues with the in-process payload"
                        )
            loop = asyncio.get_running_loop()
            new = await loop.run_in_executor(
                None, self._build_warm_replica, rid, payload
            )
            self.replicas.append(new)
            self.balancer.add_arm()
            self.stat_scale_ups += 1
            self._metrics.router_replicas(self._deployment, len(self.replicas))
            log.info(
                "decode autoscale: replica %s up in %.1fs (queue depth %s, "
                "preseeded entries so far: %s)",
                rid,
                time.perf_counter() - t0,
                self.autoscale_queue_depth,
                self.stat_preseeded_entries,
            )
        except Exception:  # noqa: BLE001 - a failed scale-up must not kill serving
            log.exception("decode autoscale: replica boot failed")
        finally:
            self._scaling = False
            self._scale_task = None

    # ------------------------------------------------------------- audits
    def allocator_audits(self) -> None:
        """Per-replica pool-consistency audits (soak/test gate)."""
        for r in self.replicas:
            r.pool.alloc.check()
