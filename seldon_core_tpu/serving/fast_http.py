"""Fast data-plane HTTP/1.1 ingress: a purpose-built asyncio.Protocol server.

Why this exists: the serving hot path (predict request -> response) spends
more CPU in a general-purpose web framework's per-request machinery than in
the entire graph walk + XLA dispatch. This server implements exactly what
the data plane needs — POST with Content-Length bodies, keep-alive, a small
exact-path route table — over the SAME transport-neutral handlers
(serving/wire.py) the aiohttp apps use, so semantics cannot drift. Measured
on the bench stack-ceiling config it roughly halves per-request server
overhead vs the aiohttp app.

Not a general web server, by design:
- no chunked request bodies (411 if no Content-Length; serving clients and
  the reference's engines always send it),
- no TLS (terminate at the LB, as the reference's ingress does),
- no websockets. Streaming RESPONSES exist for exactly one surface: the
  generative tier's per-token SSE endpoint (chunked transfer, see
  _write_stream) — request bodies stay Content-Length-framed.
The full aiohttp apps remain for everything else (admin, tests, tooling);
`PredictorServer`/platform keep them unless fast ingress is requested.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Awaitable, Callable, Mapping

from seldon_core_tpu.serving.wire import WireRequest, WireResponse, WireStreamResponse

log = logging.getLogger(__name__)

Handler = Callable[[WireRequest], Awaitable[WireResponse]]

_MAX_BODY = 64 * 1024 * 1024  # matches the aiohttp apps' client_max_size
_MAX_HEADER = 64 * 1024

# RFC 7230 3.2.6 token charset for header field-names (must stay in lockstep
# with fastcodec.cpp is_tchar — the C parser rejects non-token names too)
_TCHAR = frozenset(
    "!#$%&'*+-.^_`|~0123456789"
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ"
)

_STATUS_LINES = {
    200: b"HTTP/1.1 200 OK\r\n",
    204: b"HTTP/1.1 204 No Content\r\n",
    400: b"HTTP/1.1 400 Bad Request\r\n",
    401: b"HTTP/1.1 401 Unauthorized\r\n",
    404: b"HTTP/1.1 404 Not Found\r\n",
    411: b"HTTP/1.1 411 Length Required\r\n",
    413: b"HTTP/1.1 413 Payload Too Large\r\n",
    500: b"HTTP/1.1 500 Internal Server Error\r\n",
    503: b"HTTP/1.1 503 Service Unavailable\r\n",
    504: b"HTTP/1.1 504 Gateway Timeout\r\n",
}


def _status_line(code: int) -> bytes:
    return _STATUS_LINES.get(code) or f"HTTP/1.1 {code} Status\r\n".encode()


class PyHead:
    """One accepted request head from the pure-Python fallback parser."""

    __slots__ = ("method", "path", "headers", "clen", "body_start")

    def __init__(self, method, path, headers, clen, body_start):
        self.method = method
        self.path = path
        self.headers = headers
        self.clen = clen
        self.body_start = body_start


def parse_head_py(raw: bytes) -> "PyHead | int | tuple[int, bytes]":
    """The fallback head parse + framing policy, as a PURE function.

    Returns a PyHead (request accepted; body may still be streaming in), 0
    (head incomplete — read more), or ``(status, message)`` to reject. This
    is the semantic reference the C fast path (native/fastcodec.cpp
    http_parse_head + HttpProtocol._dispatch_parsed's policy) must agree
    with — tests/test_fast_http.py fuzzes the two against each other."""
    head_end = raw.find(b"\r\n\r\n")
    if head_end < 0:
        if len(raw) > _MAX_HEADER:
            return (400, b"header too large")
        return 0
    lines = raw[:head_end].split(b"\r\n")
    if any(b"\n" in ln or b"\r" in ln for ln in lines):
        # bare LF/CR anywhere in the head (request line included): an
        # LF-tolerant front proxy would see an extra line (e.g. a hidden
        # Transfer-Encoding header) where we see one — reject, matching
        # the C parser's whole-head CRLF discipline
        return (400, b"bad line terminator")
    try:
        method, path, _ = lines[0].decode("latin-1").split(" ", 2)
    except ValueError:
        return (400, b"bad request line")
    if not method or not path:
        return (400, b"bad request line")
    headers: dict[str, str] = {}
    for line in lines[1:]:
        if line[:1] in (b" ", b"\t"):
            # obs-fold continuation, colon or not — same rule as the C
            # parser (a colon-less fold would silently skip below)
            return (400, b"bad header name")
        k, sep, v = line.decode("latin-1").partition(":")
        if not sep:
            continue
        if not k or any(c not in _TCHAR for c in k):
            # RFC 7230 3.2.4/3.2.6: field-name must be pure token chars —
            # rejects "Transfer-Encoding : chunked" (space before colon)
            # and form-feed/NBSP/NUL variants, same as the C path
            return (400, b"bad header name")
        key = k.lower()
        # OWS is SP/HT ONLY (RFC 7230 3.2.3): str.strip()'s wider notion of
        # whitespace (form feed, vertical tab, NEL) would accept
        # "Content-Length:\x0c10" that the C parser rejects — divergence in
        # the desync family the fuzz test exists to catch
        v = v.strip(" \t")
        if key == "content-length":
            if not (v.isascii() and v.isdigit()):
                # digits-only: bare int() would also accept '+4', '-4',
                # '1_0' and unicode digits, and a negative value slips
                # past every downstream bound check
                return (400, b"bad content-length")
            if key in headers and int(headers[key]) != int(v):
                # RFC 7230 3.3.2: differing duplicate Content-Length
                # values MUST be rejected (CL.CL desync); numeric
                # comparison so '4' vs '04' tolerates, like the C path
                return (400, b"conflicting content-length")
        headers[key] = v
    if "transfer-encoding" in headers:
        # same rule as the C path: any TE (chunked, "gzip, chunked", …) is
        # rejected outright — never frame a TE request by CL
        return (400, b"Transfer-Encoding not supported")
    if "content-length" in headers:
        clen = int(headers["content-length"])
    elif method in ("GET", "HEAD", "DELETE", "OPTIONS"):
        clen = 0
    else:
        # POST/PUT without Content-Length (incl. chunked): out of this
        # server's contract — guessing clen=0 would misparse the body
        # bytes as the next request line
        return (411, b"Content-Length required")
    if clen > _MAX_BODY:
        return (413, b"body too large")
    return PyHead(method, path, headers, clen, head_end + 4)


class HttpProtocol(asyncio.Protocol):
    """One connection. Requests are processed strictly in order (no
    pipelining concurrency): parse -> schedule handler task -> write
    response -> parse next. Incoming bytes buffer while a handler runs."""

    def __init__(self, routes: Mapping[tuple[str, str], Handler]):
        self._routes = routes
        self._transport: asyncio.Transport | None = None
        self._buf = bytearray()
        self._busy = False
        self._closing = False
        # head parsed, body still streaming in: cache the parse so large
        # uploads don't re-parse (or re-copy) the buffer per TCP chunk
        self._pending_head = None

    # ------------------------------------------------------------- plumbing
    def connection_made(self, transport: asyncio.BaseTransport) -> None:
        self._transport = transport  # type: ignore[assignment]

    def connection_lost(self, exc: Exception | None) -> None:
        self._closing = True
        self._transport = None

    def data_received(self, data: bytes) -> None:
        self._buf += data
        if not self._busy:
            self._try_dispatch()

    # -------------------------------------------------------------- parsing
    def _try_dispatch(self) -> None:
        """Parse one complete request from the buffer and run its handler.
        The C head parser (native/fastcodec.cpp http_parse_head) handles the
        hot path in one pass; the Python parse below stays as the fallback
        and the semantic reference."""
        from seldon_core_tpu import native

        if self._pending_head is not None:
            # head already parsed — only waiting on body bytes (either
            # parser's head object; both cache here)
            if isinstance(self._pending_head, PyHead):
                self._dispatch_py(self._pending_head)
            else:
                self._dispatch_parsed(self._pending_head)
            return
        # only the head region crosses into C: copying the whole buffer
        # would make chunked large-body uploads O(n^2) in memcpy
        parsed = native.parse_http_head(
            bytes(self._buf[: _MAX_HEADER + 4])
        )
        if parsed is not None:
            self._dispatch_parsed(parsed)
            return
        self._try_dispatch_py()

    def _dispatch_parsed(self, parsed) -> None:
        from seldon_core_tpu import native

        buf = self._buf
        if parsed == 0:
            if len(buf) > _MAX_HEADER:
                self._respond_simple(400, b"header too large")
                self._close()
            return
        if parsed == -1:
            self._respond_simple(400, b"bad request")
            self._close()
            return
        flags = parsed.flags
        method = parsed.method
        if flags & native.HDRF_HAS_TE:
            # reject ANY Transfer-Encoding, even alongside Content-Length:
            # framing by CL while a TE-honoring front proxy frames by
            # chunked is the classic TE.CL request-smuggling desync
            self._respond_simple(400, b"Transfer-Encoding not supported")
            self._close()
            return
        if flags & native.HDRF_HAS_CLEN:
            clen = parsed.content_length
        elif method in ("GET", "HEAD", "DELETE", "OPTIONS"):
            clen = 0
        else:
            self._respond_simple(411, b"Content-Length required")
            self._close()
            return
        if clen > _MAX_BODY:
            self._respond_simple(413, b"body too large")
            self._close()
            return
        if len(buf) - parsed.body_start < clen:
            self._pending_head = parsed  # wait for the body; parse once
            return
        self._pending_head = None
        body = bytes(buf[parsed.body_start : parsed.body_start + clen])
        # gRPC-Web paths carry auth as arbitrary metadata headers (the
        # reference's oauth_token key) that the C parser's two fixed
        # capture slots don't cover — keep the validated head for a
        # targeted scan below, before the buffer is consumed. The W3C
        # traceparent propagation header (trace continuation across remote
        # engine hops) gets the same treatment, gated on a copy-free find
        # so untraced traffic pays nothing; the header name is lowercase
        # per the W3C spec (the Python fallback parser captures any case).
        head_bytes = (
            bytes(buf[: parsed.body_start])
            if parsed.path.startswith("/seldon.")
            else b""
        )
        if not head_bytes and (
            buf.find(b"traceparent", 0, parsed.body_start) != -1
            or buf.find(b"Traceparent", 0, parsed.body_start) != -1
        ):
            head_bytes = bytes(buf[: parsed.body_start])
        del buf[: parsed.body_start + clen]

        headers: dict[str, str] = {}
        if parsed.content_type is not None:
            headers["content-type"] = parsed.content_type
        if parsed.authorization is not None:
            headers["authorization"] = parsed.authorization
        if head_bytes:
            token = _header_from_head(head_bytes, b"oauth_token")
            if token is not None:
                headers["oauth_token"] = token
            tp = _header_from_head(head_bytes, b"traceparent")
            if tp is not None:
                headers["traceparent"] = tp
        path = parsed.path.split("?", 1)[0]
        req = WireRequest(
            method=method,
            path=path,
            headers=headers,
            body=body,
            declared_ctype=bool(flags & native.HDRF_HAS_CTYPE),
        )
        handler = self._routes.get((method, path))
        keep_alive = not (flags & native.HDRF_CONN_CLOSE)
        self._busy = True
        task = asyncio.ensure_future(self._run(handler, req, keep_alive))
        task.add_done_callback(self._on_handler_done)

    def _try_dispatch_py(self) -> None:
        # only the head region is copied/parsed — slicing the whole buffer
        # would make large-body uploads O(n^2) in memcpy per TCP chunk
        parsed = parse_head_py(bytes(self._buf[: _MAX_HEADER + 4]))
        if parsed == 0:
            return  # head incomplete; wait for more data
        if isinstance(parsed, tuple):
            status, text = parsed
            self._respond_simple(status, text)
            self._close()
            return
        self._dispatch_py(parsed)

    def _dispatch_py(self, parsed: "PyHead") -> None:
        buf = self._buf
        method, path, headers, clen, body_start = (
            parsed.method,
            parsed.path,
            parsed.headers,
            parsed.clen,
            parsed.body_start,
        )
        if len(buf) - body_start < clen:
            # wait for the body; cache the parse (mirrors the C path — a
            # large upload must not re-copy + re-parse per TCP chunk)
            self._pending_head = parsed
            return
        self._pending_head = None
        body = bytes(buf[body_start : body_start + clen])
        del buf[: body_start + clen]

        path = path.split("?", 1)[0]
        handler = self._routes.get((method, path))
        keep_alive = headers.get("connection", "").lower() != "close"
        req = WireRequest(
            method=method,
            path=path,
            headers=headers,
            body=body,
            declared_ctype="content-type" in headers,
        )
        self._busy = True
        task = asyncio.ensure_future(self._run(handler, req, keep_alive))
        task.add_done_callback(self._on_handler_done)

    # ------------------------------------------------------------- handling
    async def _run(self, handler: Handler | None, req: WireRequest, keep_alive: bool) -> None:
        if handler is None:
            self._respond_simple(404, b"not found", keep_alive)
            return
        try:
            resp = await handler(req)
        except Exception:  # noqa: BLE001 - handler contract is no-raise; belt+braces
            log.exception("fast-ingress handler failed for %s", req.path)
            resp = WireResponse(status=500, body=b'{"status":"FAILURE"}')
        if isinstance(resp, WireStreamResponse):
            await self._write_stream(resp, keep_alive)
            return
        self._write_response(resp, keep_alive)

    def _on_handler_done(self, task: asyncio.Task) -> None:
        if exc := task.exception():
            log.error("fast-ingress task error: %s", exc)
        self._busy = False
        if self._transport is not None and not self._closing and self._buf:
            self._try_dispatch()

    # -------------------------------------------------------------- writing
    def _write_response(self, resp: WireResponse, keep_alive: bool = True) -> None:
        t = self._transport
        if t is None:
            return
        extra = b""
        for k, v in resp.headers.items():
            extra += f"{k}: {v}\r\n".encode()
        if resp.status == 204:
            # RFC 7230 3.3.2: a 204 MUST NOT carry Content-Length or a
            # body (CORS preflights ride this) — a desync-pedantic front
            # proxy may reject the header we'd otherwise always write
            t.write(
                _status_line(204)
                + extra
                + (b"Connection: keep-alive\r\n\r\n" if keep_alive else b"Connection: close\r\n\r\n")
            )
            if not keep_alive:
                self._close()
            return
        t.write(
            _status_line(resp.status)
            + b"Content-Type: " + resp.content_type.encode() + b"\r\n"
            + b"Content-Length: " + str(len(resp.body)).encode() + b"\r\n"
            + extra
            + (b"Connection: keep-alive\r\n\r\n" if keep_alive else b"Connection: close\r\n\r\n")
            + resp.body
        )
        if not keep_alive:
            self._close()

    async def _write_stream(self, resp: WireStreamResponse, keep_alive: bool = True) -> None:
        """Streaming (SSE) response under Transfer-Encoding: chunked — the
        one place the fast ingress emits a body it does not know the length
        of up front. Each event is one chunk, flushed as it is produced, so
        a generative client sees token i while token i+1 is still being
        decoded. Chunked framing keeps the connection reusable; a consumer
        that vanishes mid-stream just ends the write loop."""
        t = self._transport
        if t is None:
            # connection already gone: still close the event source so the
            # in-flight generation is cancelled, not left running for a
            # vanished client
            aclose = getattr(resp.events, "aclose", None)
            if aclose is not None:
                try:
                    await aclose()
                except Exception:  # noqa: BLE001 - nothing to respond to
                    log.exception("stream close failed")
            return
        extra = b""
        for k, v in resp.headers.items():
            extra += f"{k}: {v}\r\n".encode()
        t.write(
            _status_line(resp.status)
            + b"Content-Type: " + resp.content_type.encode() + b"\r\n"
            + b"Transfer-Encoding: chunked\r\n"
            + b"Cache-Control: no-cache\r\n"
            + extra
            + (b"Connection: keep-alive\r\n\r\n" if keep_alive else b"Connection: close\r\n\r\n")
        )
        try:
            async for chunk in resp.events:
                if self._transport is None or self._closing:
                    break
                if not chunk:
                    continue
                self._transport.write(
                    f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n"
                )
        finally:
            # close the event source DETERMINISTICALLY: on client
            # disconnect the break above leaves the async generator
            # suspended, and only aclose() runs its finally blocks (which
            # cancel the in-flight generation) — waiting for GC would keep
            # a vanished client's sequences occupying KV slots
            aclose = getattr(resp.events, "aclose", None)
            if aclose is not None:
                try:
                    await aclose()
                except Exception:  # noqa: BLE001 - teardown must not mask the response
                    log.exception("stream close failed")
            if self._transport is not None and not self._closing:
                self._transport.write(b"0\r\n\r\n")
                if not keep_alive:
                    self._close()

    def _respond_simple(self, status: int, text: bytes, keep_alive: bool = False) -> None:
        self._write_response(
            WireResponse(status=status, body=text, content_type="text/plain"),
            keep_alive,
        )

    def _close(self) -> None:
        self._closing = True
        if self._transport is not None:
            self._transport.close()


async def start_fast_server(
    routes: Mapping[tuple[str, str], Handler], host: str, port: int
) -> asyncio.AbstractServer:
    loop = asyncio.get_running_loop()
    return await loop.create_server(lambda: HttpProtocol(routes), host, port)


# ----------------------------------------------------------- route builders
def engine_routes(service, state: dict, metrics=None) -> dict:
    """The engine data-plane route table (fast twin of serving/rest.py)."""
    from seldon_core_tpu.serving import wire

    async def predictions(req: WireRequest) -> WireResponse:
        return await wire.engine_predictions(service, req)

    async def predictions_stream(req: WireRequest):
        return await wire.engine_predictions_stream(service, req)

    async def feedback(req: WireRequest) -> WireResponse:
        return await wire.engine_feedback(service, req)

    async def ready(req: WireRequest) -> WireResponse:
        if state["paused"] or not service.executor.ready():
            return WireResponse.text("paused" if state["paused"] else "loading", 503)
        return WireResponse.text("ready")

    async def ping(req: WireRequest) -> WireResponse:
        return WireResponse.text("pong")

    async def pause(req: WireRequest) -> WireResponse:
        state["paused"] = True
        return WireResponse.text("paused")

    async def unpause(req: WireRequest) -> WireResponse:
        state["paused"] = False
        return WireResponse.text("unpaused")

    async def prometheus(req: WireRequest) -> WireResponse:
        m = metrics or getattr(service, "metrics", None)
        return WireResponse.text((m.export() if m is not None else b"").decode())

    routes: dict = {
        ("POST", "/api/v0.1/predictions"): predictions,
        # per-token SSE streaming for generative deployments; the buffered
        # /predictions contract above is untouched
        ("POST", "/api/v0.1/predictions/stream"): predictions_stream,
        ("POST", "/api/v0.1/feedback"): feedback,
        ("GET", "/ready"): ready,
        ("GET", "/ping"): ping,
        ("GET", "/metrics"): prometheus,
        ("GET", "/prometheus"): prometheus,
    }
    for method in ("GET", "POST"):
        routes[(method, "/pause")] = pause
        routes[(method, "/unpause")] = unpause

    # internal microservice API (reference internal-api.md) — same surface
    # as the aiohttp app
    def _unit_method(name: str):
        async def handler(req: WireRequest) -> WireResponse:
            return await wire.engine_unit_method(service, req, name)

        return handler

    for name in wire.INTERNAL_API_METHODS:
        routes[("POST", f"/{name}")] = _unit_method(name)
    return routes


def _header_from_head(head: bytes, name: bytes) -> str | None:
    """Pull ONE extra header out of a head the C parser has already
    VALIDATED (strict CRLF lines, token field-names, no obs-fold) — the C
    fast path copies out only content-type/authorization; gRPC-Web
    metadata keys like oauth_token need this targeted scan. LAST duplicate
    wins, matching both the Python fallback's dict assignment and the C
    parser's overwrite-on-match for its captured headers (C/Python
    agreement is the fuzz-enforced invariant here)."""
    target = name + b":"
    found: str | None = None
    for line in head.split(b"\r\n")[1:]:
        if line[: len(target)].lower() == target:
            found = line[len(target) :].strip(b" \t").decode("latin-1")
    return found


def gateway_routes(gw) -> dict:
    """The gateway data-plane route table (fast twin of gateway/app.py)."""
    from seldon_core_tpu.serving import wire

    async def predictions(req: WireRequest) -> WireResponse:
        return await wire.gateway_predictions(gw, req)

    async def feedback(req: WireRequest) -> WireResponse:
        return await wire.gateway_feedback(gw, req)

    async def token(req: WireRequest) -> WireResponse:
        return await wire.gateway_token(gw, req)

    async def ready(req: WireRequest) -> WireResponse:
        return WireResponse.text("ready")

    async def ping(req: WireRequest) -> WireResponse:
        return WireResponse.text("pong")

    async def prometheus(req: WireRequest) -> WireResponse:
        m = gw.metrics
        return WireResponse.text((m.export() if m is not None else b"").decode())

    async def grpc_web_predict(req: WireRequest) -> WireResponse:
        return await wire.gateway_grpc_web_predict(gw, req)

    async def grpc_web_feedback(req: WireRequest) -> WireResponse:
        return await wire.gateway_grpc_web_feedback(gw, req)

    routes = {
        ("POST", "/api/v0.1/predictions"): predictions,
        ("POST", "/api/v0.1/feedback"): feedback,
        ("POST", "/oauth/token"): token,
        ("GET", "/ready"): ready,
        ("GET", "/ping"): ping,
        ("GET", "/metrics"): prometheus,
        ("GET", "/prometheus"): prometheus,
    }
    async def grpc_web_preflight(req: WireRequest) -> WireResponse:
        # CORS preflight: browser gRPC-Web clients send OPTIONS with
        # Access-Control-Request-Headers for the non-simple content type +
        # metadata headers before the real POST
        return WireResponse(
            status=204,
            body=b"",
            content_type="text/plain",
            headers=dict(wire.GRPC_WEB_CORS_HEADERS),
        )

    # gRPC-Web unary: the ONE route table (wire.GRPC_WEB_ROUTES) shared
    # with the aiohttp gateway app, so the transports cannot drift
    for path, method in wire.GRPC_WEB_ROUTES:
        routes[("OPTIONS", path)] = grpc_web_preflight
        routes[("POST", path)] = (
            grpc_web_predict if method == "Predict" else grpc_web_feedback
        )
    return routes
