"""Paged KV memory subsystem for the generative tier.

The flat slot cache (PR 1-5) sized KV memory at ``n_slots * max_ctx``
worst-case per slot, and the prefix cache COPIED matched K/V into each
reader's slot row — HBM, not compute, capped concurrent users per chip.
This module replaces both with vLLM-style block-table paging (Kwon et al.,
SOSP 2023):

- ONE device-resident page pool ``[L, n_pages, h, page_size, hd]`` that
  live slots AND the prefix cache allocate from (models/decoder.py
  ``paged_kv_init`` / ``paged_copy`` own the device layout; the paged
  attention programs gather K/V through per-slot block tables);
- a host-side allocator (``PageAllocator``): free list, per-page
  refcounts, copy-on-write on the first divergent write into a shared
  page, and LRU reclaim of prefix pins when the free list runs dry;
- block tables carried as a static-shape ``[n_slots, max_pages]`` int32
  array — tiny per-dispatch host->device traffic, zero recompiles;
- reservation-based admission: a sequence admits only when the pool can
  guarantee its worst-case EXCLUSIVE page need (its full context minus
  the fully-shared prefix pages, which are counted once pool-wide), so
  admission throttles gracefully instead of deadlocking mid-decode.

Sharing model: a prefix-cache hit maps the entry's pages straight into the
reader's block table (refcount bump — no gather, no copy). Pages below the
reuse boundary are never written again by the reader; the partially-shared
boundary page is copy-on-written at the reader's first divergent write
(one page copy, batched through the ``paged_copy`` ladder). A capture pins
a retiring/prefilled slot's prompt pages (refcount bump — the old
capture-copy dispatch is gone); pinned pages whose only reference is the
pin are reclaimed LRU-first under pool pressure.

Conventions: physical page 0 is a reserved junk sink — free slots' block
tables are all-zero and masked-off writes land there, so no static-shape
dispatch can corrupt a live page. Page 0 is never allocated.
"""

from __future__ import annotations

import logging

import numpy as np

import jax

from seldon_core_tpu.models.decoder import paged_copy, paged_kv_init

log = logging.getLogger(__name__)


class PoolPin:
    """One prefix-cache pin: a refcount held on a page list (plus LRU age).
    The radix index entry that owns it stores the pin_id; eviction drops
    the refs and frees whatever nothing else references."""

    __slots__ = ("pin_id", "pages", "last_use")

    def __init__(self, pin_id: int, pages: list[int]):
        self.pin_id = pin_id
        self.pages = list(pages)
        self.last_use = 0


class PageAllocator:
    """Host-side page accounting. Pure host state — the only device work it
    ever ASKS for is the (src, dst) page-copy list ``prepare_write``
    returns, which the caller batches through the pool's copy ladder
    BEFORE its write dispatch.

    Invariant (what makes admission deadlock-free): at all times
    ``free + reclaimable >= sum(outstanding reservations)``, where
    reclaimable counts pages whose only references are prefix pins.
    ``try_admit`` refuses any admission that would break it; ``_alloc``
    only spends reservation the slot holds."""

    def __init__(self, n_pages: int, page_size: int, n_slots: int, pages_per_slot: int):
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        floor = max(pages_per_slot + 2, n_slots + 1)
        if n_pages < floor:
            raise ValueError(
                f"decode_kv_pages={n_pages} is below the minimal residency "
                f"for n_slots={n_slots} at {pages_per_slot} pages/slot "
                f"(need >= {floor}: junk page + one slot's full context + "
                "one page of slack) — admission would deadlock, erroring "
                "instead"
            )
        self.n_pages = int(n_pages)
        self.page_size = int(page_size)
        self.n_slots = int(n_slots)
        self.pages_per_slot = int(pages_per_slot)
        self.refs = np.zeros(n_pages, np.int32)
        self.refs[0] = 1  # page 0: reserved junk sink, never allocated
        self.pin_count = np.zeros(n_pages, np.int32)
        self._free: list[int] = list(range(n_pages - 1, 0, -1))
        self.block_tables = np.zeros((n_slots, pages_per_slot), np.int32)
        self._mapped = np.zeros(n_slots, np.int32)  # logical pages mapped
        self._reserved = np.zeros(n_slots, np.int64)  # pages still claimable
        self._pins: dict[int, PoolPin] = {}
        self._next_pin = 0
        self._clock = 0
        # called ONCE per reclaim wave with the list of reclaimed pin ids
        # (batched so the owner — the prefix index — rebuilds its trie
        # once, not once per pin, on the hot decode path)
        self.on_pins_reclaimed = None
        # chaos hook (engine/faults.py install_decode_faults): when > 0, the
        # next prepare_write raises as if the page budget were exhausted —
        # an induced allocator-OOM that exercises the decode loop's error
        # path without actually corrupting accounting
        self.chaos_oom_writes = 0
        self.stat_chaos_ooms = 0
        self.stat_pages_shared = 0
        self.stat_cow_copies = 0
        self.stat_reclaimed_pages = 0
        self.stat_pin_reclaims = 0

    # ------------------------------------------------------- introspection
    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def prefix_pages(self) -> int:
        """Pages whose only references are prefix pins (reclaimable)."""
        return int(np.sum((self.pin_count > 0) & (self.refs == self.pin_count)))

    @property
    def live_pages(self) -> int:
        """Pages referenced by at least one live slot (shared or not)."""
        return self.n_pages - 1 - self.free_pages - self.prefix_pages

    def reserved_total(self) -> int:
        return int(self._reserved.sum())

    def snapshot(self) -> dict:
        """One cheap host-side read of the pool's occupancy + event
        counters — the flight recorder's per-round hook and the soak/bench
        summaries read this instead of poking individual properties (one
        definition of "pool state at time t" for every consumer)."""
        return {
            "free": self.free_pages,
            "live": self.live_pages,
            "prefix": self.prefix_pages,
            "reserved": self.reserved_total(),
            "shared_total": self.stat_pages_shared,
            "cow_total": self.stat_cow_copies,
            "pin_reclaims": self.stat_pin_reclaims,
        }

    def pages_for(self, tokens: int) -> int:
        return -(-int(tokens) // self.page_size)

    def slot_pages(self, slot: int) -> list[int]:
        return [int(p) for p in self.block_tables[slot, : int(self._mapped[slot])]]

    def _reclaimable(self, exclude=()) -> int:
        mask = (self.pin_count > 0) & (self.refs == self.pin_count)
        cnt = int(mask.sum())
        for p in set(exclude):
            if mask[p]:
                cnt -= 1
        return cnt

    def check(self) -> None:
        """Internal-consistency audit (tests): every page is exactly one of
        {junk sink, free, referenced}; refs reconcile with block tables +
        pins; no free page is referenced or mapped."""
        refs = np.zeros(self.n_pages, np.int64)
        refs[0] = 1
        for s in range(self.n_slots):
            for p in self.slot_pages(s):
                refs[p] += 1
        pins = np.zeros(self.n_pages, np.int64)
        for pin in self._pins.values():
            for p in pin.pages:
                refs[p] += 1
                pins[p] += 1
        if not np.array_equal(refs, self.refs):
            raise AssertionError("refcounts diverged from block tables + pins")
        if not np.array_equal(pins, self.pin_count):
            raise AssertionError("pin counts diverged from pins")
        free = set(self._free)
        if len(free) != len(self._free):
            raise AssertionError("double-free: duplicate page in free list")
        if 0 in free:
            raise AssertionError("junk page 0 leaked into the free list")
        for p in free:
            if self.refs[p] != 0:
                raise AssertionError(f"free page {p} still referenced")
        for p in range(1, self.n_pages):
            if self.refs[p] == 0 and p not in free:
                raise AssertionError(f"page {p} leaked (unreferenced, not free)")
        if self.free_pages + self._reclaimable() < self.reserved_total():
            raise AssertionError("reservation invariant broken")

    # ----------------------------------------------------------- admission
    def try_admit(self, slot: int, shared_pages, reuse: int, extra_reserve: int = 0) -> bool:
        """Admit a sequence into ``slot``: map its matched prefix pages
        (refcount bump — the copy-free share) and reserve its worst-case
        exclusive page need. Returns False — mapping nothing — when the
        pool cannot GUARANTEE the reservation; the caller leaves the
        request queued until retirements free pages.

        ``reuse`` is the matched token span; only its fully-covered pages
        are exempt from the reservation (the partial boundary page will be
        copy-on-written at the first divergent write). ``extra_reserve``
        covers CoW the caller knows is coming (a cache_prefix capture hint
        pinning pages mid-generation)."""
        if self._mapped[slot] or self._reserved[slot]:
            raise RuntimeError(f"slot {slot} admitted while still mapped")
        n_map = self.pages_for(reuse) if reuse > 0 else 0
        shared = [int(p) for p in list(shared_pages)[:n_map]]
        if len(shared) < n_map:
            raise ValueError("matched entry holds fewer pages than reuse needs")
        need = self.pages_per_slot - (int(reuse) // self.page_size) + int(extra_reserve)
        avail = self.free_pages + self._reclaimable(exclude=shared)
        if avail - self.reserved_total() < need:
            return False
        for lp, p in enumerate(shared):
            self.block_tables[slot, lp] = p
            self.refs[p] += 1
        self._mapped[slot] = n_map
        self._reserved[slot] = need
        self.stat_pages_shared += n_map
        return True

    # ---------------------------------------------------------- allocation
    def _alloc(self, slot: int) -> int:
        if self._reserved[slot] <= 0:
            raise RuntimeError(
                f"slot {slot} allocating past its reservation — the "
                "no-deadlock invariant would be void"
            )
        if not self._free:
            self._reclaim_until_free()
        p = self._free.pop()
        self.refs[p] = 1
        self._reserved[slot] -= 1
        return p

    def _reclaim_until_free(self) -> None:
        reclaimed: list[int] = []
        while not self._free and self._pins:
            # prefer the LRU pin that actually FREES a page (one whose
            # pages include a refs==1 page): dropping a pin whose pages
            # live readers still map would destroy a prefix entry without
            # relieving any pressure. Fall back to plain LRU when no
            # single pin frees anything (e.g. a page held by two pins
            # needs both dropped — still progress).
            freeing = [
                p for p in self._pins.values()
                if any(self.refs[pg] == 1 for pg in p.pages)
            ]
            pin = min(freeing or self._pins.values(), key=lambda q: q.last_use)
            self._drop_pin(pin, reclaim=True)
            reclaimed.append(pin.pin_id)
        if reclaimed and self.on_pins_reclaimed is not None:
            self.on_pins_reclaimed(reclaimed)
        if not self._free:
            raise RuntimeError(
                "kv page pool exhausted with nothing reclaimable — "
                "reservation invariant broken (bug)"
            )

    def prepare_write(self, slot: int, start: int, count: int) -> list[tuple[int, int]]:
        """Make positions [start, start + count) writable by ``slot``:
        allocate not-yet-mapped logical pages and copy-on-write shared
        ones. Returns the (src, dst) page copies the caller MUST dispatch
        (through the pool's copy ladder) before its write dispatch.
        Positions beyond the slot's virtual length are ignored — the
        device-side write mask junk-redirects them to page 0."""
        ps = self.page_size
        end = min(int(start) + int(count), self.pages_per_slot * ps)
        if count <= 0 or start >= end:
            return []
        if self.chaos_oom_writes > 0:
            self.chaos_oom_writes -= 1
            self.stat_chaos_ooms += 1
            raise RuntimeError(
                "chaos: induced allocator OOM (page budget exhausted by "
                f"fault injection) preparing write for slot {slot}"
            )
        copies: list[tuple[int, int]] = []
        bt = self.block_tables
        for lp in range(int(start) // ps, (end - 1) // ps + 1):
            if lp >= self._mapped[slot]:
                for lpn in range(int(self._mapped[slot]), lp + 1):
                    bt[slot, lpn] = self._alloc(slot)
                self._mapped[slot] = lp + 1
            else:
                p = int(bt[slot, lp])
                if self.refs[p] > 1:
                    fresh = self._alloc(slot)
                    copies.append((p, fresh))
                    bt[slot, lp] = fresh
                    self.refs[p] -= 1
                    self.stat_cow_copies += 1
        return copies

    # ---------------------------------------------------------- retirement
    def retire(self, slot: int) -> None:
        """Return the slot's page references to the pool: pages nothing
        else references go back to the free list; pages pinned as prefix
        entries (or shared with other readers) survive."""
        for lp in range(int(self._mapped[slot])):
            p = int(self.block_tables[slot, lp])
            self.refs[p] -= 1
            if self.refs[p] == 0:
                self._free.append(p)
        self.block_tables[slot, :] = 0
        self._mapped[slot] = 0
        self._reserved[slot] = 0

    # -------------------------------------------------------- prefix pins
    def capture(self, slot: int, length: int) -> PoolPin | None:
        """Pin the pages covering the slot's leading ``length`` tokens as a
        prefix entry — a refcount bump, NO copy (the old capture dispatch
        is gone). Returns None if the span isn't materialized yet."""
        n = self.pages_for(length)
        if n < 1 or n > self._mapped[slot]:
            return None
        pin = PoolPin(self._next_pin, self.slot_pages(slot)[:n])
        self._next_pin += 1
        self._clock += 1
        pin.last_use = self._clock
        for p in pin.pages:
            self.refs[p] += 1
            self.pin_count[p] += 1
        self._pins[pin.pin_id] = pin
        return pin

    def preseed_pin(self, n: int) -> PoolPin | None:
        """Allocate ``n`` free pages directly into a prefix pin (warm
        scale-up: a new replica's pool is seeded from another replica's
        spilled pages before it serves traffic — serving/affinity_router).
        Returns None when the free list cannot cover it. The reservation
        invariant holds unchanged: the pages leave the free list but enter
        the pin-only (reclaimable) set, so ``free + reclaimable`` is
        constant."""
        n = int(n)
        if n < 1 or n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        pin = PoolPin(self._next_pin, pages)
        self._next_pin += 1
        self._clock += 1
        pin.last_use = self._clock
        for p in pages:
            self.refs[p] = 1
            self.pin_count[p] = 1
        self._pins[pin.pin_id] = pin
        return pin

    def touch(self, pin_id: int) -> None:
        pin = self._pins.get(pin_id)
        if pin is not None:
            self._clock += 1
            pin.last_use = self._clock

    def release(self, pin_id: int) -> None:
        """Drop a pin its owner no longer wants (index-cap eviction)."""
        pin = self._pins.get(pin_id)
        if pin is not None:
            self._drop_pin(pin, reclaim=False)

    def _drop_pin(self, pin: PoolPin, reclaim: bool) -> None:
        del self._pins[pin.pin_id]
        freed = 0
        for p in pin.pages:
            self.pin_count[p] -= 1
            self.refs[p] -= 1
            if self.refs[p] == 0:
                self._free.append(p)
                freed += 1
        if reclaim:
            self.stat_reclaimed_pages += freed
            self.stat_pin_reclaims += 1


class PagedKVPool:
    """Device pool state + host allocator + the CoW copy-ladder program.

    ``cache_ctx`` is the per-slot virtual context (seq + max_new; the paged
    write mask replaces the flat layout's verify/chunk headroom columns).
    ``n_pages=0`` auto-sizes to flat-equivalent capacity (every slot can
    hold its full context with zero sharing); smaller explicit budgets are
    where paging pays — admission then throttles on the reservation
    invariant instead of deadlocking."""

    def __init__(
        self,
        params,
        *,
        n_slots: int,
        cache_ctx: int,
        page_size: int = 0,
        n_pages: int = 0,
        kv_dtype: str = "",
        dtype=None,
        place=None,
        shardings_fn=None,
    ):
        import jax.numpy as jnp

        if kv_dtype not in ("", "int8"):
            raise ValueError(
                f"decode_kv_dtype {kv_dtype!r} unsupported (want '' or 'int8')"
            )
        self.page_size = int(page_size) or 16
        self.pages_per_slot = -(-int(cache_ctx) // self.page_size)
        self.n_pages = int(n_pages) or (n_slots * self.pages_per_slot + 2)
        self.kv_dtype = kv_dtype
        self._params = params
        self._dtype = dtype if dtype is not None else jnp.float32
        self._place = place or (lambda arrs: tuple(arrs))
        self.n_slots = int(n_slots)
        self.alloc = PageAllocator(
            self.n_pages, self.page_size, self.n_slots, self.pages_per_slot
        )
        self.state = self._place(
            paged_kv_init(params, self.n_pages, self.page_size, self._dtype, kv_dtype)
        )
        # tensor-parallel decode (parallel/tp.py): the scheduler hands a
        # per-buffer sharding resolver so the pool state is committed to
        # the decode mesh (payloads head-sharded, int8 scale planes
        # replicated) and the CoW copy ladder pins the SAME shardings on
        # its outputs — the donated state round-trips every program with
        # one stable layout, which is what keeps warmup's signatures
        # exactly the live ones (zero recompiles on the sharded geometry)
        self.state_shardings = (
            tuple(shardings_fn(a) for a in self.state)
            if shardings_fn is not None
            else None
        )
        copy_kw = (
            {"out_shardings": self.state_shardings}
            if self.state_shardings is not None
            else {}
        )
        self._copy_fn = jax.jit(paged_copy, donate_argnums=(0,), **copy_kw)
        buckets, b = [], 1
        while b < self.n_slots:
            buckets.append(b)
            b *= 2
        self.copy_buckets = tuple(buckets) + (self.n_slots,)
        self.stat_copy_dispatches = 0

    @property
    def virtual_ctx(self) -> int:
        return self.pages_per_slot * self.page_size

    def block_tables(self) -> np.ndarray:
        """Fresh host copy of the block tables for one dispatch (the jit
        argument must not alias the live allocator state)."""
        return self.alloc.block_tables.copy()

    def run_copies(self, copies: list[tuple[int, int]]) -> None:
        """Dispatch the round's CoW page copies through the warmed ladder
        (padding entries copy junk page 0 onto itself)."""
        i = 0
        while i < len(copies):
            batch = copies[i : i + self.copy_buckets[-1]]
            bucket = next(b for b in self.copy_buckets if b >= len(batch))
            src = np.zeros(bucket, np.int32)
            dst = np.zeros(bucket, np.int32)
            for j, (s, d) in enumerate(batch):
                src[j] = s
                dst[j] = d
            self.state = self._copy_fn(self.state, src, dst)
            self.stat_copy_dispatches += 1
            i += len(batch)

    def warmup(self) -> None:
        """Compile the copy ladder (page0 -> page0 self-copies touch no
        live bytes)."""
        for b in self.copy_buckets:
            self.state = self._copy_fn(
                self.state, np.zeros(b, np.int32), np.zeros(b, np.int32)
            )

    def compile_count(self) -> int:
        return self._copy_fn._cache_size()

    def reset(self) -> None:
        """Post-failure recovery: the state tuple was donated into a call
        that raised, so its buffers may be invalidated — reallocate, and
        drop every host mapping with it."""
        on_reclaimed = self.alloc.on_pins_reclaimed
        self.alloc = PageAllocator(
            self.n_pages, self.page_size, self.n_slots, self.pages_per_slot
        )
        self.alloc.on_pins_reclaimed = on_reclaimed
        self.state = self._place(
            paged_kv_init(
                self._params, self.n_pages, self.page_size, self._dtype, self.kv_dtype
            )
        )
