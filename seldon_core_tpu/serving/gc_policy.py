"""Serving-time garbage-collection policy: kill multi-tenant tail spikes.

Root cause (round-5 session, measured): the heterogeneous multi-tenant
bench's 70-100 ms event-loop stalls were CPython GEN-2 GC pauses, not model
compute — instrumenting gc.callbacks recorded a 74 ms gen-2 collection
exactly matching the 73 ms loop_lag_max (the r4 record's attribution to
"the wide tenant's host-side matmuls" was wrong: forcing compute offload
moved nothing, freezing the GC moved lag_max 72.9 -> 11.0 ms).

Why gen-2 is slow here: after warmup the process holds ~10^5 long-lived
objects (jaxprs, compiled-executable wrappers, module state, per-tenant
runtimes); every gen-2 collection scans all of them, on the serving core,
inside whatever event-loop callback happened to allocate the triggering
object.

The policy — the standard long-lived-server prescription (as used by large
production Python deployments; see gc.freeze docs):

1. one full collect() to drop warmup garbage, then
2. freeze() the survivors into the permanent generation, removing them
   from every future gen-2 scan.

Call after model warmup, before taking traffic. Calling again later (e.g.
after a reconciler applies a new tenant) is safe and re-freezes that
tenant's artifacts; anything in-flight at that moment is pinned forever,
so re-freeze from control-plane context, not per request. The
seldon_tpu_event_loop_lag_ms gauge (metrics/registry.py) plus the
EventLoopLagHigh alert watch the symptom in production.
"""

from __future__ import annotations

import gc
import logging

log = logging.getLogger(__name__)


def apply_serving_gc_policy() -> int:
    """Collect warmup garbage, then freeze survivors out of gen-2 scans.
    Returns the number of objects now frozen. Idempotent; cheap enough to
    call after every warmup/deployment apply from control-plane context."""
    gc.collect()
    gc.freeze()
    frozen = gc.get_freeze_count()
    log.info("serving GC policy applied: %d objects frozen out of gen-2", frozen)
    return frozen
