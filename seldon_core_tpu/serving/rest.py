"""REST server (aiohttp) — the engine's external HTTP surface.

Parity: reference engine RestClientController.java:
- POST /api/v0.1/predictions (:102) — accepts application/json bodies AND the
  reference's form-encoded ``json=`` style (microservice.py:44-52 wire quirk);
- POST /api/v0.1/feedback (:140);
- GET /ready /ping (:62-75), POST|GET /pause /unpause (:87-99) — /pause flips
  readiness false so an orchestrator drains the pod, matching the preStop
  ``curl /pause`` hook the reference operator injects;
- /metrics and /prometheus (reference scrape annotation path) — Prometheus
  exposition.
Errors return the reference's status-JSON shape with its numeric codes.
"""

from __future__ import annotations

import logging

from aiohttp import web

from seldon_core_tpu.serving.service import PredictionService
from seldon_core_tpu.serving.http_util import from_wire_response, to_wire_request

log = logging.getLogger(__name__)


def build_app(service: PredictionService, state: dict | None = None, metrics=None) -> web.Application:
    state = state if state is not None else {"paused": False}
    app = web.Application(client_max_size=64 * 1024 * 1024)
    app["state"] = state
    app["service"] = service

    # handlers delegate to the transport-neutral wire core (serving/wire.py)
    # shared with the fast ingress, so the two transports cannot drift;
    # aiohttp control-flow exceptions (413 from client_max_size etc.) raise
    # during body read and keep aiohttp's own handling
    async def predictions(request: web.Request) -> web.Response:
        from seldon_core_tpu.serving import wire

        req = await to_wire_request(request)
        return from_wire_response(await wire.engine_predictions(service, req))

    async def feedback(request: web.Request) -> web.Response:
        from seldon_core_tpu.serving import wire

        req = await to_wire_request(request)
        return from_wire_response(await wire.engine_feedback(service, req))

    async def ready(request: web.Request) -> web.Response:
        if state["paused"] or not service.executor.ready():
            return web.Response(status=503, text="paused" if state["paused"] else "loading")
        return web.Response(text="ready")

    async def ping(request: web.Request) -> web.Response:
        return web.Response(text="pong")

    async def pause(request: web.Request) -> web.Response:
        state["paused"] = True
        return web.Response(text="paused")

    async def unpause(request: web.Request) -> web.Response:
        state["paused"] = False
        return web.Response(text="unpaused")

    async def prometheus(request: web.Request) -> web.Response:
        from seldon_core_tpu.serving.http_util import prometheus_response

        return prometheus_response(request, metrics or getattr(service, "metrics", None))

    # replicated decode fleet operations (serving/affinity_router.py):
    # GET /decode/fleet the per-arm lifecycle read-out, POST /decode/drain
    # the graceful scale-down trigger (?replica=n names the arm; without
    # it the coldest serving replica drains) — the serving-tier twin of
    # the orchestrator-facing /pause drain hook above
    async def decode_fleet(request: web.Request) -> web.Response:
        status = service.decode_fleet_status()
        if status is None:
            return web.json_response(
                {"error": "no replicated decode tier"}, status=404
            )
        return web.json_response(status)

    async def decode_drain(request: web.Request) -> web.Response:
        from seldon_core_tpu.core.errors import APIException

        raw = request.query.get("replica")
        replica = None
        if raw is not None:
            try:
                replica = int(raw)
            except (TypeError, ValueError):
                return web.json_response(
                    {
                        "error": "?replica must be an integer arm id",
                        "param": "replica",
                        "got": raw,
                    },
                    status=400,
                )
        try:
            return web.json_response(await service.drain_decode_replica(replica))
        except APIException as e:
            return web.json_response({"error": str(e)}, status=e.error.http_status)

    # internal microservice API (reference internal-api.md): the endpoints
    # an engine's RemoteUnit dispatches to when THIS process is a wrapped
    # single-unit microservice; shares the wire core with everything else
    def _unit_method(method: str):
        async def handler(request: web.Request) -> web.Response:
            from seldon_core_tpu.serving import wire

            req = await to_wire_request(request)
            return from_wire_response(
                await wire.engine_unit_method(service, req, method)
            )

        return handler

    from seldon_core_tpu.serving.wire import INTERNAL_API_METHODS

    for method in INTERNAL_API_METHODS:
        app.router.add_post(f"/{method}", _unit_method(method))

    app.router.add_post("/api/v0.1/predictions", predictions)
    app.router.add_post("/api/v0.1/feedback", feedback)
    app.router.add_get("/ready", ready)
    app.router.add_get("/ping", ping)
    for method in ("GET", "POST"):
        app.router.add_route(method, "/pause", pause)
        app.router.add_route(method, "/unpause", unpause)
    app.router.add_get("/metrics", prometheus)
    app.router.add_get("/prometheus", prometheus)
    app.router.add_get("/decode/fleet", decode_fleet)
    app.router.add_post("/decode/drain", decode_drain)
    return app
