"""REST server (aiohttp) — the engine's external HTTP surface.

Parity: reference engine RestClientController.java:
- POST /api/v0.1/predictions (:102) — accepts application/json bodies AND the
  reference's form-encoded ``json=`` style (microservice.py:44-52 wire quirk);
- POST /api/v0.1/feedback (:140);
- GET /ready /ping (:62-75), POST|GET /pause /unpause (:87-99) — /pause flips
  readiness false so an orchestrator drains the pod, matching the preStop
  ``curl /pause`` hook the reference operator injects;
- /metrics and /prometheus (reference scrape annotation path) — Prometheus
  exposition.
Errors return the reference's status-JSON shape with its numeric codes.
"""

from __future__ import annotations

import logging

from aiohttp import web

from seldon_core_tpu.core.codec_json import (
    feedback_from_dict,
    message_from_dict,
    message_from_json_fast,
    message_to_dict,
    message_to_json_fast,
)
from seldon_core_tpu.core.codec_npy import is_npy
from seldon_core_tpu.core.errors import ErrorCode
from seldon_core_tpu.core.message import SeldonMessage
from seldon_core_tpu.serving.service import PredictionService
from seldon_core_tpu.serving.http_util import (
    classify_binary_body,
    npy_response,
    payload_dict,
    wire_failure,
)

log = logging.getLogger(__name__)


async def _payload_dict(request: web.Request) -> dict:
    return await payload_dict(request, ErrorCode.ENGINE_INVALID_JSON)


def build_app(service: PredictionService, state: dict | None = None, metrics=None) -> web.Application:
    state = state if state is not None else {"paused": False}
    app = web.Application(client_max_size=64 * 1024 * 1024)
    app["state"] = state
    app["service"] = service

    async def predictions(request: web.Request) -> web.Response:
        try:
            ctype = request.content_type or ""
            kind, raw = await classify_binary_body(
                request, sniff_npy=service.decode_npy
            )
            if kind != "json":
                # "npy": binary tensor fast path — the raw body IS the npy
                # tensor, no JSON envelope, no base64 (codec_npy rationale);
                # the service mirrors the kind, so out.bin_data is npy too.
                # "bin": deliberate octet-stream — opaque binData flowing
                # through the graph untouched (reference oneof semantics).
                out = await service.predict(
                    SeldonMessage(bin_data=raw), wire_npy=kind == "npy"
                )
                # is_npy guard: a bytes-out unit can answer an npy request
                # with opaque bytes — serving those as application/x-npy
                # would lie about the body; fall back to the JSON envelope
                if kind == "npy" and is_npy(out.bin_data):
                    return npy_response(out)
                # opaque binData (and any tensor produced from bytes) keeps
                # the JSON envelope — base64 binData, the pre-npy contract
                return web.Response(
                    body=message_to_json_fast(out), content_type="application/json"
                )
            if ctype.startswith("application/json"):
                # hot path: ndarray matrix parses/serializes in C
                # (native/fastcodec); envelope in Python json
                msg = message_from_json_fast(await request.read())
            else:
                msg = message_from_dict(await _payload_dict(request))
            out = await service.predict(msg)
            return web.Response(
                body=message_to_json_fast(out), content_type="application/json"
            )
        except Exception as e:  # noqa: BLE001 - wire boundary (wire_failure)
            return wire_failure(
                e,
                fallback_code=ErrorCode.ENGINE_MICROSERVICE_ERROR,
                op="predict",
                log=log,
                metrics_error=lambda c: service.metrics.ingress_error(
                    service.deployment_name, "predict", c
                ),
            )

    async def feedback(request: web.Request) -> web.Response:
        try:
            fb = feedback_from_dict(await _payload_dict(request))
            out = await service.send_feedback(fb)
            return web.json_response(message_to_dict(out))
        except Exception as e:  # noqa: BLE001 - wire boundary (wire_failure)
            return wire_failure(
                e,
                fallback_code=ErrorCode.ENGINE_MICROSERVICE_ERROR,
                op="feedback",
                log=log,
                metrics_error=lambda c: service.metrics.ingress_error(
                    service.deployment_name, "feedback", c
                ),
            )

    async def ready(request: web.Request) -> web.Response:
        if state["paused"] or not service.executor.ready():
            return web.Response(status=503, text="paused" if state["paused"] else "loading")
        return web.Response(text="ready")

    async def ping(request: web.Request) -> web.Response:
        return web.Response(text="pong")

    async def pause(request: web.Request) -> web.Response:
        state["paused"] = True
        return web.Response(text="paused")

    async def unpause(request: web.Request) -> web.Response:
        state["paused"] = False
        return web.Response(text="unpaused")

    async def prometheus(request: web.Request) -> web.Response:
        m = metrics or getattr(service, "metrics", None)
        body = m.export() if m is not None else b""
        return web.Response(body=body, content_type="text/plain")

    app.router.add_post("/api/v0.1/predictions", predictions)
    app.router.add_post("/api/v0.1/feedback", feedback)
    app.router.add_get("/ready", ready)
    app.router.add_get("/ping", ping)
    for method in ("GET", "POST"):
        app.router.add_route(method, "/pause", pause)
        app.router.add_route(method, "/unpause", unpause)
    app.router.add_get("/metrics", prometheus)
    app.router.add_get("/prometheus", prometheus)
    return app
